"""Device table: an ordered set of equal-length Columns (a columnar batch).

Analog of the reference's cudf `Table` + Spark `ColumnarBatch` of
GpuColumnVector (reference: GpuColumnVector.java `from(Table)`).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import dtypes as dt
from .column import Column

__all__ = ["Table", "Schema", "Field"]


class Field:
    def __init__(self, name: str, dtype: dt.DataType, nullable: bool = True):
        self.name = name
        self.dtype = dtype
        self.nullable = nullable

    def __repr__(self):
        return f"{self.name}:{self.dtype}"

    def __eq__(self, other):
        return (isinstance(other, Field) and other.name == self.name
                and other.dtype == self.dtype)


class Schema:
    def __init__(self, fields: Sequence[Field]):
        self.fields = list(fields)

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __getitem__(self, i):
        return self.fields[i]

    def __len__(self):
        return len(self.fields)

    def __repr__(self):
        return "Schema(" + ", ".join(map(repr, self.fields)) + ")"

    def __eq__(self, other):
        return isinstance(other, Schema) and other.fields == self.fields

    def to_arrow(self):
        import pyarrow as pa
        return pa.schema([(f.name, dt.to_arrow(f.dtype)) for f in self.fields])

    @staticmethod
    def from_arrow(schema) -> "Schema":
        return Schema([Field(f.name, dt.from_arrow(f.type), f.nullable)
                       for f in schema])


class Table:
    """Immutable batch of columns. All columns share `num_rows`."""

    def __init__(self, names: Sequence[str], columns: Sequence[Column]):
        assert len(names) == len(columns)
        if columns:
            n = columns[0].length
            for c in columns:
                assert c.length == n, "ragged table"
        self.names = list(names)
        self.columns = list(columns)

    # ------------------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return self.columns[0].length if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def schema(self) -> Schema:
        return Schema([Field(n, c.dtype) for n, c in
                       zip(self.names, self.columns)])

    @property
    def nbytes(self) -> int:
        return sum(c.nbytes for c in self.columns)

    def column(self, key) -> Column:
        if isinstance(key, int):
            return self.columns[key]
        return self.columns[self.names.index(key)]

    def select(self, names: Sequence[str]) -> "Table":
        return Table(list(names), [self.column(n) for n in names])

    def with_column(self, name: str, col: Column) -> "Table":
        names, cols = list(self.names), list(self.columns)
        if name in names:
            cols[names.index(name)] = col
        else:
            names.append(name)
            cols.append(col)
        return Table(names, cols)

    def rename(self, names: Sequence[str]) -> "Table":
        return Table(list(names), self.columns)

    def __repr__(self):
        return f"Table({self.schema}, rows={self.num_rows})"

    # ------------------------------------------------------------------
    @staticmethod
    def from_pydict(data: Dict[str, Tuple[Sequence, dt.DataType]]) -> "Table":
        names, cols = [], []
        for name, (values, dtype) in data.items():
            names.append(name)
            cols.append(Column.from_pylist(values, dtype))
        return Table(names, cols)

    @staticmethod
    def from_arrow(at) -> "Table":
        """Build from a pyarrow Table or RecordBatch.

        All column buffers transfer in ONE device_put — per-transfer
        latency dominates on tunneled TPU runtimes, so batching transfers
        is the H2D analog of the reference's single readParquet H2D copy.
        """
        import jax
        names = list(at.schema.names)
        host = [Column.host_from_arrow(at.column(i))
                for i in range(len(names))]
        dev = jax.device_put([bufs for _, _, bufs in host])
        cols = [Column.build(dtype, n, d)
                for (dtype, n, _), d in zip(host, dev)]
        return Table(names, cols)

    def to_arrow(self):
        """One device_get for every buffer of every column (per-transfer
        latency dominates on tunneled runtimes)."""
        import pyarrow as pa
        from ..utils.transfer import fetch
        host = fetch([c.device_buffers() for c in self.columns])
        arrs = [Column.arrow_from_host(c.dtype, c.length, b)
                for c, b in zip(self.columns, host)]
        return pa.Table.from_arrays(arrs, names=list(self.names))

    def to_pydict(self) -> Dict[str, list]:
        return {n: c.to_pylist() for n, c in zip(self.names, self.columns)}

    def to_pylist(self) -> List[tuple]:
        cols = [c.to_pylist() for c in self.columns]
        return list(zip(*cols)) if cols else []
