"""Device-resident Arrow-layout column.

The TPU analog of the reference's `GpuColumnVector`
(reference: sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java)
backed by cudf ColumnVector. Here a column is a bundle of jax.Arrays living in
TPU HBM:

  data     : primary values buffer, shape [capacity] (padded)
  validity : bool[capacity]; True = valid. Rows >= length are always False.
  offsets  : int32[capacity+1] for variable-width types (string/binary/list)
  children : nested child Columns (struct/list)

XLA compiles one program per shape, so capacities are bucketed to powers of
two (min 128 to match TPU lane width) — this bounds recompilation while
keeping padding <2x. The logical row count `length` is a host int; kernels
mask padding rows via `validity`.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as dt

__all__ = ["Column", "bucket_capacity", "bucket_chunks", "MIN_CAPACITY",
           "set_bucket_policy", "bucket_policy", "shape_stats",
           "reset_shape_stats", "flatten_bufs", "unflatten_bufs"]


def flatten_bufs(bufs, prefix: str = "", out=None):
    """Flatten a (possibly nested) device_buffers tree into path->array,
    for flat containers like npz spill files. Children get `ch<j>.` path
    segments."""
    if out is None:
        out = {}
    for k, v in bufs.items():
        if k == "children":
            for j, cb in enumerate(v):
                flatten_bufs(cb, f"{prefix}ch{j}.", out)
        else:
            # tpulint: allow[host-sync] spill callers pass pre-fetched
            out[prefix + k] = np.asarray(v)
    return out


def unflatten_bufs(flat):
    """Inverse of flatten_bufs."""
    bufs, kids = {}, {}
    for k, v in flat.items():
        if k.startswith("ch"):
            head, _, rest = k.partition(".")
            if rest and head[2:].isdigit():
                kids.setdefault(int(head[2:]), {})[rest] = v
                continue
        bufs[k] = v
    if kids:
        bufs["children"] = [unflatten_bufs(kids[j]) for j in sorted(kids)]
    return bufs

MIN_CAPACITY = 128

# ---------------------------------------------------------------------
# shape-bucket policy (sql.exec.shapeBuckets.*): every capacity (and,
# through ops/sortkeys.nchunks_for_len, every string-chunk count) in the
# engine rounds up onto the geometric grid {minRows * growthFactor^k}.
# The default grid (128, x2) is the historical power-of-two bucketing —
# zero behavior change. A coarser grid (e.g. minRows=4096, x4) collapses
# many nearby sizes onto one bucket: structurally equal operators at
# different input sizes then share ONE padded XLA program, shrinking the
# cold compile tail at a bounded padding cost (capacity < growthFactor
# * rows for rows > minRows — the measured bound shape_stats() reports).
# Adopted per query by runtime/program_cache.set_active_conf; process-
# global like the program cache itself (last conf wins), because the
# programs the buckets key are process-global too.
_BUCKET_MIN = MIN_CAPACITY
_BUCKET_GROWTH_BITS = 1      # log2(growthFactor); 1 == power-of-two
# advisory padding-waste accounting (racy += under the GIL is fine: the
# counters steer nothing, they only report the measured waste bound)
_shape_stats = {"bucket_requests": 0, "requested_rows": 0,
                "bucketed_rows": 0}

_ALLOWED_GROWTH = (2, 4, 8, 16)


def set_bucket_policy(min_rows: int = MIN_CAPACITY,
                      growth_factor: int = 2) -> None:
    """Install the capacity-bucket grid. `min_rows` must be a power of
    two >= MIN_CAPACITY (the TPU lane-width floor); `growth_factor` one
    of 2/4/8/16. Out-of-range values clamp to the nearest legal value
    rather than raise — a mistyped conf must not fail every query."""
    global _BUCKET_MIN, _BUCKET_GROWTH_BITS
    m = max(int(min_rows), MIN_CAPACITY)
    m = 1 << (m - 1).bit_length()           # round up to a power of two
    g = min(_ALLOWED_GROWTH, key=lambda a: abs(a - int(growth_factor)))
    _BUCKET_MIN = m
    _BUCKET_GROWTH_BITS = g.bit_length() - 1


def bucket_policy() -> tuple:
    """(min_rows, growth_factor) currently installed."""
    return _BUCKET_MIN, 1 << _BUCKET_GROWTH_BITS


def shape_stats() -> dict:
    """Padding-waste accounting since the last reset: how many rows
    callers asked for vs how many the buckets allocated. waste_frac is
    the measured padding fraction — bounded by 1 - 1/growthFactor for
    requests above the floor."""
    out = dict(_shape_stats)
    br = out["bucketed_rows"]
    out["waste_frac"] = (round(1.0 - out["requested_rows"] / br, 4)
                         if br else 0.0)
    out["policy_min_rows"] = _BUCKET_MIN
    out["policy_growth_factor"] = 1 << _BUCKET_GROWTH_BITS
    return out


def reset_shape_stats() -> None:
    for k in _shape_stats:
        _shape_stats[k] = 0


def alloc_shape(dtype: "dt.DataType", cap: int):
    """Data-buffer shape for a fixed-width column of `cap` rows.
    decimal128 stores two int64 limbs per row — every allocation site
    must use this (a flat buffer export-corrupts; see r4 q22 bug)."""
    if isinstance(dtype, dt.DecimalType) and dtype.is_decimal128:
        return (cap, 2)
    return (cap,)


def bucket_capacity(n: int) -> int:
    """Round n up onto the bucket grid {minRows * growthFactor^k}. The
    default policy (128, x2) is the historical next-power-of-two with a
    MIN_CAPACITY floor."""
    m = _BUCKET_MIN
    if n <= m:
        cap = m
    else:
        g = _BUCKET_GROWTH_BITS
        steps = -(-(int(n - 1).bit_length() - (m.bit_length() - 1)) // g)
        cap = m << (steps * g)
    _shape_stats["bucket_requests"] += 1
    _shape_stats["requested_rows"] += max(int(n), 0)
    _shape_stats["bucketed_rows"] += cap
    return cap


def bucket_chunks(n: int) -> int:
    """Round a chunk COUNT up onto the same geometric grid (floor 1).
    String-key programs are traced per chunk count; canonicalizing the
    count means nearby key lengths share one program at the cost of a
    few all-padding chunks."""
    if n <= 1:
        return 1
    g = _BUCKET_GROWTH_BITS
    steps = -(-int(n - 1).bit_length() // g)
    return 1 << (steps * g)


def _pad_to(arr: np.ndarray, capacity: int, fill=0) -> np.ndarray:
    if arr.shape[0] == capacity:
        return arr
    pad = capacity - arr.shape[0]
    return np.concatenate([arr, np.full((pad,) + arr.shape[1:], fill,
                                        dtype=arr.dtype)])


class Column:
    """An immutable device column. All device buffers share one capacity."""

    def __init__(self, dtype: dt.DataType, length: int, data, validity,
                 offsets=None, children: Optional[List["Column"]] = None):
        self.dtype = dtype
        self.length = int(length)
        self.data = data            # jax.Array [capacity] (or [0] for struct)
        self.validity = validity    # jax.Array bool [capacity]
        self.offsets = offsets      # jax.Array int32 [capacity+1] or None
        self.children = children or []

    # ------------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    @property
    def nbytes(self) -> int:
        n = self.data.size * self.data.dtype.itemsize + self.validity.size
        if self.offsets is not None:
            n += self.offsets.size * 4
        for c in self.children:
            n += c.nbytes
        return int(n)

    def __repr__(self):
        return (f"Column({self.dtype}, length={self.length}, "
                f"capacity={self.capacity})")

    # ------------------------------------------------------------------
    # Construction
    # ------------------------------------------------------------------
    @staticmethod
    def from_numpy(values: np.ndarray, dtype: dt.DataType,
                   validity: Optional[np.ndarray] = None,
                   capacity: Optional[int] = None) -> "Column":
        n = len(values)
        cap = capacity or bucket_capacity(n)
        if validity is None:
            validity = np.ones(n, dtype=np.bool_)
        vals = _pad_to(np.ascontiguousarray(values), cap)
        valid = _pad_to(validity.astype(np.bool_), cap, fill=False)
        return Column(dtype, n, jnp.asarray(vals), jnp.asarray(valid))

    @staticmethod
    def from_pylist(values: Sequence, dtype: dt.DataType) -> "Column":
        import pyarrow as pa
        arr = pa.array(values, type=dt.to_arrow(dtype))
        return Column.from_arrow(arr, dtype)

    @staticmethod
    def from_arrow(arr, dtype: Optional[dt.DataType] = None) -> "Column":
        """Build a device column from a pyarrow Array/ChunkedArray."""
        dtype, n, bufs = Column.host_from_arrow(arr, dtype)
        dev = jax.device_put(bufs)
        return Column.build(dtype, n, dev)

    @staticmethod
    def element_dtype(dtype: dt.DataType) -> dt.DataType:
        """Element type of a list layout; maps are list<struct<key,value>>."""
        if isinstance(dtype, dt.MapType):
            return dt.StructType((dt.StructField("key", dtype.key, False),
                                  dt.StructField("value", dtype.value)))
        return dtype.element

    @staticmethod
    def build(dtype: dt.DataType, n: int, bufs) -> "Column":
        """Construct a (possibly nested) Column from a bufs tree (host or
        device arrays). Nested child logical lengths ride in the `_n` leaf
        written by host_from_arrow/device_buffers."""
        if isinstance(dtype, (dt.ArrayType, dt.MapType)):
            cb = bufs["children"][0]
            child = Column.build(Column.element_dtype(dtype),
                                 int(cb["_n"]), cb)
            return Column(dtype, n, jnp.zeros(0, jnp.int8),
                          jnp.asarray(bufs["validity"]),
                          jnp.asarray(bufs["offsets"]), [child])
        if isinstance(dtype, dt.StructType):
            kids = [Column.build(f.dtype, int(cb["_n"]), cb)
                    for f, cb in zip(dtype.fields, bufs["children"])]
            return Column(dtype, n, jnp.zeros(0, jnp.int8),
                          jnp.asarray(bufs["validity"]), None, kids)
        off = bufs.get("offsets")
        return Column(dtype, n, jnp.asarray(bufs["data"]),
                      jnp.asarray(bufs["validity"]),
                      jnp.asarray(off) if off is not None else None)

    @staticmethod
    def host_from_arrow(arr, dtype: Optional[dt.DataType] = None):
        """Decode a pyarrow array into host numpy buffers (no transfer).
        Returns (dtype, length, {"data","validity"[,"offsets"]})."""
        import pyarrow as pa
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        dtype = dtype or dt.from_arrow(arr.type)
        n = len(arr)
        # tpulint: allow[host-sync] pyarrow host array — no device data
        validity = np.logical_not(np.asarray(arr.is_null()))
        cap = bucket_capacity(n)

        if isinstance(dtype, (dt.StringType, dt.BinaryType)):
            if pa.types.is_large_string(arr.type):
                arr = arr.cast(pa.string())
            if pa.types.is_large_binary(arr.type):
                arr = arr.cast(pa.binary())
            arr = arr.fill_null("" if isinstance(dtype, dt.StringType) else b"")
            buffers = arr.buffers()  # [validity, offsets, data]
            off = np.frombuffer(buffers[1], dtype=np.int32,
                                count=n + 1 + arr.offset)[arr.offset:]
            off = off - off[0]
            databuf = buffers[2]
            nbytes = int(off[-1]) if n else 0
            start = np.frombuffer(buffers[1], dtype=np.int32)[arr.offset]
            data = (np.frombuffer(databuf, dtype=np.uint8,
                                  count=start + nbytes)[start:]
                    if databuf is not None else np.zeros(0, np.uint8))
            dcap = bucket_capacity(max(nbytes, 1))
            offsets = _pad_to(off.astype(np.int32), cap + 1, fill=nbytes)
            return dtype, n, {"data": _pad_to(data, dcap),
                              "validity": _pad_to(validity, cap, False),
                              "offsets": offsets}

        if isinstance(dtype, dt.DecimalType):
            # Extract the unscaled int128 little-endian words; a plain
            # cast would rescale instead of reinterpreting.
            filled = arr.fill_null(0)
            if filled.type != pa.decimal128(38, dtype.scale):
                filled = filled.cast(pa.decimal128(38, dtype.scale))
            buf = filled.buffers()[1]
            words = np.frombuffer(buf, dtype=np.int64)
            o = filled.offset
            if dtype.is_decimal128:
                both = words[2 * o:2 * (o + n)].reshape(n, 2).copy()
                return dtype, n, {"data": _pad_to(both, cap),
                                  "validity": _pad_to(validity, cap,
                                                      False)}
            lo = words[2 * o:2 * (o + n):2].copy()
            return dtype, n, {"data": _pad_to(lo, cap),
                              "validity": _pad_to(validity, cap, False)}

        if isinstance(dtype, dt.TimestampType):
            # tpulint: allow[host-sync] pyarrow host array
            micros = np.asarray(arr.fill_null(0)
                                .cast(pa.timestamp("us")).cast(pa.int64()))
            return dtype, n, {"data": _pad_to(micros, cap),
                              "validity": _pad_to(validity, cap, False)}

        if isinstance(dtype, dt.DateType):
            # tpulint: allow[host-sync] pyarrow host array
            days = np.asarray(arr.fill_null(0).cast(pa.int32()))
            return dtype, n, {"data": _pad_to(days, cap),
                              "validity": _pad_to(validity, cap, False)}

        if isinstance(dtype, dt.NullType):
            return dtype, n, {"data": np.zeros(cap, np.int8),
                              "validity": np.zeros(cap, np.bool_)}

        if isinstance(dtype, (dt.ArrayType, dt.MapType)):
            # List layout: int32 offsets [n+1] + flattened element child.
            # Offsets are kept exactly as Arrow stores them (not normalized
            # to start at 0; null slots keep their placeholder range) —
            # every kernel derives lengths as offsets[i+1]-offsets[i] AND
            # masks by validity, so placeholder ranges are never read.
            if pa.types.is_large_list(arr.type):
                arr = arr.cast(pa.list_(arr.type.value_type))
            if isinstance(dtype, dt.MapType):
                elem_dt = dt.StructType((
                    dt.StructField("key", dtype.key, False),
                    dt.StructField("value", dtype.value)))
                child_arr = pa.StructArray.from_arrays(
                    [arr.keys, arr.items], ["key", "value"])
            else:
                elem_dt = dtype.element
                child_arr = arr.values
            off = np.frombuffer(arr.buffers()[1], dtype=np.int32,
                                count=n + 1 + arr.offset)[arr.offset:]
            cdt, cn, cbufs = Column.host_from_arrow(child_arr, elem_dt)
            cbufs["_n"] = np.int64(cn)
            last = int(off[-1]) if n else 0
            return dtype, n, {
                "validity": _pad_to(validity, cap, False),
                "offsets": _pad_to(off.astype(np.int32), cap + 1, fill=last),
                "children": [cbufs]}

        if isinstance(dtype, dt.StructType):
            kids = []
            for i, f in enumerate(dtype.fields):
                cdt, cn, cbufs = Column.host_from_arrow(arr.field(i), f.dtype)
                cbufs["_n"] = np.int64(cn)
                kids.append(cbufs)
            return dtype, n, {"validity": _pad_to(validity, cap, False),
                              "children": kids}

        # tpulint: allow[host-sync] pyarrow host array
        values = np.asarray(arr.fill_null(
            False if isinstance(dtype, dt.BooleanType) else 0))
        values = values.astype(dtype.np_dtype, copy=False)
        return dtype, n, {"data": _pad_to(values, cap),
                          "validity": _pad_to(validity, cap, False)}

    @staticmethod
    def nulls(n: int, dtype: dt.DataType) -> "Column":
        cap = bucket_capacity(n)
        if isinstance(dtype, (dt.ArrayType, dt.MapType)):
            child = Column.nulls(0, Column.element_dtype(dtype))
            return Column(dtype, n, jnp.zeros(0, jnp.int8),
                          jnp.zeros(cap, jnp.bool_),
                          jnp.zeros(cap + 1, jnp.int32), [child])
        if isinstance(dtype, dt.StructType):
            kids = [Column.nulls(n, f.dtype) for f in dtype.fields]
            return Column(dtype, n, jnp.zeros(0, jnp.int8),
                          jnp.zeros(cap, jnp.bool_), None, kids)
        np_dt = dtype.np_dtype or np.int8
        col = Column(dtype, n, jnp.zeros(alloc_shape(dtype, cap), np_dt),
                     jnp.zeros(cap, jnp.bool_))
        if dtype.is_variable_width:
            col.offsets = jnp.zeros(cap + 1, jnp.int32)
        return col

    # ------------------------------------------------------------------
    # Export
    # ------------------------------------------------------------------
    def device_buffers(self):
        d = {"data": self.data, "validity": self.validity}
        if self.offsets is not None:
            d["offsets"] = self.offsets
        if self.children:
            kids = []
            for c in self.children:
                cb = c.device_buffers()
                cb["_n"] = np.int64(c.length)
                kids.append(cb)
            d["children"] = kids
        return d

    def to_arrow(self):
        from ..utils.transfer import fetch
        bufs = fetch(self.device_buffers())
        return Column.arrow_from_host(self.dtype, self.length, bufs)

    @staticmethod
    def arrow_from_host(dtype: dt.DataType, n: int, bufs):
        """Assemble a pyarrow array from fetched host buffers."""
        import pyarrow as pa
        # tpulint: allow[host-sync] contract: bufs are FETCHED host bufs
        validity = np.asarray(bufs["validity"])[:n]
        if isinstance(dtype, (dt.ArrayType, dt.MapType)):
            # tpulint: allow[host-sync] fetched host buffers
            off = np.asarray(bufs["offsets"])[:n + 1].astype(np.int32)
            cb = bufs["children"][0]
            child = Column.arrow_from_host(Column.element_dtype(dtype),
                                           int(cb["_n"]), cb)
            if n and not validity.all():
                # null slots may hold placeholder offset ranges: zero them
                # out (dense rebuild) so the arrow array never references
                # elements a consumer could misread.
                lens = np.diff(off)
                lens[~validity] = 0
                starts = off[:-1].copy()
                starts[~validity] = 0
                dense = np.concatenate(
                    [[0], np.cumsum(lens)]).astype(np.int32)
                idx = np.concatenate(
                    [np.arange(s, s + ln) for s, ln in zip(starts, lens)]
                ) if dense[-1] else np.zeros(0, np.int64)
                child = child.take(pa.array(idx, type=pa.int64()))
                mask = np.concatenate([~validity, [False]])
                off_arr = pa.array(dense, type=pa.int32(),
                                   mask=mask)
            else:
                off_arr = pa.array(off, type=pa.int32())
            if isinstance(dtype, dt.MapType):
                return pa.MapArray.from_arrays(
                    off_arr, child.field(0), child.field(1))
            return pa.ListArray.from_arrays(off_arr, child)
        if isinstance(dtype, dt.StructType):
            kids = [Column.arrow_from_host(f.dtype, n, cb)
                    for f, cb in zip(dtype.fields, bufs["children"])]
            mask = (pa.array(~validity) if not validity.all() else None)
            return pa.StructArray.from_arrays(
                kids, [f.name for f in dtype.fields], mask=mask)
        if isinstance(dtype, (dt.StringType, dt.BinaryType)):
            # tpulint: allow[host-sync] fetched host buffers
            off = np.asarray(bufs["offsets"])[:n + 1]
            nbytes = int(off[-1]) if n else 0
            patype = dt.to_arrow(dtype)
            # pass the full (padded) data buffer: offsets may not start at 0
            arr = pa.Array.from_buffers(
                patype, n,
                [None, pa.py_buffer(off.astype(np.int32).tobytes()),
                 # tpulint: allow[host-sync] fetched host buffers
                 pa.py_buffer(np.asarray(bufs["data"]).tobytes())])
            if not validity.all():
                arr = pa.array(
                    [v if m else None
                     for v, m in zip(arr.to_pylist(), validity)],
                    type=patype)
            return arr
        # tpulint: allow[host-sync] fetched host buffers
        vals = np.asarray(bufs["data"])[:n]
        if isinstance(dtype, dt.DecimalType):
            # assemble int128 little-endian words from the unscaled limbs
            # (a cast from int64 would rescale, not reinterpret)
            if dtype.is_decimal128:
                words = np.ascontiguousarray(vals.reshape(-1)[:2 * n])
            else:
                lo = vals.astype(np.int64)
                hi = np.where(lo < 0, np.int64(-1), np.int64(0))
                words = np.empty(2 * n, np.int64)
                words[0::2] = lo
                words[1::2] = hi
            arr = pa.Array.from_buffers(
                pa.decimal128(38, dtype.scale), n,
                [None, pa.py_buffer(words.tobytes())]).cast(
                    dt.to_arrow(dtype))
        elif isinstance(dtype, dt.TimestampType):
            arr = pa.array(vals, type=pa.timestamp("us")).cast(
                dt.to_arrow(dtype))
        elif isinstance(dtype, dt.DateType):
            arr = pa.array(vals, type=pa.int32()).cast(pa.date32())
        elif isinstance(dtype, dt.NullType):
            return pa.nulls(n)
        else:
            arr = pa.array(vals, type=dt.to_arrow(dtype))
        if not validity.all():
            arr = pa.array([v if m else None
                            for v, m in zip(arr.to_pylist(), validity)],
                           type=arr.type)
        return arr

    def to_pylist(self) -> list:
        return self.to_arrow().to_pylist()

    def to_numpy(self):
        """(values[:length], validity[:length]) as host numpy arrays."""
        from ..utils.transfer import fetch
        # one async-overlapped fetch for both buffers (fetch returns
        # host numpy arrays), instead of two blocking device_gets
        data, validity = fetch((self.data, self.validity))
        return data[:self.length], validity[:self.length]
