"""Device-resident Arrow-layout column.

The TPU analog of the reference's `GpuColumnVector`
(reference: sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java)
backed by cudf ColumnVector. Here a column is a bundle of jax.Arrays living in
TPU HBM:

  data     : primary values buffer, shape [capacity] (padded)
  validity : bool[capacity]; True = valid. Rows >= length are always False.
  offsets  : int32[capacity+1] for variable-width types (string/binary/list)
  children : nested child Columns (struct/list)

XLA compiles one program per shape, so capacities are bucketed to powers of
two (min 128 to match TPU lane width) — this bounds recompilation while
keeping padding <2x. The logical row count `length` is a host int; kernels
mask padding rows via `validity`.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as dt

__all__ = ["Column", "bucket_capacity", "MIN_CAPACITY"]

MIN_CAPACITY = 128


def bucket_capacity(n: int) -> int:
    """Round n up to the next power of two, with a floor of MIN_CAPACITY."""
    if n <= MIN_CAPACITY:
        return MIN_CAPACITY
    return 1 << (int(n - 1).bit_length())


def _pad_to(arr: np.ndarray, capacity: int, fill=0) -> np.ndarray:
    if arr.shape[0] == capacity:
        return arr
    pad = capacity - arr.shape[0]
    return np.concatenate([arr, np.full((pad,) + arr.shape[1:], fill,
                                        dtype=arr.dtype)])


class Column:
    """An immutable device column. All device buffers share one capacity."""

    def __init__(self, dtype: dt.DataType, length: int, data, validity,
                 offsets=None, children: Optional[List["Column"]] = None):
        self.dtype = dtype
        self.length = int(length)
        self.data = data            # jax.Array [capacity] (or [0] for struct)
        self.validity = validity    # jax.Array bool [capacity]
        self.offsets = offsets      # jax.Array int32 [capacity+1] or None
        self.children = children or []

    # ------------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    @property
    def nbytes(self) -> int:
        n = self.data.size * self.data.dtype.itemsize + self.validity.size
        if self.offsets is not None:
            n += self.offsets.size * 4
        for c in self.children:
            n += c.nbytes
        return int(n)

    def __repr__(self):
        return (f"Column({self.dtype}, length={self.length}, "
                f"capacity={self.capacity})")

    # ------------------------------------------------------------------
    # Construction
    # ------------------------------------------------------------------
    @staticmethod
    def from_numpy(values: np.ndarray, dtype: dt.DataType,
                   validity: Optional[np.ndarray] = None,
                   capacity: Optional[int] = None) -> "Column":
        n = len(values)
        cap = capacity or bucket_capacity(n)
        if validity is None:
            validity = np.ones(n, dtype=np.bool_)
        vals = _pad_to(np.ascontiguousarray(values), cap)
        valid = _pad_to(validity.astype(np.bool_), cap, fill=False)
        return Column(dtype, n, jnp.asarray(vals), jnp.asarray(valid))

    @staticmethod
    def from_pylist(values: Sequence, dtype: dt.DataType) -> "Column":
        import pyarrow as pa
        arr = pa.array(values, type=dt.to_arrow(dtype))
        return Column.from_arrow(arr, dtype)

    @staticmethod
    def from_arrow(arr, dtype: Optional[dt.DataType] = None) -> "Column":
        """Build a device column from a pyarrow Array/ChunkedArray."""
        dtype, n, bufs = Column.host_from_arrow(arr, dtype)
        dev = jax.device_put(bufs)
        return Column(dtype, n, dev["data"], dev["validity"],
                      dev.get("offsets"))

    @staticmethod
    def host_from_arrow(arr, dtype: Optional[dt.DataType] = None):
        """Decode a pyarrow array into host numpy buffers (no transfer).
        Returns (dtype, length, {"data","validity"[,"offsets"]})."""
        import pyarrow as pa
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        dtype = dtype or dt.from_arrow(arr.type)
        n = len(arr)
        validity = np.logical_not(np.asarray(arr.is_null()))
        cap = bucket_capacity(n)

        if isinstance(dtype, (dt.StringType, dt.BinaryType)):
            if pa.types.is_large_string(arr.type):
                arr = arr.cast(pa.string())
            if pa.types.is_large_binary(arr.type):
                arr = arr.cast(pa.binary())
            arr = arr.fill_null("" if isinstance(dtype, dt.StringType) else b"")
            buffers = arr.buffers()  # [validity, offsets, data]
            off = np.frombuffer(buffers[1], dtype=np.int32,
                                count=n + 1 + arr.offset)[arr.offset:]
            off = off - off[0]
            databuf = buffers[2]
            nbytes = int(off[-1]) if n else 0
            start = np.frombuffer(buffers[1], dtype=np.int32)[arr.offset]
            data = (np.frombuffer(databuf, dtype=np.uint8,
                                  count=start + nbytes)[start:]
                    if databuf is not None else np.zeros(0, np.uint8))
            dcap = bucket_capacity(max(nbytes, 1))
            offsets = _pad_to(off.astype(np.int32), cap + 1, fill=nbytes)
            return dtype, n, {"data": _pad_to(data, dcap),
                              "validity": _pad_to(validity, cap, False),
                              "offsets": offsets}

        if isinstance(dtype, dt.DecimalType):
            # Extract the unscaled int128 little-endian words; a plain
            # cast would rescale instead of reinterpreting.
            filled = arr.fill_null(0)
            if filled.type != pa.decimal128(38, dtype.scale):
                filled = filled.cast(pa.decimal128(38, dtype.scale))
            buf = filled.buffers()[1]
            words = np.frombuffer(buf, dtype=np.int64)
            o = filled.offset
            if dtype.is_decimal128:
                both = words[2 * o:2 * (o + n)].reshape(n, 2).copy()
                return dtype, n, {"data": _pad_to(both, cap),
                                  "validity": _pad_to(validity, cap,
                                                      False)}
            lo = words[2 * o:2 * (o + n):2].copy()
            return dtype, n, {"data": _pad_to(lo, cap),
                              "validity": _pad_to(validity, cap, False)}

        if isinstance(dtype, dt.TimestampType):
            micros = np.asarray(arr.fill_null(0)
                                .cast(pa.timestamp("us")).cast(pa.int64()))
            return dtype, n, {"data": _pad_to(micros, cap),
                              "validity": _pad_to(validity, cap, False)}

        if isinstance(dtype, dt.DateType):
            days = np.asarray(arr.fill_null(0).cast(pa.int32()))
            return dtype, n, {"data": _pad_to(days, cap),
                              "validity": _pad_to(validity, cap, False)}

        if isinstance(dtype, dt.NullType):
            return dtype, n, {"data": np.zeros(cap, np.int8),
                              "validity": np.zeros(cap, np.bool_)}

        if dtype.is_nested:
            raise NotImplementedError("nested from_arrow lands with nested ops")

        values = np.asarray(arr.fill_null(
            False if isinstance(dtype, dt.BooleanType) else 0))
        values = values.astype(dtype.np_dtype, copy=False)
        return dtype, n, {"data": _pad_to(values, cap),
                          "validity": _pad_to(validity, cap, False)}

    @staticmethod
    def nulls(n: int, dtype: dt.DataType) -> "Column":
        cap = bucket_capacity(n)
        np_dt = dtype.np_dtype or np.int8
        col = Column(dtype, n, jnp.zeros(cap, np_dt), jnp.zeros(cap, jnp.bool_))
        if dtype.is_variable_width:
            col.offsets = jnp.zeros(cap + 1, jnp.int32)
        return col

    # ------------------------------------------------------------------
    # Export
    # ------------------------------------------------------------------
    def device_buffers(self):
        d = {"data": self.data, "validity": self.validity}
        if self.offsets is not None:
            d["offsets"] = self.offsets
        return d

    def to_arrow(self):
        from ..utils.transfer import fetch
        bufs = fetch(self.device_buffers())
        return Column.arrow_from_host(self.dtype, self.length, bufs)

    @staticmethod
    def arrow_from_host(dtype: dt.DataType, n: int, bufs):
        """Assemble a pyarrow array from fetched host buffers."""
        import pyarrow as pa
        validity = np.asarray(bufs["validity"])[:n]
        if isinstance(dtype, (dt.StringType, dt.BinaryType)):
            off = np.asarray(bufs["offsets"])[:n + 1]
            nbytes = int(off[-1]) if n else 0
            patype = dt.to_arrow(dtype)
            # pass the full (padded) data buffer: offsets may not start at 0
            arr = pa.Array.from_buffers(
                patype, n,
                [None, pa.py_buffer(off.astype(np.int32).tobytes()),
                 pa.py_buffer(np.asarray(bufs["data"]).tobytes())])
            if not validity.all():
                arr = pa.array(
                    [v if m else None
                     for v, m in zip(arr.to_pylist(), validity)],
                    type=patype)
            return arr
        vals = np.asarray(bufs["data"])[:n]
        if isinstance(dtype, dt.DecimalType):
            # assemble int128 little-endian words from the unscaled limbs
            # (a cast from int64 would rescale, not reinterpret)
            if dtype.is_decimal128:
                words = np.ascontiguousarray(vals.reshape(-1)[:2 * n])
            else:
                lo = vals.astype(np.int64)
                hi = np.where(lo < 0, np.int64(-1), np.int64(0))
                words = np.empty(2 * n, np.int64)
                words[0::2] = lo
                words[1::2] = hi
            arr = pa.Array.from_buffers(
                pa.decimal128(38, dtype.scale), n,
                [None, pa.py_buffer(words.tobytes())]).cast(
                    dt.to_arrow(dtype))
        elif isinstance(dtype, dt.TimestampType):
            arr = pa.array(vals, type=pa.timestamp("us")).cast(
                dt.to_arrow(dtype))
        elif isinstance(dtype, dt.DateType):
            arr = pa.array(vals, type=pa.int32()).cast(pa.date32())
        elif isinstance(dtype, dt.NullType):
            return pa.nulls(n)
        else:
            arr = pa.array(vals, type=dt.to_arrow(dtype))
        if not validity.all():
            arr = pa.array([v if m else None
                            for v, m in zip(arr.to_pylist(), validity)],
                           type=arr.type)
        return arr

    def to_pylist(self) -> list:
        return self.to_arrow().to_pylist()

    def to_numpy(self):
        """(values[:length], validity[:length]) as host numpy arrays."""
        return (np.asarray(jax.device_get(self.data))[:self.length],
                np.asarray(jax.device_get(self.validity))[:self.length])
