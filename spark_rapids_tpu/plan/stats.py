"""Bottom-up row / NDV statistics over logical plans.

The Catalyst-CBO analog (reference: spark.sql.cbo.* statistics +
FilterEstimation/JoinEstimation): every logical node gets an estimated
row count and a per-column number-of-distinct-values (NDV) estimate,
propagated bottom-up. Scans sample their first ~64K rows once (cached on
the scan node, so repeated plans of a cached DataFrame pay nothing) and
extrapolate NDV with a Chao1-style estimator; filters scale rows by the
same per-conjunct selectivities the placement CBO uses; joins apply the
classic |L|*|R| / max(ndv(lk), ndv(rk)) equi-join formula; aggregates
shrink to the product of key NDVs.

Consumers: the join-reorder pass (plan/cbo.py) ranks left-deep join
orders by these estimates. Estimates are advisory — a bad estimate can
cost performance, never correctness.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, Optional

from . import logical as L

__all__ = ["Stats", "compute_stats", "scan_column_ndv",
           "calibration_scope", "calibration_lookup", "logical_fp",
           "join_set_fp", "attach_calibration_fps",
           "harvest_calibration", "calibration_stats",
           "clear_calibration", "export_calibration",
           "import_calibration"]

# Rows sampled (from the first batch / the arrow table head) for NDV.
SAMPLE_ROWS = 1 << 16

# ---------------------------------------------------------------------
# Session-scoped cardinality calibration (the AQE feedback loop).
#
# After a query runs, `harvest_calibration` records each operator's
# OBSERVED numOutputRows keyed by the structural fingerprint of its
# logical subtree (the same gensym-normalized expr_fp identity the
# reuse pass and result cache key on). `compute_stats` consults the
# table first, so the next plan of the same subtree — in this session —
# estimates from measurement instead of heuristics. Join subtrees also
# record under an ORDER-INDEPENDENT key (the frozenset of their flat
# relation fingerprints), which is what lets the join-reorder DP
# (plan/cbo.py) cost a relation subset by the cardinality an earlier
# order actually produced.
#
# Lookups are scoped: they only fire inside a `calibration_scope(True)`
# (Planner.plan enters it when sql.adaptive.enabled AND
# sql.adaptive.calibration.enabled), so a session that turns AQE off
# plans exactly as if the table did not exist. Entries are advisory —
# a stale entry can cost performance, never correctness.
# ---------------------------------------------------------------------
_CAL_LOCK = threading.Lock()
_CAL: Dict[Any, float] = {}
_CAL_STATS = {"calibration_hits": 0, "calibration_updates": 0}
_CAL_TLS = threading.local()


@contextmanager
def calibration_scope(enabled: bool):
    """Enable calibration lookups on this thread (planning only)."""
    prev = getattr(_CAL_TLS, "enabled", False)
    _CAL_TLS.enabled = bool(enabled)
    try:
        yield
    finally:
        _CAL_TLS.enabled = prev


def calibration_lookup(key) -> Optional[float]:
    """Observed row count for a fingerprint key, or None. Counts a hit
    only inside an enabled scope."""
    if key is None or not getattr(_CAL_TLS, "enabled", False):
        return None
    with _CAL_LOCK:
        v = _CAL.get(key)
        if v is not None:
            _CAL_STATS["calibration_hits"] += 1
        return v


def _calibration_record(key, rows: float) -> None:
    with _CAL_LOCK:
        _CAL[key] = float(rows)
        _CAL_STATS["calibration_updates"] += 1


def calibration_stats() -> Dict[str, int]:
    with _CAL_LOCK:
        out = dict(_CAL_STATS)
        out["calibration_entries"] = len(_CAL)
        return out


def clear_calibration() -> None:
    with _CAL_LOCK:
        _CAL.clear()
        for k in _CAL_STATS:
            _CAL_STATS[k] = 0


def export_calibration():
    """The calibration table as a picklable [(key, rows), ...] — the
    fleet warm-state payload (fleet/member.py). Keys are nested tuples
    of primitives (logical_fp/join_set_fp), so they survive the wire
    intact."""
    with _CAL_LOCK:
        return list(_CAL.items())


def import_calibration(table) -> int:
    """Merge a peer's exported calibration table. Peer entries only
    fill HOLES — a locally observed row count reflects THIS process's
    data view and always wins. Returns entries adopted."""
    if not table:
        return 0
    adopted = 0
    with _CAL_LOCK:
        for key, rows in table:
            key = _freeze(key)
            if key in _CAL:
                continue
            _CAL[key] = float(rows)
            adopted += 1
        if adopted:
            _CAL_STATS["calibration_updates"] += adopted
    return adopted


def _freeze(key):
    """Normalize list-shaped wire keys back to the tuple form the
    fingerprint functions produce (defensive: pickle preserves tuples,
    but a JSON-bounced payload would not)."""
    if isinstance(key, list):
        return tuple(_freeze(k) for k in key)
    return key


def logical_fp(node: L.LogicalPlan):
    """CARDINALITY fingerprint of a logical subtree, memoized on the
    node (`_*_cache` convention, so expr_fp skips the memo attr).

    Row counts are invariant to projection placement and column
    pruning, so the fingerprint hashes only the cardinality skeleton —
    scans, filter conditions, join how/keys, grouping keys, limits —
    and SEES THROUGH row-preserving wrappers (Project/Sort/Window/
    Repartition). That invariance is load-bearing: lookups fire at the
    join-reorder stage (pre-prune) while harvest keys come from the
    final converted tree (post-prune); a full structural fp would never
    match across the two, and its repr/hash cost scales with embedded
    bound-expression trees."""
    fp = getattr(node, "_calib_fp_cache", None)
    if fp is None:
        fp = node._calib_fp_cache = _card_fp(node)
    return fp


def _card_fp(node: L.LogicalPlan):
    from ..runtime.program_cache import expr_fp, exprs_fp
    if isinstance(node, (L.Project, L.Sort, L.Repartition, L.WindowOp)):
        return logical_fp(node.children[0])   # row-preserving
    if isinstance(node, L.Filter):
        return ("F", expr_fp(node.condition),
                logical_fp(node.children[0]))
    if isinstance(node, L.Join):
        return ("J", node.how,
                exprs_fp(node.left_keys), exprs_fp(node.right_keys),
                expr_fp(getattr(node, "condition", None)),
                logical_fp(node.children[0]),
                logical_fp(node.children[1]))
    if isinstance(node, L.Aggregate):
        # groups depend on keys only — different agg columns over the
        # same keys legitimately share one observation
        return ("A", exprs_fp(node.keys), logical_fp(node.children[0]))
    if isinstance(node, L.Limit):
        return ("L", int(node.n), logical_fp(node.children[0]))
    if isinstance(node, L.Union):
        return ("U",) + tuple(logical_fp(c) for c in node.children)
    if isinstance(node, L.InMemoryScan):
        return ("S", "mem", id(node.arrow))   # session-scoped identity
    if isinstance(node, L.ParquetScan):
        # rows depend on the files, the pushed row-group filters, and
        # the data version — not on the projected column subset
        return ("S", "parquet", tuple(node.paths),
                expr_fp(node.filters), expr_fp(node.snapshot))
    if isinstance(node, L.Expand):
        return ("X", "Expand", len(node.include_masks),
                logical_fp(node.children[0]))
    if not node.children:
        paths = getattr(node, "paths", None) or getattr(node, "path",
                                                        None)
        if paths:
            return ("S", type(node).__qualname__,
                    tuple(paths) if not isinstance(paths, str)
                    else paths)
        return ("S", type(node).__qualname__, id(node))
    # unknown operator: type + child skeletons. Two same-typed siblings
    # over one child could falsely share — advisory rows only, never a
    # correctness risk.
    return ("X", type(node).__qualname__) + tuple(
        logical_fp(c) for c in node.children)


def _flatten_rels(node: L.LogicalPlan):
    """Relations of a flat inner-equi join chain, seeing through the
    pass-through projections session.join leaves between chained joins
    — the SAME flattening discipline as cbo._flatten_chain, so a jset
    key harvested from an executed join matches the key the reorder
    pass looks up for the same relation set. A non-inner join anywhere
    poisons the chain (order is semantics there, so subset keys would
    lie)."""
    from .cbo import _is_passthrough, _reorderable_join
    if isinstance(node, L.Project) and _is_passthrough(node) \
            and _reorderable_join(node.children[0]):
        return _flatten_rels(node.children[0])
    if isinstance(node, L.Join):
        if not _reorderable_join(node):
            return None
        l = _flatten_rels(node.children[0])
        r = _flatten_rels(node.children[1])
        if l is None or r is None:
            return None
        return l + r
    return [node]


def join_set_fp(node: L.LogicalPlan):
    """Order-independent key for an inner-equi join subtree: the
    frozenset of its flat relations' fingerprints. Any join order over
    the same relation set produces the same multiset of output rows,
    so one observed cardinality prices every order."""
    if not isinstance(node, L.Join):
        return None
    rels = _flatten_rels(node)
    if rels is None or len(rels) < 2:
        return None
    # fp tuples are hashable by construction (expr_fp falls back to
    # ("id", id) for anything that isn't) — hash them directly; repr()
    # would stringify embedded foreign values (arrow buffers!) at
    # data-proportional cost
    return ("jset", frozenset(logical_fp(r) for r in rels))


def attach_calibration_fps(logical: L.LogicalPlan, physical) -> None:
    """Stamp the planning-time fingerprints onto the physical node so
    post-run harvest can key observations without re-deriving the
    logical tree. Underscore attrs are invisible to the reuse pass's
    node_fp, so attachments never split exchange-reuse identity."""
    if physical is None or not getattr(_CAL_TLS, "enabled", False):
        return
    physical._calib_fp = logical_fp(logical)
    jfp = join_set_fp(logical)
    if jfp is not None:
        physical._calib_set_fp = jfp


def harvest_calibration(root_exec, ctx) -> int:
    """Record observed output cardinalities of a finished run into the
    calibration table. Skipped wholesale when the tree contains a
    limit/top-k (truncated pulls underreport every producer below
    them) and when the conf gates calibration off. Returns the number
    of entries recorded."""
    from ..config import ADAPTIVE_CALIBRATION, ADAPTIVE_ENABLED
    conf = getattr(ctx, "conf", None)
    if conf is None or not (conf.get(ADAPTIVE_ENABLED)
                            and conf.get(ADAPTIVE_CALIBRATION)):
        return 0
    nodes, stack, seen = [], [root_exec], set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        tname = type(node).__name__
        if "Limit" in tname or "TopK" in tname:
            return 0
        nodes.append(node)
        stack.extend(node.children)
    recorded = 0
    for node in nodes:
        ms = ctx.metrics.get(node._op_id)
        if ms is None:
            continue
        rows = ms.get("numOutputRows", 0)
        if not rows or rows <= 0:
            continue
        for attr in ("_calib_fp", "_calib_set_fp"):
            key = getattr(node, attr, None)
            if key is not None:
                _calibration_record(key, float(rows))
                recorded += 1
    return recorded


class Stats:
    """Row estimate + lazy per-column NDV lookup. `rows` is None when the
    subtree has no estimable source. `ndv_of(name)` returns an NDV
    estimate for an output column or None when unknown."""

    __slots__ = ("rows", "_ndv_of")

    def __init__(self, rows: Optional[float],
                 ndv_of: Optional[Callable[[str], Optional[float]]] = None):
        self.rows = rows
        self._ndv_of = ndv_of or (lambda name: None)

    def ndv_of(self, name: str) -> Optional[float]:
        nd = self._ndv_of(name)
        if nd is None:
            return None
        if self.rows is not None:
            nd = min(nd, self.rows)
        return max(nd, 1.0)


def _chao1(counts, sample_n: int, total_rows: float) -> float:
    """Extrapolate sample distinct count to the full column: Chao1
    lower-bound estimator d + f1^2/(2*f2); an all-singleton sample is
    read as a unique(-ish) column."""
    import numpy as np
    d = int(counts.shape[0])
    if sample_n >= total_rows:
        return float(d)
    f1 = int(np.count_nonzero(counts == 1))
    f2 = int(np.count_nonzero(counts == 2))
    if f1 >= sample_n or (f1 == d and f2 == 0):
        return float(total_rows)        # every sampled value unique
    est = d + (f1 * f1) / (2.0 * max(f2, 1))
    return float(min(max(est, d), total_rows))


def _sample_arrow_column(node: L.LogicalPlan, name: str):
    """First-SAMPLE_ROWS slice of a scan column as a pyarrow array, or
    None when the scan cannot serve one cheaply."""
    if isinstance(node, L.InMemoryScan):
        if name not in node.arrow.schema.names:
            return None
        return node.arrow.column(name).slice(0, SAMPLE_ROWS)
    if isinstance(node, L.CachedScan):
        if not node.batches or name not in node.schema.names:
            return None
        from ..exec.nodes import _batch_to_arrow
        at = getattr(node, "_stats_sample_cache", None)
        if at is None:
            at = _batch_to_arrow(node.batches[0]).slice(0, SAMPLE_ROWS)
            node._stats_sample_cache = at
        if name not in at.schema.names:
            return None
        return at.column(name)
    return None


def scan_column_ndv(node: L.LogicalPlan, name: str) -> Optional[float]:
    """NDV estimate for one scan column, sampled once and cached on the
    node (leaf nodes survive re-planning, so the sample is paid once per
    DataFrame, not once per query execution)."""
    cache: Dict[str, Optional[float]] = getattr(node, "_ndv_cache", None)
    if cache is None:
        cache = node._ndv_cache = {}
    if name in cache:
        return cache[name]
    ndv: Optional[float] = None
    try:
        from .planner import _estimate_rows
        rows = _estimate_rows(node)
        arr = _sample_arrow_column(node, name)
        if arr is not None and rows:
            import numpy as np
            import pyarrow.compute as pc
            vc = pc.value_counts(arr)
            counts = np.asarray(vc.field("counts"))
            ndv = _chao1(counts, len(arr), float(rows))
    except Exception:
        ndv = None
    cache[name] = ndv
    return ndv


def _proj_ndv_map(exprs) -> Dict[str, Optional[str]]:
    """Output name -> source column name for pass-through / renamed
    columns; computed expressions map to None (NDV unknown)."""
    from ..expr.expressions import Alias, ColumnRef
    out: Dict[str, Optional[str]] = {}
    for e in exprs:
        if isinstance(e, ColumnRef):
            out[e.name] = e.name
        elif isinstance(e, Alias) and isinstance(e.child, ColumnRef):
            out[e.name] = e.child.name
        else:
            out[getattr(e, "name", "?")] = None
    return out


def _key_name(expr) -> Optional[str]:
    """Single column name a join/group key resolves to, else None."""
    from .optimizer import refs_of
    refs = refs_of(expr)
    if refs is not None and len(refs) == 1:
        return next(iter(refs))
    return None


def _join_rows(node: L.Join, ls: Stats, rs: Stats) -> Optional[float]:
    if ls.rows is None or rs.rows is None:
        return None
    if node.how in ("left_semi", "left_anti"):
        return ls.rows * (0.5 if node.how == "left_semi" else 0.5)
    rows = ls.rows * rs.rows
    for lk, rk in zip(node.left_keys, node.right_keys):
        ln, rn = _key_name(lk), _key_name(rk)
        ndv_l = (ls.ndv_of(ln) if ln else None) or ls.rows
        ndv_r = (rs.ndv_of(rn) if rn else None) or rs.rows
        rows /= max(ndv_l, ndv_r, 1.0)
    if node.how in ("left", "full"):
        rows = max(rows, ls.rows)
    if node.how in ("right", "full"):
        rows = max(rows, rs.rows)
    return rows


def compute_stats(node: L.LogicalPlan) -> Stats:
    """Bottom-up (rows, ndv) estimate for a logical subtree. Inside a
    calibration scope, an observed cardinality for this exact subtree
    overrides the analytic row estimate (NDV propagation unchanged —
    observation measures rows, not distincts)."""
    s = _compute_stats_raw(node)
    rows = calibration_lookup(logical_fp(node)) \
        if getattr(_CAL_TLS, "enabled", False) else None
    if rows is not None:
        s = Stats(rows, s._ndv_of)
    return s


def _compute_stats_raw(node: L.LogicalPlan) -> Stats:
    from .cbo import _selectivity
    from .planner import _estimate_rows

    if isinstance(node, (L.InMemoryScan, L.CachedScan, L.ParquetScan,
                         L.TextScan)):
        rows = _estimate_rows(node)
        return Stats(None if rows is None else float(rows),
                     lambda n, _nd=node: scan_column_ndv(_nd, n))

    if isinstance(node, L.Filter):
        cs = compute_stats(node.children[0])
        rows = (None if cs.rows is None
                else cs.rows * _selectivity(node.condition))
        return Stats(rows, cs._ndv_of)

    if isinstance(node, L.Project):
        cs = compute_stats(node.children[0])
        m = _proj_ndv_map(node.exprs)

        def ndv(n, _m=m, _cs=cs):
            src = _m.get(n)
            return None if src is None else _cs.ndv_of(src)
        return Stats(cs.rows, ndv)

    if isinstance(node, L.Join):
        ls = compute_stats(node.children[0])
        rs = compute_stats(node.children[1])
        rows = _join_rows(node, ls, rs)
        lnames = set(node.left.schema.names)

        def ndv(n, _ls=ls, _rs=rs, _ln=lnames):
            return _ls.ndv_of(n) if n in _ln else _rs.ndv_of(n)
        return Stats(rows, ndv)

    if isinstance(node, L.Aggregate):
        cs = compute_stats(node.children[0])
        if cs.rows is None:
            return Stats(None)
        groups = 1.0
        known = True
        for k in node.keys:
            kn = _key_name(k)
            nd = cs.ndv_of(kn) if kn else None
            if nd is None:
                known = False
                break
            groups *= nd
        rows = min(groups, cs.rows) if known else \
            min(cs.rows, max(cs.rows ** 0.75, 1.0))
        key_names = {k.name for k in node.keys}

        def ndv(n, _cs=cs, _keys=key_names):
            return _cs.ndv_of(n) if n in _keys else None
        return Stats(max(rows, 1.0), ndv)

    if isinstance(node, L.Limit):
        cs = compute_stats(node.children[0])
        rows = (node.n if cs.rows is None
                else min(float(node.n), cs.rows))
        return Stats(float(rows), cs._ndv_of)

    if isinstance(node, L.Union):
        parts = [compute_stats(c) for c in node.children]
        if any(p.rows is None for p in parts):
            return Stats(None)
        return Stats(sum(p.rows for p in parts))

    if isinstance(node, (L.Sort, L.Repartition, L.WindowOp)):
        cs = compute_stats(node.children[0])
        return Stats(cs.rows, cs._ndv_of)

    rows = _estimate_rows(node)
    return Stats(None if rows is None else float(rows))
