"""Bottom-up row / NDV statistics over logical plans.

The Catalyst-CBO analog (reference: spark.sql.cbo.* statistics +
FilterEstimation/JoinEstimation): every logical node gets an estimated
row count and a per-column number-of-distinct-values (NDV) estimate,
propagated bottom-up. Scans sample their first ~64K rows once (cached on
the scan node, so repeated plans of a cached DataFrame pay nothing) and
extrapolate NDV with a Chao1-style estimator; filters scale rows by the
same per-conjunct selectivities the placement CBO uses; joins apply the
classic |L|*|R| / max(ndv(lk), ndv(rk)) equi-join formula; aggregates
shrink to the product of key NDVs.

Consumers: the join-reorder pass (plan/cbo.py) ranks left-deep join
orders by these estimates. Estimates are advisory — a bad estimate can
cost performance, never correctness.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

from . import logical as L

__all__ = ["Stats", "compute_stats", "scan_column_ndv"]

# Rows sampled (from the first batch / the arrow table head) for NDV.
SAMPLE_ROWS = 1 << 16


class Stats:
    """Row estimate + lazy per-column NDV lookup. `rows` is None when the
    subtree has no estimable source. `ndv_of(name)` returns an NDV
    estimate for an output column or None when unknown."""

    __slots__ = ("rows", "_ndv_of")

    def __init__(self, rows: Optional[float],
                 ndv_of: Optional[Callable[[str], Optional[float]]] = None):
        self.rows = rows
        self._ndv_of = ndv_of or (lambda name: None)

    def ndv_of(self, name: str) -> Optional[float]:
        nd = self._ndv_of(name)
        if nd is None:
            return None
        if self.rows is not None:
            nd = min(nd, self.rows)
        return max(nd, 1.0)


def _chao1(counts, sample_n: int, total_rows: float) -> float:
    """Extrapolate sample distinct count to the full column: Chao1
    lower-bound estimator d + f1^2/(2*f2); an all-singleton sample is
    read as a unique(-ish) column."""
    import numpy as np
    d = int(counts.shape[0])
    if sample_n >= total_rows:
        return float(d)
    f1 = int(np.count_nonzero(counts == 1))
    f2 = int(np.count_nonzero(counts == 2))
    if f1 >= sample_n or (f1 == d and f2 == 0):
        return float(total_rows)        # every sampled value unique
    est = d + (f1 * f1) / (2.0 * max(f2, 1))
    return float(min(max(est, d), total_rows))


def _sample_arrow_column(node: L.LogicalPlan, name: str):
    """First-SAMPLE_ROWS slice of a scan column as a pyarrow array, or
    None when the scan cannot serve one cheaply."""
    if isinstance(node, L.InMemoryScan):
        if name not in node.arrow.schema.names:
            return None
        return node.arrow.column(name).slice(0, SAMPLE_ROWS)
    if isinstance(node, L.CachedScan):
        if not node.batches or name not in node.schema.names:
            return None
        from ..exec.nodes import _batch_to_arrow
        at = getattr(node, "_stats_sample_cache", None)
        if at is None:
            at = _batch_to_arrow(node.batches[0]).slice(0, SAMPLE_ROWS)
            node._stats_sample_cache = at
        if name not in at.schema.names:
            return None
        return at.column(name)
    return None


def scan_column_ndv(node: L.LogicalPlan, name: str) -> Optional[float]:
    """NDV estimate for one scan column, sampled once and cached on the
    node (leaf nodes survive re-planning, so the sample is paid once per
    DataFrame, not once per query execution)."""
    cache: Dict[str, Optional[float]] = getattr(node, "_ndv_cache", None)
    if cache is None:
        cache = node._ndv_cache = {}
    if name in cache:
        return cache[name]
    ndv: Optional[float] = None
    try:
        from .planner import _estimate_rows
        rows = _estimate_rows(node)
        arr = _sample_arrow_column(node, name)
        if arr is not None and rows:
            import numpy as np
            import pyarrow.compute as pc
            vc = pc.value_counts(arr)
            counts = np.asarray(vc.field("counts"))
            ndv = _chao1(counts, len(arr), float(rows))
    except Exception:
        ndv = None
    cache[name] = ndv
    return ndv


def _proj_ndv_map(exprs) -> Dict[str, Optional[str]]:
    """Output name -> source column name for pass-through / renamed
    columns; computed expressions map to None (NDV unknown)."""
    from ..expr.expressions import Alias, ColumnRef
    out: Dict[str, Optional[str]] = {}
    for e in exprs:
        if isinstance(e, ColumnRef):
            out[e.name] = e.name
        elif isinstance(e, Alias) and isinstance(e.child, ColumnRef):
            out[e.name] = e.child.name
        else:
            out[getattr(e, "name", "?")] = None
    return out


def _key_name(expr) -> Optional[str]:
    """Single column name a join/group key resolves to, else None."""
    from .optimizer import refs_of
    refs = refs_of(expr)
    if refs is not None and len(refs) == 1:
        return next(iter(refs))
    return None


def _join_rows(node: L.Join, ls: Stats, rs: Stats) -> Optional[float]:
    if ls.rows is None or rs.rows is None:
        return None
    if node.how in ("left_semi", "left_anti"):
        return ls.rows * (0.5 if node.how == "left_semi" else 0.5)
    rows = ls.rows * rs.rows
    for lk, rk in zip(node.left_keys, node.right_keys):
        ln, rn = _key_name(lk), _key_name(rk)
        ndv_l = (ls.ndv_of(ln) if ln else None) or ls.rows
        ndv_r = (rs.ndv_of(rn) if rn else None) or rs.rows
        rows /= max(ndv_l, ndv_r, 1.0)
    if node.how in ("left", "full"):
        rows = max(rows, ls.rows)
    if node.how in ("right", "full"):
        rows = max(rows, rs.rows)
    return rows


def compute_stats(node: L.LogicalPlan) -> Stats:
    """Bottom-up (rows, ndv) estimate for a logical subtree."""
    from .cbo import _selectivity
    from .planner import _estimate_rows

    if isinstance(node, (L.InMemoryScan, L.CachedScan, L.ParquetScan,
                         L.TextScan)):
        rows = _estimate_rows(node)
        return Stats(None if rows is None else float(rows),
                     lambda n, _nd=node: scan_column_ndv(_nd, n))

    if isinstance(node, L.Filter):
        cs = compute_stats(node.children[0])
        rows = (None if cs.rows is None
                else cs.rows * _selectivity(node.condition))
        return Stats(rows, cs._ndv_of)

    if isinstance(node, L.Project):
        cs = compute_stats(node.children[0])
        m = _proj_ndv_map(node.exprs)

        def ndv(n, _m=m, _cs=cs):
            src = _m.get(n)
            return None if src is None else _cs.ndv_of(src)
        return Stats(cs.rows, ndv)

    if isinstance(node, L.Join):
        ls = compute_stats(node.children[0])
        rs = compute_stats(node.children[1])
        rows = _join_rows(node, ls, rs)
        lnames = set(node.left.schema.names)

        def ndv(n, _ls=ls, _rs=rs, _ln=lnames):
            return _ls.ndv_of(n) if n in _ln else _rs.ndv_of(n)
        return Stats(rows, ndv)

    if isinstance(node, L.Aggregate):
        cs = compute_stats(node.children[0])
        if cs.rows is None:
            return Stats(None)
        groups = 1.0
        known = True
        for k in node.keys:
            kn = _key_name(k)
            nd = cs.ndv_of(kn) if kn else None
            if nd is None:
                known = False
                break
            groups *= nd
        rows = min(groups, cs.rows) if known else \
            min(cs.rows, max(cs.rows ** 0.75, 1.0))
        key_names = {k.name for k in node.keys}

        def ndv(n, _cs=cs, _keys=key_names):
            return _cs.ndv_of(n) if n in _keys else None
        return Stats(max(rows, 1.0), ndv)

    if isinstance(node, L.Limit):
        cs = compute_stats(node.children[0])
        rows = (node.n if cs.rows is None
                else min(float(node.n), cs.rows))
        return Stats(float(rows), cs._ndv_of)

    if isinstance(node, L.Union):
        parts = [compute_stats(c) for c in node.children]
        if any(p.rows is None for p in parts):
            return Stats(None)
        return Stats(sum(p.rows for p in parts))

    if isinstance(node, (L.Sort, L.Repartition, L.WindowOp)):
        cs = compute_stats(node.children[0])
        return Stats(cs.rows, cs._ndv_of)

    rows = _estimate_rows(node)
    return Stats(None if rows is None else float(rows))
