"""Logical optimizations: filter pushdown + column pruning.

The reference gets both from Spark Catalyst for free; standalone we do
them here:
- `push_filters` moves Filter conditions below pass-through Projects and
  into the matching side of Joins (inner: both sides; left/semi/anti:
  left only; right: right only), so joins see pre-filtered inputs.
- `prune` flows required attributes top-down through
  Project/Filter/Aggregate/Sort/Limit/Join chains and shrinks scans AND
  join gather widths (the join expansion gathers only surviving columns).
"""
from __future__ import annotations

from typing import Optional, Set

from ..expr.expressions import Alias, BoundRef, ColumnRef, Expression
from . import logical as L

__all__ = ["optimize", "refs_of", "push_filters"]


def refs_of(e: Expression) -> Optional[Set[str]]:
    """Column names referenced by an unbound expression tree.
    None = unknown (contains a raw BoundRef) — disables pruning."""
    if isinstance(e, ColumnRef):
        return {e.name}
    if isinstance(e, BoundRef):
        return None
    out: Set[str] = set()
    for c in e.children:
        if c is None:
            continue
        r = refs_of(c)
        if r is None:
            return None
        out |= r
    return out


def _refs_of_all(exprs) -> Optional[Set[str]]:
    out: Set[str] = set()
    for e in exprs:
        if e is None:
            continue
        r = refs_of(e)
        if r is None:
            return None
        out |= r
    return out


def prune(plan: L.LogicalPlan,
          required: Optional[Set[str]]) -> L.LogicalPlan:
    if isinstance(plan, L.InMemoryScan):
        if required is not None:
            names = [n for n in plan.arrow.schema.names if n in required]
            if len(names) < len(plan.arrow.schema.names):
                return L.InMemoryScan(plan.arrow.select(names))
        return plan
    if isinstance(plan, L.CachedScan):
        return plan  # already device-resident; pruning would copy
    if isinstance(plan, L.ParquetScan):
        if required is not None:
            names = [f.name for f in plan.schema.fields
                     if f.name in required]
            if len(names) < len(plan.schema.fields):
                return L.ParquetScan(plan.paths, columns=names,
                                     dv=plan.dv)
        return plan
    if isinstance(plan, L.TextScan):
        if required is not None:
            names = [f.name for f in plan.schema.fields
                     if f.name in required]
            if len(names) < len(plan.schema.fields):
                return L.TextScan(plan.paths, plan.fmt, plan._full_schema,
                                  names, plan.options)
        return plan
    if isinstance(plan, L.Project):
        exprs = plan.exprs
        if required is not None:
            kept = [e for e in exprs if e.name in required]
            if kept:
                exprs = kept
        child_req = _refs_of_all(exprs)
        child = prune(plan.child, child_req)
        return L.Project(child, exprs)
    if isinstance(plan, L.Filter):
        creq = None
        if required is not None:
            r = refs_of(plan.condition)
            creq = None if r is None else (required | r)
        child = prune(plan.child, creq)
        if isinstance(child, L.ParquetScan):
            # attach pushable conjuncts for row-group pruning (the
            # filterBlocks analog: GpuParquetScan.scala:679); the Filter
            # stays above for exact row filtering
            conj = extract_conjuncts(plan.condition)
            if conj:
                child = L.ParquetScan(child.paths, child._schema,
                                      child.columns,
                                      (child.filters or []) + conj,
                                      dv=child.dv)
        return L.Filter(child, plan.condition)
    if isinstance(plan, L.Aggregate):
        creq = _refs_of_all(list(plan.keys) +
                            [a.child for _, a in plan.aggs])
        child = prune(plan.child, creq)
        return L.Aggregate(child, plan.keys, plan.aggs)
    if isinstance(plan, L.Sort):
        creq = None
        if required is not None:
            r = _refs_of_all([o.expr for o in plan.orders])
            creq = None if r is None else (required | r)
        child = prune(plan.child, creq)
        return L.Sort(child, plan.orders, plan.global_sort)
    if isinstance(plan, L.Limit):
        return L.Limit(prune(plan.child, required), plan.n)
    if isinstance(plan, L.Union):
        return L.Union([prune(c, None) for c in plan.children])
    if isinstance(plan, L.Join):
        lnames = set(plan.left.schema.names)
        rnames = set(plan.right.schema.names)
        lkr = _refs_of_all(plan.left_keys)
        rkr = _refs_of_all(plan.right_keys)
        ckr = (_refs_of_all([plan.condition])
               if plan.condition is not None else set())
        lreq = rreq = None
        if (required is not None and lkr is not None and rkr is not None
                and ckr is not None and not (lnames & rnames)):
            lreq = ({n for n in required if n in lnames} | lkr
                    | (ckr & lnames))
            rreq = ({n for n in required if n in rnames} | rkr
                    | (ckr & rnames))
        return L.Join(prune(plan.left, lreq), prune(plan.right, rreq),
                      plan.left_keys, plan.right_keys, plan.how,
                      condition=plan.condition)
    if isinstance(plan, L.WindowOp):
        return L.WindowOp(prune(plan.child, None), plan.wcols)
    if isinstance(plan, L.Repartition):
        return L.Repartition(prune(plan.child, None), plan.num_partitions,
                             plan.keys)
    return plan


def _rebuild(plan: L.LogicalPlan, kids) -> L.LogicalPlan:
    """Reconstruct a node over new children (re-binding expressions)."""
    if isinstance(plan, L.Project):
        return L.Project(kids[0], plan.exprs)
    if isinstance(plan, L.Filter):
        return L.Filter(kids[0], plan.condition)
    if isinstance(plan, L.Aggregate):
        return L.Aggregate(kids[0], plan.keys, plan.aggs)
    if isinstance(plan, L.Sort):
        return L.Sort(kids[0], plan.orders, plan.global_sort)
    if isinstance(plan, L.Limit):
        return L.Limit(kids[0], plan.n)
    if isinstance(plan, L.Union):
        return L.Union(kids)
    if isinstance(plan, L.Join):
        return L.Join(kids[0], kids[1], plan.left_keys, plan.right_keys,
                      plan.how, condition=plan.condition)
    if isinstance(plan, L.WindowOp):
        return L.WindowOp(kids[0], plan.wcols)
    if isinstance(plan, L.Repartition):
        return L.Repartition(kids[0], plan.num_partitions, plan.keys)
    return plan


def extract_conjuncts(cond: Expression):
    """Pull (name, op, literal) conjuncts usable for row-group stats
    pruning out of a condition; non-matching branches are skipped (they
    simply don't prune)."""
    from ..expr.expressions import (And, ColumnRef, Eq, Ge, Gt, Le, Lt,
                                    Literal)
    out = []

    def walk(e):
        if isinstance(e, And):
            walk(e.children[0])
            walk(e.children[1])
            return
        ops = {Ge: ">=", Gt: ">", Le: "<=", Lt: "<", Eq: "="}
        t = type(e)
        if t in ops and len(e.children) == 2:
            l, r = e.children
            flip = {">=": "<=", ">": "<", "<=": ">=", "<": ">", "=": "="}
            if isinstance(l, ColumnRef) and isinstance(r, Literal) \
                    and r.value is not None:
                out.append((l.name, ops[t], r.value))
            elif isinstance(r, ColumnRef) and isinstance(l, Literal) \
                    and l.value is not None:
                out.append((r.name, flip[ops[t]], l.value))

    walk(cond)
    return out


def _passthrough_names(project: L.Project) -> Set[str]:
    """Output names that are plain same-named column references."""
    out = set()
    for e in project.exprs:
        if isinstance(e, ColumnRef):
            out.add(e.name)
    return out


def _split_conjuncts(cond: Expression):
    from ..expr.expressions import And
    if isinstance(cond, And):
        return (_split_conjuncts(cond.children[0])
                + _split_conjuncts(cond.children[1]))
    return [cond]


def _and_all(preds):
    from ..expr.expressions import And
    out = preds[0]
    for p in preds[1:]:
        out = And(out, p)
    return out


def extract_within(cond: Expression, names: Set[str]):
    """Weaker predicate IMPLIED by `cond` that references only `names`,
    or None (Spark's extractPredicatesWithinOutputSet): And keeps either
    side, Or needs both. Lets an OR-of-ANDs filter like TPC-H q7's
    (supp='FR' AND cust='DE') OR (supp='DE' AND cust='FR') push
    supp IN ('FR','DE') below the join."""
    from ..expr.expressions import And, Or
    if isinstance(cond, And):
        a = extract_within(cond.children[0], names)
        b = extract_within(cond.children[1], names)
        if a is not None and b is not None:
            return And(a, b)
        return a if a is not None else b
    if isinstance(cond, Or):
        a = extract_within(cond.children[0], names)
        b = extract_within(cond.children[1], names)
        if a is not None and b is not None:
            return Or(a, b)
        return None
    refs = refs_of(cond)
    if refs is not None and refs <= names:
        return cond
    return None


def push_filters(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Sink Filters below pass-through Projects and into Join sides:
    whole one-sided conjuncts move (and are removed above); derived
    OR-extracted predicates are ADDED below while the original filter
    stays (necessary-not-sufficient)."""
    kids = [push_filters(c) for c in plan.children]
    plan = _rebuild(plan, kids)
    if not isinstance(plan, L.Filter):
        return plan
    child = plan.child
    refs = refs_of(plan.condition)
    if refs is None:
        return plan
    if isinstance(child, L.Project) and refs <= _passthrough_names(child):
        return L.Project(
            push_filters(L.Filter(child.child, plan.condition)),
            child.exprs)
    if isinstance(child, L.Join):
        lnames = set(child.left.schema.names)
        rnames = set(child.right.schema.names)
        # names present on BOTH sides are ambiguous in the join output:
        # a conjunct touching one stays above; one-side-only conjuncts
        # still push (the common on=['k'] natural-join shape)
        shared = lnames & rnames
        lonly = lnames - shared
        ronly = rnames - shared
        left_ok = child.how in ("inner", "left", "left_semi", "left_anti")
        right_ok = child.how in ("inner", "right")
        lparts, rparts, rest = [], [], []
        for c in _split_conjuncts(plan.condition):
            r = refs_of(c)
            if r is not None and r & shared:
                rest.append(c)
            elif r is not None and r <= lnames and left_ok:
                lparts.append(c)
            elif r is not None and r <= rnames and right_ok:
                rparts.append(c)
            else:
                rest.append(c)
        # derived one-sided weakenings of the residual conjuncts
        for c in rest:
            if refs_of(c) is not None and refs_of(c) & shared:
                continue
            if left_ok:
                d = extract_within(c, lonly)
                if d is not None and refs_of(d) != refs_of(c):
                    lparts.append(d)
            if right_ok:
                d = extract_within(c, ronly)
                if d is not None and refs_of(d) != refs_of(c):
                    rparts.append(d)
        if not lparts and not rparts:
            return plan
        new_left = child.left
        new_right = child.right
        if lparts:
            new_left = push_filters(L.Filter(new_left, _and_all(lparts)))
        if rparts:
            new_right = push_filters(L.Filter(new_right,
                                              _and_all(rparts)))
        out = L.Join(new_left, new_right, child.left_keys,
                     child.right_keys, child.how,
                     condition=child.condition)
        if rest:
            return L.Filter(out, _and_all(rest))
        return out
    return plan


def rewrite_distinct_aggs(plan: L.LogicalPlan) -> L.LogicalPlan:
    """count(DISTINCT x) -> two-level hash aggregation (the
    single-distinct-child case of Catalyst's RewriteDistinctAggregates):
    an inner DISTINCT Aggregate over (keys..., x) deduplicates, an outer
    Count over the deduped value finishes. Both levels ride
    HashAggregateExec's bucketed hash pass (incl. the hash-once string
    keying) instead of CollectAggExec's full multi-chunk lexsort — the
    q16 straggler shape. Count skips nulls, so the inner null-x group
    drops out in the outer Count exactly like count(DISTINCT)."""
    from ..expr.aggregates import Count, CountDistinct

    def rewrite(node):
        kids = [rewrite(c) for c in node.children]
        node = _rebuild(node, kids)
        if not (isinstance(node, L.Aggregate) and node.aggs
                and all(type(a) is CountDistinct for _, a in node.aggs)):
            return node
        # one shared distinct child only (multiple distinct children
        # need an Expand; keep those on the sort path)
        if len({repr(a.child) for _, a in node.aggs}) != 1:
            return node
        key_names = [k.name for k in node.keys]
        if len(set(key_names)) != len(key_names):
            return node
        val = "__cd_val"
        if val in key_names:
            return node
        x = node.aggs[0][1].child
        inner = L.Aggregate(node.children[0],
                            node.keys + [Alias(x, val)], [])
        outer = L.Aggregate(inner, [ColumnRef(nm) for nm in key_names],
                            [(nm, Count(ColumnRef(val)))
                             for nm, _ in node.aggs])
        return outer

    return rewrite(plan)


def optimize(plan: L.LogicalPlan, conf=None) -> L.LogicalPlan:
    # Aggregate/Project at the root define their own required set; start
    # unconstrained and let node rules narrow it.
    plan = push_filters(plan)
    if conf is not None:
        from ..config import DISTINCT_AGG_REWRITE, JOIN_REORDER_ENABLED
        if conf.get(DISTINCT_AGG_REWRITE):
            plan = rewrite_distinct_aggs(plan)
        if conf.get(JOIN_REORDER_ENABLED):
            from .cbo import reorder_joins
            plan = reorder_joins(plan, conf)
    return prune(plan, None)
