"""Logical optimizations. Round-1: column pruning into scans.

The reference gets pruning from Spark Catalyst for free; standalone we do
it here: required attributes flow top-down through
Project/Filter/Aggregate/Sort/Limit chains and shrink scans (dropping e.g.
unused string columns before the host->HBM transfer, which profiling shows
dominates scan time).
"""
from __future__ import annotations

from typing import Optional, Set

from ..expr.expressions import BoundRef, ColumnRef, Expression
from . import logical as L

__all__ = ["optimize", "refs_of"]


def refs_of(e: Expression) -> Optional[Set[str]]:
    """Column names referenced by an unbound expression tree.
    None = unknown (contains a raw BoundRef) — disables pruning."""
    if isinstance(e, ColumnRef):
        return {e.name}
    if isinstance(e, BoundRef):
        return None
    out: Set[str] = set()
    for c in e.children:
        if c is None:
            continue
        r = refs_of(c)
        if r is None:
            return None
        out |= r
    return out


def _refs_of_all(exprs) -> Optional[Set[str]]:
    out: Set[str] = set()
    for e in exprs:
        if e is None:
            continue
        r = refs_of(e)
        if r is None:
            return None
        out |= r
    return out


def prune(plan: L.LogicalPlan,
          required: Optional[Set[str]]) -> L.LogicalPlan:
    if isinstance(plan, L.InMemoryScan):
        if required is not None:
            names = [n for n in plan.arrow.schema.names if n in required]
            if len(names) < len(plan.arrow.schema.names):
                return L.InMemoryScan(plan.arrow.select(names))
        return plan
    if isinstance(plan, L.CachedScan):
        return plan  # already device-resident; pruning would copy
    if isinstance(plan, L.ParquetScan):
        if required is not None:
            names = [f.name for f in plan.schema.fields
                     if f.name in required]
            if len(names) < len(plan.schema.fields):
                return L.ParquetScan(plan.paths, columns=names)
        return plan
    if isinstance(plan, L.Project):
        child_req = _refs_of_all(plan.exprs)
        child = prune(plan.child, child_req)
        return L.Project(child, plan.exprs)
    if isinstance(plan, L.Filter):
        creq = None
        if required is not None:
            r = refs_of(plan.condition)
            creq = None if r is None else (required | r)
        child = prune(plan.child, creq)
        return L.Filter(child, plan.condition)
    if isinstance(plan, L.Aggregate):
        creq = _refs_of_all(list(plan.keys) +
                            [a.child for _, a in plan.aggs])
        child = prune(plan.child, creq)
        return L.Aggregate(child, plan.keys, plan.aggs)
    if isinstance(plan, L.Sort):
        creq = None
        if required is not None:
            r = _refs_of_all([o.expr for o in plan.orders])
            creq = None if r is None else (required | r)
        child = prune(plan.child, creq)
        return L.Sort(child, plan.orders, plan.global_sort)
    if isinstance(plan, L.Limit):
        return L.Limit(prune(plan.child, required), plan.n)
    if isinstance(plan, L.Union):
        return L.Union([prune(c, None) for c in plan.children])
    if isinstance(plan, L.Join):
        # the Join schema is positional over ALL child columns, so children
        # cannot be pruned without rewriting parent BoundRefs
        return L.Join(prune(plan.left, None), prune(plan.right, None),
                      plan.left_keys, plan.right_keys, plan.how)
    if isinstance(plan, L.WindowOp):
        return L.WindowOp(prune(plan.child, None), plan.wcols)
    if isinstance(plan, L.Repartition):
        return L.Repartition(prune(plan.child, None), plan.num_partitions,
                             plan.keys)
    return plan


def optimize(plan: L.LogicalPlan) -> L.LogicalPlan:
    # Aggregate/Project at the root define their own required set; start
    # unconstrained and let node rules narrow it.
    return prune(plan, None)
