"""Plan-level exchange reuse — the ReuseExchange rule analog.

(reference: Spark's ReuseExchange / ReuseSubquery physical rules and the
plugin's GpuReusedExchangeExec rendering.) A post-fusion pass over the
PHYSICAL tree fingerprints every exchange subtree (structural: class
names, plan-config attributes, expression fingerprints via the
program-cache's gensym-normalized `expr_fp`, child subtrees) and
rewrites later duplicates to `ReusedExchangeExec` nodes that delegate to
the first occurrence — one map phase / broadcast build per DISTINCT
subtree per query. Self-joins and reused CTE-shaped scans stop paying
the shuffle twice.

Safety posture: a fingerprint miss (attribute we cannot fingerprint)
makes the subtree UNIQUE, never merged — false negatives cost a shuffle,
false positives would corrupt results.
"""
from __future__ import annotations

import threading
from typing import Optional, Tuple

from ..exec.base import ExecContext, TpuExec

__all__ = ["reuse_exchanges", "ReusedExchangeExec"]

# exec-node attributes that are runtime identity, never plan config
_SKIP_ATTRS = {"children", "lore_id", "audit_report", "fusion_opt_out"}


def _norm_names(fp):
    """Erase column-name attributes from an expr_fp tuple: exchange
    subtrees hold BOUND expressions, which emit by ordinal — `k` vs the
    session's gensym rename `__join_r1_k` is the same data. Ordinals
    and dtypes still distinguish genuinely different columns."""
    if isinstance(fp, tuple):
        if len(fp) == 2 and fp[0] == "_name" and isinstance(fp[1], str):
            return ("_name", "?")
        return tuple(_norm_names(x) for x in fp)
    return fp


def _value_fp(v) -> Optional[tuple]:
    """Structural fingerprint of one plan-config attribute value; None
    when the value cannot be fingerprinted (subtree becomes unique)."""
    from ..expr.expressions import Expression
    from ..runtime.program_cache import expr_fp
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return ("lit", v)
    if isinstance(v, Expression):
        return ("expr", _norm_names(expr_fp(v)))
    if isinstance(v, (list, tuple)):
        parts = tuple(_value_fp(x) for x in v)
        return None if any(p is None for p in parts) else ("seq", parts)
    if isinstance(v, dict):
        try:
            items = sorted(v.items())
        except TypeError:
            return None
        parts = tuple((k, _value_fp(x)) for k, x in items)
        return (None if any(p is None for _, p in parts)
                else ("map", parts))
    from ..columnar.table import Schema
    if isinstance(v, Schema):
        return ("schema", _schema_fp(v))
    from ..exec.base import TpuExec
    if isinstance(v, TpuExec):
        # nested plan nodes held as attributes (FusedStageExec.members,
        # AQE plan wrappers): fingerprint structurally like children
        fp = node_fp(v)
        return None if fp is None else ("exec", fp)
    try:
        import pyarrow as pa
        if isinstance(v, pa.Table):
            # zero-copy memory identity: planning a self-join wraps the
            # one session table in fresh pa.Table objects per branch,
            # but the chunks still point at the same buffers — same
            # addresses + offsets + lengths IS the same bytes, while a
            # genuine copy stays unique (conservative, never false)
            parts = [tuple(str(f.type) for f in v.schema), v.num_rows]
            for column in v.columns:
                for ch in column.chunks:
                    parts.append((ch.offset, len(ch),
                                  tuple(b.address if b is not None else 0
                                        for b in ch.buffers())))
            return ("arrow", tuple(parts))
    except Exception:
        pass
    # anything else (cached arrow tables, reader objects...): identity
    # fingerprint — the SAME object is trivially the same data (the
    # self-join case, where both scans hold one cached table), while
    # distinct-but-equal objects stay unique. Never falsely shared.
    return ("id", id(v))


def _schema_fp(schema) -> tuple:
    # dtypes only: post-binding, column names are labels — the bytes an
    # exchange materializes are fully determined by the child tree and
    # the bound (ordinal-addressed) expressions
    return tuple(str(f.dtype) for f in schema.fields)


def _is_identity_project(node) -> bool:
    """A bound Project that only renames: every output unwraps (through
    Alias) to BoundRef(ordinal=i) at its own position, covering the
    whole child schema — a pure label change, zero data effect."""
    from ..expr.expressions import Alias, BoundRef
    child = node.children[0]
    bound = getattr(node, "bound", None)
    if bound is None or len(bound) != len(child.schema.fields):
        return False
    for i, e in enumerate(bound):
        while isinstance(e, Alias):
            e = e.child
        if not (isinstance(e, BoundRef) and e.ordinal == i):
            return False
    return True


def _canonical(node: TpuExec) -> TpuExec:
    """See through pure-rename Projects so `Exchange(Scan)` and
    `Exchange(Project[x AS __join_r1_x](Scan))` — the shape every
    self-join produces — fingerprint identically. The fusion pass can
    wrap those same rename chains into a FusedStageExec before the
    reuse pass runs, so a fused stage whose members are ALL identity
    Projects is seen through too."""
    from ..exec.fused import FusedStageExec
    from ..exec.nodes import ProjectExec

    def _ident(n):
        return (isinstance(n, ProjectExec) and n.children
                and _is_identity_project(n))

    while True:
        if _ident(node):
            node = node.children[0]
            continue
        if (isinstance(node, FusedStageExec) and node.children
                and node.members and all(_ident(m) for m in node.members)):
            node = node.children[0]
            continue
        return node


def node_fp(node: TpuExec) -> Optional[tuple]:
    """Structural fingerprint of a physical subtree. Public attributes
    are plan config (n, keys, paths...); underscore attributes are
    runtime state (locks, programs, materialized shuffles) and are
    skipped. Any non-fingerprintable public attribute poisons the
    subtree (returns None): it stays unique rather than risk a false
    merge."""
    node = _canonical(node)
    parts = [("cls", type(node).__name__),
             ("schema", _schema_fp(node.schema))]
    for k in sorted(vars(node)):
        if k.startswith("_") or k in _SKIP_ATTRS:
            continue
        fp = _value_fp(vars(node)[k])
        if fp is None:
            return None
        parts.append((k, fp))
    kids = []
    for c in node.children:
        cfp = node_fp(c)
        if cfp is None:
            return None
        kids.append(cfp)
    parts.append(("children", tuple(kids)))
    return tuple(parts)


class ReusedExchangeExec(TpuExec):
    """Stand-in for a duplicate exchange subtree: every read delegates
    to the first occurrence's materialization (shared under the
    target's own lock), so the duplicate costs zero map work. Carries
    the replaced node's lore id and renders the target's in describe().
    No children: the shared subtree stays owned (and released) by its
    original parent."""

    def __init__(self, target: TpuExec, original: TpuExec):
        super().__init__([], original.schema)
        self.target = target
        self.lore_id = getattr(original, "lore_id", None)
        self._hit_lock = threading.Lock()
        self._hit_ctxs = set()

    def describe(self):
        tid = getattr(self.target, "lore_id", "?")
        return f"ReusedExchange[loreId={self.lore_id} -> {tid}]"

    def num_partitions(self, ctx):
        return self.target.num_partitions(ctx)

    def _count_hit(self, ctx: ExecContext):
        """One exchangeReuseHits per (execution, node): a map/build
        phase this query did NOT re-run."""
        with self._hit_lock:
            if id(ctx) in self._hit_ctxs:
                return
            if len(self._hit_ctxs) > 64:
                self._hit_ctxs.clear()
            self._hit_ctxs.add(id(ctx))
        ctx.metrics_for(self._op_id).add("exchangeReuseHits", 1)

    # ---- exchange API, delegated (AQE readers call these) -------------
    def stage_stats(self, ctx: ExecContext):
        self._count_hit(ctx)
        return self.target.stage_stats(ctx)

    def read_slice(self, ctx: ExecContext, rpid: int, chunk: int = 0,
                   nchunks: int = 1):
        self._count_hit(ctx)
        return self.target.read_slice(ctx, rpid, chunk=chunk,
                                      nchunks=nchunks)

    def execute_partition(self, ctx: ExecContext, pid: int):
        self._count_hit(ctx)
        for b in self.target.execute_partition(ctx, pid):
            ctx.check_cancel()
            yield b

    def release(self):
        # the target is still parented by its first occurrence — it is
        # NOT ours to release (double-release would drop shared blocks
        # while the original parent may still replay them); children is
        # empty, so super().release() recurses into nothing
        super().release()


def _reusable(node: TpuExec) -> bool:
    from ..exec.broadcast import BroadcastExchangeExec
    from ..exec.exchange import ShuffleExchangeExec
    from ..exec.mesh_exchange import MeshExchangeExec
    return isinstance(node, (ShuffleExchangeExec, BroadcastExchangeExec,
                             MeshExchangeExec))


def reuse_exchanges(root: TpuExec, conf) -> Tuple[TpuExec, int]:
    """Rewrite duplicate exchange subtrees to ReusedExchangeExec nodes.
    Returns (new_root, hits). Post-fusion, pre-LORE-wrap."""
    from ..config import EXCHANGE_REUSE
    from ..exec.aqe import AqeShufflePlan
    if not conf.get(EXCHANGE_REUSE):
        return root, 0
    seen = {}
    replaced = {}  # id(duplicate exchange) -> its ReusedExchangeExec
    hits = 0

    def walk(node: TpuExec) -> TpuExec:
        nonlocal hits
        if isinstance(node, ReusedExchangeExec):
            return node
        node.children = [walk(c) for c in node.children]
        # AqeShufflePlan keeps DIRECT exchange references (outside the
        # children list) and calls stage_stats on them — swap replaced
        # duplicates there too, or the dedup'd map phase still runs
        p = getattr(node, "plan", None)
        if isinstance(p, AqeShufflePlan):
            p.exchanges = [replaced.get(id(e), e) for e in p.exchanges]
        if not _reusable(node):
            return node
        fp = node_fp(node)
        if fp is None:
            return node
        first = seen.get(fp)
        if first is not None and first is not node:
            hits += 1
            r = ReusedExchangeExec(first, node)
            replaced[id(node)] = r
            return r
        seen[fp] = node
        return node

    return walk(root), hits
