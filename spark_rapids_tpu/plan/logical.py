"""Logical plan nodes (the input to TpuOverrides planning).

The host "Catalyst" analog: since this framework is standalone (no Spark JVM
in-process for round 1), the DataFrame API builds these nodes directly; the
planner (plan/planner.py) then plays the role of GpuOverrides
(reference: GpuOverrides.scala:5017) — wrap, tag, convert to Tpu execs, and
insert transitions.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..columnar import dtypes as dt
from ..columnar.table import Schema, Field
from ..expr.expressions import Alias, Expression, ColumnRef
from ..expr import aggregates as agg
from .typesig import check_tree as _tsig

__all__ = ["LogicalPlan", "InMemoryScan", "CachedScan", "ParquetScan", "Project", "Filter", "Expand",
           "Aggregate", "Join", "Sort", "SortOrder", "Limit", "Union",
           "Repartition", "WindowOp", "Generate", "TextScan"]


class LogicalPlan:
    children: List["LogicalPlan"] = []

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    def node_name(self) -> str:
        return type(self).__name__

    def tree_string(self, indent=0) -> str:
        s = "  " * indent + self.describe() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def describe(self) -> str:
        return self.node_name()


class InMemoryScan(LogicalPlan):
    """Scan over a host (pyarrow) table; batches stream host->HBM."""

    def __init__(self, arrow_table):
        self.arrow = arrow_table
        self.children = []
        self._schema = Schema.from_arrow(arrow_table.schema)

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"InMemoryScan[rows={self.arrow.num_rows}] {self._schema}"


class CachedScan(LogicalPlan):
    """Scan over HBM-resident device batches — the analog of the
    reference's GpuInMemoryTableScanExec + ParquetCachedBatchSerializer
    (reference: ParquetCachedBatchSerializer.scala): df.cache() pins the
    columnar data on device so repeated queries skip host decode + H2D."""

    def __init__(self, batches, schema):
        self.batches = list(batches)
        self._schema = schema
        self.children = []

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"CachedScan[{len(self.batches)} device batches] {self._schema}"


class ParquetScan(LogicalPlan):
    def __init__(self, paths: Sequence[str], schema: Optional[Schema] = None,
                 columns: Optional[Sequence[str]] = None, filters=None,
                 dv=None, delta_version=None):
        import pyarrow.parquet as pq
        self.paths = list(paths)
        self.columns = list(columns) if columns is not None else None
        # (name, op, value) conjuncts for row-group pruning, attached by
        # the optimizer from a Filter directly above the scan
        self.filters = list(filters) if filters else None
        # {path: (table_root, deletionVector descriptor)}: dead-row
        # masks applied lazily inside the scan (Delta DVs)
        self.dv = dict(dv) if dv else None
        # bind-time snapshot: (path, mtime_ns, size) per file, plus the
        # Delta table version when read through read_delta. An overwrite
        # between actions refreshes the plan (DataFrame._execute); one
        # mid-query raises (io/snapshot.py). Public attrs on purpose —
        # both flow into the structural plan fingerprint, which is how
        # a table write invalidates dependent result-cache entries.
        from ..io.snapshot import scan_snapshot
        self.snapshot = scan_snapshot(self.paths)
        self.delta_version = delta_version
        if schema is None:
            schema = Schema.from_arrow(pq.read_schema(self.paths[0]))
            if self.columns is not None:
                schema = Schema([f for f in schema.fields
                                 if f.name in self.columns])
        self._schema = schema
        self.children = []

    def refresh_snapshot(self) -> bool:
        """Re-stat the pinned files; True when anything changed."""
        from ..io.snapshot import scan_snapshot
        cur = scan_snapshot(self.paths)
        if cur != self.snapshot:
            self.snapshot = cur
            return True
        return False

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"ParquetScan[{len(self.paths)} files] {self._schema}"


class TextScan(LogicalPlan):
    """Lazy CSV / JSON-lines / ORC scan (reference: GpuCSVScan.scala:57,
    GpuJsonScan.scala, GpuOrcScan.scala:78). Schema comes from metadata or
    a first-block sample; decode happens per batch at execution."""

    def __init__(self, paths: Sequence[str], fmt: str,
                 schema: Optional[Schema] = None, columns=None,
                 options=None):
        from ..exec.text_scan import infer_text_schema
        from ..io.snapshot import scan_snapshot
        self.children = []
        self.paths = list(paths)
        self.fmt = fmt
        self.columns = list(columns) if columns else None
        self.options = options
        # bind-time file pinning, same contract as ParquetScan.snapshot
        self.snapshot = scan_snapshot(self.paths)
        if schema is not None and not isinstance(schema, Schema):
            schema = Schema.from_arrow(schema)   # accept pyarrow schemas
        self._full_schema = schema or infer_text_schema(
            self.paths[0], fmt, options)
        if self.columns is not None:
            want = set(self.columns)
            self._schema = Schema([f for f in self._full_schema.fields
                                   if f.name in want])
        else:
            self._schema = self._full_schema

    @property
    def schema(self):
        return self._schema

    def describe(self):
        cols = f", columns={self.columns}" if self.columns else ""
        return f"TextScan[{self.fmt}, {len(self.paths)} files{cols}]"


class Project(LogicalPlan):
    def __init__(self, child: LogicalPlan, exprs: Sequence[Expression]):
        from ..expr.expressions import UnsupportedExpr
        from ..expr.host_eval import host_output_dtype
        self.child = child
        self.children = [child]
        self.exprs = list(exprs)
        self.bound = []
        self.bind_errors: List[Optional[str]] = []
        fields = []
        for e in self.exprs:
            try:
                b = _tsig(e.bind(child.schema),
                          where=f"Project expr {e.name!r}")
                self.bound.append(b)
                self.bind_errors.append(None)
                fields.append(Field(e.name, b.dtype))
            except UnsupportedExpr as err:
                # TPU cannot run this expression; keep the unbound tree
                # for the host-fallback exec (GpuCpuBridge analog) when
                # the output dtype is still derivable
                hd = host_output_dtype(e)
                if hd is None:
                    raise
                self.bound.append(None)
                self.bind_errors.append(str(err))
                fields.append(Field(e.name, hd))
        self._schema = Schema(fields)

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"Project[{', '.join(map(repr, self.exprs))}]"


class Filter(LogicalPlan):
    def __init__(self, child: LogicalPlan, condition: Expression):
        from ..expr.expressions import UnsupportedExpr
        self.child = child
        self.children = [child]
        self.condition = condition
        self.bind_error: Optional[str] = None
        try:
            self.bound = _tsig(condition.bind(child.schema),
                               where="Filter condition")
        except UnsupportedExpr as err:
            self.bound = None
            self.bind_error = str(err)

    @property
    def schema(self):
        return self.child.schema

    def describe(self):
        return f"Filter[{self.condition!r}]"


class Aggregate(LogicalPlan):
    """Grouped or ungrouped aggregation.

    aggs are (output_name, AggExpr) pairs; keys are grouping expressions.
    """

    def __init__(self, child: LogicalPlan, keys: Sequence[Expression],
                 aggs: Sequence[Tuple[str, agg.AggExpr]]):
        self.child = child
        self.children = [child]
        self.keys = list(keys)
        self.aggs = list(aggs)
        self.bound_keys = [_tsig(k.bind(child.schema),
                                 where=f"Aggregate key {k.name!r}")
                           for k in self.keys]
        self.bound_aggs = [(n, _tsig(a.bind(child.schema),
                                     where=f"Aggregate agg {n!r}"))
                           for n, a in self.aggs]
        fields = [Field(k.name, bk.dtype)
                  for k, bk in zip(self.keys, self.bound_keys)]
        fields += [Field(n, a.dtype) for n, a in self.bound_aggs]
        self._schema = Schema(fields)

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return (f"Aggregate[keys={[repr(k) for k in self.keys]}, "
                f"aggs={[n for n, _ in self.aggs]}]")


class Expand(LogicalPlan):
    """GROUPING SETS expansion feeding an Aggregate (reference:
    GpuExpandExec.scala). Output = child columns ++ grouping-key columns
    (validity dropped where a set excludes the key) ++ grouping_id."""

    def __init__(self, child: LogicalPlan, key_exprs: Sequence[Expression],
                 key_names: Sequence[str], include_masks, gid_name: str):
        self.child = child
        self.children = [child]
        self.key_exprs = list(key_exprs)
        self.key_names = list(key_names)
        self.include_masks = [tuple(m) for m in include_masks]
        self.gid_name = gid_name
        self.bound_keys = [_tsig(k.bind(child.schema),
                                 where=f"Expand key {k.name!r}")
                           for k in self.key_exprs]
        fields = list(child.schema.fields)
        fields += [Field(n, k.dtype)
                   for n, k in zip(self.key_names, self.bound_keys)]
        fields.append(Field(gid_name, dt.INT64))
        self._schema = Schema(fields)

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return (f"Expand[{len(self.include_masks)} sets, "
                f"keys={self.key_names}]")


class Join(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression], how: str = "inner",
                 condition: Optional[Expression] = None):
        assert how in ("inner", "left", "right", "full", "left_semi",
                       "left_anti", "cross")
        self.left, self.right = left, right
        self.children = [left, right]
        self.how = how
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.bound_left_keys = [_tsig(k.bind(left.schema),
                                      where=f"Join left key {k.name!r}")
                                for k in self.left_keys]
        self.bound_right_keys = [_tsig(k.bind(right.schema),
                                       where=f"Join right key {k.name!r}")
                                 for k in self.right_keys]
        lf = list(left.schema.fields)
        rf = list(right.schema.fields)
        # non-equi condition binds over the COMBINED schema (the
        # reference's AST-compiled join conditions, AstUtil.scala)
        self.condition = condition
        self.bound_condition = (_tsig(condition.bind(Schema(lf + rf)),
                                      where="Join condition")
                                if condition is not None else None)
        if how in ("left_semi", "left_anti"):
            fields = lf
        else:
            fields = lf + rf
        self._schema = Schema(fields)

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"Join[{self.how}, on={list(zip(self.left_keys, self.right_keys))}]"


class SortOrder:
    def __init__(self, expr: Expression, ascending: bool = True,
                 nulls_first: Optional[bool] = None):
        self.expr = expr
        self.ascending = ascending
        # Spark default: nulls first for asc, nulls last for desc
        self.nulls_first = ascending if nulls_first is None else nulls_first

    def __repr__(self):
        d = "ASC" if self.ascending else "DESC"
        nf = "NULLS FIRST" if self.nulls_first else "NULLS LAST"
        return f"{self.expr!r} {d} {nf}"


class Sort(LogicalPlan):
    def __init__(self, child: LogicalPlan, orders: Sequence[SortOrder],
                 global_sort: bool = True):
        self.child = child
        self.children = [child]
        self.orders = list(orders)
        self.global_sort = global_sort
        self.bound_orders = [SortOrder(
            _tsig(o.expr.bind(child.schema),
                  where=f"Sort key {o.expr!r}"),
            o.ascending, o.nulls_first)
                             for o in self.orders]

    @property
    def schema(self):
        return self.child.schema

    def describe(self):
        return f"Sort[{self.orders}]"


class Limit(LogicalPlan):
    def __init__(self, child: LogicalPlan, n: int):
        self.child = child
        self.children = [child]
        self.n = n

    @property
    def schema(self):
        return self.child.schema

    def describe(self):
        return f"Limit[{self.n}]"


class Union(LogicalPlan):
    def __init__(self, children: Sequence[LogicalPlan]):
        self.children = list(children)
        s0 = self.children[0].schema
        for c in self.children[1:]:
            if [f.dtype for f in c.schema.fields] != [f.dtype for f in
                                                      s0.fields]:
                raise ValueError("UNION schema mismatch")
        self._schema = s0

    @property
    def schema(self):
        return self._schema


class WindowOp(LogicalPlan):
    """Appends window-function columns (reference: GpuWindowExec planning
    in GpuWindowExecMeta.scala — round-1 requires one shared spec)."""

    def __init__(self, child: LogicalPlan, wcols):
        self.child = child
        self.children = [child]
        self.wcols = list(wcols)          # (name, WindowExpr) unbound
        self.bound = [(n, w.bind(child.schema)) for n, w in self.wcols]
        for _n, _w in self.bound:
            if getattr(_w, 'child', None) is not None:
                _tsig(_w.child, where=f"WindowOp column {_n!r}")
        self._schema = Schema(list(child.schema.fields)
                              + [Field(n, w.dtype) for n, w in self.bound])

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"WindowOp[{[n for n, _ in self.wcols]}]"


class Generate(LogicalPlan):
    """Explode/posexplode: appends generated columns, one output row per
    element (reference: GpuGenerateExec.scala GpuExplode/GpuPosExplode).
    Output = all child columns + [pos]? + (col | key,value)."""

    def __init__(self, child: LogicalPlan, generator, out_names):
        self.child = child
        self.children = [child]
        self.generator = generator              # unbound Explode/PosExplode
        self.bound = _tsig(generator.bind(child.schema),
                           where="Generate generator")
        self.out_names = list(out_names)
        gen_dt = self.bound.dtype
        gen_fields = []
        if self.bound.with_position:
            gen_fields.append(Field(self.out_names[0], dt.INT32))
        if isinstance(self.bound.child.dtype, dt.MapType):
            # map explode: key + value columns
            for f, nm in zip(gen_dt.fields, self.out_names[-2:]):
                gen_fields.append(Field(nm, f.dtype))
        else:
            gen_fields.append(Field(self.out_names[-1], gen_dt))
        self._schema = Schema(list(child.schema.fields) + gen_fields)

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"Generate[{self.generator!r}]"


class MapInPandas(LogicalPlan):
    """Batch-wise pandas transform in a pooled python worker process
    (reference: GpuMapInPandasExec)."""

    def __init__(self, child: LogicalPlan, fn, schema: Schema):
        self.child = child
        self.children = [child]
        self.fn = fn
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def describe(self):
        name = getattr(self.fn, "__name__", "fn")
        return f"MapInPandas[{name}]"


class GroupedMapInPandas(LogicalPlan):
    """Per-group pandas transform (applyInPandas / AggregateInPandas):
    the planner repartitions by key so groups are whole per partition
    (reference: GpuFlatMapGroupsInPandasExec,
    GpuAggregateInPandasExec.scala:51). `fn` is the worker-side wrapper
    (already closed over the user function + keys)."""

    def __init__(self, child: LogicalPlan, fn, schema: Schema,
                 key_names):
        self.child = child
        self.children = [child]
        self.fn = fn
        self._schema = schema
        self.key_names = list(key_names)

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"GroupedMapInPandas[keys={self.key_names}]"


class CoGroupInPandas(LogicalPlan):
    """Cogrouped pandas transform (reference:
    GpuFlatMapCoGroupsInPandasExec): both children repartition by their
    keys; fn is the worker-side _CoGroupApply wrapper."""

    def __init__(self, left: LogicalPlan, right: LogicalPlan, fn,
                 schema: Schema, lkeys, rkeys):
        self.children = [left, right]
        self.fn = fn
        self._schema = schema
        self.lkeys = list(lkeys)
        self.rkeys = list(rkeys)

    @property
    def schema(self):
        return self._schema

    def describe(self):
        return f"CoGroupInPandas[{self.lkeys} x {self.rkeys}]"


class Repartition(LogicalPlan):
    def __init__(self, child: LogicalPlan, num_partitions: int,
                 keys: Optional[Sequence[Expression]] = None):
        self.child = child
        self.children = [child]
        self.num_partitions = num_partitions
        self.keys = list(keys) if keys else None
        self.bound_keys = ([_tsig(k.bind(child.schema),
                                  where=f"Repartition key {k.name!r}")
                            for k in self.keys]
                           if self.keys else None)

    @property
    def schema(self):
        return self.child.schema

    def describe(self):
        return f"Repartition[{self.num_partitions}]"
