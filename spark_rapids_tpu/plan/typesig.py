"""TypeSig: per-expression supported-type signatures + doc generation.

(reference: TypeChecks.scala:125 TypeSig algebra; generates
docs/supported_ops.md and tools/generated_files/supportedExprs.csv.)
A TypeSig is a set of supported DataType classes; expressions are
registered with input/output signatures, `check()` is used by binders for
uniform error text, and `generate_supported_ops()` emits the docs table.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple, Type

from ..columnar import dtypes as dt

__all__ = ["TypeSig", "SIGS", "register", "check", "check_tree",
           "AUDIT_CHECKS", "audit_register", "audit_check",
           "generate_supported_ops"]


class TypeSig:
    def __init__(self, *classes: Type[dt.DataType], note: str = ""):
        self.classes = tuple(classes)
        self.note = note

    def __add__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(*(self.classes + other.classes),
                       note=self.note or other.note)

    def supports(self, dtype: dt.DataType) -> bool:
        return isinstance(dtype, self.classes)

    def describe(self) -> str:
        names = sorted({c.__name__.replace("Type", "")
                        for c in self.classes})
        s = ", ".join(names)
        return f"{s} ({self.note})" if self.note else s


BOOL = TypeSig(dt.BooleanType)
INTEGRAL = TypeSig(dt.ByteType, dt.ShortType, dt.IntegerType, dt.LongType)
FLOATING = TypeSig(dt.FloatType, dt.DoubleType)
DECIMAL = TypeSig(dt.DecimalType,
                  note="up to 38 digits (exact decimal128 kernels); "
                       "see docs/compatibility.md")
NUMERIC = INTEGRAL + FLOATING + DECIMAL
DATETIME = TypeSig(dt.DateType, dt.TimestampType)
STRING = TypeSig(dt.StringType, dt.BinaryType)
NULL = TypeSig(dt.NullType)
ALL_COMMON = NUMERIC + DATETIME + STRING + BOOL + NULL

# expression class name -> (input TypeSig, description)
SIGS: Dict[str, Tuple[TypeSig, str]] = {}


def register(name: str, sig: TypeSig, desc: str = ""):
    SIGS[name] = (sig, desc)


def _at(where: str = "", lore_id=None) -> str:
    """Render the bind-site context suffix for check errors: the node
    path of the failing bind site (and, when the audit pass supplies
    one, the lore id) instead of just the expression name."""
    parts = []
    if lore_id is not None:
        parts.append(f"loreId={lore_id}")
    if where:
        parts.append(f"at {where}")
    return f" [{', '.join(parts)}]" if parts else ""


def check(name: str, dtype: dt.DataType, what: str = "",
          where: str = "", lore_id=None):
    from ..expr.expressions import UnsupportedExpr
    ent = SIGS.get(name)
    if ent is not None and not ent[0].supports(dtype):
        raise UnsupportedExpr(
            f"{what or name} does not support input type {dtype} on TPU "
            f"(supported: {ent[0].describe()})" + _at(where, lore_id))


def check_tree(expr, where: str = ""):
    """Uniform binder gate: walk a BOUND expression tree and check each
    node's primary input (first child) dtype against its registered
    signature (reference: TypeChecks.tagExprForGpu, TypeChecks.scala:716
    — there per-parameter; here the subject input, with later params
    enforced by the binders). Unregistered nodes pass — signatures are
    deliberately no STRICTER than the binders, so this adds uniform
    error text and the docs table without shadowing real support.
    `where` names the bind site (logical node + role) for error text."""
    if expr is None:
        return expr
    name = type(expr).__name__
    ent = SIGS.get(name)
    kids = getattr(expr, "children", None) or []
    if ent is not None and kids:
        cdt = getattr(kids[0], "dtype", None)
        if cdt is not None and not ent[0].supports(cdt):
            from ..expr.expressions import UnsupportedExpr
            raise UnsupportedExpr(
                f"{name} does not support input type {cdt} on TPU "
                f"(supported: {ent[0].describe()})" + _at(where))
    for c in kids:
        check_tree(c, where)
    return expr


# -- audit checks -------------------------------------------------------
# Kernel-truth refinements NARROWER than the bind-time signatures: the
# binders accept these shapes, but the device kernels cannot actually run
# them (dtype layouts the emit path mishandles, decimal/timezone edges).
# The plan auditor (analysis/audit.py) evaluates them pre-execution and
# turns what used to be an opaque mid-query XLA/Arrow error into a
# plan-time `will_not_work` verdict. Each entry: expression class name
# -> (fn(dtype) -> reason-or-None, doc note).
AUDIT_CHECKS: Dict[str, Tuple[Callable[[dt.DataType], Optional[str]],
                              str]] = {}


def audit_register(name: str, fn: Callable[[dt.DataType], Optional[str]],
                   note: str = ""):
    AUDIT_CHECKS[name] = (fn, note)


def audit_check(name: str, dtype: dt.DataType) -> Optional[str]:
    """Reason this (expression, primary-input dtype) pair will NOT work
    at runtime despite binding, or None when no audit rule objects."""
    ent = AUDIT_CHECKS.get(name)
    if ent is None or dtype is None:
        return None
    return ent[0](dtype)


# -- registry (mirrors the expression surface; the binders stay the
# source of truth for enforcement, this drives docs + uniform errors) ----
for _n in ("Add", "Subtract", "Multiply", "Divide", "IntDivide",
           "Remainder", "Pmod", "Negate", "Abs", "Round"):
    register(_n, NUMERIC, "arithmetic")
for _n in ("Eq", "Ne", "Lt", "Le", "Gt", "Ge", "EqNullSafe"):
    register(_n, ALL_COMMON, "comparison")
for _n in ("And", "Or", "Not"):
    register(_n, BOOL, "boolean")
NESTED = TypeSig(dt.ArrayType, dt.MapType, dt.StructType)
for _n in ("IsNull", "IsNotNull"):
    register(_n, ALL_COMMON + NESTED, "null test (validity only)")
for _n in ("Coalesce", "If", "CaseWhen", "In"):
    register(_n, ALL_COMMON, "conditional (no nested branches)")
register("IsNaN", FLOATING, "NaN test")
for _n in ("BitwiseAnd", "BitwiseOr", "BitwiseXor", "BitwiseNot",
           "ShiftLeft", "ShiftRight"):
    register(_n, INTEGRAL, "bitwise")
for _n in ("MathUnary", "Pow", "Atan2"):
    register(_n, NUMERIC, "double math")
for _n in ("Length", "Upper", "Lower", "Substring", "ConcatStr",
           "Contains", "StartsWith", "EndsWith", "Like", "Trim",
           "Reverse", "Instr", "Pad", "Repeat", "ConcatWs"):
    register(_n, STRING, "string")
for _n in ("RLike", "RegexpExtract", "RegexpReplace"):
    register(_n, STRING,
             "regex (NFA subset; others run via CPU fallback)")
register("Cast", ALL_COMMON, "cast matrix per docs/compatibility.md")
for _n in ("Sum", "Min", "Max"):
    register(_n, NUMERIC + DATETIME + BOOL + NULL, "aggregate")
for _n in ("Count", "CountStar"):
    register(_n, ALL_COMMON + NESTED, "aggregate over any type")
for _n in ("First", "Last"):
    register(_n, NUMERIC + DATETIME + BOOL + STRING + NULL,
             "aggregate; string/binary via the sort-collect path")
DEC64 = TypeSig(dt.DecimalType, note="precision <= 18 only")
for _n in ("Avg", "VarianceSamp", "StddevSamp", "Variance", "Stddev"):
    register(_n, INTEGRAL + FLOATING + DEC64 + BOOL + NULL,
             "aggregate; decimal limited to p<=18 "
             "(sum/count explicitly for p>18)")
register("Greatest", NUMERIC + DATETIME + STRING, "n-ary minmax")
register("Least", NUMERIC + DATETIME + STRING, "n-ary minmax")
for _n in ("Size", "GetArrayItem", "ElementAt", "ArrayContains",
           "SortArray", "Explode", "PosExplode", "ArrayTransform",
           "ArrayFilter", "ArrayExists", "ArrayForAll", "ArrayAggregate"):
    register(_n, TypeSig(dt.ArrayType, dt.MapType), "collection")
for _n in ("CreateArray", "CreateNamedStruct"):
    register(_n, ALL_COMMON + NESTED, "nested constructor")
register("GetStructField", TypeSig(dt.StructType), "struct extractor")
for _n in ("MapKeys", "MapValues"):
    register(_n, TypeSig(dt.MapType), "map extractor")
for _n in ("ArrayMin", "ArrayMax"):
    register(_n, TypeSig(dt.ArrayType),
             "numeric/temporal elements; decimal p<=18")
register("CountDistinct", ALL_COMMON,
         "exact distinct count via segmented sort")
register("ApproxCountDistinct", ALL_COMMON,
         "HyperLogLog++ sketch, O(2^p) state; rsd -> p in [4,12] "
         "(docs/compatibility.md: 32-bit hash, no bias table)")
for _n in ("Percentile", "Median"):
    register(_n, INTEGRAL + FLOATING,
             "exact rank selection via segmented sort")
register("ApproxPercentile", INTEGRAL + FLOATING,
         "t-digest sketch, O(C) centroid state; float64 interpolated "
         "results (docs/compatibility.md)")
for _n in ("CollectList", "CollectSet"):
    register(_n, ALL_COMMON,
             "aggregate -> array<T>; requires GROUP BY (sort-collect)")

register("BloomFilterAggregate", ALL_COMMON,
         "Bloom filter build, fixed num_bits bit-vector state "
         "(ungrouped; reference GpuBloomFilterAggregate)")
register("BloomFilterMightContain", ALL_COMMON,
         "membership probe against a foldable bloom_filter_agg result")

# -- datetime fields / arithmetic ---------------------------------------
DATE = TypeSig(dt.DateType)
TS = TypeSig(dt.TimestampType)
for _n in ("Year", "Quarter", "Month", "DayOfMonth", "DayOfWeek",
           "DayOfYear"):
    register(_n, DATETIME, "datetime field extraction")
for _n in ("Hour", "Minute", "Second"):
    register(_n, TS, "time field extraction")
for _n in ("DateAdd", "DateSub", "LastDay"):
    register(_n, DATE, "date arithmetic")
register("DateDiff", DATE, "day difference")
register("ToDate", STRING + DATE + TS,
         "string parse per format (docs/compatibility.md pattern subset)")
register("ToTimestamp", STRING + DATE + TS,
         "string parse per format (docs/compatibility.md pattern subset)")
for _n in ("FromUTCTimestamp", "ToUTCTimestamp"):
    register(_n, TS, "TZif-backed zone conversion (utils/tzdb)")

# -- JSON / URL ---------------------------------------------------------
register("GetJsonObject", STRING,
         "device byte-tape for scalar paths; wildcard paths via CPU "
         "bridge (docs/compatibility.md)")
register("FromJson", STRING,
         "schema-driven; runs via CPU bridge (host row interpreter)")
register("ToJson", ALL_COMMON + NESTED,
         "runs via CPU bridge (host row interpreter)")
register("ParseUrl", STRING,
         "runs via CPU bridge (host row interpreter)")

# -- misc ---------------------------------------------------------------
register("Murmur3Hash", ALL_COMMON,
         "Spark-compatible murmur3_x86_32, device kernel")
register("XxHash64", ALL_COMMON,
         "Spark-compatible xxhash64 (seed 42, int64), device kernel; "
         "strings exact under 32 bytes (docs/compatibility.md)")
register("HiveHash", ALL_COMMON,
         "Hive 31-polynomial hashCode (int32), device kernel")
register("Literal", ALL_COMMON + NESTED, "constant")
register("Alias", ALL_COMMON + NESTED, "name binding (pass-through)")
register("ColumnRef", ALL_COMMON + NESTED, "column reference")
register("PyUDF", ALL_COMMON,
         "AST-compiled to expressions when possible, else "
         "jax.pure_callback host evaluation (udf-compiler analog)")
register("WindowExpr", NUMERIC + DATETIME + STRING + BOOL + NULL,
         "window function over its input column; ranking functions "
         "take no input (per-function frame rules enforced at bind)")


# -- audit refinements (see AUDIT_CHECKS above) -------------------------
def _no_decimal128(path_desc: str):
    def chk(d: dt.DataType) -> Optional[str]:
        if isinstance(d, dt.DecimalType) and d.is_decimal128:
            return (f"{path_desc} reads the flat unscaled int64 buffer, "
                    f"but decimal precision > 18 travels as two-limb "
                    f"[cap, 2] pairs (ops/decimal128.py) — the result "
                    f"shape breaks downstream kernels")
        return None
    return chk


audit_register("MathUnary", _no_decimal128("the double-math path"),
               "decimal limited to precision <= 18")


def generate_supported_ops() -> str:
    lines = ["# Supported expressions (TPU)",
             "",
             "Generated from the TypeSig registry "
             "(`spark_rapids_tpu/plan/typesig.py`), the analog of the "
             "reference's docs/supported_ops.md from TypeChecks.",
             "",
             "Expression | Supported input types | Notes",
             "-----------|----------------------|------"]
    for name in sorted(SIGS):
        sig, desc = SIGS[name]
        lines.append(f"{name} | {sig.describe()} | {desc}")
    return "\n".join(lines) + "\n"
