"""Plan-time whole-stage fusion pass.

Runs after optimizer/CBO rewrites, conversion, lore-id assignment and
the static audit: greedily groups maximal chains of fusible narrow
operators (TpuExec.fusable_stage is non-None) into FusedStageExec
(exec/fused.py) — one jitted program per stage instead of one per
operator.

Fusion barriers (a chain never crosses them):
  * any operator without a pure batch transform (exchanges, shuffles,
    scans, host fallbacks, python exec, aggregates, joins, sorts —
    their fusable_stage() is None);
  * CachedScanExec bases: fusing over the HBM batch cache would break
    the aggregates' cached whole-input fast path and make buffer
    donation unsafe, so cached chains are left to the consuming
    operators' own collapse;
  * nodes the static auditor flagged `recompile_risk` — fusing them
    would multiply every recompile across the whole stage program;
  * per-node opt-out: `node.fusion_opt_out = True`.

Operators that already collapse their child chain into their own
program (aggregate update, limit clip, sort collect, join probe
pre-stage) declare `fuses_child_chain = True`; the pass leaves exactly
the prefix they will consume unfused so the same work is not wrapped
twice.
"""
from __future__ import annotations

from typing import List, Tuple

from ..analysis.audit import RECOMPILE_RISK
from ..exec.base import TpuExec
from ..exec.fused import FusedStageExec
from ..exec.nodes import CachedScanExec

__all__ = ["fuse_stages"]


def _max_lore(root: TpuExec) -> int:
    best = [0]

    def walk(n):
        lid = getattr(n, "lore_id", None)
        if isinstance(lid, int):
            best[0] = max(best[0], abs(lid))
        for m in getattr(n, "members", []) or []:
            walk(m)
        for c in n.children:
            walk(c)

    walk(root)
    return best[0]


def fuse_stages(root: TpuExec, conf,
                report=None) -> Tuple[TpuExec, List[str]]:
    """Rewrite `root`, grouping fusable chains into FusedStageExec.
    Returns (new_root, group_lines) where group_lines describe each
    group for explain("VALIDATE") / the plan_audit event."""
    from ..config import STAGE_FUSION_ENABLED, STAGE_FUSION_MAX_OPS
    if not conf.get(STAGE_FUSION_ENABLED):
        return root, []
    max_ops = max(2, int(conf.get(STAGE_FUSION_MAX_OPS)))
    risky = set()
    if report is not None:
        risky = {v.lore_id for v in report.of_kind(RECOMPILE_RISK)
                 if v.lore_id is not None}

    groups: List[FusedStageExec] = []

    def fusable(n: TpuExec) -> bool:
        return (len(n.children) == 1
                and not isinstance(n, FusedStageExec)
                and n.fusable_stage() is not None
                and not getattr(n, "fusion_opt_out", False)
                and getattr(n, "lore_id", None) not in risky)

    def walk(node: TpuExec) -> TpuExec:
        chain, cur = [], node
        while len(chain) < max_ops and fusable(cur):
            chain.append(cur)
            cur = cur.children[0]
        if len(chain) >= 2 and not isinstance(cur, CachedScanExec):
            fused = FusedStageExec(chain, walk(cur))
            groups.append(fused)
            return fused
        recurse(node)
        return node

    def recurse(node: TpuExec) -> None:
        if getattr(node, "fuses_child_chain", False) and node.children:
            # skip the prefix the operator collapses itself
            # (collapse_fusable in exec/base.py) so it is not fused twice
            ro = getattr(node, "fusion_require_ordinals", False)
            parent, cur = node, node.children[0]
            while (cur.children
                   and cur.fusable_stage() is not None
                   and not (ro and not cur.preserves_ordinals())):
                parent, cur = cur, cur.children[0]
            parent.children[0] = walk(cur)
            for i in range(1, len(node.children)):
                node.children[i] = walk(node.children[i])
        else:
            node.children = [walk(c) for c in node.children]

    new_root = walk(root)
    next_id = _max_lore(new_root)
    lines = []
    for g in groups:
        next_id += 1
        g.lore_id = next_id
        lines.append(g.describe())
    return new_root, lines
