"""Plan-time whole-stage fusion pass.

Runs after optimizer/CBO rewrites, conversion, lore-id assignment and
the static audit: greedily groups maximal chains of fusible narrow
operators (TpuExec.fusable_stage is non-None) into FusedStageExec
(exec/fused.py) — one jitted program per stage instead of one per
operator.

Fusion barriers (a chain never crosses them):
  * any operator without a pure batch transform (exchanges, shuffles,
    scans, host fallbacks, python exec, aggregates, joins, sorts —
    their fusable_stage() is None);
  * CachedScanExec bases: fusing over the HBM batch cache would break
    the aggregates' cached whole-input fast path and make buffer
    donation unsafe, so cached chains are left to the consuming
    operators' own collapse;
  * nodes the static auditor flagged `recompile_risk` — fusing them
    would multiply every recompile across the whole stage program;
  * per-node opt-out: `node.fusion_opt_out = True`.

Operators that already collapse their child chain into their own
program (aggregate update, limit clip, sort collect, join probe
pre-stage) declare `fuses_child_chain = True`; the pass leaves exactly
the prefix they will consume unfused so the same work is not wrapped
twice.
"""
from __future__ import annotations

from typing import List, Tuple

from ..analysis.audit import RECOMPILE_RISK
from ..exec.base import TpuExec
from ..exec.fused import FusedStageExec
from ..exec.nodes import CachedScanExec

__all__ = ["fuse_stages", "fuse_spmd_stages"]


def _max_lore(root: TpuExec) -> int:
    best = [0]

    def walk(n):
        lid = getattr(n, "lore_id", None)
        if isinstance(lid, int):
            best[0] = max(best[0], abs(lid))
        for m in getattr(n, "members", []) or []:
            walk(m)
        for c in n.children:
            walk(c)

    walk(root)
    return best[0]


def fuse_stages(root: TpuExec, conf,
                report=None) -> Tuple[TpuExec, List[str]]:
    """Rewrite `root`, grouping fusable chains into FusedStageExec.
    Returns (new_root, group_lines) where group_lines describe each
    group for explain("VALIDATE") / the plan_audit event."""
    from ..config import STAGE_FUSION_ENABLED, STAGE_FUSION_MAX_OPS
    if not conf.get(STAGE_FUSION_ENABLED):
        return root, []
    max_ops = max(2, int(conf.get(STAGE_FUSION_MAX_OPS)))
    risky = set()
    if report is not None:
        risky = {v.lore_id for v in report.of_kind(RECOMPILE_RISK)
                 if v.lore_id is not None}

    groups: List[FusedStageExec] = []

    def fusable(n: TpuExec) -> bool:
        return (len(n.children) == 1
                and not isinstance(n, FusedStageExec)
                and n.fusable_stage() is not None
                and not getattr(n, "fusion_opt_out", False)
                and getattr(n, "lore_id", None) not in risky)

    def walk(node: TpuExec) -> TpuExec:
        chain, cur = [], node
        while len(chain) < max_ops and fusable(cur):
            chain.append(cur)
            cur = cur.children[0]
        if len(chain) >= 2 and not isinstance(cur, CachedScanExec):
            fused = FusedStageExec(chain, walk(cur))
            groups.append(fused)
            return fused
        recurse(node)
        return node

    def recurse(node: TpuExec) -> None:
        if getattr(node, "fuses_child_chain", False) and node.children:
            # skip the prefix the operator collapses itself
            # (collapse_fusable in exec/base.py) so it is not fused twice
            ro = getattr(node, "fusion_require_ordinals", False)
            parent, cur = node, node.children[0]
            while (cur.children
                   and cur.fusable_stage() is not None
                   and not (ro and not cur.preserves_ordinals())):
                parent, cur = cur, cur.children[0]
            parent.children[0] = walk(cur)
            for i in range(1, len(node.children)):
                node.children[i] = walk(node.children[i])
        else:
            node.children = [walk(c) for c in node.children]

    new_root = walk(root)
    next_id = _max_lore(new_root)
    lines = []
    for g in groups:
        next_id += 1
        g.lore_id = next_id
        lines.append(g.describe())
    return new_root, lines


def fuse_spmd_stages(root: TpuExec, conf) -> Tuple[TpuExec, List[str]]:
    """Flip the mesh exchange from operator boundary to sharding
    annotation: group each `MeshExchangeExec` with its fusible consumer
    into a `SpmdStageExec` that runs partition ids + all_to_all +
    consumer inside ONE shard_map program (exec/spmd_stage.py).

    Runs after `fuse_stages`/`reuse_exchanges`/result-cache
    substitution so it sees the final operator tree (a filter/project
    chain over the exchange may already be one FusedStageExec — its
    composed `fusable_stage()` fuses as a single chain member).

    Patterns, matched top-down:
      * final-mode HashAggregateExec directly over a MeshExchangeExec
        (the partial→exchange→final shape `_agg` plants) -> kind "agg";
        aggregates carrying "custom" host-side state reducers
        (t-digest) cannot trace inside shard_map and are skipped;
      * a single-child fusable chain ending at a MeshExchangeExec ->
        kind "chain";
      * any remaining MeshExchangeExec (shuffled-join inputs) -> a bare
        kind "exchange" stage: one single-round collective program plus
        the staged-byte stats hook AQE's mesh rules read.

    The round-based exchange is NOT removed — it stays inside the stage
    as the bounded-memory / fault-degradation fallback."""
    from ..config import MESH_COMPRESS, MESH_DEVICES, SPMD_STAGE_ENABLED
    mesh_n = conf.get(MESH_DEVICES)
    if not conf.get(SPMD_STAGE_ENABLED) or not mesh_n or mesh_n <= 1:
        return root, []
    if conf.get(MESH_COMPRESS):
        # byte-plane shuffle compression is a feature of the STAGED
        # round-based exchange; the fused program moves shards
        # in-program where packing has nothing to act on
        return root, []
    from ..exec.aggregate import HashAggregateExec
    from ..exec.mesh_exchange import MeshExchangeExec
    from ..exec.spmd_stage import SpmdStageExec

    stages: List[SpmdStageExec] = []

    def agg_traceable(agg: HashAggregateExec) -> bool:
        # "custom" reducers merge through a host-side callback
        # (g_merge_custom) — untraceable inside shard_map
        return not any("custom" in a.state_reducers for a in agg.aggs)

    def fusable(n: TpuExec) -> bool:
        return (len(n.children) == 1
                and n.fusable_stage() is not None
                and not getattr(n, "fusion_opt_out", False))

    def walk(node: TpuExec) -> TpuExec:
        if (isinstance(node, HashAggregateExec) and node.mode == "final"
                and len(node.children) == 1
                and isinstance(node.children[0], MeshExchangeExec)
                and agg_traceable(node)):
            ex = node.children[0]
            st = SpmdStageExec(ex, consumer=node, kind="agg")
            stages.append(st)
            _walk_into(st)
            return st
        chain, cur = [], node
        while fusable(cur):
            chain.append(cur)
            cur = cur.children[0]
        if chain and isinstance(cur, MeshExchangeExec):
            st = SpmdStageExec(cur, chain=chain, kind="chain")
            stages.append(st)
            _walk_into(st)
            return st
        if isinstance(node, MeshExchangeExec):
            st = SpmdStageExec(node, kind="exchange")
            stages.append(st)
            _walk_into(st)
            return st
        node.children = [walk(c) for c in node.children]
        return node

    def _walk_into(st: "SpmdStageExec") -> None:
        # recurse into the shared map subtree, keeping the fallback
        # exchange's child pointer in sync with the wrapped tree
        st.children = [walk(c) for c in st.children]
        st.exchange.children = list(st.children)

    new_root = walk(root)
    next_id = _max_lore(new_root)
    lines = []
    for st in stages:
        next_id += 1
        st.lore_id = next_id
        lines.append(st.describe())
    return new_root, lines
