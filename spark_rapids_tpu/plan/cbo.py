"""Cost-based optimizer: join reordering + device-vs-host placement.

Two cost-based passes live here:

1. **Join reordering** (`reorder_joins`, conf
   `sql.optimizer.joinReorder.enabled`, ON by default). The analog of
   Catalyst's `CostBasedJoinReorder`: maximal chains of INNER equi-joins
   are flattened into (relations, equi-edges), each relation gets a
   row/NDV estimate from plan/stats.py, and a Selinger-style dynamic
   program over left-deep orders picks the order minimizing the sum of
   intermediate cardinalities (chains larger than
   `sql.optimizer.joinReorder.maxDpRelations` fall back to a greedy
   min-intermediate extension). Outer/semi/anti/cross joins and joins
   with non-equi conditions are never reordered across — they bound the
   chains (reordering through them would change results). Each emitted
   join places the smaller estimated side on the right (the build side),
   keeping the planner's broadcast decisions consistent with the new
   order. Deviations vs Catalyst's DP are documented in
   docs/compatibility.md.

2. **Device-vs-host placement** (`apply_cbo`, conf
   `sql.optimizer.cbo.enabled`, OFF by default like the reference's
   CostBasedOptimizer.scala + GpuCostModel): every jitted device
   dispatch costs a fixed overhead, so a TINY input is often faster
   through the host row interpreter than through XLA. Tiny
   Project/Filter nodes whose expressions the host interpreter covers
   are tagged for the CPU bridge, visible in explain ("CBO: ...")."""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from . import logical as L

__all__ = ["apply_cbo", "estimate_rows_selective", "reorder_joins"]

# rough per-conjunct selectivities (reference: spark CBO FilterEstimation)
_SEL = {"Eq": 0.05, "EqNullSafe": 0.05, "In": 0.1,
        "Lt": 0.33, "Le": 0.33, "Gt": 0.33, "Ge": 0.33,
        "Like": 0.1, "RLike": 0.1, "Contains": 0.1,
        "StartsWith": 0.1, "EndsWith": 0.1,
        "IsNull": 0.1, "IsNotNull": 0.9}


def _selectivity(e) -> float:
    name = type(e).__name__
    if name == "And":
        a, b = e.children
        return _selectivity(a) * _selectivity(b)
    if name == "Or":
        a, b = e.children
        return min(1.0, _selectivity(a) + _selectivity(b))
    if name == "Not":
        return max(0.0, 1.0 - _selectivity(e.children[0]))
    return _SEL.get(name, 0.5)


def estimate_rows_selective(node: L.LogicalPlan):
    """Row estimate WITH filter selectivities applied (the planner's
    broadcast input stays conservative/upper-bound; the CBO wants the
    expected size)."""
    from .planner import _estimate_rows
    if isinstance(node, L.Filter):
        child = estimate_rows_selective(node.children[0])
        if child is None:
            return None
        return child * _selectivity(node.condition)
    if isinstance(node, (L.Project, L.Sort, L.Repartition, L.WindowOp)):
        return estimate_rows_selective(node.children[0])
    return _estimate_rows(node)


def _host_covers(exprs) -> bool:
    from ..expr.host_eval import _RULES

    def covered(e):
        if e is None:
            return False
        if type(e).__name__ not in _RULES:
            return False
        return all(covered(c) for c in e.children if c is not None)

    return all(covered(e) for e in exprs)


def apply_cbo(meta, conf):
    """Walk the tagged PlanMeta tree; tag tiny host-coverable
    Project/Filter nodes for the CPU bridge. Mutates meta in place.
    No-op when CPU fallback is disallowed — a CBO tag must never turn
    a valid device plan into a failure."""
    from ..config import CBO_SMALL_INPUT_ROWS
    if not conf.allow_cpu_fallback:
        return
    small = conf.get(CBO_SMALL_INPUT_ROWS)
    _walk(meta, small)


def _walk(meta, small: int):
    node = meta.node
    if isinstance(node, (L.Project, L.Filter)) \
            and not meta.reasons and not meta.host_reasons:
        est = estimate_rows_selective(node.children[0])
        exprs = ([node.bound] if isinstance(node, L.Filter)
                 else list(node.exprs))
        if est is not None and est <= small and _host_covers(
                [e for e in exprs if e is not None]):
            meta.will_use_host(
                f"CBO: ~{int(est)} input rows <= {small}; host "
                f"interpreter beats device dispatch at this size")
    for c in meta.children:
        _walk(c, small)


# ======================================================================
# Cost-based join reordering
# ======================================================================

# only overrule the written join order when the modeled cost win is at
# least this decisive. Estimates are coarse (sampled NDVs, fixed filter
# selectivities) and the model is blind to broadcast-threshold and
# build-reuse effects, so marginal rewrites trade a known-good plan for
# estimate noise: on the SF1 sweep, written orders the model branded
# 8-11x worse (q7/q8/q21) actually ran FASTER than the model's pick,
# while the real stragglers (q5 at 17x, q2's subquery chain at 40x)
# model far above this bar.
_REWRITE_MIN_RATIO = 12.0

# AQE calibration only overrides the analytic model when the observed
# cardinalities say the written-order estimate was off by at least this
# factor. Measurements that CONFIRM the estimates add no information
# the static CBO lacked — re-optimizing on them just swaps a known-good
# order for cost-model noise and pays the recompile.
_CALIBRATION_ERROR_FACTOR = 8.0


class _Edge:
    """One equi-join conjunct between two relations of a flattened
    chain: unbound key expressions plus the owning relation indices."""

    __slots__ = ("a", "b", "a_key", "b_key", "sel")

    def __init__(self, a: int, b: int, a_key, b_key):
        self.a, self.b = a, b
        self.a_key, self.b_key = a_key, b_key
        self.sel = 1.0          # filled in once stats are known


def _is_passthrough(project: L.Project) -> bool:
    """True when every output is a plain same-named column reference —
    the shape session.join emits above each inner join (key dedup /
    __join_r* drop). Flattening through it is safe: it neither renames
    nor computes, only selects."""
    from ..expr.expressions import ColumnRef
    return all(type(e) is ColumnRef for e in project.exprs)


def _reorderable_join(node: L.LogicalPlan) -> bool:
    return (isinstance(node, L.Join) and node.how == "inner"
            and node.condition is None and bool(node.left_keys))


def _flatten_chain(root: L.Join):
    """Flatten a maximal inner-equi-join chain (seeing through the
    pass-through projections between joins) into relations + edges.
    Returns (relations, edges) or None when the chain is not safely
    flattenable (ambiguous key ownership, duplicate column names)."""
    from .optimizer import refs_of
    relations: List[L.LogicalPlan] = []
    edges: List[_Edge] = []

    def owner(refs, idxs) -> Optional[int]:
        hit = None
        for i in idxs:
            if refs <= set(relations[i].schema.names):
                if hit is not None:
                    return None          # ambiguous (duplicate names)
                hit = i
        return hit

    def rec(node) -> Optional[List[int]]:
        if isinstance(node, L.Project) and _is_passthrough(node) \
                and _reorderable_join(node.children[0]):
            return rec(node.children[0])
        if _reorderable_join(node):
            li = rec(node.left)
            ri = rec(node.right)
            if li is None or ri is None:
                return None
            for lk, rk in zip(node.left_keys, node.right_keys):
                lrefs, rrefs = refs_of(lk), refs_of(rk)
                if not lrefs or not rrefs:
                    return None
                a = owner(lrefs, li)
                b = owner(rrefs, ri)
                if a is None or b is None:
                    return None
                edges.append(_Edge(a, b, lk, rk))
            return li + ri
        relations.append(node)
        return [len(relations) - 1]

    if rec(root) is None or len(relations) < 3:
        return None
    # global name uniqueness: rebinding keys by name over a rebuilt
    # chain is only sound when no two relations share a column name
    seen = set()
    for r in relations:
        for n in r.schema.names:
            if n in seen:
                return None
            seen.add(n)
    return relations, edges


def _edge_selectivities(edges: List[_Edge], stats) -> None:
    for e in edges:
        from .stats import _key_name
        an, bn = _key_name(e.a_key), _key_name(e.b_key)
        ndv_a = (stats[e.a].ndv_of(an) if an else None) or stats[e.a].rows
        ndv_b = (stats[e.b].ndv_of(bn) if bn else None) or stats[e.b].rows
        e.sel = 1.0 / max(ndv_a, ndv_b, 1.0)


def _set_rows(s: frozenset, stats, edges, cal=None) -> float:
    """Order-independent cardinality of a joined relation set: product
    of relation rows times the selectivity of every internal edge.
    `cal` (subset -> observed rows or None) overrides the analytic
    product with a cardinality an earlier execution of the same
    relation set actually measured — the AQE calibration loop."""
    if cal is not None:
        observed = cal(s)
        if observed is not None:
            return observed
    rows = 1.0
    for i in s:
        rows *= stats[i].rows
    for e in edges:
        if e.a in s and e.b in s:
            rows *= e.sel
    return rows


def _dp_order(n: int, stats, edges, cal=None) -> List[int]:
    """Selinger-style DP over left-deep orders: best (cost, order) per
    relation subset; extensions must stay connected (no cross products
    unless the chain itself is disconnected, which cannot happen — every
    flattened join contributed an edge). Cost = Σ intermediate rows plus
    the build-side rows of each step."""
    adj: Dict[int, set] = {i: set() for i in range(n)}
    for e in edges:
        adj[e.a].add(e.b)
        adj[e.b].add(e.a)
    best: Dict[frozenset, Tuple[float, List[int]]] = {
        frozenset({i}): (0.0, [i]) for i in range(n)}
    for size in range(2, n + 1):
        nxt: Dict[frozenset, Tuple[float, List[int]]] = {}
        for s, (cost, order) in best.items():
            if len(s) != size - 1:
                continue
            for j in range(n):
                if j in s or not (adj[j] & s):
                    continue
                s2 = frozenset(s | {j})
                rows = _set_rows(s2, stats, edges, cal)
                c2 = cost + _step_cost(_set_rows(s, stats, edges, cal),
                                       rows, stats[j].rows)
                cur = nxt.get(s2)
                if cur is None or c2 < cur[0]:
                    nxt[s2] = (c2, order + [j])
        best.update(nxt)
    full = frozenset(range(n))
    return best[full][1] if full in best else list(range(n))


def _step_cost(prev_rows: float, out_rows: float, rel_rows: float) -> float:
    """Cost of one left-deep extension: the build side is materialized
    once (min side), and the step streams max(probe input, output) rows.
    Charging the PROBE input — not just the output — matters: fact-table
    spines with FK single-match joins stream rows through in place
    (output <= input, near-free per probe), and a model that only counts
    output cardinality wrongly brands those written orders catastrophic
    (q7/q8's written orders looked 8-11x worse than 'optimal' yet ran
    2-5x faster than the model's pick)."""
    return max(prev_rows, out_rows) + min(rel_rows, prev_rows)


def _order_cost(order: List[int], stats, edges, cal=None) -> float:
    """Cost of one left-deep order under the DP's model (Σ _step_cost).
    Used both to rank candidate orders and to cost the WRITTEN order
    for the rewrite gate."""
    cost = 0.0
    s = {order[0]}
    for j in order[1:]:
        prev_rows = _set_rows(frozenset(s), stats, edges, cal)
        s.add(j)
        rows = _set_rows(frozenset(s), stats, edges, cal)
        cost += _step_cost(prev_rows, rows, stats[j].rows)
    return cost


def _greedy_order(n: int, stats, edges, cal=None) -> List[int]:
    """Beyond the DP bound: start from the smallest relation and
    repeatedly add the connected relation minimizing the intermediate
    cardinality."""
    adj: Dict[int, set] = {i: set() for i in range(n)}
    for e in edges:
        adj[e.a].add(e.b)
        adj[e.b].add(e.a)
    start = min(range(n), key=lambda i: stats[i].rows)
    order = [start]
    done = {start}
    while len(order) < n:
        cands = {j for i in done for j in adj[i]} - done
        if not cands:
            cands = set(range(n)) - done
        j = min(cands, key=lambda j_: _set_rows(
            frozenset(done | {j_}), stats, edges, cal))
        order.append(j)
        done.add(j)
    return order


def _contains_agg(node: L.LogicalPlan) -> bool:
    if isinstance(node, L.Aggregate):
        return True
    return any(_contains_agg(c) for c in node.children)


def _rebuild_chain(relations, edges, order, stats,
                   cal=None) -> L.LogicalPlan:
    """Left-deep rebuild in the chosen order; each step puts the smaller
    estimated side on the RIGHT so the planner's build/broadcast choice
    (right child) stays consistent with the reorder.

    Exception: a relation whose subtree holds an Aggregate is kept off
    the STREAM SPINE (the leftmost path the executor re-runs on every
    plan execution). Build sides are materialized once and cached
    across re-executions, while the stream spine re-runs every time —
    streaming an aggregate re-pays the whole aggregation per run (the
    q21 shape: two per-order count-distinct subtrees streamed instead
    of built cost 3s of the 3.5s regression)."""
    cur = relations[order[0]]
    cur_set = {order[0]}
    cur_rows = stats[order[0]].rows
    # does the current stream spine (leftmost leaf path) hold an agg?
    spine_agg = _contains_agg(cur)
    for j in order[1:]:
        cur_keys, rel_keys = [], []
        for e in edges:
            if e.a in cur_set and e.b == j:
                cur_keys.append(e.a_key)
                rel_keys.append(e.b_key)
            elif e.b in cur_set and e.a == j:
                cur_keys.append(e.b_key)
                rel_keys.append(e.a_key)
        rel = relations[j]
        rel_rows = stats[j].rows
        rel_agg = _contains_agg(rel)
        if not cur_keys:
            # disconnected extension (cannot normally happen): keep a
            # cross join so semantics are preserved
            cur = L.Join(cur, rel, [], [], "cross")
        elif rel_agg:
            # agg relation builds; the spine stays whatever cur's was
            cur = L.Join(cur, rel, cur_keys, rel_keys, "inner")
        elif spine_agg:
            # evict the agg from the spine: the accumulated chain
            # (agg included) becomes a cached build, rel the new spine
            cur = L.Join(rel, cur, rel_keys, cur_keys, "inner")
            spine_agg = False
        elif rel_rows <= cur_rows:
            cur = L.Join(cur, rel, cur_keys, rel_keys, "inner")
        else:
            cur = L.Join(rel, cur, rel_keys, cur_keys, "inner")
        cur_set.add(j)
        out_set = frozenset(cur_set)
        cur_rows = _set_rows(out_set, stats, edges, cal)
    return cur


def reorder_joins(plan: L.LogicalPlan, conf) -> L.LogicalPlan:
    """Reorder maximal inner-equi-join chains by estimated cost. Only
    rewrites when every relation in a chain has a row estimate; the
    original column order is restored with a projection so the rewrite
    is invisible to everything above it."""
    from ..config import JOIN_REORDER_DP_RELATIONS
    from ..expr.expressions import ColumnRef
    from .optimizer import _rebuild
    from .stats import compute_stats
    max_dp = conf.get(JOIN_REORDER_DP_RELATIONS)

    def rewrite(node):
        if _reorderable_join(node):
            flat = _flatten_chain(node)
            if flat is not None:
                relations, edges = flat
                relations = [rewrite(r) for r in relations]
                stats = [compute_stats(r) for r in relations]
                if all(s.rows is not None for s in stats):
                    _edge_selectivities(edges, stats)
                    n = len(relations)
                    # AQE calibration: price a relation subset by the
                    # cardinality an earlier order of the same set
                    # actually produced (order-independent jset keys,
                    # plan/stats.py), falling back to the analytic
                    # product when nothing was observed
                    from .stats import calibration_lookup, logical_fp
                    rel_fps = [logical_fp(r) for r in relations]

                    def cal(s, _fps=rel_fps):
                        if len(s) < 2:
                            return None
                        return calibration_lookup(
                            ("jset", frozenset(_fps[i] for i in s)))
                    # re-optimize from observations only on DECISIVE
                    # estimate error: when the measured cardinalities
                    # roughly confirm the analytic model, plan exactly
                    # as the static CBO would — the row model is too
                    # coarse to overrule a known-good order on marginal
                    # differences, and the churned plan pays recompiles
                    # for it (q5 steady state regressed ~2x when
                    # accurate estimates were "re-optimized")
                    idorder = list(range(n))
                    written_static = _order_cost(idorder, stats, edges,
                                                 None)
                    written_cal = _order_cost(idorder, stats, edges,
                                              cal)
                    use_cal = (written_cal > 0 and written_static > 0
                               and max(written_static / written_cal,
                                       written_cal / written_static)
                               >= _CALIBRATION_ERROR_FACTOR)
                    c = cal if use_cal else None
                    order = (_dp_order(n, stats, edges, c)
                             if n <= max_dp
                             else _greedy_order(n, stats, edges, c))
                    # conservative gate: estimates are coarse (sampled
                    # NDVs, fixed filter selectivities), so only
                    # overrule the written order when the modeled win
                    # is DECISIVE — marginal rewrites trade a known-good
                    # plan for estimate noise (q7/q8/q9 regressed 2-5x
                    # on sub-2x modeled wins; q5's straggler order is
                    # modeled >10x worse than optimal)
                    written = written_cal if use_cal else written_static
                    best = _order_cost(order, stats, edges, c)
                    if best * _REWRITE_MIN_RATIO <= written:
                        joined = _rebuild_chain(relations, edges, order,
                                                stats, cal)
                        # restore the original output schema (names +
                        # order)
                        return L.Project(joined,
                                         [ColumnRef(nm) for nm in
                                          node.schema.names])
        kids = [rewrite(c) for c in node.children]
        return _rebuild(node, kids)

    return rewrite(plan)
