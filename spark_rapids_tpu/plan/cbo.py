"""Cost-based optimizer: device-vs-host placement from row estimates.

Analog of the reference's CostBasedOptimizer.scala + GpuCostModel: the
reference's CBO estimates operator cost and keeps a plan section on CPU
when moving it to the GPU wouldn't pay for the row<->columnar
transitions. The TPU translation: every jitted device dispatch costs a
fixed overhead (trace/compile amortized, but dispatch + H2D/D2H for
tiny batches is microseconds-to-milliseconds), so a TINY input is
often faster through the host row interpreter than through XLA. When
`sql.optimizer.cbo.enabled` is on, Project/Filter nodes whose
estimated input is below `sql.optimizer.cbo.smallInputRows` AND whose
expressions the host interpreter covers are tagged for the CPU bridge,
with the decision visible in explain ("CBO: ...").

Like the reference, the CBO defaults OFF — estimates are coarse and the
device path is correct regardless; this is a latency tune for
tiny-table workloads."""
from __future__ import annotations

from . import logical as L

__all__ = ["apply_cbo", "estimate_rows_selective"]

# rough per-conjunct selectivities (reference: spark CBO FilterEstimation)
_SEL = {"Eq": 0.05, "EqNullSafe": 0.05, "In": 0.1,
        "Lt": 0.33, "Le": 0.33, "Gt": 0.33, "Ge": 0.33,
        "Like": 0.1, "RLike": 0.1, "Contains": 0.1,
        "StartsWith": 0.1, "EndsWith": 0.1,
        "IsNull": 0.1, "IsNotNull": 0.9}


def _selectivity(e) -> float:
    name = type(e).__name__
    if name == "And":
        a, b = e.children
        return _selectivity(a) * _selectivity(b)
    if name == "Or":
        a, b = e.children
        return min(1.0, _selectivity(a) + _selectivity(b))
    if name == "Not":
        return max(0.0, 1.0 - _selectivity(e.children[0]))
    return _SEL.get(name, 0.5)


def estimate_rows_selective(node: L.LogicalPlan):
    """Row estimate WITH filter selectivities applied (the planner's
    broadcast input stays conservative/upper-bound; the CBO wants the
    expected size)."""
    from .planner import _estimate_rows
    if isinstance(node, L.Filter):
        child = estimate_rows_selective(node.children[0])
        if child is None:
            return None
        return child * _selectivity(node.condition)
    if isinstance(node, (L.Project, L.Sort, L.Repartition, L.WindowOp)):
        return estimate_rows_selective(node.children[0])
    return _estimate_rows(node)


def _host_covers(exprs) -> bool:
    from ..expr.host_eval import _RULES

    def covered(e):
        if e is None:
            return False
        if type(e).__name__ not in _RULES:
            return False
        return all(covered(c) for c in e.children if c is not None)

    return all(covered(e) for e in exprs)


def apply_cbo(meta, conf):
    """Walk the tagged PlanMeta tree; tag tiny host-coverable
    Project/Filter nodes for the CPU bridge. Mutates meta in place.
    No-op when CPU fallback is disallowed — a CBO tag must never turn
    a valid device plan into a failure."""
    from ..config import CBO_SMALL_INPUT_ROWS
    if not conf.allow_cpu_fallback:
        return
    small = conf.get(CBO_SMALL_INPUT_ROWS)
    _walk(meta, small)


def _walk(meta, small: int):
    node = meta.node
    if isinstance(node, (L.Project, L.Filter)) \
            and not meta.reasons and not meta.host_reasons:
        est = estimate_rows_selective(node.children[0])
        exprs = ([node.bound] if isinstance(node, L.Filter)
                 else list(node.exprs))
        if est is not None and est <= small and _host_covers(
                [e for e in exprs if e is not None]):
            meta.will_use_host(
                f"CBO: ~{int(est)} input rows <= {small}; host "
                f"interpreter beats device dispatch at this size")
    for c in meta.children:
        _walk(c, small)
