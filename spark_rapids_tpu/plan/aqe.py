"""Adaptive query execution driver: replan at exchange boundaries.

The control-loop half of AQE (reference: Spark's AdaptiveSparkPlanExec
driving QueryStage materialization + GpuCustomShuffleReaderExec /
OptimizeSkewedJoin / DemoteBroadcastHashJoin in reverse). The reader
half — `exec/aqe.py` — computes coalesced/split task groups lazily from
materialized partition stats; this module makes execution STAGE-WISE:
before the consumer launches, the driver walks the physical plan
bottom-up, materializes each shuffle stage via the existing exchange
pool, and replans between stage completion and consumer launch:

  1. JOIN DEMOTION: a shuffled hash join whose build side materializes
     under `autoBroadcastJoinThreshold` is rewritten in place to a
     broadcast hash join over the already-shuffled build blocks — the
     stream-side map phase never runs (the biggest single win: q2/q16
     shapes where the CBO overestimates a filtered build side).
  2. COALESCE + SKEW-SPLIT: the per-plan task groups (AqeShufflePlan)
     are forced eagerly so every decision is taken — and logged — at a
     stage boundary rather than on first read.

Every decision is an `aqe_replan` event-log record (lore ids old→new)
and feeds the EXPLAIN ANALYZE annotations. The driver runs on the
query's own thread under the service's cancellation checkpoints
(`ctx.check_cancel` before every stage barrier) and takes no locks of
its own — stage materialization happens under each exchange's existing
lockdep-witnessed instance lock, never under a planner-wide lock.

Observed cardinalities harvested after the run (plan/stats.py
`harvest_calibration`) close the loop: the session-scoped calibration
table corrects CBO estimates for later plans of the same subtrees.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List

__all__ = ["run_stage_driver", "aqe_stats", "reset_stats"]

# session-process AQE decision counters (bench --smoke `extra.aqe`)
_STATS_LOCK = threading.Lock()
_STATS = {"coalesced_partitions": 0, "skew_splits": 0, "demotions": 0,
          "mesh_reshards": 0, "mesh_demotions": 0}


def aqe_stats() -> Dict[str, int]:
    """Process-lifetime AQE decision counters, merged with the
    calibration table's counters (bench --smoke records these)."""
    with _STATS_LOCK:
        out: Dict[str, int] = dict(_STATS)
    from .stats import calibration_stats
    out.update(calibration_stats())
    return out


def reset_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


def _bump(key: str, amount: int = 1) -> None:
    if amount:
        with _STATS_LOCK:
            _STATS[key] += amount


def _max_lore_id(root) -> int:
    mx = 0
    stack, seen = [root], set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        lid = getattr(node, "lore_id", None)
        if isinstance(lid, int):
            mx = max(mx, lid)
        stack.extend(node.children)
    return mx


def run_stage_driver(root, ctx, conf) -> List[Dict[str, Any]]:
    """Stage-wise AQE pass over a physical plan, between planning and
    the consumer launch. Returns the decision records for the
    `aqe_replan` event (re-served verbatim on re-execution of a cached
    root, so every run's event log is self-contained). Mutations are
    in-place and sticky — the same properties the exchange
    memoization already relies on."""
    from ..config import ADAPTIVE_ENABLED
    if not conf.get(ADAPTIVE_ENABLED) or getattr(ctx, "planning", False):
        return []
    from ..exec.aqe import AQEShuffleReadExec
    from ..exec.join import HashJoinExec
    from ..exec.spmd_stage import SpmdStageExec

    decisions: List[Dict[str, Any]] = []
    seen_plans: set = set()
    lore_alloc = [0]  # lazily seeded from the tree's max lore id

    def visit(node):
        ctx.check_cancel()
        if isinstance(node, HashJoinExec):
            # demotion must be judged BEFORE the stream subtree is
            # visited: forcing the stream reader's groups would run the
            # very map phase demotion exists to skip
            _maybe_demote(node, ctx, conf, decisions, lore_alloc, root)
            _maybe_demote_mesh(node, ctx, conf, decisions, lore_alloc,
                               root)
        for c in list(node.children):
            visit(c)
        if isinstance(node, SpmdStageExec):
            # mesh analog of partition coalescing: exact staged bytes
            # shrink the active mesh axis for small stages (the
            # decision logic lives on the stage, which owns the stats)
            from ..profiler import tracing
            with tracing.span("aqe.reshard", "aqe", ctx):
                d = node.plan_reshard(ctx, conf)
            if d is not None:
                decisions.append(d)
                if not getattr(node, "_reshard_counted", False):
                    node._reshard_counted = True
                    _bump("mesh_reshards")
        if isinstance(node, AQEShuffleReadExec):
            # stage barrier: materialize (exchange pool) + replan
            from ..profiler import tracing
            with tracing.span("aqe.stage_materialize", "aqe", ctx,
                              lore_id=getattr(node, "lore_id", None)):
                node.plan.groups(ctx)
            d = node.plan.decision
            if d is not None and id(node.plan) not in seen_plans:
                seen_plans.add(id(node.plan))
                decisions.append(d)
                if not getattr(node.plan, "_stats_counted", False):
                    node.plan._stats_counted = True
                    _bump("coalesced_partitions",
                          int(d.get("coalesced_away", 0)))
                    _bump("skew_splits", int(d.get("split_slices", 0)))

    visit(root)
    return decisions


def _maybe_demote(join, ctx, conf, decisions, lore_alloc, root) -> None:
    """Shuffled-hash-join → broadcast-join demotion at the build-side
    stage boundary (reference: Spark's DemoteBroadcastHashJoin /
    OptimizeLocalShuffleReader family, inverted: we PROMOTE to
    broadcast when runtime stats beat the estimate). The build
    exchange's materialized blocks become the broadcast child; the
    stream side drops its exchange entirely and reads the pre-shuffle
    subtree, so the stream map phase is skipped."""
    from ..config import ADAPTIVE_DEMOTE_ENABLED, BROADCAST_THRESHOLD
    prev = getattr(join, "_aqe_demoted", None)
    if prev is not None:
        decisions.append(prev)
        return
    thr = conf.get(BROADCAST_THRESHOLD)
    if not (conf.get(ADAPTIVE_DEMOTE_ENABLED) and thr >= 0
            and join.per_partition):
        return
    from ..exec.aqe import AQEShuffleReadExec
    from ..exec.exchange import ShuffleExchangeExec
    stream, build = join.children
    if not isinstance(stream, AQEShuffleReadExec) \
            or not isinstance(build, AQEShuffleReadExec):
        return
    sex = stream.children[0]
    # only a plain, not-yet-materialized stream exchange can be
    # skipped: a ReusedExchange has no children to unwrap (the shared
    # subtree belongs to its first occurrence), and a map phase that
    # already ran has nothing left to save
    if not isinstance(sex, ShuffleExchangeExec) or not sex.children \
            or sex._shuffle is not None:
        return
    bex = build.children[0]        # ShuffleExchangeExec or ReusedExchange
    if not hasattr(bex, "stage_stats"):
        return
    ctx.check_cancel()
    # stage barrier: the build map phase materializes NOW (under the
    # exchange's own lock, via the exchange pool) and reports exact
    # serialized bytes — the runtime stat the planning estimate missed
    from ..profiler import tracing
    with tracing.span("aqe.demote_build_materialize", "aqe", ctx):
        build_bytes = int(sum(bex.stage_stats(ctx)))
    if build_bytes > thr:
        return
    from ..exec.broadcast import BroadcastExchangeExec
    bcast = BroadcastExchangeExec(bex, bex.schema)
    if not lore_alloc[0]:
        lore_alloc[0] = _max_lore_id(root)
    lore_alloc[0] += 1
    bcast.lore_id = lore_alloc[0]
    old_lores = [getattr(n, "lore_id", None) for n in (stream, sex, build)]
    join.children = [sex.children[0], bcast]
    join.per_partition = False
    d = {"rule": "demote_broadcast_join",
         "join_lore": getattr(join, "lore_id", None),
         "old_lores": old_lores, "new_lores": [bcast.lore_id],
         "build_bytes": build_bytes, "threshold": int(thr)}
    join._aqe_demoted = d
    ctx.metrics_for(join._op_id).set("aqeDemotedBuildBytes", build_bytes)
    decisions.append(d)
    _bump("demotions")


def _maybe_demote_mesh(join, ctx, conf, decisions, lore_alloc,
                       root) -> None:
    """The mesh-path twin of `_maybe_demote`: a shuffled hash join whose
    inputs are bare SpmdStageExec exchange stages. The build stage is
    materialized to its STAGED handles only (map side runs, collective
    does not); when the exact staged bytes fit the broadcast threshold,
    the build side broadcasts straight from those handles and the
    stream side drops its stage entirely — NEITHER side's collective
    program runs."""
    from ..config import ADAPTIVE_DEMOTE_ENABLED, BROADCAST_THRESHOLD
    prev = getattr(join, "_aqe_mesh_demoted", None)
    if prev is not None:
        decisions.append(prev)
        return
    thr = conf.get(BROADCAST_THRESHOLD)
    if not (conf.get(ADAPTIVE_DEMOTE_ENABLED) and thr >= 0
            and join.per_partition):
        return
    from ..exec.spmd_stage import SpmdStageExec
    stream, build = join.children
    if not (isinstance(stream, SpmdStageExec)
            and isinstance(build, SpmdStageExec)
            and stream.kind == "exchange" and build.kind == "exchange"):
        return
    # only a cold stream stage can be skipped: once staged or degraded,
    # its map phase already ran and there is nothing left to save
    if stream._staged is not None or stream._degraded \
            or build._degraded or not stream.children:
        return
    ctx.check_cancel()
    # stage barrier: the build map phase drains into spill handles NOW
    # and reports exact device bytes (the mesh MapOutputStatistics)
    from ..profiler import tracing
    with tracing.span("aqe.demote_mesh_materialize", "aqe", ctx):
        build_bytes = int(build.stage_bytes(ctx))
    if build_bytes > thr:
        return
    from ..exec.broadcast import BroadcastExchangeExec
    src = build.staged_source(own=True)
    bcast = BroadcastExchangeExec(src, src.schema)
    if not lore_alloc[0]:
        lore_alloc[0] = _max_lore_id(root)
    lore_alloc[0] += 1
    bcast.lore_id = lore_alloc[0]
    old_lores = [getattr(n, "lore_id", None) for n in (stream, build)]
    join.children = [stream.children[0], bcast]
    join.per_partition = False
    d = {"rule": "demote_broadcast_join", "mesh": True,
         "join_lore": getattr(join, "lore_id", None),
         "old_lores": old_lores, "new_lores": [bcast.lore_id],
         "build_bytes": build_bytes, "threshold": int(thr)}
    join._aqe_mesh_demoted = d
    ctx.metrics_for(join._op_id).set("aqeDemotedBuildBytes", build_bytes)
    decisions.append(d)
    _bump("mesh_demotions")
