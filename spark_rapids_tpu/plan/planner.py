"""Planner: logical plan -> TPU physical plan with tagging + explain.

The GpuOverrides analog (reference: GpuOverrides.scala:5017-5191 apply path;
RapidsMeta.scala:87 tagging). Flow: wrap each logical node in a PlanMeta,
tag it (record `willNotWorkOnTpu` reasons), then convert — per-node
replacement rules live in `_RULES`, keyed by logical node class, mirroring
the reference's `execs` map (GpuOverrides.scala:4801).

Round-1 fallback policy: a node whose expressions cannot run on TPU raises
at conversion with the collected reasons (transparent CPU fallback execs
arrive with the host expression interpreter).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Type

from ..config import TpuConf, EXPLAIN
from ..exec import aggregate as agg_exec
from ..exec import nodes as x
from ..exec.base import TpuExec
from ..expr.expressions import UnsupportedExpr
from . import logical as L

__all__ = ["Planner", "PlanMeta", "plan_query"]


class PlanMeta:
    """Wrapper recording per-node TPU support (RapidsMeta analog).

    Three states per node: runs on TPU (*), runs on the HOST CPU via the
    fallback interpreter (!cpu, query still succeeds), or cannot run at
    all (!, query fails at convert)."""

    def __init__(self, node: L.LogicalPlan):
        self.node = node
        self.children = [PlanMeta(c) for c in node.children]
        self.reasons: List[str] = []
        self.host_reasons: List[str] = []
        # the physical subtree this node converted to (set by _convert);
        # its lore_id surfaces in explain so a hot operator in a profile
        # report maps directly to a lore.idsToDump replay id
        self.exec_node: Optional[TpuExec] = None

    def will_not_work(self, reason: str):
        self.reasons.append(reason)

    def will_use_host(self, reason: str):
        self.host_reasons.append(reason)

    @property
    def can_run_on_tpu(self) -> bool:
        return not self.reasons

    def explain_lines(self, only_not_on_tpu: bool, indent=0) -> List[str]:
        lines = []
        tag = ("!cpu" if self.host_reasons and not self.reasons
               else "*" if self.can_run_on_tpu else "!")
        lore = getattr(self.exec_node, "lore_id", None)
        lore_tag = f" [loreId={lore}]" if lore is not None else ""
        desc = f"{'  ' * indent}{tag}{lore_tag} {self.node.describe()}"
        if self.reasons:
            desc += "  <-- cannot run on TPU because " + "; ".join(
                self.reasons)
        elif self.host_reasons:
            desc += ("  <-- will run on CPU because "
                     + "; ".join(self.host_reasons))
        if not only_not_on_tpu or self.reasons or self.host_reasons:
            lines.append(desc)
        for c in self.children:
            lines.extend(c.explain_lines(only_not_on_tpu, indent + 1))
        return lines


_RULES: Dict[Type[L.LogicalPlan], Callable] = {}


def _rule(cls):
    def deco(fn):
        _RULES[cls] = fn
        return fn
    return deco


@_rule(L.InMemoryScan)
def _scan(meta: PlanMeta, conv, conf) -> TpuExec:
    return x.InMemoryScanExec(meta.node.arrow, meta.node.schema)


@_rule(L.CachedScan)
def _cached(meta, conv, conf):
    from ..exec.nodes import CachedScanExec
    return CachedScanExec(meta.node.batches, meta.node.schema)


@_rule(L.ParquetScan)
def _pq(meta, conv, conf):
    from ..config import BATCH_SIZE_ROWS
    from ..exec.coalesce import CoalesceBatchesExec
    n = meta.node
    scan = x.ParquetScanExec(n.paths, n.schema, n.columns,
                             filters=n.filters,
                             dv=getattr(n, "dv", None),
                             snapshot=getattr(n, "snapshot", None),
                             delta_version=getattr(n, "delta_version",
                                                   None))
    if len(n.paths) > 1:
        # many-small-files: coalesce toward the batch target
        # (GpuCoalesceBatches after scans, GpuTransitionOverrides.scala:77);
        # fan-in sized from the first file's row count (footer metadata)
        import pyarrow.parquet as pq
        try:
            counts = [pq.ParquetFile(p).metadata.num_rows
                      for p in n.paths]
            avg = sum(counts) // max(len(counts), 1)
        except Exception:
            avg = 0
        target = conf.get(BATCH_SIZE_ROWS)
        if 0 < avg < target // 2:
            fan_in = min(max(1, target // max(avg, 1)), len(n.paths))
            return CoalesceBatchesExec(scan, target, fan_in)
    return scan


@_rule(L.TextScan)
def _textscan(meta, conv, conf):
    from ..exec.text_scan import (AvroScanExec, CsvScanExec,
                                  JsonScanExec, OrcScanExec)
    n = meta.node
    cls = {"csv": CsvScanExec, "json": JsonScanExec,
           "orc": OrcScanExec, "avro": AvroScanExec}[n.fmt]
    return cls(n.paths, n._full_schema, n.columns, n.options)


@_rule(L.Project)
def _project(meta, conv, conf):
    child = conv(meta.children[0])
    n = meta.node
    if any(b is None for b in n.bound) or meta.host_reasons:
        # _tag already copied bind errors into host_reasons; dedupe
        reason = "; ".join(dict.fromkeys(
            [e for e in n.bind_errors if e] + meta.host_reasons))
        if not conf.allow_cpu_fallback:
            raise UnsupportedExpr(reason)
        from ..exec.host_fallback import HostProjectExec
        return HostProjectExec(child, n.exprs, n.schema, reason)
    return x.ProjectExec(child, n.bound, n.schema)


@_rule(L.Filter)
def _filter(meta, conv, conf):
    child = conv(meta.children[0])
    n = meta.node
    if n.bound is None or meta.host_reasons:
        reason = "; ".join(dict.fromkeys(
            ([n.bind_error] if n.bind_error else [])
            + meta.host_reasons))
        if not conf.allow_cpu_fallback:
            raise UnsupportedExpr(reason)
        from ..exec.host_fallback import HostFilterExec
        return HostFilterExec(child, n.condition, reason)
    return x.FilterExec(child, n.bound)


def _aqe_wrap(exchange, conf, allow_split=False, plan=None,
              role="stream"):
    """Wrap a file-shuffle exchange with an adaptive reader when enabled
    (GpuCustomShuffleReaderExec analog). Mesh exchanges re-plan at trace
    time instead, so they pass through."""
    from ..config import (ADAPTIVE_COALESCE_ENABLED, ADAPTIVE_ENABLED,
                          ADAPTIVE_SKEW_ENABLED, ADAPTIVE_SKEW_FACTOR,
                          ADAPTIVE_SKEW_MIN_BYTES, ADAPTIVE_TARGET_BYTES)
    from ..exec.exchange import ShuffleExchangeExec
    if not conf.get(ADAPTIVE_ENABLED) or \
            not isinstance(exchange, ShuffleExchangeExec):
        return exchange, None
    from ..exec.aqe import AqeShufflePlan, AQEShuffleReadExec
    if plan is None:
        plan = AqeShufflePlan([exchange],
                              conf.get(ADAPTIVE_TARGET_BYTES),
                              conf.get(ADAPTIVE_SKEW_FACTOR),
                              conf.get(ADAPTIVE_SKEW_MIN_BYTES),
                              allow_split
                              and conf.get(ADAPTIVE_SKEW_ENABLED),
                              allow_coalesce=conf.get(
                                  ADAPTIVE_COALESCE_ENABLED))
    else:
        plan.exchanges.append(exchange)
    return AQEShuffleReadExec(exchange, plan, role), plan


def _make_hash_exchange(child, bound_keys, conf):
    """Choose the exchange transport: mesh collective (all_to_all over
    ICI when spark.rapids.tpu.mesh.devices > 0) or the host file shuffle
    (the reference's UCX vs MULTITHREADED mode split,
    RapidsConf.scala:2216-2230)."""
    from ..config import MESH_DEVICES, SHUFFLE_PARTITIONS
    mesh_n = conf.get(MESH_DEVICES)
    if mesh_n and mesh_n > 1:
        from ..exec.mesh_exchange import MeshExchangeExec
        return MeshExchangeExec(child, mesh_n, bound_keys, child.schema)
    from ..exec.exchange import ShuffleExchangeExec
    return ShuffleExchangeExec(child, conf.get(SHUFFLE_PARTITIONS),
                               bound_keys, child.schema)


@_rule(L.Expand)
def _expand(meta, conv, conf):
    from ..exec.expand import ExpandExec
    n = meta.node
    return ExpandExec(conv(meta.children[0]), n.bound_keys,
                      n.include_masks, n.schema)


@_rule(L.Aggregate)
def _agg(meta, conv, conf):
    from ..config import MESH_DEVICES, SHUFFLE_PARTITIONS
    child = conv(meta.children[0])
    n = meta.node
    names = [nm for nm, _ in n.bound_aggs]
    aggs = [a for _, a in n.bound_aggs]
    for k in n.bound_keys:
        if k.dtype.is_nested:
            raise UnsupportedExpr(
                f"group-by key {k!r} has nested type {k.dtype}")
    has_collect = any(getattr(a, "is_collect", False) for a in aggs)
    if not n.keys:
        if has_collect:
            # ungrouped sort-path aggregates (count distinct, median,
            # collect_*): single-segment CollectAggExec
            return agg_exec.CollectAggExec(child, [], [], names, aggs,
                                           n.schema)
        return agg_exec.UngroupedAggExec(child, names, aggs, n.schema)
    key_names = [k.name for k in n.keys]
    if has_collect:
        # variable-width results can't ride the partial/final flat-state
        # wire: hash-exchange the raw rows on the grouping keys, then each
        # partition's sort-collect is final (disjoint keys)
        from ..exec.base import ExecContext as _Ctx
        nparts_c = conf.get(SHUFFLE_PARTITIONS)
        if child.num_partitions(_Ctx(conf, planning=True)) > 1 \
                and nparts_c > 1:
            exch = _make_hash_exchange(child, n.bound_keys, conf)
            exch, _ = _aqe_wrap(exch, conf, allow_split=False)
            return agg_exec.CollectAggExec(exch, key_names, n.bound_keys,
                                           names, aggs, n.schema,
                                           per_partition=True)
        return agg_exec.CollectAggExec(child, key_names, n.bound_keys,
                                       names, aggs, n.schema)
    # distributed topology: PARTIAL agg per input partition (rows shrink
    # to group count), exchange the partial states on the grouping keys,
    # FINAL merge per output partition (reference: partial/final
    # GpuHashAggregateExec around GpuShuffleExchangeExec)
    from ..exec.base import ExecContext
    nparts = conf.get(SHUFFLE_PARTITIONS)
    mesh_n = conf.get(MESH_DEVICES)
    multi_input = child.num_partitions(
        ExecContext(conf, planning=True)) > 1
    # Small HBM-cached input on a single host: complete mode can take
    # the one-round-trip whole-input program; at scale the
    # partial/exchange/final topology pipelines better
    base = child
    while len(base.children) == 1:
        base = base.children[0]
    from ..exec.nodes import CachedScanExec
    if isinstance(base, CachedScanExec) and mesh_n <= 1:
        total = sum(b.capacity for b in base.batches)
        if total <= (1 << 21):
            multi_input = False
    keys_ok = all(not (k.dtype.is_nested) for k in n.bound_keys)
    if keys_ok and ((multi_input and nparts > 1) or mesh_n > 1):
        from ..expr.expressions import BoundRef
        partial = agg_exec.HashAggregateExec(
            child, key_names, n.bound_keys, names, aggs, child.schema,
            mode="partial")
        pkeys = [BoundRef(i, k.dtype, f.name)
                 for i, (k, f) in enumerate(
                     zip(n.bound_keys, partial.schema.fields))]
        exch = _make_hash_exchange(partial, pkeys, conf)
        # adaptive coalescing of small reduce partitions (splitting would
        # break group completeness, so allow_split=False)
        exch, _ = _aqe_wrap(exch, conf, allow_split=False)
        return agg_exec.HashAggregateExec(exch, key_names, pkeys,
                                          names, aggs, n.schema,
                                          mode="final")
    return agg_exec.HashAggregateExec(child, key_names, n.bound_keys,
                                      names, aggs, n.schema)


@_rule(L.Limit)
def _limit(meta, conv, conf):
    return x.LimitExec(conv(meta.children[0]), meta.node.n)


@_rule(L.Union)
def _union(meta, conv, conf):
    return x.UnionExec([conv(c) for c in meta.children], meta.node.schema)


@_rule(L.Sort)
def _sort(meta, conv, conf):
    from ..exec.sort import SortExec
    for o in meta.node.bound_orders:
        if o.expr.dtype.is_nested:
            raise UnsupportedExpr(
                f"sort key {o.expr!r} has nested type "
                f"{o.expr.dtype} (not orderable on TPU)")
    return SortExec(conv(meta.children[0]), meta.node.bound_orders,
                    meta.node.schema)


def _estimate_rows(node: L.LogicalPlan):
    """Best-effort row estimate from scan metadata (the planner's
    broadcast-decision input; reference: size estimates feeding
    useSizedJoin / autoBroadcastJoinThreshold)."""
    if isinstance(node, L.InMemoryScan):
        return node.arrow.num_rows
    if isinstance(node, L.CachedScan):
        return sum(b.num_rows for b in node.batches)
    if isinstance(node, L.ParquetScan):
        cached = getattr(node, "_est_rows_cache", False)
        if cached is not False:
            return cached
        import pyarrow.parquet as pq
        try:
            rows = sum(pq.ParquetFile(p).metadata.num_rows
                       for p in node.paths)
        except Exception:
            rows = None
        node._est_rows_cache = rows
        return rows
    if isinstance(node, (L.Project, L.Filter, L.Sort, L.Repartition,
                         L.WindowOp)):
        # filters keep the upper bound (a conservative broadcast choice)
        return _estimate_rows(node.children[0])
    if isinstance(node, L.Limit):
        child = _estimate_rows(node.children[0])
        return node.n if child is None else min(node.n, child)
    if isinstance(node, L.Union):
        parts = [_estimate_rows(c) for c in node.children]
        return None if any(p is None for p in parts) else sum(parts)
    if isinstance(node, L.Aggregate):
        return _estimate_rows(node.children[0])
    return None


def _row_width_bytes(schema) -> int:
    w = 1  # validity
    for f in schema.fields:
        if f.dtype.is_variable_width:
            w += 24
        elif getattr(f.dtype, "is_decimal128", False):
            w += 16
        else:
            w += (f.dtype.np_dtype.itemsize if f.dtype.np_dtype else 8)
    return w


def _estimate_bytes(node: L.LogicalPlan):
    rows = _estimate_rows(node)
    if rows is None:
        return None
    return rows * _row_width_bytes(node.schema)


@_rule(L.Join)
def _join(meta, conv, conf):
    from ..config import BROADCAST_THRESHOLD, MESH_DEVICES, \
        SHUFFLE_PARTITIONS
    from ..exec.join import HashJoinExec
    n = meta.node
    for k in list(n.bound_left_keys or []) + list(n.bound_right_keys or []):
        if k.dtype.is_nested:
            raise UnsupportedExpr(
                f"join key {k!r} has nested type {k.dtype}")
    left, right = conv(meta.children[0]), conv(meta.children[1])
    mesh_n = conf.get(MESH_DEVICES)
    thr = conf.get(BROADCAST_THRESHOLD)
    est = _estimate_bytes(meta.children[1].node)
    broadcast_ok = thr >= 0 and est is not None and est <= thr
    equi = (n.how != "cross" and n.bound_left_keys
            and all(lk.dtype == rk.dtype for lk, rk in
                    zip(n.bound_left_keys, n.bound_right_keys)))
    cond = n.bound_condition
    if not equi and cond is not None:
        if n.bound_left_keys:
            # equi keys exist but are unusable (dtype mismatch): refusing
            # beats silently joining on the residual condition alone
            raise UnsupportedExpr(
                "equi-join keys have mismatched types "
                f"{[(lk.dtype, rk.dtype) for lk, rk in zip(n.bound_left_keys, n.bound_right_keys)]}; "
                "cast both sides to a common type")
        # no equi keys: broadcast nested-loop join on the condition
        # (GpuBroadcastNestedLoopJoinExecBase analog)
        from ..exec.join import NestedLoopJoinExec
        how = "inner" if n.how == "cross" else n.how
        return NestedLoopJoinExec(left, right, how, n.schema, cond)
    if mesh_n > 1 and equi and not broadcast_ok:
        # big build: hash-exchange both sides on the join keys over the
        # mesh, then each shard joins its co-partitioned slice
        # (GpuShuffledSizedHashJoinExec spirit over the collective)
        from ..exec.mesh_exchange import MeshExchangeExec
        lex = MeshExchangeExec(left, mesh_n, n.bound_left_keys,
                               left.schema)
        rex = MeshExchangeExec(right, mesh_n, n.bound_right_keys,
                               right.schema)
        return HashJoinExec(lex, rex, n.bound_left_keys,
                            n.bound_right_keys, n.how, n.schema,
                            per_partition=True, condition=cond)
    if mesh_n <= 1 and equi and not broadcast_ok and est is not None:
        # single-host big-build join: file-shuffle both sides so each
        # partition's build slice is bounded (sized-join analog)
        from ..exec.exchange import ShuffleExchangeExec
        nparts = conf.get(SHUFFLE_PARTITIONS)
        if nparts > 1:
            left, right = _maybe_bloom_prefilter(left, right, n, meta,
                                                 conf)
            lex = ShuffleExchangeExec(left, nparts, n.bound_left_keys,
                                      left.schema)
            rex = ShuffleExchangeExec(right, nparts, n.bound_right_keys,
                                      right.schema)
            # adaptive skew join: split oversized stream partitions into
            # row slices; the build reader replays the full partition per
            # slice. Splitting is only sound for joins where every output
            # row of a partition depends on (stream row, full build) —
            # right/full outer track matched-build state across the whole
            # partition, so those keep whole partitions.
            allow_split = n.how in ("inner", "left", "left_semi",
                                    "left_anti")
            lread, plan = _aqe_wrap(lex, conf, allow_split=allow_split)
            rread, _ = _aqe_wrap(rex, conf, plan=plan, role="build")
            return HashJoinExec(lread, rread, n.bound_left_keys,
                                n.bound_right_keys, n.how, n.schema,
                                per_partition=True, condition=cond)
    # broadcast hash join: build side collected once behind a
    # BroadcastExchangeExec (async background build + reuse-pass
    # dedupe target), stream partitions probe it
    # (GpuBroadcastHashJoinExecBase analog)
    from ..exec.broadcast import BroadcastExchangeExec
    return HashJoinExec(left,
                        BroadcastExchangeExec(right, right.schema),
                        n.bound_left_keys, n.bound_right_keys, n.how,
                        n.schema, condition=cond)


def _maybe_bloom_prefilter(left, right, n, meta, conf):
    """Wrap the stream (left) side of a shuffled equi-join in a runtime
    bloom filter built from the join's OWN build side, so non-matching
    rows never reach the exchange (reference: GpuBloomFilter* runtime
    filters via InSubqueryExec). The build subtree is wrapped in
    SharedBuildExec so the filter and the join's build exchange consume
    ONE materialization — no double scan, and no scan-shape
    restriction. Only for join types where an unmatched stream row
    contributes nothing. Returns (left', right')."""
    from ..config import (JOIN_BLOOM_ENABLED, JOIN_BLOOM_MAX_BUILD_ROWS)
    if not conf.get(JOIN_BLOOM_ENABLED):
        return left, right
    if n.how not in ("inner", "left_semi", "right"):
        return left, right
    if len(n.bound_left_keys or []) != 1:
        return left, right               # single-key filters only
    if n.bound_left_keys[0].dtype != n.bound_right_keys[0].dtype:
        # murmur3 hashes int32/int64 representations of equal values
        # differently: a mixed-width equi-join through the bloom filter
        # would silently drop matching stream rows
        return left, right
    from ..exec.runtime_filter import (RuntimeBloomFilterExec,
                                       SharedBuildExec)
    max_rows = conf.get(JOIN_BLOOM_MAX_BUILD_ROWS)
    est_rows = _estimate_rows(meta.children[1].node)
    if est_rows is None or est_rows > max_rows:
        # no estimate (unknown-cardinality shapes): a filter sized
        # blind can saturate (FPR ~1) and charge k probes per stream
        # row for zero pruning — skip. Aggregates/filters/scans DO
        # estimate (upper bounds), so non-scan builds stay eligible.
        return left, right
    shared = SharedBuildExec(right)
    return RuntimeBloomFilterExec(left, shared, n.bound_left_keys[0],
                                  n.bound_right_keys[0],
                                  max(64, int(est_rows))), shared


@_rule(L.WindowOp)
def _window(meta, conv, conf):
    """Stage window expressions: one WindowExec per distinct
    (partition, order) spec, chained — each appends its columns; a final
    projection restores the requested column order (the reference splits
    the same way, GpuWindowExecMeta.scala:182)."""
    from ..columnar.table import Field, Schema
    from ..exec.window import WindowExec, spec_signature
    n = meta.node
    groups = {}
    for nm, w in n.bound:
        groups.setdefault(spec_signature(w.spec), []).append((nm, w))
    child = conv(meta.children[0])
    if len(groups) == 1:
        return WindowExec(child, [nm for nm, _ in n.bound],
                          [w for _, w in n.bound], n.schema)
    cur = child
    cur_fields = list(meta.children[0].node.schema.fields)
    nchild = len(cur_fields)
    appended = {}
    for cols in groups.values():
        out_fields = cur_fields + [Field(nm, w.dtype) for nm, w in cols]
        for j, (nm, _) in enumerate(cols):
            appended[nm] = len(cur_fields) + j
        cur = WindowExec(cur, [nm for nm, _ in cols],
                         [w for _, w in cols], Schema(out_fields))
        cur_fields = out_fields
    # reorder appended columns back to request order
    from ..exec.nodes import ProjectExec
    from ..expr.expressions import BoundRef
    refs = ([BoundRef(i, f.dtype, f.name)
             for i, f in enumerate(n.schema.fields[:nchild])]
            + [BoundRef(appended[f.name], f.dtype, f.name)
               for f in n.schema.fields[nchild:]])
    return ProjectExec(cur, refs, n.schema)


@_rule(L.Generate)
def _generate(meta, conv, conf):
    from ..exec.generate import GenerateExec
    n = meta.node
    return GenerateExec(conv(meta.children[0]), n.bound, n.schema)


@_rule(L.MapInPandas)
def _map_in_pandas(meta, conv, conf):
    from ..exec.python_exec import ArrowEvalPythonExec
    n = meta.node
    return ArrowEvalPythonExec(conv(meta.children[0]), n.fn, n.schema)


@_rule(L.GroupedMapInPandas)
def _grouped_map_in_pandas(meta, conv, conf):
    from ..config import SHUFFLE_PARTITIONS
    from ..exec.exchange import ShuffleExchangeExec
    from ..exec.python_exec import GroupedMapPythonExec
    from ..expr.expressions import col as _col
    n = meta.node
    child = conv(meta.children[0])
    nparts = max(1, conf.get(SHUFFLE_PARTITIONS))
    keys = [_col(k).bind(n.children[0].schema) for k in n.key_names]
    # ALWAYS exchange: even at nparts=1 a multi-partition child must
    # gather so a key spanning source partitions stays one group
    child = ShuffleExchangeExec(child, nparts, keys, child.schema)
    return GroupedMapPythonExec(child, n.fn, n.schema, n.key_names)


@_rule(L.CoGroupInPandas)
def _cogroup_in_pandas(meta, conv, conf):
    from ..config import SHUFFLE_PARTITIONS
    from ..exec.exchange import ShuffleExchangeExec
    from ..exec.python_exec import CoGroupPythonExec
    from ..expr.expressions import col as _col
    n = meta.node
    left = conv(meta.children[0])
    right = conv(meta.children[1])
    nparts = max(1, conf.get(SHUFFLE_PARTITIONS))
    # ALWAYS exchange (even nparts=1): aligns partition counts across
    # the two sides and gathers split groups
    lkeys = [_col(k).bind(n.children[0].schema) for k in n.lkeys]
    rkeys = [_col(k).bind(n.children[1].schema) for k in n.rkeys]
    left = ShuffleExchangeExec(left, nparts, lkeys, left.schema)
    right = ShuffleExchangeExec(right, nparts, rkeys, right.schema)
    return CoGroupPythonExec(left, right, n.fn, n.schema)


@_rule(L.Repartition)
def _repart(meta, conv, conf):
    from ..config import MESH_DEVICES
    n = meta.node
    child = conv(meta.children[0])
    # the mesh collective produces exactly mesh-many partitions; honor an
    # explicit different repartition count via the file shuffle instead
    if n.bound_keys and conf.get(MESH_DEVICES) == n.num_partitions \
            and n.num_partitions > 1:
        from ..exec.mesh_exchange import MeshExchangeExec
        return MeshExchangeExec(child, conf.get(MESH_DEVICES),
                                n.bound_keys, n.schema)
    from ..exec.exchange import ShuffleExchangeExec
    return ShuffleExchangeExec(child, n.num_partitions, n.bound_keys,
                               n.schema)


class Planner:
    def __init__(self, conf: Optional[TpuConf] = None):
        self.conf = conf or TpuConf()

    # explain lines of the most recent plan() call (set whenever the
    # explain mode requests them; DataFrame.explain returns them)
    last_explain: List[str] = []
    # AuditReport of the most recent plan() call (analysis/audit.py)
    last_audit = None

    def plan(self, root: L.LogicalPlan) -> TpuExec:
        # calibration lookups (observed cardinalities from earlier runs
        # in this session) are live for the whole planning pass —
        # optimizer join-reorder included — and only there, so a
        # session with AQE off plans as if the table did not exist
        from ..config import ADAPTIVE_CALIBRATION, ADAPTIVE_ENABLED
        from .stats import calibration_scope
        with calibration_scope(self.conf.get(ADAPTIVE_ENABLED)
                               and self.conf.get(ADAPTIVE_CALIBRATION)):
            return self._plan_scoped(root)

    def _plan_scoped(self, root: L.LogicalPlan) -> TpuExec:
        from .optimizer import optimize
        root = optimize(root, self.conf)
        meta = PlanMeta(root)
        self._tag(meta)
        from ..config import CBO_ENABLED
        if self.conf.get(CBO_ENABLED):
            from .cbo import apply_cbo
            apply_cbo(meta, self.conf)
        explain_mode = self.conf.explain
        # convert BEFORE printing explain: lore ids live on the physical
        # nodes, and explain surfaces them ([loreId=N]) so profile-report
        # sinks map straight to lore.idsToDump replay ids. A conversion
        # failure still prints the tagged tree first, then re-raises.
        root_exec, conv_err = None, None
        try:
            root_exec = self._convert(meta)
        except UnsupportedExpr as e:
            conv_err = e
        from ..utils.lore import apply_lore_dump, assign_lore_ids
        if root_exec is not None:
            assign_lore_ids(root_exec)
        # static plan audit: a pure tree walk predicting fallback /
        # will-not-work / recompile-risk per node BEFORE any execution
        # (analysis/audit.py; the NOT_ON_TPU tagging discipline)
        from ..analysis.audit import audit_plan
        report = audit_plan(meta, self.conf)
        self.last_audit = report
        if root_exec is not None:
            # whole-stage fusion pass (plan/fusion.py): runs after the
            # audit because recompile_risk lore ids are fusion barriers,
            # and before explain so VALIDATE can render the groups
            from .fusion import fuse_stages
            root_exec, fusion_groups = fuse_stages(root_exec, self.conf,
                                                   report)
            report.fusion_groups = fusion_groups
            # exchange reuse (Spark's ReuseExchange analog): duplicate
            # exchange subtrees collapse to ReusedExchange nodes AFTER
            # fusion (fused chains are part of the subtree identity)
            from .reuse import reuse_exchanges
            root_exec, reuse_hits = reuse_exchanges(root_exec, self.conf)
            root_exec.exchange_reuse_hits = reuse_hits
            # fragment tier of the cross-query result cache: an
            # exchange subtree whose map output is already cached (from
            # a PREVIOUS query) becomes a CachedFragmentExec source —
            # cross-query what reuse_exchanges is intra-query
            from ..runtime import result_cache
            root_exec, frag_hits = result_cache.substitute_fragments(
                root_exec, self.conf)
            root_exec.result_cache_fragment_hits = frag_hits
            # SPMD stage grouping (plan/fusion.py): each surviving mesh
            # exchange fuses with its consumer into ONE shard_map
            # program — runs last so it sees the final tree (reused /
            # cache-substituted exchanges must not be double-wrapped)
            from .fusion import fuse_spmd_stages
            root_exec, spmd_groups = fuse_spmd_stages(root_exec,
                                                      self.conf)
            report.fusion_groups = fusion_groups + spmd_groups
            # ride the physical root so the profiler wrapper can emit
            # the plan_audit event without re-walking
            root_exec.audit_report = report
        self.last_explain = []
        if explain_mode in ("ALL", "NOT_ON_TPU", "VALIDATE"):
            if explain_mode == "VALIDATE":
                self.last_explain = report.lines()
            else:
                self.last_explain = meta.explain_lines(
                    explain_mode == "NOT_ON_TPU")
                self.last_explain.extend(
                    v.describe() for v in report.findings)
            for line in self.last_explain:
                print(line)
        if conv_err is not None:
            raise conv_err
        from ..config import AUDIT_STRICT
        if self.conf.get(AUDIT_STRICT):
            report.raise_if_blocked()
        return apply_lore_dump(root_exec, self.conf)

    def _tag(self, meta: PlanMeta):
        node = meta.node
        if type(node) not in _RULES:
            meta.will_not_work(
                f"no TPU replacement rule for {node.node_name()}")
        if isinstance(node, L.Filter) and node.bound is None:
            if self.conf.allow_cpu_fallback:
                meta.will_use_host(node.bind_error)
            else:
                meta.will_not_work(node.bind_error)
        if isinstance(node, L.Project) and any(b is None
                                               for b in node.bound):
            reason = "; ".join(e for e in node.bind_errors if e)
            if self.conf.allow_cpu_fallback:
                meta.will_use_host(reason)
            else:
                meta.will_not_work(reason)
        for c in meta.children:
            self._tag(c)

    def _convert(self, meta: PlanMeta) -> TpuExec:
        if not meta.can_run_on_tpu:
            raise UnsupportedExpr("; ".join(meta.reasons))
        rule = _RULES[type(meta.node)]
        try:
            meta.exec_node = rule(meta, self._convert, self.conf)
            # stamp calibration fingerprints (no-op outside an enabled
            # calibration scope) so post-run harvest can key observed
            # cardinalities without re-deriving the logical tree
            try:
                from .stats import attach_calibration_fps
                attach_calibration_fps(meta.node, meta.exec_node)
            except Exception:
                pass
            return meta.exec_node
        except ModuleNotFoundError as e:
            raise UnsupportedExpr(
                f"{meta.node.node_name()} not yet implemented on TPU "
                f"({e.name} missing)") from e


def plan_query(root: L.LogicalPlan,
               conf: Optional[TpuConf] = None) -> TpuExec:
    return Planner(conf).plan(root)
