"""Allocation-discipline diagnostics: retry coverage + leak checking.

Analog of the reference's AllocationRetryCoverageTracker.scala (which
flags device allocations made OUTSIDE the OOM-retry framework — those
are the allocations that kill a query instead of spilling) and the
shutdown leak-check hooks (Plugin.scala:625 RapidsBufferCatalog leak
assertions).

Coverage tracking is opt-in (`memory.retryCoverage.enabled`): when on,
every DeviceManager.reserve() records the engine call-site and whether
a retry scope (with_retry / retry_no_split) was active on the thread.
`coverage_report()` feeds the test that keeps operator allocations
inside the retry discipline. Leak checking is always available:
`leak_report()` snapshots open spill handles + reserved device bytes,
and `assert_no_leaks()` is the teardown hook."""
from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, Optional

__all__ = ["retry_scope", "in_retry_scope", "enable_retry_coverage",
           "record_allocation", "coverage_report", "reset_coverage",
           "leak_report", "assert_no_leaks", "record_device_watermark",
           "record_host_watermark", "reset_watermarks",
           "watermarks_snapshot", "record_query_bytes",
           "record_query_spill", "query_attribution",
           "reset_query_attribution"]

_tls = threading.local()
_enabled = False
_lock = threading.Lock()
# site -> [covered_count, uncovered_count]
_sites: Dict[str, list] = defaultdict(lambda: [0, 0])


class retry_scope:
    """Marks the dynamic extent of an OOM-retry region on this thread."""

    def __enter__(self):
        _tls.depth = getattr(_tls, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.depth = getattr(_tls, "depth", 1) - 1
        return False


def in_retry_scope() -> bool:
    return getattr(_tls, "depth", 0) > 0


def enable_retry_coverage(on: bool = True):
    global _enabled
    _enabled = on


def _call_site() -> str:
    import sys
    f = sys._getframe(2)
    pkg_sep = "spark_rapids_tpu"
    while f is not None:
        fn = f.f_code.co_filename
        if pkg_sep in fn and "/memory/" not in fn:
            short = fn.split(pkg_sep + "/", 1)[-1]
            return f"{short}:{f.f_lineno}"
        f = f.f_back
    return "<external>"


def record_allocation():
    """Called by DeviceManager.reserve when coverage tracking is on."""
    if not _enabled:
        return
    site = _call_site()
    with _lock:
        _sites[site][0 if in_retry_scope() else 1] += 1


def coverage_report() -> Dict[str, dict]:
    with _lock:
        return {s: {"covered": c, "uncovered": u}
                for s, (c, u) in sorted(_sites.items())}


def reset_coverage():
    with _lock:
        _sites.clear()


# -- memory watermarks ---------------------------------------------------
# Peak device/host reservation gauges for the query event log: the
# managers record every successful reservation here, so the profiler can
# report how close a query came to its budgets even when nothing OOMed
# (previously these numbers only surfaced in OOM error text).
_WM_LOCK = threading.Lock()
_wm = {"devicePeakBytes": 0, "hostPeakBytes": 0}


def record_device_watermark(reserved_bytes: int):
    with _WM_LOCK:
        if reserved_bytes > _wm["devicePeakBytes"]:
            _wm["devicePeakBytes"] = reserved_bytes


def record_host_watermark(reserved_bytes: int):
    with _WM_LOCK:
        if reserved_bytes > _wm["hostPeakBytes"]:
            _wm["hostPeakBytes"] = reserved_bytes


def reset_watermarks():
    """Re-arm the peak gauges (the profiler calls this at query start;
    concurrent queries in one process share the gauges — peaks are then
    attributed to whichever query's log closes them out)."""
    with _WM_LOCK:
        _wm["devicePeakBytes"] = 0
        _wm["hostPeakBytes"] = 0


def watermarks_snapshot() -> dict:
    """Peak device/host reservation since the last reset, plus the spill
    store's cumulative counters and the host manager's pressure metrics
    (only for singletons that already exist — reading a gauge must not
    instantiate a memory manager)."""
    with _WM_LOCK:
        out = dict(_wm)
    from . import host as _host
    from . import spill as _spill
    store = _spill._STORE
    if store is not None:
        out["spill"] = dict(store.metrics)
    hm = _host._GLOBAL
    if hm is not None:
        out["hostPressure"] = dict(hm.metrics)
    qid = _current_query_id()
    if qid is not None:
        rec = query_attribution(qid)
        if rec:
            out["queryAttribution"] = rec
    return out


# -- per-query attribution ----------------------------------------------
# The query service tags each worker thread with its query_id
# (service/query_manager.py _query_scope); the memory managers report
# every reserve/release/spill-pressure event here so concurrent queries'
# footprints stay separable in the event log and leak reports.
_QA_LOCK = threading.Lock()
# query_id -> {"deviceBytes", "devicePeakBytes", "hostBytes",
#              "hostPeakBytes", "spillPressureBytes"}
_query_attr: Dict[str, dict] = {}


def _current_query_id():
    try:
        from ..service.query_manager import current_query_id
        return current_query_id()
    except Exception:
        return None


def record_query_bytes(kind: str, delta: int):
    """Attribute a device/host reservation delta (`kind` is 'device' or
    'host', delta signed) to the current thread's query, if any."""
    qid = _current_query_id()
    if qid is None:
        return
    with _QA_LOCK:
        rec = _query_attr.setdefault(qid, {
            "deviceBytes": 0, "devicePeakBytes": 0,
            "hostBytes": 0, "hostPeakBytes": 0,
            "spillPressureBytes": 0})
        cur_key, peak_key = f"{kind}Bytes", f"{kind}PeakBytes"
        rec[cur_key] = max(0, rec[cur_key] + int(delta))
        if rec[cur_key] > rec[peak_key]:
            rec[peak_key] = rec[cur_key]


def record_query_spill(nbytes: int):
    """Attribute spill pressure (bytes the spill cascade was asked to
    free) to the query that triggered it."""
    qid = _current_query_id()
    if qid is None:
        return
    with _QA_LOCK:
        rec = _query_attr.setdefault(qid, {
            "deviceBytes": 0, "devicePeakBytes": 0,
            "hostBytes": 0, "hostPeakBytes": 0,
            "spillPressureBytes": 0})
        rec["spillPressureBytes"] += int(nbytes)


def query_attribution(query_id: Optional[str] = None):
    """Attribution snapshot: one query's record, or all of them."""
    with _QA_LOCK:
        if query_id is not None:
            return dict(_query_attr.get(query_id) or {})
        return {q: dict(r) for q, r in _query_attr.items()}


def reset_query_attribution(query_id: Optional[str] = None):
    with _QA_LOCK:
        if query_id is None:
            _query_attr.clear()
        else:
            _query_attr.pop(query_id, None)


# -- leak checking ------------------------------------------------------
def leak_report() -> dict:
    """Open spill handles (count/bytes, by state and priority) plus the
    DeviceManager's outstanding reservation."""
    from .device import device_manager
    from .spill import spill_store
    store = spill_store()
    with store._lock:
        handles = list(store._handles.values())
    by_state: Dict[str, int] = defaultdict(int)
    by_prio: Dict[int, int] = defaultdict(int)
    total = 0
    for h in handles:
        by_state[str(h.state)] += 1
        by_prio[h.priority] += 1
        total += h.nbytes
    return {"openHandles": len(handles), "openBytes": total,
            "byState": dict(by_state), "byPriority": dict(by_prio),
            "deviceReservedBytes": device_manager().reserved}


def assert_no_leaks(allow_reserved_bytes: int = 0):
    """Teardown hook: raises when spill handles remain open or device
    reservations exceed `allow_reserved_bytes` (cached plans that park
    exchange outputs must be release()d first — ADVICE r3)."""
    rep = leak_report()
    if rep["openHandles"] or rep["deviceReservedBytes"] \
            > allow_reserved_bytes:
        raise AssertionError(f"resource leak: {rep}")
