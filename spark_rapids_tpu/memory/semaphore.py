"""TpuSemaphore: bound tasks concurrently on the device.

Analog of the reference's GpuSemaphore (reference: GpuSemaphore.scala:183,
PrioritySemaphore.scala): a counting semaphore with priority ordering;
tasks acquire before device work and release around host-side I/O so
another task's kernels can occupy the chip.

Query-service integration: `acquire` takes the pool-weight-derived
priority (heavier pools map to more-negative values — the heap pops the
smallest first), accepts a CancelToken so a cancelled query stops
waiting for the chip instead of blocking forever, and returns the wait
time so callers can attribute `semaphoreWaitMs` per query (the
`metrics` dict stays the process-wide total, surfaced as
`semaphoreAcquires` on the root MetricSet)."""
from __future__ import annotations

import heapq
import itertools
import threading
from contextlib import contextmanager

from ..runtime import ledger, lockdep

__all__ = ["TpuSemaphore"]

# lockdep resource key for any permit of any TpuSemaphore instance:
# permits from different sessions never form real cycles with each
# other, and class-keying is what lets the witness see permit-then-lock
# vs lock-then-permit inversions across threads
PERMIT = "TpuSemaphore.permit"


class TpuSemaphore:
    def __init__(self, permits: int = 2):
        self._permits = permits
        self._available = permits
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._waiters = []          # heap of (priority, seq)
        self._dead = set()          # abandoned waiter entries (cancelled)
        self._seq = itertools.count()
        self._holders = {}          # thread name -> permits held
        self.metrics = {"acquireWaitTime": 0.0, "acquires": 0}

    def _purge_dead(self):
        while self._waiters and tuple(self._waiters[0]) in self._dead:
            self._dead.discard(tuple(heapq.heappop(self._waiters)))

    def _note_held(self, delta: int):
        # caller holds self._cond
        name = threading.current_thread().name
        n = self._holders.get(name, 0) + delta
        if n <= 0:
            self._holders.pop(name, None)
        else:
            self._holders[name] = n

    def acquire(self, priority: int = 0, token=None) -> float:
        """Block until a permit is granted in priority order; returns
        seconds spent waiting. With a CancelToken, the wait polls it and
        a tripped token abandons the slot (raising QueryCancelled)."""
        import time
        t0 = time.perf_counter()
        with self._cond:
            seq = next(self._seq)
            ent = (priority, seq)
            heapq.heappush(self._waiters, ent)
            try:
                while True:
                    self._purge_dead()
                    if self._available > 0 and self._waiters[0] == ent:
                        break
                    if token is not None:
                        self._cond.wait(timeout=0.05)
                        token.check()
                    else:
                        self._cond.wait()
            except BaseException:
                # leave no ghost head blocking the heap
                self._dead.add(ent)
                self._cond.notify_all()
                raise
            heapq.heappop(self._waiters)
            self._available -= 1
            waited = time.perf_counter() - t0
            self.metrics["acquires"] += 1
            self.metrics["acquireWaitTime"] += waited
            self._note_held(+1)
            self._cond.notify_all()
        lockdep.note_acquired(PERMIT)
        ledger.note_acquire("permit", tag="TpuSemaphore.acquire")
        return waited

    def try_acquire(self) -> bool:
        """Non-blocking permit grab for opportunistic extra parallelism
        (exchange map pools): succeeds only when a permit is free AND
        nobody is queued for it — never steals from a priority waiter.
        Callers must have a guaranteed-progress fallback (the exchange
        pool's ridden caller permit) since this can fail forever while
        blocked tasks pin every permit."""
        with self._cond:
            self._purge_dead()
            if self._available > 0 and not self._waiters:
                self._available -= 1
                self.metrics["acquires"] += 1
                self._note_held(+1)
                got = True
            else:
                got = False
        if got:
            lockdep.note_acquired(PERMIT)
            ledger.note_acquire("permit", tag="TpuSemaphore.try_acquire")
        return got

    def release(self):
        with self._cond:
            self._available += 1
            self._note_held(-1)
            self._cond.notify_all()
        lockdep.note_released(PERMIT)
        ledger.note_release("permit")

    @contextmanager
    def hold(self, priority: int = 0, token=None):
        self.acquire(priority, token=token)
        try:
            yield
        finally:
            self.release()

    def debug_state(self) -> dict:
        """Point-in-time introspection for the lockdep dump and the
        concurrency_report event: who holds permits, who is queued."""
        with self._cond:
            return {
                "permits": self._permits,
                "available": self._available,
                "holders": dict(self._holders),
                "waiters": len(self._waiters) - len(self._dead),
            }
