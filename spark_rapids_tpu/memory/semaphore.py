"""TpuSemaphore: bound tasks concurrently on the device.

Analog of the reference's GpuSemaphore (reference: GpuSemaphore.scala:183,
PrioritySemaphore.scala): a counting semaphore with priority ordering;
tasks acquire before device work and release around host-side I/O so
another task's kernels can occupy the chip.
"""
from __future__ import annotations

import heapq
import itertools
import threading
from contextlib import contextmanager

__all__ = ["TpuSemaphore"]


class TpuSemaphore:
    def __init__(self, permits: int = 2):
        self._permits = permits
        self._available = permits
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._waiters = []          # heap of (priority, seq)
        self._seq = itertools.count()
        self.metrics = {"acquireWaitTime": 0.0, "acquires": 0}

    def acquire(self, priority: int = 0):
        import time
        t0 = time.perf_counter()
        with self._cond:
            seq = next(self._seq)
            heapq.heappush(self._waiters, (priority, seq))
            while not (self._available > 0
                       and self._waiters[0] == (priority, seq)):
                self._cond.wait()
            heapq.heappop(self._waiters)
            self._available -= 1
            self.metrics["acquires"] += 1
            self.metrics["acquireWaitTime"] += time.perf_counter() - t0
            self._cond.notify_all()

    def release(self):
        with self._cond:
            self._available += 1
            self._cond.notify_all()

    @contextmanager
    def hold(self, priority: int = 0):
        self.acquire(priority)
        try:
            yield
        finally:
            self.release()
