"""Handle-based spill framework: HBM -> host DRAM -> disk.

Port-in-spirit of the reference's SpillFramework
(reference: spill/SpillFramework.scala:51-140): operators own
SpillableBatchHandle objects instead of raw batches; the store can demote
any handle that is not currently materialized. Demotion cascades
device->host->disk under the host-memory limit; `materialize()` promotes
back to device. Priorities: lower spill-order value spills first (the
reference's SpillPriorities).
"""
from __future__ import annotations

import os
import threading
import uuid
from typing import Dict, List, Optional

import numpy as np

from ..columnar.column import Column, flatten_bufs, unflatten_bufs
from ..columnar.table import Schema, Table
from ..exec.batch import DeviceBatch
from ..utils.transfer import fetch
from .device import DeviceManager, device_manager

__all__ = ["SpillStore", "SpillableBatchHandle", "spill_store"]

DEVICE, HOST, DISK = "device", "host", "disk"


def _write_spill_file(path: str, flat: Dict[str, np.ndarray], pool) -> None:
    """Spill file format: one JSON header line ({key: {dtype, shape}})
    followed by each array's raw C-order bytes in header order. Bytes
    are staged through the PinnedStagingPool so steady-state spilling
    reuses the same pow2 host buffers as the scan path instead of
    churning fresh allocations per handle; without a pool (conf-less
    store) arrays write directly."""
    import json
    from ..runtime import faults
    if faults.ACTIVE:
        faults.hit("spill.write")
    header = {k: {"dtype": str(a.dtype), "shape": list(a.shape)}
              for k, a in flat.items()}
    with open(path, "wb") as f:
        f.write((json.dumps(header) + "\n").encode("utf-8"))
        for k in header:
            raw = np.ascontiguousarray(flat[k])
            n = raw.nbytes
            if n == 0:
                continue
            if pool is None:
                raw.tofile(f)
                continue
            lease = pool.acquire(n)
            try:
                dst = np.frombuffer(lease.view(), np.uint8)
                dst[:] = raw.reshape(-1).view(np.uint8)
                f.write(lease.view())
            finally:
                lease.release()


def _read_spill_file(path: str) -> Dict[str, np.ndarray]:
    import json
    flat: Dict[str, np.ndarray] = {}
    with open(path, "rb") as f:
        header = json.loads(f.readline().decode("utf-8"))
        for k, meta in header.items():
            dtype = np.dtype(meta["dtype"])
            count = int(np.prod(meta["shape"], dtype=np.int64))
            a = np.fromfile(f, dtype=dtype, count=count)
            flat[k] = a.reshape(meta["shape"])
    return flat


class SpillableBatchHandle:
    """One spillable columnar batch. Not thread-safe per handle; the store
    lock serializes spills."""

    def __init__(self, store: "SpillStore", batch: DeviceBatch,
                 priority: int = 0):
        self.store = store
        self.priority = priority
        self.id = uuid.uuid4().hex
        self.state = DEVICE
        self._batch = batch
        self._host = None          # host pytree
        self._disk_path = None
        self._meta = None          # (schema, names, num_rows, capacity)
        self.nbytes = batch.nbytes
        self._pinned = 0

    # -- spill path ----------------------------------------------------
    def spill_to_host(self, charge_budget: bool = True) -> int:
        if self.state != DEVICE or self._pinned:
            return 0
        # draw from the GLOBAL host budget (HostAlloc analog); denied ->
        # cascade straight to disk instead of growing host RSS. The
        # disk path re-enters with charge_budget=False (transient host
        # staging, not a host-tier residency).
        hm = getattr(self.store, "host_mgr", None)
        if charge_budget and hm is not None:
            from .host import HostBudgetExceeded
            try:
                hm.reserve(self.nbytes)
            except HostBudgetExceeded:
                if self.store.spill_dir:
                    return self.spill_to_disk(self.store.spill_dir)
                return 0
            self._host_reserved = True
        b = self._batch
        tree = {
            "cols": [c.device_buffers() for c in b.table.columns],
            "mask": b.row_mask,
        }
        from ..profiler import tracing
        with tracing.span("spill.to_host", "spill_write", tier="host",
                          bytes=self.nbytes):
            # tpulint: allow[sync-under-lock] spill D2H must run under the store lock: the handle's state machine (DEVICE->HOST) and the pressure sweep that chose this victim both key off it; audited PR 10, no waiter can need the device result
            self._host = fetch(tree)
        self._meta = (b.table.schema, list(b.table.names), b.num_rows,
                      b.capacity)
        self._batch = None
        self.state = HOST
        return self.nbytes

    def _release_host(self):
        if getattr(self, "_host_reserved", False):
            hm = getattr(self.store, "host_mgr", None)
            if hm is not None:
                hm.release(self.nbytes)
            self._host_reserved = False

    def spill_to_disk(self, spill_dir: str) -> int:
        if self._pinned:
            return 0
        if self.state == DEVICE:
            self.spill_to_host(charge_budget=False)
        if self.state != HOST:
            return 0
        self._release_host()
        os.makedirs(spill_dir, exist_ok=True)
        path = os.path.join(spill_dir, f"spill-{self.id}.bin")
        flat = {}
        for i, bufs in enumerate(self._host["cols"]):
            flatten_bufs(bufs, f"c{i}_", flat)
        # tpulint: allow[host-sync] _host tier is already on the host
        flat["mask"] = np.asarray(self._host["mask"])
        from ..profiler import tracing
        with tracing.span("spill.to_disk", "spill_write", tier="disk",
                          bytes=self.nbytes):
            _write_spill_file(path, flat,
                              getattr(self.store, "staging", None))
        self._disk_path = path
        self._host = None
        self.state = DISK
        return self.nbytes

    # -- promote back ----------------------------------------------------
    def materialize(self) -> DeviceBatch:
        import jax
        # pin first: the reserve() below may fire the spill hook, which
        # must not demote the handle being promoted (re-entrancy guard)
        self.pin()
        from ..profiler import tracing
        sp = (tracing.open_span("spill.materialize", "spill_read",
                                tier=("disk" if self.state == DISK
                                      else "host"),
                                bytes=self.nbytes)
              if self.state != DEVICE else None)
        try:
            if self.state == DEVICE:
                return self._batch
            if self.state == DISK:
                data = _read_spill_file(self._disk_path)
                schema, names, num_rows, capacity = self._meta
                cols = []
                for i in range(len(names)):
                    flat = {k.split("_", 1)[1]: data[k] for k in data
                            if k.startswith(f"c{i}_")}
                    cols.append(unflatten_bufs(flat))
                self._host = {"cols": cols, "mask": data["mask"]}
                os.unlink(self._disk_path)
                self._disk_path = None
                self.state = HOST
            schema, names, num_rows, capacity = self._meta
            self.store.dm.reserve(self.nbytes)
            dev = jax.device_put(self._host)
            cols = [Column.build(f.dtype, num_rows, d)
                    for f, d in zip(schema.fields, dev["cols"])]
            batch = DeviceBatch(Table(names, cols), num_rows, dev["mask"],
                                capacity)
            self._batch = batch
            self._host = None
            self._release_host()
            self.state = DEVICE
            return batch
        finally:
            if sp is not None:
                sp.end()
            self.unpin()

    def pin(self):
        self._pinned += 1

    def unpin(self):
        self._pinned = max(0, self._pinned - 1)

    def close(self):
        if self._disk_path and os.path.exists(self._disk_path):
            os.unlink(self._disk_path)
        if self.state == DEVICE and self._batch is not None:
            self.store.dm.release(self.nbytes)
        self._release_host()
        self._batch = None
        self._host = None
        self.store._remove(self)
        from ..runtime import ledger
        ledger.note_release("spill_handle", token=self.id)


class SpillStore:
    """Registry of spillable handles + the DeviceManager spill hook
    (the reference's device/host store pair)."""

    def __init__(self, dm: Optional[DeviceManager] = None,
                 spill_dir: str = "/tmp/srtpu-spill",
                 host_limit: int = 32 << 30, host_mgr=None,
                 staging=None):
        self.dm = dm or device_manager()
        self.spill_dir = spill_dir
        self.host_limit = host_limit
        self.host_mgr = host_mgr
        self.staging = staging    # PinnedStagingPool for disk-write I/O
        from ..runtime import lockdep
        self._lock = lockdep.rlock("SpillStore._lock")
        self._handles: Dict[str, SpillableBatchHandle] = {}
        self.dm.register_spill_hook(self.spill)
        if host_mgr is not None:
            # global host pressure (async writes / arenas over budget)
            # demotes this store's host tier to disk
            host_mgr.register_pressure_hook(self.host_pressure)
        self.metrics = {"spillToHost": 0, "spillToDisk": 0,
                        "spillBytes": 0}

    def host_pressure(self, bytes_needed: int) -> int:
        """HostMemoryManager hook: demote host-tier handles to disk."""
        freed = 0
        with self._lock:
            for h in sorted((h for h in self._handles.values()
                             if h.state == HOST),
                            key=lambda h: (h.priority, -h.nbytes)):
                if freed >= bytes_needed:
                    break
                got = h.spill_to_disk(self.spill_dir)
                if got:
                    self.metrics["spillToDisk"] += 1
                    self.metrics["spillBytes"] += got
                    freed += got
        return freed

    def add_batch(self, batch: DeviceBatch,
                  priority: int = 0) -> SpillableBatchHandle:
        self.dm.reserve(batch.nbytes)
        h = SpillableBatchHandle(self, batch, priority)
        with self._lock:
            self._handles[h.id] = h
        from ..runtime import ledger
        ledger.note_acquire("spill_handle", h.nbytes, token=h.id,
                            tag=f"SpillStore.add_batch[{h.id[:8]}]")
        return h

    def _remove(self, h: SpillableBatchHandle):
        with self._lock:
            self._handles.pop(h.id, None)

    def spill(self, bytes_needed: int) -> int:
        """DeviceManager pressure hook: demote device handles (lowest
        priority first, biggest first) until enough is freed; cascade to
        disk if host memory is over its limit."""
        freed = 0
        with self._lock:
            device_handles = sorted(
                (h for h in self._handles.values() if h.state == DEVICE),
                key=lambda h: (h.priority, -h.nbytes))
            for h in device_handles:
                if freed >= bytes_needed:
                    break
                got = h.spill_to_host()
                if got:
                    self.dm.release(got)
                    freed += got
                    self.metrics["spillToHost"] += 1
                    self.metrics["spillBytes"] += got
            host_bytes = sum(h.nbytes for h in self._handles.values()
                             if h.state == HOST)
            if host_bytes > self.host_limit:
                for h in sorted((h for h in self._handles.values()
                                 if h.state == HOST),
                                key=lambda h: (h.priority, -h.nbytes)):
                    if host_bytes <= self.host_limit:
                        break
                    got = h.spill_to_disk(self.spill_dir)
                    if got:  # pinned handles return 0 and stay in RAM
                        self.metrics["spillToDisk"] += 1
                        host_bytes -= got
        return freed


_STORE: Optional[SpillStore] = None
_STORE_LOCK = threading.Lock()


def spill_store(conf=None) -> SpillStore:
    global _STORE
    with _STORE_LOCK:
        if _STORE is None:
            kw = {}
            if conf is not None:
                from ..config import HOST_SPILL_LIMIT, SPILL_DIR
                from .host import host_manager, staging_pool
                kw = {"spill_dir": conf.get(SPILL_DIR),
                      "host_limit": conf.get(HOST_SPILL_LIMIT),
                      "host_mgr": host_manager(conf),
                      "staging": staging_pool(conf)}
            _STORE = SpillStore(device_manager(conf), **kw)
        return _STORE
