"""Global host-memory budget (the HostAlloc analog).

The reference bounds executor host DRAM with one HostAlloc pool
(HostAlloc.scala:36; limits RapidsConf.scala:337-353): pinned pool +
non-pinned limit, allocations past the limit blocking or spilling the
host store. Standalone analog: ONE process-wide byte budget that every
host-resident consumer draws from —

  - the spill store's HOST tier (device batches demoted to host DRAM)
  - async write buffers (TrafficController in-flight bytes)
  - shuffle-assembly arenas (HostArena reservations)

Pressure hooks (the spill store registers its host->disk cascade) free
host bytes when a reservation would overflow; a reservation that still
cannot fit raises HostBudgetExceeded so the caller can route around
host DRAM entirely (spill_to_host falls through to disk). Like the
TrafficController, ONE outstanding reservation is always admitted so a
single oversized buffer cannot wedge the process.
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional

__all__ = ["HostMemoryManager", "HostBudgetExceeded", "host_manager"]


class HostBudgetExceeded(MemoryError):
    pass


class HostMemoryManager:
    def __init__(self, budget_bytes: int):
        self.budget = int(budget_bytes)
        self._reserved = 0
        self._holders = 0
        self._lock = threading.RLock()
        self._hooks: List[Callable[[int], int]] = []
        self.metrics = {"pressureCalls": 0, "pressureFreed": 0}

    def register_pressure_hook(self, fn: Callable[[int], int]):
        """fn(bytes_needed) -> bytes freed (e.g. host->disk demotion)."""
        with self._lock:
            if fn not in self._hooks:
                self._hooks.append(fn)

    @property
    def reserved(self) -> int:
        with self._lock:
            return self._reserved

    def try_reserve(self, nbytes: int) -> bool:
        with self._lock:
            if self.budget <= 0 \
                    or self._reserved + nbytes <= self.budget \
                    or self._holders == 0:
                self._reserved += nbytes
                self._holders += 1
                cur = self._reserved
            else:
                return False
        from .diagnostics import record_host_watermark
        record_host_watermark(cur)
        return True

    def reserve(self, nbytes: int):
        """Reserve host bytes, firing pressure hooks when over budget.
        Raises HostBudgetExceeded when hooks cannot make room and other
        reservations are outstanding."""
        if self.try_reserve(nbytes):
            return
        need = nbytes
        self.metrics["pressureCalls"] += 1
        for fn in list(self._hooks):
            try:
                freed = fn(need)
            except Exception:
                freed = 0
            self.metrics["pressureFreed"] += int(freed or 0)
            if self.try_reserve(nbytes):
                return
        raise HostBudgetExceeded(
            f"host reservation of {nbytes} bytes over budget "
            f"{self.budget} ({self._reserved} reserved)")

    def force_reserve(self, nbytes: int):
        """Unconditional reservation (soft-admit): accounting may
        exceed the budget; later reservations see the pressure."""
        with self._lock:
            self._reserved += nbytes
            self._holders += 1
            cur = self._reserved
        from .diagnostics import record_host_watermark
        record_host_watermark(cur)

    def release(self, nbytes: int):
        with self._lock:
            self._reserved = max(0, self._reserved - nbytes)
            self._holders = max(0, self._holders - 1)


_GLOBAL: Optional[HostMemoryManager] = None
_LOCK = threading.Lock()


def host_manager(conf=None) -> HostMemoryManager:
    global _GLOBAL
    with _LOCK:
        if _GLOBAL is None:
            budget = 0
            if conf is not None:
                from ..config import HOST_MEMORY_LIMIT
                budget = conf.get(HOST_MEMORY_LIMIT)
            _GLOBAL = HostMemoryManager(budget)
        elif conf is not None and _GLOBAL.budget == 0:
            # a conf-less caller (e.g. shuffle arena) may have created
            # the singleton unlimited; the first configured limit
            # upgrades it rather than being silently ignored
            from ..config import HOST_MEMORY_LIMIT
            _GLOBAL.budget = conf.get(HOST_MEMORY_LIMIT)
        return _GLOBAL
