"""Global host-memory budget (the HostAlloc analog).

The reference bounds executor host DRAM with one HostAlloc pool
(HostAlloc.scala:36; limits RapidsConf.scala:337-353): pinned pool +
non-pinned limit, allocations past the limit blocking or spilling the
host store. Standalone analog: ONE process-wide byte budget that every
host-resident consumer draws from —

  - the spill store's HOST tier (device batches demoted to host DRAM)
  - async write buffers (TrafficController in-flight bytes)
  - shuffle-assembly arenas (HostArena reservations)

Pressure hooks (the spill store registers its host->disk cascade) free
host bytes when a reservation would overflow; a reservation that still
cannot fit raises HostBudgetExceeded so the caller can route around
host DRAM entirely (spill_to_host falls through to disk). Like the
TrafficController, ONE outstanding reservation is always admitted so a
single oversized buffer cannot wedge the process.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

__all__ = ["HostMemoryManager", "HostBudgetExceeded", "host_manager",
           "PinnedStagingPool", "StagingBuffer", "staging_pool"]


class HostBudgetExceeded(MemoryError):
    pass


class HostMemoryManager:
    def __init__(self, budget_bytes: int):
        self.budget = int(budget_bytes)
        self._reserved = 0
        self._holders = 0
        self._lock = threading.RLock()
        self._hooks: List[Callable[[int], int]] = []
        self.metrics = {"pressureCalls": 0, "pressureFreed": 0}

    def register_pressure_hook(self, fn: Callable[[int], int]):
        """fn(bytes_needed) -> bytes freed (e.g. host->disk demotion)."""
        with self._lock:
            if fn not in self._hooks:
                self._hooks.append(fn)

    @property
    def reserved(self) -> int:
        with self._lock:
            return self._reserved

    def try_reserve(self, nbytes: int) -> bool:
        with self._lock:
            if self.budget <= 0 \
                    or self._reserved + nbytes <= self.budget \
                    or self._holders == 0:
                self._reserved += nbytes
                self._holders += 1
                cur = self._reserved
            else:
                return False
        from ..runtime import ledger
        from .diagnostics import record_host_watermark, record_query_bytes
        record_host_watermark(cur)
        record_query_bytes("host", nbytes)
        ledger.note_acquire("host_bytes", nbytes,
                            tag="HostMemoryManager.try_reserve")
        return True

    def reserve(self, nbytes: int):
        """Reserve host bytes, firing pressure hooks when over budget.
        Raises HostBudgetExceeded when hooks cannot make room and other
        reservations are outstanding."""
        if self.try_reserve(nbytes):
            return
        need = nbytes
        self.metrics["pressureCalls"] += 1
        from .diagnostics import record_query_spill
        record_query_spill(need)
        for fn in list(self._hooks):
            try:
                freed = fn(need)
            except Exception:
                freed = 0
            self.metrics["pressureFreed"] += int(freed or 0)
            if self.try_reserve(nbytes):
                return
        exc = HostBudgetExceeded(
            f"host reservation of {nbytes} bytes over budget "
            f"{self.budget} ({self._reserved} reserved)")
        from ..runtime import ledger
        ledger.attach_dump(exc)   # who holds the budget, by thread/query
        raise exc

    def force_reserve(self, nbytes: int):
        """Unconditional reservation (soft-admit): accounting may
        exceed the budget; later reservations see the pressure."""
        with self._lock:
            self._reserved += nbytes
            self._holders += 1
            cur = self._reserved
        from ..runtime import ledger
        from .diagnostics import record_host_watermark, record_query_bytes
        record_host_watermark(cur)
        record_query_bytes("host", nbytes)
        ledger.note_acquire("host_bytes", nbytes,
                            tag="HostMemoryManager.force_reserve")

    def release(self, nbytes: int):
        with self._lock:
            self._reserved = max(0, self._reserved - nbytes)
            self._holders = max(0, self._holders - 1)
        from ..runtime import ledger
        from .diagnostics import record_query_bytes
        record_query_bytes("host", -nbytes)
        ledger.note_release("host_bytes", nbytes)


# ----------------------------------------------------------------------
# Pinned staging pool (the HostAlloc pinned-pool analog)
# ----------------------------------------------------------------------
_STAGING_FLOOR = 64 * 1024


def _staging_bucket(nbytes: int) -> int:
    """Pow2 size class so buffers (and the H2D upload shapes cut from
    them) repeat across chunks instead of compiling/allocating fresh."""
    c = _STAGING_FLOOR
    while c < nbytes:
        c <<= 1
    return c


class StagingBuffer:
    """One leased staging buffer: a pow2-capacity uint8 array plus the
    caller's requested length. Return it with release() (or via the
    pool) so the next chunk reuses the allocation."""

    __slots__ = ("array", "nbytes", "_pool", "_cached")

    def __init__(self, array, nbytes: int, pool: "PinnedStagingPool",
                 cached: bool):
        self.array = array          # np.uint8[capacity]
        self.nbytes = int(nbytes)   # live prefix the caller asked for
        self._pool = pool
        self._cached = cached       # counted against the pool budget

    @property
    def capacity(self) -> int:
        return int(self.array.shape[0])

    def view(self) -> memoryview:
        """Writable view of the live prefix (readinto target)."""
        return memoryview(self.array)[:self.nbytes]

    def release(self):
        self._pool.release(self)


class PinnedStagingPool:
    """Reusable size-bucketed host staging buffers for raw-chunk H2D
    uploads (reference: HostAlloc.scala pinned pool / PinnedMemoryPool).

    The device parquet scan used to allocate a fresh host buffer per
    column chunk (file read + snappy decompress target + upload source);
    this pool leases pow2-bucketed uint8 arrays instead, so steady-state
    scans stop churning the allocator and upload shapes stay constant.
    Cached bytes are accounted against the global host budget
    (`memory.host.limitBytes`); when the pool is full, extra leases are
    served as transient buffers that simply drop on release."""

    def __init__(self, max_bytes: int,
                 manager: Optional[HostMemoryManager] = None):
        self.max_bytes = int(max_bytes)
        self._manager = manager
        self._free: Dict[int, List] = {}     # bucket -> free arrays
        self._held = 0                       # cached bytes (free + leased)
        self._lock = threading.Lock()
        self.metrics = {"stagingPoolHits": 0, "stagingPoolMisses": 0,
                        "stagingPoolTransient": 0,
                        "stagingPoolHeldBytes": 0}

    def acquire(self, nbytes: int) -> StagingBuffer:
        import numpy as np

        from ..runtime import ledger
        cap = _staging_bucket(max(int(nbytes), 1))
        with self._lock:
            lst = self._free.get(cap)
            if lst:
                self.metrics["stagingPoolHits"] += 1
                buf = StagingBuffer(lst.pop(), nbytes, self, True)
                ledger.note_acquire("staging_lease", cap, token=id(buf),
                                    tag="PinnedStagingPool.acquire")
                return buf
            grow = self._held + cap <= self.max_bytes
            if grow:
                self._held += cap
                self.metrics["stagingPoolHeldBytes"] = self._held
                self.metrics["stagingPoolMisses"] += 1
            else:
                self.metrics["stagingPoolTransient"] += 1
        if grow and self._manager is not None:
            # cached buffers draw from the host budget like any other
            # host-resident consumer; a refusal demotes to transient
            if not self._manager.try_reserve(cap):
                with self._lock:
                    self._held -= cap
                    self.metrics["stagingPoolHeldBytes"] = self._held
                grow = False
        arr = np.empty(cap, np.uint8)
        buf = StagingBuffer(arr, nbytes, self, grow)
        ledger.note_acquire("staging_lease", cap, token=id(buf),
                            tag="PinnedStagingPool.acquire")
        return buf

    def release(self, buf: StagingBuffer):
        from ..runtime import ledger
        ledger.note_release("staging_lease", buf.capacity, token=id(buf))
        if not buf._cached:
            return                            # transient: let GC take it
        if ledger.poison_enabled():
            # turn latent use-after-release into deterministic garbage:
            # the recycled array reads 0xAB, not whatever the next
            # lease happens to write (the PR 4 corruption class)
            buf.array.fill(ledger.POISON_BYTE)
        with self._lock:
            self._free.setdefault(buf.capacity, []).append(buf.array)

    def clear(self) -> int:
        """Drop all cached free buffers, releasing their host budget.
        Returns bytes freed (pressure-hook shape)."""
        with self._lock:
            drops = [(cap, len(lst)) for cap, lst in self._free.items()]
            freed = sum(cap * n for cap, n in drops)
            self._free.clear()
            self._held -= freed
            self.metrics["stagingPoolHeldBytes"] = self._held
        if self._manager is not None:
            for cap, n in drops:              # one reservation per buffer
                for _ in range(n):
                    self._manager.release(cap)
        return freed

    @property
    def held_bytes(self) -> int:
        with self._lock:
            return self._held


_STAGING: Optional[PinnedStagingPool] = None
_GLOBAL: Optional[HostMemoryManager] = None
_LOCK = threading.Lock()


def host_manager(conf=None) -> HostMemoryManager:
    global _GLOBAL
    with _LOCK:
        if _GLOBAL is None:
            budget = 0
            if conf is not None:
                from ..config import HOST_MEMORY_LIMIT
                budget = conf.get(HOST_MEMORY_LIMIT)
            _GLOBAL = HostMemoryManager(budget)
        elif conf is not None and _GLOBAL.budget == 0:
            # a conf-less caller (e.g. shuffle arena) may have created
            # the singleton unlimited; the first configured limit
            # upgrades it rather than being silently ignored
            from ..config import HOST_MEMORY_LIMIT
            _GLOBAL.budget = conf.get(HOST_MEMORY_LIMIT)
        return _GLOBAL


def staging_pool(conf=None) -> PinnedStagingPool:
    """Process-wide pinned staging pool (sized once, by the first
    configured caller; conf-less callers get the default cap)."""
    global _STAGING
    if _STAGING is None:
        from ..config import HOST_STAGING_POOL_BYTES
        cap = (conf.get(HOST_STAGING_POOL_BYTES) if conf is not None
               else HOST_STAGING_POOL_BYTES.default)
        mgr = host_manager(conf)          # takes _LOCK itself: call first
        with _LOCK:
            if _STAGING is None:
                _STAGING = PinnedStagingPool(cap, mgr)
    return _STAGING
