"""Split-and-retry: out-of-core execution for bigger-than-HBM inputs.

The reference's RmmRapidsRetryIterator (reference:
RmmRapidsRetryIterator.scala:36-105 `withRetry(input, splitPolicy)(fn)`):
an idempotent fn over spillable input re-executes on OOM, with the input
split in half when retrying alone cannot help. Here OOM is either our
analytic BudgetExceeded or XLA's RESOURCE_EXHAUSTED; both route through
the same split loop. Inputs are DeviceBatch halves split by capacity
(static shapes: each half keeps a power-of-two capacity).
"""
from __future__ import annotations

import gc
from typing import Callable, Iterator, List

import jax.numpy as jnp

from ..columnar.column import Column
from ..columnar.table import Table
from ..exec.batch import DeviceBatch
from .device import BudgetExceeded

__all__ = ["with_retry", "split_batch_in_half", "OutOfCoreError",
           "is_oom_error"]

MAX_SPLITS = 12


class OutOfCoreError(Exception):
    pass


#: how much of the exception's own message is ITS message: the status
#: line jaxlib/XLA put first. Matching beyond this (or past the first
#: line) starts matching user data embedded in the repr — a ValueError
#: quoting a row that says "out of memory" is not an OOM.
_OOM_HEAD_CHARS = 256


def _message_head(e: Exception) -> str:
    return str(e).split("\n", 1)[0][:_OOM_HEAD_CHARS]


def is_oom_error(e: Exception) -> bool:
    if isinstance(e, BudgetExceeded):
        return True
    # typed classification first: jaxlib raises XlaRuntimeError with
    # the canonical status name leading the message ("RESOURCE_EXHAUSTED:
    # ..."); some builds expose .status — honor it when present
    if type(e).__name__ == "XlaRuntimeError":
        status = getattr(e, "status", None)
        if status is not None and "RESOURCE_EXHAUSTED" in str(status):
            return True
        head = _message_head(e)
        return ("RESOURCE_EXHAUSTED" in head
                or "out of memory" in head.lower())
    head = _message_head(e)
    return ("RESOURCE_EXHAUSTED" in head or "Out of memory" in head
            or "out of memory" in head)


def split_batch_in_half(batch: DeviceBatch) -> List[DeviceBatch]:
    """Slice a batch into two capacity halves (no data movement for
    variable-width columns: offsets slices still index the shared data
    buffer)."""
    cap = batch.capacity
    if cap <= 128:
        raise OutOfCoreError("cannot split a minimum-capacity batch")
    half = cap // 2
    outs = []
    for lo, hi in ((0, half), (half, cap)):
        cols = []
        for c in batch.table.columns:
            if c.offsets is not None:
                off = c.offsets[lo:hi + 1]
                cols.append(Column(c.dtype, max(0, min(c.length, hi) - lo),
                                   c.data, c.validity[lo:hi], off))
            else:
                cols.append(Column(c.dtype, max(0, min(c.length, hi) - lo),
                                   c.data[lo:hi], c.validity[lo:hi]))
        outs.append(DeviceBatch(Table(batch.table.names, cols),
                                max(0, min(batch.num_rows, hi) - lo),
                                batch.row_mask[lo:hi], half))
    return outs


def with_retry(batch: DeviceBatch,
               fn: Callable[[DeviceBatch], object],
               max_splits: int = MAX_SPLITS) -> Iterator[object]:
    """Run `fn` (idempotent!) over `batch`, splitting in half and retrying
    on device OOM. Yields one result per final sub-batch, in row order."""
    from .diagnostics import retry_scope
    stack: List[tuple] = [(batch, 0)]
    while stack:
        b, depth = stack.pop(0)
        try:
            # compute INSIDE the scope, yield OUTSIDE: a generator
            # suspended at yield would otherwise hold the scope open and
            # misattribute the consumer's allocations as retry-covered
            with retry_scope():
                res = fn(b)
            yield res
        except Exception as e:  # noqa: BLE001 - filtered below
            if not is_oom_error(e):
                raise
            gc.collect()
            if depth >= max_splits:
                raise OutOfCoreError(
                    f"still OOM after {depth} splits") from e
            halves = split_batch_in_half(b)
            stack = [(halves[0], depth + 1), (halves[1], depth + 1)] + stack


def retry_no_split(fn: Callable[[], object], retries: int = 2):
    """Run `fn` (idempotent), retrying after gc + spill-hook pressure on
    device OOM — for operators whose semantics forbid input splitting
    (e.g. window frames spanning the whole partition). The GpuRetryOOM
    half of the reference's retry framework without GpuSplitAndRetryOOM."""
    from .diagnostics import retry_scope
    attempt = 0
    while True:
        try:
            with retry_scope():
                return fn()
        except Exception as e:  # noqa: BLE001 - filtered below
            if not is_oom_error(e) or attempt >= retries:
                raise
            attempt += 1
            gc.collect()
            try:
                from .device import device_manager
                device_manager().trigger_spill()
            # tpulint: allow[retry-swallows-cancel] best-effort spill nudge; the outer handler already classified via is_oom_error
            except Exception:
                pass
