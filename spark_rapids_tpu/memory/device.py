"""HBM budget manager — the RMM-pool analog.

XLA owns the real allocator and gives no alloc-failure callback
(SURVEY.md §7.3 item 2), so the design is *inverted* from the reference's
reactive RmmSpark interruption: the engine budgets HBM analytically.
Operators reserve estimated bytes before launching a kernel; a failed
reservation (or a caught RESOURCE_EXHAUSTED from XLA) triggers the spill
store, then the retry framework re-executes with spilled/split inputs
(reference: GpuDeviceManager.scala:182, DeviceMemoryEventHandler.scala:36).
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional

import jax

__all__ = ["DeviceManager", "BudgetExceeded", "device_manager"]


class BudgetExceeded(Exception):
    """Raised when an HBM reservation cannot be satisfied even after
    spilling everything spillable."""


class DeviceManager:
    def __init__(self, budget_bytes: Optional[int] = None,
                 alloc_fraction: float = 0.85):
        self._lock = threading.RLock()
        self._reserved = 0
        self._spill_hooks: List[Callable[[int], int]] = []
        if budget_bytes is None:
            budget_bytes = self._detect_budget(alloc_fraction)
        self.budget = budget_bytes

    @staticmethod
    def _detect_budget(fraction: float) -> int:
        try:
            stats = jax.devices()[0].memory_stats()
            if stats and "bytes_limit" in stats:
                return int(stats["bytes_limit"] * fraction)
        except Exception:
            pass
        return int(12 * (1 << 30) * fraction)  # v5e-ish default

    # ------------------------------------------------------------------
    def register_spill_hook(self, hook: Callable[[int], int]):
        """hook(bytes_needed) -> bytes_freed; called under pressure."""
        self._spill_hooks.append(hook)

    @property
    def reserved(self) -> int:
        return self._reserved

    def try_reserve(self, nbytes: int, _record: bool = True) -> bool:
        if _record:
            from .diagnostics import record_allocation
            record_allocation()
        with self._lock:
            if self._reserved + nbytes <= self.budget:
                self._reserved += nbytes
                cur = self._reserved
            else:
                return False
        from ..runtime import ledger
        from .diagnostics import record_device_watermark, \
            record_query_bytes
        record_device_watermark(cur)
        record_query_bytes("device", nbytes)
        ledger.note_acquire("device_bytes", nbytes,
                            tag="DeviceManager.try_reserve")
        return True

    def reserve(self, nbytes: int):
        """Reserve, spilling as needed; raises BudgetExceeded if the spill
        store cannot free enough. Coverage records ONCE per logical
        allocation: here at entry, with the spill-retry loop's repeat
        try_reserve attempts unrecorded."""
        from .diagnostics import record_allocation
        record_allocation()
        if self.try_reserve(nbytes, _record=False):
            return
        for hook in self._spill_hooks:
            # recompute the shortfall under the lock on every attempt:
            # concurrent reservations move _reserved between hook calls
            with self._lock:
                needed = nbytes - (self.budget - self._reserved)
            if needed > 0:
                from .diagnostics import record_query_spill
                record_query_spill(needed)
                hook(needed)
            if self.try_reserve(nbytes, _record=False):
                return
        exc = BudgetExceeded(
            f"need {nbytes} bytes, reserved {self._reserved} of "
            f"{self.budget} and spill store exhausted")
        from ..runtime import ledger
        ledger.attach_dump(exc)   # who holds the budget, by thread/query
        raise exc

    def release(self, nbytes: int):
        with self._lock:
            self._reserved = max(0, self._reserved - nbytes)
        from ..runtime import ledger
        from .diagnostics import record_query_bytes
        record_query_bytes("device", -nbytes)
        ledger.note_release("device_bytes", nbytes)

    def trigger_spill(self, nbytes: Optional[int] = None):
        """Ask the spill store to free memory proactively (the retry
        framework's pressure valve between attempts)."""
        need = nbytes if nbytes is not None else max(self.budget // 4, 1)
        for hook in self._spill_hooks:
            hook(need)


_GLOBAL: Optional[DeviceManager] = None
_GLOBAL_LOCK = threading.Lock()


def device_manager(conf=None) -> DeviceManager:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            budget = None
            frac = 0.85
            if conf is not None:
                from ..config import HBM_POOL_BYTES, HBM_POOL_FRACTION
                budget = conf.get(HBM_POOL_BYTES)
                frac = conf.get(HBM_POOL_FRACTION)
            _GLOBAL = DeviceManager(budget, frac)
        return _GLOBAL
