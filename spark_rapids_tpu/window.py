"""Window function API: WindowSpec + window expressions.

Mirrors pyspark.sql.Window / the reference's window package
(reference: sql-plugin/.../window/ — GpuWindowExec, GpuRunningWindowExec,
GpuBatchedBoundedWindowExec). Frames supported:

  - ROWS BETWEEN a AND b (bounded/unbounded, all agg fns incl. min/max)
  - RANGE BETWEEN a AND b over one numeric/date/timestamp order key
  - the Spark default frame (RANGE UNBOUNDED PRECEDING..CURRENT ROW when
    ordered — peer rows included; whole partition when unordered)
  - lag/lead, ranking (row_number/rank/dense_rank/percent_rank/cume_dist/
    ntile), first_value/last_value/nth_value

Usage:
    from spark_rapids_tpu.window import Window
    w = Window.partition_by("k").order_by("ts")
    df.select(F.col("v"), row_number().over(w).alias("rn"))
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .expr.expressions import Expression, UnsupportedExpr, _wrap
from .plan.logical import SortOrder

__all__ = ["Window", "WindowSpec", "WindowExpr", "row_number", "rank",
           "dense_rank", "percent_rank", "cume_dist", "ntile", "lag",
           "lead", "first_value", "last_value", "nth_value", "win_sum",
           "win_count", "win_min", "win_max", "win_avg", "CURRENT_ROW",
           "UNBOUNDED"]

UNBOUNDED = object()
CURRENT_ROW = 0


class WindowSpec:
    """frame_mode: "rows", "range", or None (resolve Spark's default at
    bind: whole partition when unordered, RANGE UNBOUNDED..CURRENT ROW —
    peers included — when ordered)."""

    def __init__(self, partition_keys=(), orders=(), frame=None,
                 frame_mode=None):
        self.partition_keys = list(partition_keys)
        self.orders = list(orders)
        self.frame = frame
        self.frame_mode = frame_mode

    def partition_by(self, *keys) -> "WindowSpec":
        from .functions import _to_expr
        return WindowSpec([_to_expr(k) for k in keys], self.orders,
                          self.frame, self.frame_mode)

    def order_by(self, *orders) -> "WindowSpec":
        from .functions import _to_expr
        sos = []
        for o in orders:
            if isinstance(o, SortOrder):
                sos.append(o)
            else:
                sos.append(SortOrder(_to_expr(o), True))
        return WindowSpec(self.partition_keys, sos, self.frame,
                          self.frame_mode)

    def rows_between(self, start, end) -> "WindowSpec":
        return WindowSpec(self.partition_keys, self.orders, (start, end),
                          "rows")

    def range_between(self, start, end) -> "WindowSpec":
        return WindowSpec(self.partition_keys, self.orders, (start, end),
                          "range")


class _WindowBuilder:
    """Window.partition_by(...) entry point (class-method style)."""

    @staticmethod
    def partition_by(*keys) -> WindowSpec:
        return WindowSpec().partition_by(*keys)

    @staticmethod
    def order_by(*orders) -> WindowSpec:
        return WindowSpec().order_by(*orders)

    unboundedPreceding = UNBOUNDED
    unboundedFollowing = UNBOUNDED
    currentRow = CURRENT_ROW


Window = _WindowBuilder


class WindowExpr(Expression):
    """fn OVER spec. Bound by the Window logical node."""

    FNS = ("row_number", "rank", "dense_rank", "percent_rank",
           "cume_dist", "ntile", "lag", "lead", "first_value",
           "last_value", "nth_value", "sum", "count", "min", "max", "avg")
    RANKING = ("row_number", "rank", "dense_rank", "percent_rank",
               "cume_dist", "ntile")

    def __init__(self, fn: str, child: Optional[Expression],
                 spec: WindowSpec, offset: int = 1,
                 default=None):
        assert fn in self.FNS
        self.fn = fn
        self.child = child
        self.spec = spec
        self.offset = offset
        self.default = default
        self.children = [c for c in [child] if c is not None]

    def bind(self, schema):
        frame, mode = self.spec.frame, self.spec.frame_mode
        if mode is None:
            # Spark default: whole partition when unordered, RANGE
            # UNBOUNDED..CURRENT ROW (peer-inclusive) when ordered
            if self.spec.orders:
                frame, mode = (UNBOUNDED, CURRENT_ROW), "range"
            else:
                frame, mode = (UNBOUNDED, UNBOUNDED), "rows"
        b = WindowExpr(self.fn,
                       self.child.bind(schema) if self.child else None,
                       WindowSpec(
                           [k.bind(schema) for k in self.spec.partition_keys],
                           [SortOrder(o.expr.bind(schema), o.ascending,
                                      o.nulls_first)
                            for o in self.spec.orders],
                           frame, mode),
                       self.offset, self.default)
        from .columnar import dtypes as dt
        # bounded-frame decimal128 min/max: two-limb sparse-table RMQ
        # (exec/window.py _rmq_d128) — no plan-time gate needed anymore
        if self.fn in self.RANKING:
            if not b.spec.orders:
                raise UnsupportedExpr(f"{self.fn} requires ORDER BY")
            b.dtype = (dt.FLOAT64 if self.fn in ("percent_rank",
                                                 "cume_dist") else dt.INT32)
        elif self.fn in ("lag", "lead", "first_value", "last_value",
                         "nth_value"):
            b.dtype = b.child.dtype
        elif self.fn == "count":
            b.dtype = dt.INT64
        elif self.fn == "avg":
            b.dtype = dt.FLOAT64
        else:
            from .expr.aggregates import Sum, Min, Max
            proto = {"sum": Sum, "min": Min, "max": Max}[self.fn](b.child)
            proto._resolve_type()
            b.dtype = proto.dtype
        return b

    @property
    def name(self):
        return f"{self.fn}()"

    def __repr__(self):
        return f"{self.fn}(...) OVER (...)"


class _PendingWindowFn:
    def __init__(self, fn, child=None, offset=1, default=None):
        self.fn = fn
        self.child = child
        self.offset = offset
        self.default = default

    def over(self, spec: WindowSpec) -> WindowExpr:
        return WindowExpr(self.fn, self.child, spec, self.offset,
                          self.default)


def row_number():
    return _PendingWindowFn("row_number")


def rank():
    return _PendingWindowFn("rank")


def dense_rank():
    return _PendingWindowFn("dense_rank")


def percent_rank():
    return _PendingWindowFn("percent_rank")


def cume_dist():
    return _PendingWindowFn("cume_dist")


def ntile(n: int):
    if n <= 0:
        raise ValueError("ntile bucket count must be positive")
    return _PendingWindowFn("ntile", offset=n)


def lag(e, offset: int = 1, default=None):
    return _PendingWindowFn("lag", _wrap(e), offset, default)


def lead(e, offset: int = 1, default=None):
    return _PendingWindowFn("lead", _wrap(e), offset, default)


def first_value(e):
    return _PendingWindowFn("first_value", _wrap(e))


def last_value(e):
    return _PendingWindowFn("last_value", _wrap(e))


def nth_value(e, n: int):
    if n <= 0:
        raise ValueError("nth_value n must be positive")
    return _PendingWindowFn("nth_value", _wrap(e), offset=n)


def win_sum(e):
    return _PendingWindowFn("sum", _wrap(e))


def win_count(e):
    return _PendingWindowFn("count", _wrap(e))


def win_min(e):
    return _PendingWindowFn("min", _wrap(e))


def win_max(e):
    return _PendingWindowFn("max", _wrap(e))


def win_avg(e):
    return _PendingWindowFn("avg", _wrap(e))
