"""Typed configuration registry — the RapidsConf analog.

(reference: sql-plugin/.../RapidsConf.scala — builder DSL, startup vs
runtime entries, and markdown doc generation for docs/configs.md.)

Usage:
    conf = TpuConf({"spark.rapids.tpu.sql.batchSizeRows": 1 << 21})
    conf.batch_size_rows

`generate_docs()` emits docs/configs.md content from the registry, like the
reference's `RapidsConf.help()` doc emitters.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

__all__ = ["TpuConf", "ConfEntry", "REGISTRY", "generate_docs"]

REGISTRY: Dict[str, "ConfEntry"] = {}


class ConfEntry:
    def __init__(self, key: str, default: Any, doc: str, typ: Callable,
                 internal: bool = False, startup: bool = False):
        self.key = key
        self.default = default
        self.doc = doc
        self.typ = typ
        self.internal = internal
        self.startup = startup
        REGISTRY[key] = self

    def get(self, conf: "TpuConf"):
        raw = conf._settings.get(self.key, self.default)
        if raw is None:
            return None
        if self.typ is bool and isinstance(raw, str):
            return raw.lower() in ("true", "1", "yes")
        return self.typ(raw)


def _conf(key, default, doc, typ, **kw):
    return ConfEntry(f"spark.rapids.tpu.{key}", default, doc, typ, **kw)


# ----------------------------------------------------------------------
# Registry (grouped roughly like the reference's RapidsConf sections)
# ----------------------------------------------------------------------
SQL_ENABLED = _conf("sql.enabled", True,
                    "Enable TPU acceleration of SQL operators.", bool)
BATCH_SIZE_ROWS = _conf(
    "sql.batchSizeRows", 1 << 20,
    "Target rows per columnar batch read into HBM. Batches are padded to "
    "power-of-two capacities to bound XLA recompilation.", int)
BATCH_SIZE_BYTES = _conf(
    "sql.batchSizeBytes", 512 * 1024 * 1024,
    "Soft cap on device bytes per batch (analog of "
    "spark.rapids.sql.batchSizeBytes).", int)
CONCURRENT_TASKS = _conf(
    "sql.concurrentTpuTasks", 2,
    "Max tasks concurrently admitted to the TPU (TpuSemaphore permits; "
    "analog of spark.rapids.sql.concurrentGpuTasks).", int)
HBM_POOL_FRACTION = _conf(
    "memory.tpu.allocFraction", 0.85,
    "Fraction of HBM the memory manager may budget for columnar data.",
    float)
HBM_POOL_BYTES = _conf(
    "memory.tpu.poolBytes", None,
    "Explicit HBM budget in bytes; overrides allocFraction when set.",
    int)
HOST_MEMORY_LIMIT = _conf(
    "memory.host.limitBytes", 0,
    "GLOBAL host-DRAM byte budget shared by the spill store's host "
    "tier, async write buffers, and shuffle-assembly arenas "
    "(HostAlloc.scala:36 analog; limits RapidsConf.scala:337-353). "
    "Reservations over budget fire the host->disk pressure cascade; "
    "0 = unlimited.", int)
HOST_SPILL_LIMIT = _conf(
    "memory.host.spillStorageSize", 32 * 1024 * 1024 * 1024,
    "Bytes of host DRAM usable for spilled device buffers before "
    "cascading to disk.", int)
SPILL_DIR = _conf(
    "memory.spill.dir", "/tmp/srtpu-spill",
    "Directory for disk-tier spill files.", str)
OOM_MAX_RETRIES = _conf(
    "memory.oom.maxRetries", 8,
    "Bounded retries after device OOM before giving up "
    "(analog of DeviceMemoryEventHandler maxFailedOOMRetries).", int)
SHUFFLE_PARTITIONS = _conf(
    "sql.shuffle.partitions", 8,
    "Default partition count for exchanges (spark.sql.shuffle.partitions).",
    int)
SHUFFLE_DIR = _conf(
    "shuffle.dir", "/tmp/srtpu-shuffle",
    "Directory for multithreaded host shuffle files.", str)
SHUFFLE_WRITER_THREADS = _conf(
    "shuffle.multiThreaded.writer.threads", 4,
    "Thread pool size for shuffle writes "
    "(analog of RapidsShuffleManager MULTITHREADED mode).", int)
SHUFFLE_READER_THREADS = _conf(
    "shuffle.multiThreaded.reader.threads", 4,
    "Thread pool size for shuffle reads.", int)
EXCHANGE_MAP_THREADS = _conf(
    "sql.exec.exchange.mapThreads", 0,
    "Worker threads executing an exchange's map-side child partitions "
    "concurrently (each worker runs a full map partition: child "
    "execute, device partition pass, host slicing, shuffle write). "
    "Device admission still goes through the TpuSemaphore, so chip "
    "concurrency stays bounded by sql.concurrentTpuTasks; this conf "
    "overlaps the HOST halves (decode, slicing, serialization, file "
    "I/O) across partitions (the RapidsShuffleThreadedWriter analog). "
    "0 = auto (min(4, cpu cores)); 1 = serial map side.", int)
EXCHANGE_ASYNC_BROADCAST = _conf(
    "sql.exec.exchange.asyncBroadcast.enabled", True,
    "Materialize a broadcast join's build side on a background thread "
    "started when the JOIN begins executing, so the build overlaps the "
    "stream side's scan/decode instead of serializing in front of it "
    "(GpuBroadcastExchangeExec async-collect analog). The join blocks "
    "on the future at probe time, bounded by broadcastTimeoutSecs.",
    bool)
EXCHANGE_BROADCAST_TIMEOUT = _conf(
    "sql.exec.exchange.broadcastTimeoutSecs", 300.0,
    "Upper bound on the join's wait for an async broadcast build "
    "(spark.sql.broadcastTimeout analog). On timeout the join degrades "
    "to the synchronous build path on the calling thread and counts "
    "broadcastTimeoutFallbacks — it never hangs. 0 = wait forever.",
    float)
EXCHANGE_REUSE = _conf(
    "sql.exec.exchange.reuse.enabled", True,
    "Plan-level exchange deduplication (Spark's ReuseExchange rule): "
    "after fusion, structurally identical exchange subtrees (same "
    "fingerprint under gensym normalization) are rewritten to "
    "ReusedExchange nodes sharing the first occurrence's materialized "
    "shuffle blocks — one map phase per distinct subtree per query. "
    "Hits surface as exchangeReuseHits in EXPLAIN ANALYZE and the "
    "event log.", bool)
TEXT_BLOCK_SIZE = _conf(
    "sql.text.blockSize", 32 * 1024 * 1024,
    "Host decode block size (bytes) for streaming CSV/JSON scans.", int)
ADAPTIVE_ENABLED = _conf(
    "sql.adaptive.enabled", True,
    "Adaptive post-shuffle re-planning: coalesce small reduce partitions "
    "toward the target size and split skewed join stream partitions "
    "(analog of spark.sql.adaptive.* + GpuCustomShuffleReaderExec).", bool)
ADAPTIVE_TARGET_BYTES = _conf(
    "sql.adaptive.advisoryPartitionSizeInBytes", 64 * 1024 * 1024,
    "Advisory post-shuffle partition size: adjacent reduce partitions "
    "smaller than this coalesce into one task "
    "(spark.sql.adaptive.advisoryPartitionSizeInBytes).", int)
ADAPTIVE_SKEW_FACTOR = _conf(
    "sql.adaptive.skewJoin.skewedPartitionFactor", 5,
    "A join stream partition is skewed when its bytes exceed this factor "
    "times the median partition size (and the min threshold).", int)
ADAPTIVE_SKEW_MIN_BYTES = _conf(
    "sql.adaptive.skewJoin.skewedPartitionThresholdInBytes",
    256 * 1024 * 1024,
    "Minimum bytes before a stream partition is considered skewed.", int)
ADAPTIVE_COALESCE_ENABLED = _conf(
    "sql.adaptive.coalescePartitions.enabled", True,
    "AQE rule 1: merge small contiguous post-shuffle partitions toward "
    "advisoryPartitionSizeInBytes at the stage boundary "
    "(spark.sql.adaptive.coalescePartitions.enabled). Off: one task per "
    "reduce partition.", bool)
ADAPTIVE_SKEW_ENABLED = _conf(
    "sql.adaptive.skewJoin.enabled", True,
    "AQE rule 2: split join stream partitions exceeding "
    "skewedPartitionFactor x median (and the byte threshold) into "
    "row-balanced slices, each probing the full matching build "
    "partition (spark.sql.adaptive.skewJoin.enabled).", bool)
ADAPTIVE_DEMOTE_ENABLED = _conf(
    "sql.adaptive.joinDemotion.enabled", True,
    "AQE rule 3: when a shuffled hash join's build side materializes "
    "under autoBroadcastJoinThreshold, rewrite the remaining stage to a "
    "broadcast hash join and skip the stream-side shuffle entirely "
    "(runtime inverse of Spark's DemoteBroadcastHashJoin).", bool)
ADAPTIVE_CALIBRATION = _conf(
    "sql.adaptive.calibration.enabled", True,
    "Feed observed output cardinalities back into plan/stats.py as a "
    "session-scoped calibration table keyed by structural plan "
    "fingerprints, correcting CBO row estimates (join reorder) for "
    "later plans of the same subtrees.", bool)
SHUFFLE_COMPRESS = _conf(
    "shuffle.compression.codec", "lz4",
    "Shuffle wire compression: none|lz4|zstd (nvcomp analog, host-side).",
    str)
EXPLAIN = _conf(
    "sql.explain", "NONE",
    "Explain TPU planning: NONE|NOT_ON_TPU|ALL|VALIDATE "
    "(analog of spark.rapids.sql.explain). NOT_ON_TPU/ALL print the "
    "tagged plan plus static-audit findings; VALIDATE prints the full "
    "plan-audit verdict tree (ok / will_fallback / will_not_work / "
    "recompile_risk per node, see docs/static_analysis.md).", str)
AUDIT_STRICT = _conf(
    "sql.audit.strict", False,
    "Fail at PLAN time when the static plan auditor finds a "
    "will_not_work verdict (unregistered expression, dtype the device "
    "kernels cannot actually run): raises UnsupportedExpr carrying the "
    "lore id + node path of every blocked site instead of dying "
    "mid-query with an opaque XLA error. will_fallback and "
    "recompile_risk verdicts never fail the plan.", bool)
ALLOW_CPU_FALLBACK = _conf(
    "sql.allowCpuFallback", True,
    "Allow operators that cannot run on TPU to fall back to the host CPU "
    "path instead of failing.", bool)
STAGE_FUSION_ENABLED = _conf(
    "sql.exec.stageFusion.enabled", True,
    "Whole-stage XLA fusion: at plan time, collapse maximal chains of "
    "narrow operators (Filter, Project, limit-mask, the expression-eval "
    "front half of aggregates, probe-side join pre-projection, sort-key "
    "computation) into one FusedStage node compiled as a single jitted "
    "program, eliminating per-operator dispatches and intermediate "
    "batch materialization (the WholeStageCodegen analog). Barriers: "
    "exchanges, host fallbacks, cached scans, and nodes the static "
    "auditor flags recompile_risk. Per-node opt-out: set "
    "`node.fusion_opt_out = True` on the physical node.", bool)
STAGE_FUSION_MAX_OPS = _conf(
    "sql.exec.stageFusion.maxOps", 16,
    "Maximum number of member operators in one fused stage; longer "
    "chains are split. Bounds single-program XLA compile time.", int)
PROGRAM_CACHE_ENABLED = _conf(
    "sql.exec.programCache.enabled", True,
    "Process-global XLA program cache (runtime/program_cache.py): "
    "jitted operator programs are keyed by (operator class, program "
    "tag, expression fingerprint, donation flags, backend, "
    "jit-relevant conf fingerprint, input avals signature) and shared "
    "across exec instances, DataFrames, and Sessions, so a fresh "
    "same-shaped query tree performs zero new XLA compiles on a warm "
    "process. Off: every exec instance jits privately (pre-cache "
    "behavior).", bool)
PROGRAM_CACHE_MAX_ENTRIES = _conf(
    "sql.exec.programCache.maxEntries", 512,
    "LRU capacity of the process-global program cache, in cached "
    "programs (one per distinct key, including the avals signature). "
    "Power-of-two capacity bucketing keeps distinct signatures per "
    "site small, so the default comfortably holds a full TPC-H sweep. "
    "Each live XLA:CPU executable pins ~10-20 memory mappings, so the "
    "bound is also a vm.max_map_count budget (~11k maps at 512): "
    "raising it far beyond the default risks mmap exhaustion in "
    "long-lived many-query processes. Eviction counts surface as "
    "program_cache_evictions in the xla_compile event record.", int)
SHAPE_BUCKET_MIN_ROWS = _conf(
    "sql.exec.shapeBuckets.minRows", 128,
    "Floor of the capacity-bucket grid (columnar/column.py "
    "set_bucket_policy): every device buffer capacity rounds up onto "
    "{minRows * growthFactor^k}. Rounded to a power of two, minimum "
    "128 (TPU lane width). Raising the floor collapses many small "
    "batch sizes onto one bucket so structurally equal operators "
    "share one padded XLA program — fewer cold compiles, bounded "
    "extra padding. Adopted process-globally at query start "
    "(program_cache.set_active_conf), like the program cache it "
    "feeds.", int)
SHAPE_BUCKET_GROWTH = _conf(
    "sql.exec.shapeBuckets.growthFactor", 2,
    "Growth factor of the capacity-bucket grid (one of 2/4/8/16). "
    "2 is the historical next-power-of-two bucketing; 4 compiles "
    "~half as many distinct shapes per operator at a padding-waste "
    "bound of 1 - 1/growthFactor (measured waste surfaces in "
    "columnar.column.shape_stats and the bench --compile-tail "
    "report). String-key chunk counts canonicalize on the same grid "
    "(ops/sortkeys.nchunks_for_len).", int)
COMPILE_POOL_ENABLED = _conf(
    "sql.exec.compilePool.enabled", True,
    "Background XLA compilation (runtime/compile_pool.py): a bounded "
    "pool of daemon threads (tpu-compile-N) compiles stage programs "
    "ahead of first dispatch — downstream fused-stage programs are "
    "submitted at query launch and compile while upstream stages "
    "execute; warm-pack preloads compile speculatively at service "
    "startup. Dispatch NEVER waits on a background compile: a sync "
    "miss compiles inline exactly as before (a duplicate compile is "
    "accepted over a stall), and speculative tasks yield while "
    "queries are running (admission-aware). Background failures — "
    "including injected xla.compile faults — are swallowed, counted "
    "(program_cache_background_failures), and fall back to the sync "
    "path.", bool)
COMPILE_POOL_THREADS = _conf(
    "sql.exec.compilePool.threads", 2,
    "Worker threads in the background compile pool. Compilation is "
    "CPU-bound in the XLA C++ compiler (GIL released), so a small "
    "pool overlaps well with query execution without starving "
    "dispatch.", int)
WARM_PACK_PATH = _conf(
    "sql.service.warmPack.path", "",
    "Warm-pack manifest preloaded at service startup "
    "(runtime/warm_pack.py): recorded query texts are re-planned "
    "(constructing the program-cache builders) and each recorded "
    "program signature is compiled in the background pool, so the "
    "first user-visible query per shape is already warm. The "
    "manifest is validated against the host CPU-feature fingerprint "
    "and version; a mismatched or corrupt pack is skipped with a "
    "warning, never an error. Empty: no preload. Hard-disabled by "
    "SRTPU_COMPILE_CACHE=0 alongside the persistent XLA cache.", str)
WARM_PACK_RECORD = _conf(
    "sql.service.warmPack.record", "",
    "When set to a path, the session records every sql() text and "
    "every program-cache key it compiles, and save_warm_pack() (or "
    "server shutdown) writes the manifest there. Program keys "
    "containing identity fallbacks (('id', ...)) are excluded — they "
    "cannot match across processes (see the unstable-program-key "
    "lint rule).", str)
WARM_PACK_REPLAY = _conf(
    "sql.service.warmPack.replay", True,
    "Warm-pack preload strategy. True (default): execute each "
    "recorded query once at startup, which compiles every program in "
    "its tree — including programs built lazily inside "
    "execute_partition that a plan-only pass cannot reach — at the "
    "cost of startup wall time proportional to the recorded "
    "workload. False: plan-only preload; construction-time programs "
    "are compiled speculatively through the background pool and "
    "lazily-built programs still compile sync on first dispatch.",
    bool)
RESULT_CACHE_ENABLED = _conf(
    "sql.cache.enabled", False,
    "Process-global cross-query result & fragment cache "
    "(runtime/result_cache.py): whole-query Arrow results and hot "
    "exchange map outputs are keyed on name/gensym-blind structural "
    "plan fingerprints composed with scan snapshot versions (parquet "
    "path+mtime+size sets, Delta table version), so a table write "
    "soundly invalidates every dependent entry. A whole-query hit is "
    "answered on the service fast path without consuming an admission "
    "slot. Off by default (Spark/Presto posture): repeat traffic "
    "opts in per session.", bool)
RESULT_CACHE_MAX_BYTES = _conf(
    "sql.cache.maxBytes", 256 * 1024 * 1024,
    "Byte budget of the result cache across both tiers (whole-query "
    "Arrow results + cached exchange fragments). Least-recently-used "
    "entries are evicted past the budget; cached bytes also charge "
    "the host-memory budget (spark.rapids.tpu.memory.host.limitBytes) "
    "and are released first under host-memory pressure.", int)
RESULT_CACHE_FRAGMENTS = _conf(
    "sql.cache.fragments.enabled", True,
    "Fragment tier of the result cache: materialized exchange map "
    "outputs are cached by exchange-subtree fingerprint and served as "
    "cached sources (CachedFragmentExec) in later plans, eliding the "
    "whole map phase. Only consulted when sql.cache.enabled is on.", bool)
RESULT_CACHE_MAX_ENTRY_BYTES = _conf(
    "sql.cache.maxEntryBytes", 64 * 1024 * 1024,
    "Largest single result or fragment the cache will store. Results "
    "bigger than this execute normally and are never cached (a "
    "full-table scan must not wipe the working set of an interactive "
    "dashboard mix).", int)
METRICS_LEVEL = _conf(
    "sql.metrics.level", "MODERATE",
    "Metric verbosity: ESSENTIAL|MODERATE|DEBUG.", str)
METRICS_SYNC = _conf(
    "sql.metrics.sync", False,
    "Synchronize the device stream at batch boundaries inside operator "
    "timers (a trivial op is enqueued and block_until_ready'd before "
    "the timer stops). OFF by default: jax dispatch is async, so "
    "default op-time metrics measure DISPATCH time and actual kernel "
    "execution is attributed to whichever downstream operator first "
    "blocks (usually the D2H fetch at the plan root) — see "
    "docs/observability.md. Turning this on yields debug-grade "
    "per-operator execution times at the cost of pipelining.", bool)
EVENT_LOG_ENABLED = _conf(
    "sql.eventLog.enabled", False,
    "Write a structured per-query JSONL event log (the Spark event-log "
    "analog): plan with lore ids, per-operator MetricSet snapshots, "
    "memory watermarks, shuffle bytes, XLA compile stats. Consumed by "
    "tools/profile_report.py and EXPLAIN ANALYZE post-processing.",
    bool)
EVENT_LOG_DIR = _conf(
    "sql.eventLog.dir", "/tmp/srtpu-events",
    "Directory for per-query event-log JSONL files.", str)
TRACE_ENABLED = _conf(
    "sql.trace.enabled", True,
    "Open per-query spans (profiler/tracing.py) around queue wait, "
    "planning, AQE stage decisions, compiles, pool map tasks, shuffle "
    "fetches, spills, collective launches and retry/degrade recovery. "
    "Spans assemble into one trace per query — written to the event "
    "log as trace_span records (when sql.eventLog.enabled) and reduced "
    "to critical-path latency shares (profiler/critical_path.py) shown "
    "in EXPLAIN ANALYZE root annotations and profile_report --trace. "
    "Overhead is gated <3% on the q6 A/B (tests/test_tracing.py).",
    bool)
TRACE_SAMPLE_RATE = _conf(
    "sql.trace.sampleRate", 1.0,
    "Fraction of queries traced (0.0-1.0). Sampling is deterministic "
    "on the query id (crc32 bucket), so a query's driver threads, "
    "pool workers and executor fragments always agree on the decision "
    "and retries of the same query id re-sample identically.", float)
TELEMETRY_ENABLED = _conf(
    "sql.telemetry.enabled", True,
    "Expose the process-global telemetry registry (profiler/"
    "telemetry.py: latency/queue-wait histograms, admission and cache "
    "counters, pool-saturation and memory-watermark gauges) through "
    "the service gateway's `metrics` verb and its Prometheus text "
    "dump. Recording itself is always-on and O(1) per observation; "
    "this gates the scrape surface.", bool)
MULTITHREADED_READ_THREADS = _conf(
    "sql.format.parquet.multiThreadedRead.numThreads", 4,
    "Thread pool for the multithreaded (cloud) parquet reader "
    "(analog of spark.rapids.sql.multiThreadedRead.numThreads).", int)
PARQUET_READER_TYPE = _conf(
    "sql.format.parquet.reader.type", "AUTO",
    "AUTO|PERFILE|COALESCING|MULTITHREADED (GpuParquetScan reader "
    "types). AUTO picks COALESCING when the scan has many files "
    "smaller than the coalescing target (fewer host->device uploads), "
    "else MULTITHREADED (decode prefetch overlapping device "
    "compute).", str)
PARQUET_DEVICE_DECODE = _conf(
    "sql.format.parquet.deviceDecode.enabled", True,
    "Decode eligible Parquet column chunks ON DEVICE (flat "
    "INT32/INT64/FLOAT/DOUBLE/BYTE_ARRAY chunks; UNCOMPRESSED or "
    "SNAPPY; PLAIN or dictionary encoded; v1 and v2 data pages): raw "
    "bytes upload once, PLAIN lane assembly + RLE run expansion + "
    "string offset extraction + def-level masking run as XLA programs "
    "(GpuParquetScan.scala:3364 Table.readParquet analog). Snappy "
    "pages decompress per-page on the multithreaded prefetch pool, "
    "off the compute thread. Ineligible columns fall back to host "
    "pyarrow per column (reason counters in EXPLAIN ANALYZE). On the "
    "CPU backend the path only fires when this conf is set "
    "explicitly: host pyarrow decode and the 'device' kernels share "
    "the same silicon there, and pyarrow's native decoder wins.", bool)
PARQUET_DEVICE_SNAPPY = _conf(
    "sql.parquet.deviceSnappy", False,
    "Decompress qualifying snappy pages ON DEVICE (jitted XLA scan "
    "over the parsed literal/copy element table: run-ownership map + "
    "log-depth pointer doubling resolves every output byte to a "
    "literal source — the nvcomp-snappy analog). Applies to v1 PLAIN "
    "pages of non-nullable chunks whose element table fits a "
    "static-shape bucket; the host walks only the tag bytes. Other "
    "pages keep the host prefetch-pool decompress. Off by default: "
    "per-page output shapes vary, so cold scans pay extra XLA "
    "compiles.", bool)
HOST_STAGING_POOL_BYTES = _conf(
    "memory.host.stagingPoolBytes", 256 * 1024 * 1024,
    "Byte cap on the pinned staging pool: reusable pow2-bucketed host "
    "buffers for raw-chunk reads, snappy decompression targets, and "
    "H2D upload staging in the device parquet scan (HostAlloc pinned "
    "pool analog). Cached buffers draw from memory.host.limitBytes; "
    "leases past the cap are transient (freed on release).", int)
PARQUET_COALESCING_TARGET = _conf(
    "sql.format.parquet.coalescing.targetBytes", 128 << 20,
    "COALESCING reader: files group until their on-disk size reaches "
    "this target; each group's files decode in parallel and upload as "
    "one batch stream (GpuParquetScan COALESCING analog).", int)
CLUSTER_EXECUTORS = _conf(
    "cluster.executors", 0,
    "Executor worker processes for host-side scan decode (the "
    "driver/executor split of Plugin.scala; 0 = in-process). The TPU "
    "client stays in the driver — executors parallelize host decode and "
    "ship Arrow IPC back; heartbeat loss requeues their tasks.", int)
CLUSTER_BLOCK_ADVERTISE_HOST = _conf(
    "cluster.blockServer.advertiseHost", "127.0.0.1",
    "Host address the shuffle block server advertises to peers in its "
    "block locations (the server itself binds 0.0.0.0, so remote "
    "executors can connect when this is set to a routable address). "
    "Default keeps the single-host topology: every executor process "
    "lives on this machine and fetches over loopback.", str)
CLUSTER_HEARTBEAT_TIMEOUT = _conf(
    "cluster.heartbeatTimeoutSeconds", 3.0,
    "Executor liveness: no heartbeat for this long marks the executor "
    "lost and re-executes its in-flight tasks "
    "(RapidsShuffleHeartbeatManager analog).", float)
FAULTS_PLAN = _conf(
    "sql.debug.faults.plan", None,
    "Deterministic fault-injection plan (runtime/faults.py): "
    "';'-separated rules `point[:selector]*[:action]` over the named "
    "fault points (block.fetch, rpc.send, executor.task, "
    "device.dispatch, exchange.map, spill.write, xla.compile). "
    "Selectors: nth=N, prob=P, seed=S, times=K, query=SUB, op=NAME; "
    "actions: raise=NAME, delay=MS, kill. Same plan + seed injects the "
    "identical failure sequence. The SRTPU_FAULTS env var installs the "
    "same grammar process-wide (spark-rapids-jni CUDA fault-injection "
    "analog). None disables with zero overhead.", str)
SHUFFLE_MAX_REGENERATIONS = _conf(
    "sql.shuffle.maxRegenerations", 2,
    "Upper bound on lineage-based shuffle regeneration rounds per "
    "distributed query: on FetchFailed/executor loss the driver "
    "re-executes only the lost map partitions on surviving executors "
    "and retries the reduce, at most this many times before the "
    "failure propagates (Spark stage-retry analog).", int)
FETCH_RETRY_MAX = _conf(
    "sql.shuffle.fetch.maxRetries", 2,
    "Transport-level retries per shuffle block fetch before the "
    "FetchFailed escalates to the driver's lineage regeneration. "
    "Retries wait exponential-backoff-with-jitter delays "
    "(runtime/backoff.py) starting at sql.shuffle.fetch.retryWaitMs.",
    int)
FETCH_RETRY_WAIT_MS = _conf(
    "sql.shuffle.fetch.retryWaitMs", 50.0,
    "Base backoff delay (ms) for shuffle block fetch retries; attempt "
    "k waits min(base * 2^k, 10s) with deterministic jitter.", float)
SERVICE_MAX_QUERY_RETRIES = _conf(
    "sql.service.maxQueryRetries", 1,
    "Transparent re-admissions of a query that failed with a "
    "classified-TRANSIENT error (runtime/faults.is_transient_error: "
    "FetchFailed, executor loss, injected faults, connection resets — "
    "never cancellation, deadline, or user errors). Each retry is a "
    "fresh admission with the ORIGINAL deadline still binding, "
    "surfaced as a query_retry event. 0 disables.", int)
DEGRADE_TO_HOST = _conf(
    "sql.exec.degradeToHost.enabled", True,
    "Graceful device->host degradation: an operator whose device "
    "kernel raises a non-OOM, non-cancellation error re-evaluates the "
    "batch on the host interpreter (exec/host_fallback path), and "
    "after two device failures on the same program stops dispatching "
    "to the device for the remainder of the query (counted as "
    "degradedToHost, event-logged as degrade_to_host, visible in "
    "EXPLAIN ANALYZE).", bool)
MAX_READER_BATCH_SIZE_ROWS = _conf(
    "sql.reader.batchSizeRows", 1 << 21,
    "Soft limit on rows per scan batch.", int)
AGG_OPTIMISTIC_GROUPS = _conf(
    "sql.agg.optimisticGroups", 4096,
    "HBM-cached grouped aggregations first try ONE fused device program "
    "whose output is sized to this many groups (plus an overflow flag); "
    "low-cardinality queries then cost a single device round trip. "
    "On overflow the exact multi-pass path re-runs. 0 disables.", int)
# (decimal128 is always-on: exact two-limb kernels in ops/decimal128.py;
# the former sql.decimal128.enabled gate had no remaining effect and was
# removed rather than shipped as a silent no-op)
LORE_DUMP_IDS = _conf(
    "sql.lore.idsToDump", None,
    "LORE ids whose input batches should be dumped for replay "
    "(analog of spark.rapids.sql.lore.idsToDumpPath).", str)
LORE_DUMP_PATH = _conf(
    "sql.lore.dumpPath", "/tmp/srtpu-lore",
    "Directory for LORE operator dumps.", str)
JOIN_BLOOM_ENABLED = _conf(
    "sql.join.bloomFilter.enabled", False,
    "Runtime bloom-filter join pruning: shuffled inner/left_semi/right "
    "equi-joins with a small scan-shaped build side run the build once "
    "into a device bloom filter and mask the stream side BEFORE its "
    "exchange (reference: GpuBloomFilterAggregate + "
    "GpuBloomFilterMightContain via InSubqueryExec runtime filters). "
    "Off by default pending broader production soak.", bool)
JOIN_BLOOM_MAX_BUILD_ROWS = _conf(
    "sql.join.bloomFilter.maxBuildRows", 4_000_000,
    "Upper bound on the ESTIMATED build-side rows for runtime "
    "bloom-filter creation (filter memory is ~1 byte/bit at 8 "
    "bits/row).", int)
DELTA_DV_ENABLED = _conf(
    "delta.deletionVectors.enabled", False,
    "DELETE writes a deletion-vector (roaring bitmap) file marking "
    "dead rows instead of rewriting the data file (reference: Delta "
    "DV support in delta-33x GpuDeltaParquetFileFormat/GpuDeleteCommand"
    "). Reads apply DVs regardless of this flag.", bool)
FILECACHE_ENABLED = _conf(
    "filecache.enabled", False,
    "Cache scan input files on local disk, keyed by (path, mtime, "
    "size) with LRU eviction — repeated scans of network-mounted "
    "inputs skip the fetch (reference: spark.rapids.filecache.enabled, "
    "GpuFileCache). Off by default: pure overhead for local inputs.",
    bool)
FILECACHE_DIR = _conf(
    "filecache.dir", "/tmp/srtpu-filecache",
    "Local directory for cached input files.", str)
FILECACHE_MAX_BYTES = _conf(
    "filecache.maxBytes", 16 << 30,
    "Upper bound on cached bytes; least-recently-used entries evict "
    "past it.", int)
CBO_ENABLED = _conf(
    "sql.optimizer.cbo.enabled", False,
    "Cost-based device-vs-host placement: tiny Project/Filter inputs "
    "the host interpreter covers run on the CPU bridge instead of "
    "paying a device dispatch (reference: CostBasedOptimizer.scala + "
    "GpuCostModel, also default-off). Decisions show in explain as "
    "'CBO: ...'.", bool)
CBO_SMALL_INPUT_ROWS = _conf(
    "sql.optimizer.cbo.smallInputRows", 64,
    "CBO small-input bound: estimated input rows at or below this run "
    "host-side when coverable.", int)
DISTINCT_AGG_REWRITE = _conf(
    "sql.optimizer.distinctAggRewrite.enabled", True,
    "Rewrite count(DISTINCT x) into a two-level hash aggregation (the "
    "single-distinct-child case of Catalyst's "
    "RewriteDistinctAggregates): an inner DISTINCT group-by over "
    "(keys..., x) then an outer Count. Both levels run the bucketed "
    "hash-aggregate pass (incl. hash-once string keying) instead of "
    "CollectAggExec's full multi-chunk lexsort.", bool)
JOIN_REORDER_ENABLED = _conf(
    "sql.optimizer.joinReorder.enabled", True,
    "Cost-based join reordering (analog of Catalyst's "
    "CostBasedJoinReorder / spark.sql.cbo.joinReorder.enabled): maximal "
    "chains of INNER equi-joins are reordered into the left-deep order "
    "minimizing estimated intermediate cardinalities, from bottom-up "
    "row/NDV estimates (sampled scan statistics, Chao1 extrapolation). "
    "Outer/semi/anti/cross joins and non-equi conditions are never "
    "reordered across. The smaller estimated side of every join lands "
    "on the build side, keeping broadcast decisions consistent.", bool)
JOIN_REORDER_DP_RELATIONS = _conf(
    "sql.optimizer.joinReorder.maxDpRelations", 8,
    "Join chains with at most this many relations are ordered by exact "
    "dynamic programming over left-deep orders (Selinger); larger "
    "chains use a greedy min-intermediate-cardinality extension "
    "(analog of spark.sql.cbo.joinReorder.dp.threshold).", int)
PYTHON_CONCURRENT_WORKERS = _conf(
    "python.concurrentPythonWorkers", 4,
    "Worker-process slots for pandas transforms (mapInPandas); "
    "acquisition blocks above it (reference: "
    "spark.rapids.python.concurrentPythonWorkers, "
    "PythonWorkerSemaphore).", int)
MESH_COMPRESS = _conf(
    "mesh.shuffle.compress", False,
    "Compress mesh-exchange round buffers ON DEVICE before the "
    "cross-shard move (byte-plane packing - the TPU-native nvcomp-LZ4 "
    "analog, NvcompLZ4CompressionCodec.scala; LZ4 itself is a "
    "sequential match chain that does not vectorize on the VPU). "
    "~4x on int-dominated payloads; incompressible buffers move raw "
    "when packing would not shrink them.", bool)
DELTA_AUTOCOMPACT_MIN_FILES = _conf(
    "delta.autoCompact.minFiles", 0,
    "When > 0, a Delta append auto-compacts once the table holds at "
    "least this many live files smaller than half the target size "
    "(reference: delta auto-compaction / "
    "GpuOptimizeWriteExchangeExec). 0 disables.", int)
DELTA_AUTOCOMPACT_TARGET_BYTES = _conf(
    "delta.autoCompact.targetBytes", 128 << 20,
    "Target output file size for Delta OPTIMIZE / auto-compaction.",
    int)
PYTHON_GROUPED_CHUNK_BYTES = _conf(
    "python.groupedChunkBytes", 64 << 20,
    "applyInPandas/aggregate-in-pandas partitions larger than this "
    "many host bytes ship to the python worker in chunks cut at GROUP "
    "boundaries (OOM-safe: a group is never split).", int)
RETRY_COVERAGE_ENABLED = _conf(
    "memory.retryCoverage.enabled", False,
    "Track, per engine call-site, whether device allocations happen "
    "inside an OOM-retry scope (with_retry / retry_no_split) — the "
    "allocations outside it are the ones that die instead of spilling "
    "(reference: AllocationRetryCoverageTracker.scala). Debug tool; "
    "report via memory.diagnostics.coverage_report().", bool)
ASYNC_WRITE_ENABLED = _conf(
    "sql.asyncWrite.enabled", True,
    "Run file-part encode + disk I/O on a writer pool off the compute "
    "thread (reference: io/async AsyncOutputStream; "
    "spark.rapids.sql.asyncWrite.queryOutput.enabled).", bool)
ASYNC_WRITE_MAX_IN_FLIGHT = _conf(
    "sql.asyncWrite.maxInFlightHostMemoryBytes", 2 << 30,
    "Upper bound on host bytes held by scheduled-but-unfinished async "
    "writes; submissions block above it (always admitting one task), "
    "so a slow disk cannot pile the query's output into host memory "
    "(reference: TrafficController).", int)
ASYNC_WRITE_THREADS = _conf(
    "sql.asyncWrite.numThreads", 4,
    "Writer-pool threads for the async write path.", int)
SORT_OOC_ENABLED = _conf(
    "sql.sort.outOfCore.enabled", True,
    "Enable out-of-core sort (range-exchange to spill files + "
    "per-partition sorts) for big inputs.", bool)
SORT_OOC_THRESHOLD = _conf(
    "sql.sort.outOfCore.thresholdBytes", 2 << 30,
    "Device bytes of sort input above which the out-of-core path "
    "activates.", int)
WINDOW_CHUNK_ROWS = _conf(
    "sql.window.chunkRows", 1 << 22,
    "Row count above which chunkable window specs (running frames + "
    "ranking over fixed-width keys) stream chunk-by-chunk through the "
    "out-of-core sort with carried per-partition state, so a window "
    "partition no longer must fit device memory (reference: "
    "GpuRunningWindowExec batched running windows). 0 disables.", int)
AGG_STRING_HASH_KEYS = _conf(
    "sql.agg.stringHashKeys.enabled", True,
    "Hash-once 64-bit keying of string group-by columns: the "
    "aggregation hash pass derives its bucket hashes from the same "
    "packed order-key chunk words the exact verify step compares "
    "(xxhash64-style fold), so string keys are read once per batch "
    "instead of twice (murmur3 walk + chunk build). Collisions stay "
    "exact — a row joins a bucket only when the chunk compare against "
    "the bucket representative passes; colliding rows retry the next "
    "round and survivors take the sort path (cudf hash-based string "
    "keying analog).", bool)
AGG_MAX_MERGE_ROWS = _conf(
    "sql.agg.maxMergeRows", 1 << 21,
    "Upper bound on buffered partial-aggregate rows merged in one "
    "concat pass. Buffered partials live in the spill store; when the "
    "total group state exceeds this, the aggregation repartitions every "
    "partial into hash buckets of disjoint keys and merges/finalizes "
    "each bucket separately — the out-of-core fallback "
    "(GpuAggregateExec.scala:863-894 repartition algorithm analog).", int)
AGG_FORCE_MERGE_PASSES = _conf(
    "sql.agg.forceSinglePassMerge", False,
    "Testing: force aggregate merge in one concat pass.", bool, internal=True)
JOIN_BUILD_BUDGET = _conf(
    "sql.join.buildSideBudgetBytes", 2 << 30,
    "When a join partition's build side exceeds this many bytes, both "
    "sides are rehashed into disjoint-key sub-partitions (spillable "
    "piles) joined one at a time, so builds bigger than device memory "
    "complete instead of dying (GpuSubPartitionHashJoin.scala:617 "
    "analog). 0 disables.", int)
BROADCAST_THRESHOLD = _conf(
    "sql.autoBroadcastJoinThreshold", 10 * 1024 * 1024,
    "Build sides estimated at or below this many bytes use a broadcast "
    "hash join (build collected once, no exchange); larger builds "
    "shuffle both sides on the join keys and join per partition "
    "(analog of spark.sql.autoBroadcastJoinThreshold + the reference's "
    "useSizedJoin decision). -1 disables broadcast.", int)
MESH_DEVICES = _conf(
    "mesh.devices", 0,
    "Number of devices in the SPMD execution mesh. When > 0, hash "
    "exchanges run as one all_to_all collective over ICI "
    "(jax.sharding.Mesh) instead of the host file shuffle — the TPU-pod "
    "analog of the reference's UCX shuffle mode. 0 disables (single-chip "
    "+ host shuffle).", int)
SPMD_STAGE_ENABLED = _conf(
    "mesh.spmdStage.enabled", True,
    "Fuse a mesh exchange with its consumer (final hash aggregate, "
    "fusable filter/project chain, co-partitioned join input) into ONE "
    "shard_map program per stage: partition ids, the all_to_all "
    "collective, and the consumer run inside the same jitted program — "
    "no per-round host sync and no spill-handle park/unpark between "
    "exchange and consumer. Stages whose staged working set exceeds "
    "mesh.spmdStage.maxBytes (and any stage hit by a mesh.collective "
    "fault) fall back to the streaming round-based exchange.", bool)
SPMD_STAGE_MAX_BYTES = _conf(
    "mesh.spmdStage.maxBytes", 256 << 20,
    "Working-set budget for a fused SPMD stage: the stage drains its "
    "map side first, and when the staged bytes exceed this the stage "
    "degrades to the bounded-memory round-based exchange instead of "
    "materializing everything into one collective round (the bounce-"
    "buffer memory model keeps peak HBM at O(devices * round) there).",
    int)
SPMD_RESHARD_ENABLED = _conf(
    "mesh.spmdStage.reshard.enabled", True,
    "AQE mesh analog of partition coalescing: after the map side of a "
    "fused SPMD stage materializes, shrink the ACTIVE mesh axis for "
    "small stages (partition ids drawn mod n_active < n_devices) so "
    "tiny reduce states do not shard 8 ways; trailing shards receive "
    "nothing and emit no batches. Decided from exact staged byte "
    "stats, recorded as an aqe_replan decision.", bool)
SPMD_RESHARD_MIN_BYTES = _conf(
    "mesh.spmdStage.reshard.minBytesPerShard", 1 << 20,
    "Target minimum staged bytes per active shard for the AQE mesh "
    "re-shard rule: the active axis halves until each remaining shard "
    "would see at least this many bytes (or one shard remains).", int)
SERVICE_QUERY_TIMEOUT_SECS = _conf(
    "sql.service.queryTimeoutSecs", 0.0,
    "Wall-clock deadline per query, measured from submission (queue "
    "time counts). Past it the query's CancelToken trips and the next "
    "cooperative checkpoint (batch/stage/shuffle boundary, semaphore "
    "wait) raises QueryTimedOut; queued queries past their deadline "
    "are killed without ever being admitted. 0 = no deadline.", float)
SERVICE_SCHEDULER_MODE = _conf(
    "sql.service.scheduler.mode", "fair",
    "Cross-query scheduling policy: 'fair' (deficit-round-robin across "
    "weighted pools, FIFO within a pool — the Spark fair-scheduler "
    "analog) or 'fifo' (global submission order, pools ignored).", str)
SERVICE_SCHEDULER_POOLS = _conf(
    "sql.service.scheduler.pools", "default:1",
    "Weighted scheduler pools as 'name:weight,name:weight,...'. Under "
    "saturation a pool's admission share is proportional to its "
    "weight; a query picks its pool via sql.service.pool (unknown "
    "pool names are created on the fly with weight 1).", str)
SERVICE_POOL = _conf(
    "sql.service.pool", "default",
    "Scheduler pool this session's queries submit into (the "
    "spark.scheduler.pool analog). Pool weight also becomes the "
    "TpuSemaphore acquire priority, so heavier pools win device "
    "admission ties.", str)
SERVICE_MAX_CONCURRENT = _conf(
    "sql.service.maxConcurrentQueries", 4,
    "Upper bound on queries RUNNING concurrently in one engine "
    "process; further admitted work queues in the scheduler. Distinct "
    "from sql.concurrentTpuTasks, which bounds tasks on the chip "
    "within the already-admitted queries.", int)
SERVICE_ADMISSION_ENABLED = _conf(
    "sql.service.admission.enabled", True,
    "Memory-aware admission control: a query is only admitted when "
    "its plan-derived device+host estimate fits alongside the "
    "already-admitted queries' estimates (scan sizes + join build "
    "sides from the planner's cardinality estimator). Queries whose "
    "solo estimate exceeds the budget still run — alone.", bool)
SERVICE_ADMISSION_DEVICE_FRACTION = _conf(
    "sql.service.admission.deviceFraction", 0.8,
    "Fraction of the DeviceManager budget the admission controller "
    "hands out to concurrently admitted query estimates.", float)
SERVICE_ADMISSION_HOST_FRACTION = _conf(
    "sql.service.admission.hostFraction", 0.8,
    "Fraction of the HostMemoryManager budget admission may commit "
    "(ignored while the host budget is unlimited).", float)
SERVICE_ADMISSION_DEVICE_LIMIT = _conf(
    "sql.service.admission.deviceLimitBytes", 0,
    "Explicit admission byte budget for device estimates; overrides "
    "deviceFraction * DeviceManager budget when > 0.", int,
    internal=True)
FLEET_DIRECTORY = _conf(
    "sql.fleet.directory", None,
    "Root directory of the fleet peer registry (fleet/directory.py). "
    "When set, serve() joins the multi-host serving fabric: register "
    "in the directory, start the peer cache server, pull warm state "
    "from the longest-lived peer, and consult peers on result-cache "
    "misses. Unset (the default) disables the fleet entirely.", str)
FLEET_ADVERTISE_HOST = _conf(
    "sql.fleet.advertiseHost", "127.0.0.1",
    "Host peers use to reach this member's peer cache server (the "
    "address written into the peer directory). Single-box fleets keep "
    "the loopback default; multi-host deployments set the reachable "
    "interface.", str)
FLEET_CONSULT_FANOUT = _conf(
    "sql.fleet.consultFanout", 2,
    "How many rendezvous-ordered peers a result-cache miss probes "
    "before recomputing locally. 1 asks only the key's owner; higher "
    "values tolerate membership churn (an entry published before a "
    "join may live one step down the preference order) at the cost of "
    "extra round trips on a true fleet-wide miss.", int)
FLEET_FETCH_TIMEOUT_SECS = _conf(
    "sql.fleet.fetchTimeoutSecs", 5.0,
    "Socket timeout per peer-cache request (connect + transfer). A "
    "peer slower than this is treated as a miss after the bounded "
    "retries — recomputing locally is always sound.", float)
FLEET_FETCH_RETRIES = _conf(
    "sql.fleet.fetchRetries", 2,
    "Transient-failure retries per peer-cache fetch, on "
    "deterministic-jitter backoff (runtime/backoff.py). Structural "
    "failures (protocol violations) never retry.", int)
FLEET_FETCH_BACKOFF_MS = _conf(
    "sql.fleet.fetchBackoffMs", 20.0,
    "Base backoff between peer-cache fetch retries; doubles per "
    "attempt with deterministic jitter seeded per (peer, verb).",
    float)
FLEET_INVALIDATE_RETRIES = _conf(
    "sql.fleet.invalidateRetries", 1,
    "Retries per peer when broadcasting a cache invalidation. "
    "Deliveries are best-effort by design — a peer that misses the "
    "broadcast holds entries under keys no requester will compute "
    "again (keys embed scan snapshots), and the requester-side "
    "snapshot re-stat rejects the race window.", int)
FLEET_EXPORT_MAX_BYTES = _conf(
    "sql.fleet.exportMaxBytes", 256 << 20,
    "Byte budget for the export store — the LRU index of locally "
    "computed results a member serves to peers. Held by reference to "
    "the result cache's own immutable tables, so this bounds the "
    "index's ability to pin evicted entries alive, not a second copy.",
    int)
FLEET_WARM_PULL = _conf(
    "sql.fleet.warmPull", True,
    "Cold-join warm-state publication: pull the warm-pack manifest "
    "and calibration table from the longest-lived live peer at join "
    "and replay it through the background compile pool, so a fresh "
    "process reaches steady-state latency within its first few "
    "queries. Advisory — any failure serves cold.", bool)
FLEET_TENANT_MAX_INFLIGHT = _conf(
    "sql.fleet.tenantMaxInflight", 0,
    "Fleet-wide cap on one tenant's in-flight routed queries (the "
    "route verb's admission control); a tenant at its cap gets "
    "rejected leases until it completes work. 0 = unlimited.", int)
FLEET_PEER_MAX_INFLIGHT = _conf(
    "sql.fleet.peerMaxInflight", 0,
    "Per-peer in-flight ceiling for the router: past it, a query "
    "spills to the next peer in its fingerprint's rendezvous order "
    "(stable, so overflow lands warm too). When every peer is "
    "saturated the sticky choice queues rather than spill cold. "
    "0 = unlimited (always sticky).", int)
LOCKDEP_ENABLED = _conf(
    "sql.debug.lockdep.enabled", False,
    "Runtime lockdep witness (runtime/lockdep.py): wrap engine locks, "
    "the TpuSemaphore permit and exchange ride slot, record the "
    "acquisition-order graph, and report lock-order cycles at edge "
    "FORMATION time plus bounded-pool self-waits. Deadline kills "
    "attach an all-threads held-resource dump to QueryTimedOut and "
    "the event log. Locks created before the session exist are only "
    "covered when env SRTPU_LOCKDEP=1 was set before import. Debug "
    "tool; overhead is small (<3% on the test suite) but nonzero.",
    bool)
LOCKDEP_RAISE = _conf(
    "sql.debug.lockdep.raiseOnCycle", True,
    "With lockdep enabled: raise LockOrderViolation/PoolSelfWait at "
    "the acquisition that forms the cycle (fail fast, the kernel-"
    "lockdep behavior). False records findings for the "
    "concurrency_report event without raising.", bool)
LEDGER_ENABLED = _conf(
    "sql.debug.ledger.enabled", False,
    "Runtime resource ledger (runtime/ledger.py): count every "
    "acquire/release of device/host reservations, staging leases, "
    "spill handles, shuffle pins, semaphore permits, ride slots and "
    "result-cache charges, attribute them to the submitting query, "
    "and assert owner-scoped kinds balance at every terminal state "
    "(FINISHED, CANCELLED, TIMED_OUT alike). Deadline kills and "
    "budget-exhaustion errors attach an outstanding-holders dump "
    "(kind, site, thread, query) next to the lockdep dump, and every "
    "profiled query emits a resource_ledger event. Acquisitions made "
    "before the session exist are only covered when env SRTPU_LEDGER=1 "
    "was set first. Debug tool; overhead <5% on the test suite.",
    bool)
LEDGER_RAISE = _conf(
    "sql.debug.ledger.raiseOnImbalance", True,
    "With the ledger enabled: raise ResourceLeakError when a query "
    "finishes cleanly with owner-scoped resources outstanding (fail "
    "fast). False records findings for the resource_ledger event "
    "without raising; error-path imbalances are always recorded, "
    "never raised over the original error.", bool)
LEDGER_POISON = _conf(
    "sql.debug.ledger.poison", False,
    "With the ledger enabled: fill released cached staging buffers "
    "with 0xAB before they return to the pool free list, turning "
    "latent use-after-release reads (the PR 4 corruption class) into "
    "deterministic garbage instead of data-dependent flakes. Debug "
    "mode: adds a memset per lease release.", bool)
RACEDEP_ENABLED = _conf(
    "sql.debug.racedep.enabled", False,
    "Runtime data-race witness (runtime/racedep.py): Eraser-style "
    "lockset tracking on instrumented shared structures (program "
    "cache observed table, telemetry registry, result-cache LRU, "
    "shuffle map-file slots, operator metric sets), recording "
    "(thread, lockset) per access and reporting when a shared slot's "
    "candidate lockset collapses to empty. Locks created before the "
    "session exist are only lockset-visible when env SRTPU_RACEDEP=1 "
    "was set before import. Debug tool; overhead is small (<3% on "
    "instrumented query paths) but nonzero.", bool)
RACEDEP_RAISE = _conf(
    "sql.debug.racedep.raiseOnRace", True,
    "With racedep enabled: raise DataRaceDetected at the access that "
    "collapses a shared slot's lockset (fail fast). False records "
    "findings for the race_report event without raising.", bool)


class TpuConf:
    """Immutable-ish snapshot of settings, resolved against the registry."""

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._settings = dict(settings or {})

    def get(self, entry: ConfEntry):
        return entry.get(self)

    def is_set(self, entry: ConfEntry) -> bool:
        """Whether the user supplied this key (vs the registry default).
        Lets auto policies defer to an explicit setting."""
        return entry.key in self._settings

    def set(self, key: str, value) -> "TpuConf":
        s = dict(self._settings)
        s[key] = value
        return TpuConf(s)

    # Convenience accessors used across the engine.
    @property
    def batch_size_rows(self):
        return self.get(BATCH_SIZE_ROWS)

    @property
    def shuffle_partitions(self):
        return self.get(SHUFFLE_PARTITIONS)

    @property
    def concurrent_tasks(self):
        return self.get(CONCURRENT_TASKS)

    @property
    def explain(self):
        return self.get(EXPLAIN).upper()

    @property
    def allow_cpu_fallback(self):
        return self.get(ALLOW_CPU_FALLBACK)


def generate_docs() -> str:
    """Emit configs.md content (the reference generates docs/configs.md
    from RapidsConf the same way)."""
    lines = ["# spark-rapids-tpu configuration", "",
             "Name | Description | Default", "-----|-------------|--------"]
    for key in sorted(REGISTRY):
        e = REGISTRY[key]
        if e.internal:
            continue
        lines.append(f"{e.key} | {e.doc} | {e.default}")
    return "\n".join(lines) + "\n"
