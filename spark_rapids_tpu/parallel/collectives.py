"""ICI partition exchange: the all-to-all shuffle core.

Replaces the reference's UCX peer-to-peer transfer path
(reference: shuffle-plugin/.../UCXShuffleTransport.scala:49,
RapidsShuffleClient/Server) with a single XLA collective: rows are bucketed
by target shard inside each shard (one stable sort, static shapes), then
`jax.lax.all_to_all` moves the buckets over ICI. No bounce buffers, no tag
matching, no flow control — XLA schedules the transfer.

All functions here run INSIDE shard_map (they reference an axis name).
Bucket capacity is static = the shard's batch capacity (safe upper bound:
all local rows could target one shard). A tighter 2x-expected bucket with
overflow retry is the planned optimization.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["exchange_rows"]


def exchange_rows(arrays: Sequence[jnp.ndarray], mask, pids,
                  n_shards: int, axis_name: str = "data"):
    """Exchange rows so each row lands on shard `pids[row]`.

    arrays: per-shard [cap] buffers (fixed-width row payloads).
    mask:   bool[cap] live rows.
    pids:   int32[cap] target shard per row (garbage where dead).

    Returns (out_arrays [n*cap], out_mask [n*cap]) on each shard: the rows
    received from all shards, dead-padded.
    """
    cap = mask.shape[0]
    eff_pid = jnp.where(mask, pids, n_shards)      # dead rows -> bucket n
    order = jnp.argsort(eff_pid, stable=True)
    pid_sorted = eff_pid[order]
    # rank within each target bucket
    ranks = jnp.arange(cap)
    bucket_start = jnp.searchsorted(pid_sorted, jnp.arange(n_shards + 1),
                                    side="left")
    rank_in_bucket = ranks - bucket_start[jnp.clip(pid_sorted, 0, n_shards)]
    live_sorted = pid_sorted < n_shards

    safe_pid = jnp.clip(pid_sorted, 0, n_shards - 1)
    safe_rank = jnp.clip(rank_in_bucket, 0, cap - 1)

    out_arrays = []
    for a in arrays:
        a_sorted = a[order]
        send = jnp.zeros((n_shards, cap), a.dtype)
        # scatter-add: dead rows contribute identity even when their
        # clipped (pid, rank) collides with a live slot
        send = send.at[safe_pid, safe_rank].add(
            jnp.where(live_sorted, a_sorted, jnp.zeros_like(a_sorted)))
        recv = jax.lax.all_to_all(send, axis_name, split_axis=0,
                                  concat_axis=0, tiled=False)
        out_arrays.append(recv.reshape(-1))
    send_mask = jnp.zeros((n_shards, cap), jnp.bool_)
    send_mask = send_mask.at[safe_pid, safe_rank].max(live_sorted)
    recv_mask = jax.lax.all_to_all(send_mask, axis_name, split_axis=0,
                                   concat_axis=0, tiled=False)
    return out_arrays, recv_mask.reshape(-1)
