"""ICI partition exchange: the all-to-all shuffle core.

Replaces the reference's UCX peer-to-peer transfer path
(reference: shuffle-plugin/.../UCXShuffleTransport.scala:49,
RapidsShuffleClient/Server) with a single XLA collective: rows are bucketed
by target shard inside each shard (one stable sort, static shapes), then
`jax.lax.all_to_all` moves the buckets over ICI. No bounce buffers, no tag
matching, no flow control — XLA schedules the transfer.

All functions here run INSIDE shard_map (they reference an axis name).
Bucket capacity is static = the shard's batch capacity (safe upper bound:
all local rows could target one shard). A tighter 2x-expected bucket with
overflow retry is the planned optimization.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["exchange_rows", "exchange_cvs"]


def exchange_rows(arrays: Sequence[jnp.ndarray], mask, pids,
                  n_shards: int, axis_name: str = "data"):
    """Exchange rows so each row lands on shard `pids[row]`.

    arrays: per-shard [cap] buffers (fixed-width row payloads).
    mask:   bool[cap] live rows.
    pids:   int32[cap] target shard per row (garbage where dead).

    Returns (out_arrays [n*cap], out_mask [n*cap]) on each shard: the rows
    received from all shards, dead-padded.
    """
    cap = mask.shape[0]
    eff_pid = jnp.where(mask, pids, n_shards)      # dead rows -> bucket n
    order = jnp.argsort(eff_pid, stable=True)
    pid_sorted = eff_pid[order]
    # rank within each target bucket
    ranks = jnp.arange(cap)
    bucket_start = jnp.searchsorted(pid_sorted, jnp.arange(n_shards + 1),
                                    side="left")
    rank_in_bucket = ranks - bucket_start[jnp.clip(pid_sorted, 0, n_shards)]
    live_sorted = pid_sorted < n_shards

    safe_pid = jnp.clip(pid_sorted, 0, n_shards - 1)
    safe_rank = jnp.clip(rank_in_bucket, 0, cap - 1)

    out_arrays = []
    for a in arrays:
        as_bool = a.dtype == jnp.bool_
        if as_bool:
            a = a.astype(jnp.uint8)  # scatter-add rejects bool operands
        a_sorted = a[order]
        # trailing dims (e.g. decimal128 limb pairs [cap, 2]) ride along
        send = jnp.zeros((n_shards,) + a.shape, a.dtype)
        # scatter-add: dead rows contribute identity even when their
        # clipped (pid, rank) collides with a live slot
        live_b = live_sorted.reshape((cap,) + (1,) * (a.ndim - 1))
        send = send.at[safe_pid, safe_rank].add(
            jnp.where(live_b, a_sorted, jnp.zeros_like(a_sorted)))
        recv = jax.lax.all_to_all(send, axis_name, split_axis=0,
                                  concat_axis=0, tiled=False)
        flat = recv.reshape((-1,) + a.shape[1:])
        out_arrays.append(flat.astype(jnp.bool_) if as_bool else flat)
    send_mask = jnp.zeros((n_shards, cap), jnp.bool_)
    send_mask = send_mask.at[safe_pid, safe_rank].max(live_sorted)
    recv_mask = jax.lax.all_to_all(send_mask, axis_name, split_axis=0,
                                   concat_axis=0, tiled=False)
    return out_arrays, recv_mask.reshape(-1)


def _exchange_bytes(data, offsets, row_mask, row_pids, n_shards: int,
                    axis_name: str):
    """Move string bytes to each byte's row's target shard. Bytes within a
    (source, target) bucket keep source row order — the same invariant the
    row exchange provides — so lengths received via the row path rebuild
    the offsets on the receive side."""
    from ..ops.strings import byte_row_map
    bcap = data.shape[0]
    row = byte_row_map(offsets, bcap)
    bmask = row_mask[row] & (jnp.arange(bcap) < offsets[-1])
    bpids = row_pids[row]
    (out,), _ = exchange_rows([data], bmask, bpids, n_shards, axis_name)
    return out


def exchange_cvs(cvs: Sequence, mask, pids, n_shards: int,
                 axis_name: str = "data"):
    """Exchange the rows of a list of CVs (fixed-width and string columns)
    so each live row lands on shard pids[row].

    Returns (out_cvs, out_mask) with row capacity n_shards * cap. String
    columns arrive as packed (gap-free) byte buffers with rebuilt offsets.
    Runs INSIDE shard_map.
    """
    from ..ops.kernel_utils import CV
    from ..ops.strings import rebuild_strings

    cap = mask.shape[0]
    payload = []       # fixed-width arrays riding the row exchange
    layout = []        # per-cv: ("fixed", payload_idx) | ("str", idx, data)
    for cv in cvs:
        if cv.offsets is None:
            layout.append(("fixed", len(payload)))
            payload.append(cv.data)
        else:
            lens = (cv.offsets[1:] - cv.offsets[:-1]).astype(jnp.int32)
            layout.append(("str", len(payload), cv))
            payload.append(lens)
        payload.append(cv.validity.astype(jnp.uint8))
    out_payload, out_mask = exchange_rows(payload, mask, pids, n_shards,
                                          axis_name)
    out_cvs = []
    for spec in layout:
        if spec[0] == "fixed":
            _, i = spec
            out_cvs.append(CV(out_payload[i],
                              out_payload[i + 1].astype(jnp.bool_)))
        else:
            _, i, cv = spec
            lens_r = out_payload[i]
            valid_r = out_payload[i + 1].astype(jnp.bool_)
            bytes_r = _exchange_bytes(cv.data, cv.offsets, mask, pids,
                                      n_shards, axis_name)
            bcap = cv.data.shape[0]
            # per source-shard block: bytes packed from block start; row
            # starts are the within-block exclusive cumsum of lengths
            lens2 = lens_r.reshape(n_shards, cap)
            excl = jnp.cumsum(lens2, axis=1) - lens2
            base = (jnp.arange(n_shards, dtype=jnp.int32) * bcap)[:, None]
            starts = (base + excl).reshape(-1).astype(jnp.int32)
            out_cvs.append(rebuild_strings(
                CV(bytes_r, valid_r), starts,
                lens_r.reshape(-1).astype(jnp.int32)))
    return out_cvs, out_mask
