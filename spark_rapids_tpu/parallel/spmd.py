"""SPMD distributed query execution over a device mesh.

The multi-chip execution mode: data-parallel row shards per chip, XLA
collectives over ICI for the exchange (the reference's distributed shuffle,
RapidsShuffleManager + UCX, reference: RapidsShuffleInternalManagerBase.scala)
— redesigned as a single compiled SPMD program: each chip scans/filters its
shard, hash-exchanges rows to key-owning chips via all_to_all, then runs the
local segmented aggregation. One jit, one launch, no per-block RPC.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map is the public spelling from ~0.6; older jax ships it as
# jax.experimental.shard_map.shard_map
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops import sortkeys as sk
from ..ops.hash import partition_ids
from ..ops.kernel_utils import CV
from ..columnar import dtypes as dt
from .collectives import exchange_rows

__all__ = ["make_distributed_groupby_sum", "local_group_sum"]


def local_group_sum(keys, vals, mask):
    """Segmented sum by int64 key on one shard: returns (keys_out,
    sums_out, live_out) with capacity == input capacity."""
    cap = mask.shape[0]
    kcv = CV(keys, mask)
    arrays = [jnp.logical_not(mask).astype(jnp.uint8)]
    arrays += sk.order_keys(kcv, dt.INT64)
    # allow_host=False: this traces under shard_map, where the CPU
    # host-callback sort deadlocks (see ops.sortkeys.lexsort)
    perm = sk.lexsort(arrays, allow_host=False)
    sorted_arrays = [a[perm] for a in arrays]
    boundary = sk.group_boundaries(sorted_arrays)
    seg_ids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    live_sorted = mask[perm]
    v_sorted = jnp.where(live_sorted, vals[perm], 0)
    sums = jax.ops.segment_sum(v_sorted, seg_ids, cap)
    seg_live = jax.ops.segment_max(live_sorted.astype(jnp.int32),
                                   seg_ids, cap) > 0
    seg_start = jax.ops.segment_min(jnp.arange(cap), seg_ids, cap)
    src = perm[jnp.clip(seg_start, 0, cap - 1)]
    keys_out = jnp.where(seg_live, keys[src], 0)
    return keys_out, sums, seg_live


def make_distributed_groupby_sum(mesh: Mesh, axis_name: str = "data"):
    """Build the jitted SPMD step: filter -> hash exchange -> grouped sum.

    Input arrays are row-sharded [N] over the mesh; outputs are sharded
    [N * n_shards] per-chip group results (keys owned disjointly by chip).
    """
    n = mesh.devices.size

    def step(keys, vals, mask, threshold):
        def shard_fn(k, v, m, thr):
            # local filter (the scan+filter stage of the query)
            live = m & (v > thr[0])
            pids = partition_ids([CV(k, live)], [dt.INT64], n)
            (karr, varr), mask2 = exchange_rows([k, v], live, pids, n,
                                                axis_name)
            ko, so, lo = local_group_sum(karr, varr, mask2)
            return ko, so, lo

        return _shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(axis_name), P(axis_name), P(axis_name), P()),
            out_specs=(P(axis_name), P(axis_name), P(axis_name)),
        )(keys, vals, mask, threshold)

    return jax.jit(step)
