"""Device mesh helpers for SPMD execution over ICI/DCN.

The TPU-native replacement for the reference's executor topology: instead
of NCCL/UCX peer endpoints (reference: shuffle-plugin UCX.scala:71), a
jax.sharding.Mesh names the chips and XLA lowers collectives onto ICI.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "P", "NamedSharding", "Mesh", "shard_rows",
           "mesh_topology_key", "mesh_fingerprint"]


def mesh_topology_key(n_devices: int, axis_name: str = "data") -> tuple:
    """Program-cache key component for shard_map/mesh programs:
    (n_devices, axis name, device kind). A collective program's lowering
    bakes in the mesh topology — replica groups, ICI routing, the
    device target — so two topologies must never share a cache entry or
    a warm-pack manifest entry (the mesh-program-key lint rule polices
    that every mesh program in exec/ keys on this)."""
    return ("mesh", int(n_devices), str(axis_name), _device_kind())


def mesh_fingerprint() -> str:
    """Host-level mesh identity mixed into the warm-pack fingerprint:
    device kind + visible device count. A pack recorded on an 8-device
    mesh must not preload into a 1-device process (the sharded
    signatures could never dispatch there) and vice versa."""
    try:
        n = len(jax.devices())
    except RuntimeError:
        n = 0
    return f"mesh:{_device_kind()}:{n}"


def _device_kind() -> str:
    try:
        return str(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = "data") -> Mesh:
    try:
        devs = jax.devices()
    except RuntimeError:
        # default platform broken/absent (e.g. a libtpu client/terminal
        # mismatch through the tunnel): fall back to the CPU platform
        devs = jax.devices("cpu")
    if n_devices is not None and len(devs) < n_devices:
        # a TPU tunnel may own the default platform with one chip; the
        # virtual CPU mesh (xla_force_host_platform_device_count) still
        # exists on the cpu platform — fall back to it
        try:
            cpu = jax.devices("cpu")
            if len(cpu) >= n_devices:
                devs = cpu
        except RuntimeError:
            pass
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)}; set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count=N "
                f"with JAX_PLATFORMS=cpu for virtual meshes")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def shard_rows(mesh: Mesh, arr, axis_name: str = "data"):
    """Place a [rows, ...] array row-sharded across the mesh."""
    return jax.device_put(arr, NamedSharding(mesh, P(axis_name)))
