"""Timezone database: TZif transition tables as device arrays.

The TPU analog of the reference's GpuTimeZoneDB (jni TimeZoneDB /
sql-plugin datetimeExpressions.scala GpuFromUTCTimestamp /
GpuToUTCTimestamp): the reference materializes the JVM timezone rules into
a device table and resolves offsets with a binary search per row; here the
IANA TZif files (RFC 8536) are parsed directly and the searchsorted runs on
the VPU — one fused gather per batch, no host loop.

UTC->wall: offset = offs[searchsorted(trans_utc, ts) - 1]
wall->UTC: Java/Spark disambiguation (earlier offset at overlaps, shift
forward through gaps) falls out of thresholding each transition at
trans[i] + max(off[i-1], off[i]) in wall time.
"""
from __future__ import annotations

import os
import struct
from functools import lru_cache
from typing import Tuple

import numpy as np

__all__ = ["load_transitions", "utc_to_wall_tables", "wall_to_utc_tables"]

_TZPATHS = ("/usr/share/zoneinfo", "/usr/lib/zoneinfo",
            "/usr/share/lib/zoneinfo", "/etc/zoneinfo")


def _tzfile(name: str) -> bytes:
    if "/" in name and (name.startswith("/") or ".." in name):
        raise ValueError(f"invalid timezone name: {name}")
    for base in _TZPATHS:
        p = os.path.join(base, name)
        if os.path.exists(p):
            with open(p, "rb") as f:
                return f.read()
    # fall back to the pip tzdata package (hermetic environments)
    try:
        import importlib.resources as res
        pkg = "tzdata.zoneinfo." + ".".join(name.split("/")[:-1]) \
            if "/" in name else "tzdata.zoneinfo"
        fname = name.split("/")[-1]
        return (res.files(pkg) / fname).read_bytes()
    except Exception:
        raise ValueError(f"unknown timezone: {name!r}")


def _parse_tzif(data: bytes):
    """Parse a TZif file (RFC 8536); prefers the 64-bit v2+ block.
    Returns (trans_unix_seconds int64[n], offsets_seconds int32[n],
    initial_offset_seconds)."""

    def parse_block(buf, off, time_size):
        magic, ver = buf[off:off + 4], buf[off + 4:off + 5]
        if magic != b"TZif":
            raise ValueError("not a TZif file")
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt,
         charcnt) = struct.unpack(">6I", buf[off + 20:off + 44])
        p = off + 44
        fmt = ">%dq" % timecnt if time_size == 8 else ">%di" % timecnt
        trans = np.array(struct.unpack(fmt, buf[p:p + timecnt * time_size]),
                         dtype=np.int64)
        p += timecnt * time_size
        idx = np.frombuffer(buf[p:p + timecnt], dtype=np.uint8)
        p += timecnt
        ttinfo = []
        for i in range(typecnt):
            utoff, isdst, _desig = struct.unpack(
                ">iBB", buf[p + i * 6:p + i * 6 + 6])
            ttinfo.append((utoff, isdst))
        p += typecnt * 6 + charcnt
        # skip leap seconds + std/wall + ut/local indicators
        p += leapcnt * (time_size + 4) + isstdcnt + isutcnt
        return ver, trans, idx, ttinfo, p

    ver, trans, idx, ttinfo, end = parse_block(data, 0, 4)
    footer = b""
    if ver >= b"2":
        # the v2+ 64-bit block immediately follows the v1 block
        ver, trans, idx, ttinfo, end2 = parse_block(data, end, 8)
        # v2/v3 footer: '\n' POSIX-TZ '\n' (RFC 8536 §3.3)
        tail = data[end2:]
        if tail.startswith(b"\n"):
            footer = tail[1:].split(b"\n", 1)[0]
    offs = np.array([ttinfo[i][0] for i in idx], dtype=np.int32) \
        if len(idx) else np.zeros(0, np.int32)
    # initial period: first non-DST type, else type 0 (RFC 8536 §3.2)
    init = 0
    for utoff, isdst in ttinfo:
        if not isdst:
            init = utoff
            break
    else:
        if ttinfo:
            init = ttinfo[0][0]
    trans, offs = _extend_with_posix_rule(trans, offs, footer.decode(
        "ascii", "ignore"))
    return trans, offs, init


# ---------------------------------------------------------------------
# POSIX TZ footer: extends rules past the last stored transition (slim
# zic output stores few explicit transitions and relies on the footer;
# the reference's GpuTimeZoneDB materializes rules to a max year the
# same way).
# ---------------------------------------------------------------------
_MAX_YEAR = 2100


def _parse_posix_offset(s: str, i: int):
    """[+|-]hh[:mm[:ss]] -> (seconds WEST of UTC per POSIX, next index)"""
    sign = 1
    if i < len(s) and s[i] in "+-":
        sign = -1 if s[i] == "-" else 1
        i += 1
    parts = [0, 0, 0]
    for p in range(3):
        j = i
        while j < len(s) and s[j].isdigit():
            j += 1
        if j == i:
            break
        parts[p] = int(s[i:j])
        i = j
        if i < len(s) and s[i] == ":":
            i += 1
        else:
            break
    return sign * (parts[0] * 3600 + parts[1] * 60 + parts[2]), i


def _skip_name(s: str, i: int):
    if i < len(s) and s[i] == "<":
        return s.index(">", i) + 1
    while i < len(s) and not (s[i].isdigit() or s[i] in "+-,"):
        i += 1
    return i


def _parse_posix_rule(s: str, i: int):
    """Mm.w.d[/time] or Jn[/time] or n[/time] -> (spec, time_secs, i)"""
    t = 7200  # default 02:00 local
    if s[i] == "M":
        j = i + 1
        nums = []
        while len(nums) < 3:
            k = j
            while k < len(s) and s[k].isdigit():
                k += 1
            nums.append(int(s[j:k]))
            j = k + 1 if k < len(s) and s[k] == "." else k
        spec = ("M", nums[0], nums[1], nums[2])
        i = j
    elif s[i] == "J":
        j = i + 1
        k = j
        while k < len(s) and s[k].isdigit():
            k += 1
        spec = ("J", int(s[j:k]))
        i = k
    else:
        k = i
        while k < len(s) and s[k].isdigit():
            k += 1
        spec = ("n", int(s[i:k]))
        i = k
    if i < len(s) and s[i] == "/":
        t, i = _parse_posix_offset(s, i + 1)
    return spec, t, i


def _rule_day(year: int, spec) -> int:
    """Days since epoch of the rule date in `year` (local calendar)."""
    import datetime as _dt
    if spec[0] == "M":
        _, m, w, d = spec
        first = _dt.date(year, m, 1)
        # day-of-week d (0=Sunday); POSIX week w (5 = last)
        dow_first = (first.weekday() + 1) % 7  # Monday=0 -> Sunday=0 idx
        day = 1 + (d - dow_first) % 7 + (w - 1) * 7
        ndays = ((_dt.date(year + (m == 12), (m % 12) + 1, 1)
                  - first).days)
        while day > ndays:
            day -= 7
        return (first + _dt.timedelta(days=day - 1)
                - _dt.date(1970, 1, 1)).days
    if spec[0] == "J":   # 1-based day, Feb 29 never counted
        n = spec[1]
        leap = (year % 4 == 0 and year % 100 != 0) or year % 400 == 0
        adj = 1 if (leap and n >= 60) else 0
        return (_dt.date(year, 1, 1) - _dt.date(1970, 1, 1)).days \
            + n - 1 + adj
    return (_dt.date(year, 1, 1)
            - _dt.date(1970, 1, 1)).days + spec[1]


def _extend_with_posix_rule(trans, offs, footer: str):
    """Append footer-rule transitions from after the last stored
    transition through _MAX_YEAR."""
    if not footer:
        return trans, offs
    try:
        i = _skip_name(footer, 0)
        std_off, i = _parse_posix_offset(footer, i)
        std = -std_off              # POSIX offsets are west-positive
        if i >= len(footer):        # no DST: constant offset
            return trans, offs
        i = _skip_name(footer, i)
        if i < len(footer) and footer[i] not in ",":
            dst_off, i = _parse_posix_offset(footer, i)
            dst = -dst_off
        else:
            dst = std + 3600
        if i >= len(footer) or footer[i] != ",":
            return trans, offs
        start_spec, start_t, i = _parse_posix_rule(footer, i + 1)
        if i >= len(footer) or footer[i] != ",":
            return trans, offs
        end_spec, end_t, i = _parse_posix_rule(footer, i + 1)
    except Exception:
        return trans, offs
    import datetime as _dt
    last = int(trans[-1]) if len(trans) else 0
    year0 = max(1970, _dt.datetime.fromtimestamp(
        max(last, 0), tz=_dt.timezone.utc).year)
    new_t, new_o = [], []
    for y in range(year0, _MAX_YEAR + 1):
        # DST start: local standard time -> UTC via std offset
        t_start = _rule_day(y, start_spec) * 86400 + start_t - std
        # DST end: local DST time -> UTC via dst offset
        t_end = _rule_day(y, end_spec) * 86400 + end_t - dst
        for t, o in sorted([(t_start, dst), (t_end, std)]):
            if t > last:
                new_t.append(t)
                new_o.append(o)
    if not new_t:
        return trans, offs
    return (np.concatenate([trans, np.array(new_t, np.int64)]),
            np.concatenate([offs, np.array(new_o, np.int32)]))


@lru_cache(maxsize=64)
def load_transitions(tz: str) -> Tuple[np.ndarray, np.ndarray]:
    """(trans_utc_micros int64[n+1], offsets_micros int64[n+1]) with a
    sentinel first row covering times before the first transition."""
    if tz in ("UTC", "Z", "GMT", "Etc/UTC", "Etc/GMT"):
        return (np.array([np.iinfo(np.int64).min], np.int64),
                np.zeros(1, np.int64))
    trans, offs, init = _parse_tzif(_tzfile(tz))
    t = np.concatenate([[np.iinfo(np.int64).min // 2], trans * 1_000_000])
    o = np.concatenate([[init], offs.astype(np.int64)]) * 1_000_000
    return t.astype(np.int64), o.astype(np.int64)


@lru_cache(maxsize=64)
def utc_to_wall_tables(tz: str):
    return load_transitions(tz)


@lru_cache(maxsize=64)
def wall_to_utc_tables(tz: str):
    """Thresholds in WALL time: trans[i] + max(off[i-1], off[i]) gives
    Java's earlier-offset-at-overlap / shift-through-gap semantics."""
    t, o = load_transitions(tz)
    if len(t) == 1:
        return t, o
    prev = np.concatenate([[o[0]], o[:-1]])
    thresh = t + np.maximum(prev, o)
    thresh[0] = t[0]
    # enforce monotonicity (pathological zones)
    thresh = np.maximum.accumulate(thresh)
    return thresh.astype(np.int64), o
