"""Timezone database: TZif transition tables as device arrays.

The TPU analog of the reference's GpuTimeZoneDB (jni TimeZoneDB /
sql-plugin datetimeExpressions.scala GpuFromUTCTimestamp /
GpuToUTCTimestamp): the reference materializes the JVM timezone rules into
a device table and resolves offsets with a binary search per row; here the
IANA TZif files (RFC 8536) are parsed directly and the searchsorted runs on
the VPU — one fused gather per batch, no host loop.

UTC->wall: offset = offs[searchsorted(trans_utc, ts) - 1]
wall->UTC: Java/Spark disambiguation (earlier offset at overlaps, shift
forward through gaps) falls out of thresholding each transition at
trans[i] + max(off[i-1], off[i]) in wall time.
"""
from __future__ import annotations

import os
import struct
from functools import lru_cache
from typing import Tuple

import numpy as np

__all__ = ["load_transitions", "utc_to_wall_tables", "wall_to_utc_tables"]

_TZPATHS = ("/usr/share/zoneinfo", "/usr/lib/zoneinfo",
            "/usr/share/lib/zoneinfo", "/etc/zoneinfo")


def _tzfile(name: str) -> bytes:
    if "/" in name and (name.startswith("/") or ".." in name):
        raise ValueError(f"invalid timezone name: {name}")
    for base in _TZPATHS:
        p = os.path.join(base, name)
        if os.path.exists(p):
            with open(p, "rb") as f:
                return f.read()
    # fall back to the pip tzdata package (hermetic environments)
    try:
        import importlib.resources as res
        pkg = "tzdata.zoneinfo." + ".".join(name.split("/")[:-1]) \
            if "/" in name else "tzdata.zoneinfo"
        fname = name.split("/")[-1]
        return (res.files(pkg) / fname).read_bytes()
    except Exception:
        raise ValueError(f"unknown timezone: {name!r}")


def _parse_tzif(data: bytes):
    """Parse a TZif file (RFC 8536); prefers the 64-bit v2+ block.
    Returns (trans_unix_seconds int64[n], offsets_seconds int32[n],
    initial_offset_seconds)."""

    def parse_block(buf, off, time_size):
        magic, ver = buf[off:off + 4], buf[off + 4:off + 5]
        if magic != b"TZif":
            raise ValueError("not a TZif file")
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt,
         charcnt) = struct.unpack(">6I", buf[off + 20:off + 44])
        p = off + 44
        fmt = ">%dq" % timecnt if time_size == 8 else ">%di" % timecnt
        trans = np.array(struct.unpack(fmt, buf[p:p + timecnt * time_size]),
                         dtype=np.int64)
        p += timecnt * time_size
        idx = np.frombuffer(buf[p:p + timecnt], dtype=np.uint8)
        p += timecnt
        ttinfo = []
        for i in range(typecnt):
            utoff, isdst, _desig = struct.unpack(
                ">iBB", buf[p + i * 6:p + i * 6 + 6])
            ttinfo.append((utoff, isdst))
        p += typecnt * 6 + charcnt
        # skip leap seconds + std/wall + ut/local indicators
        p += leapcnt * (time_size + 4) + isstdcnt + isutcnt
        return ver, trans, idx, ttinfo, p

    ver, trans, idx, ttinfo, end = parse_block(data, 0, 4)
    if ver >= b"2":
        # the v2+ 64-bit block immediately follows the v1 block
        ver, trans, idx, ttinfo, _ = parse_block(data, end, 8)
    offs = np.array([ttinfo[i][0] for i in idx], dtype=np.int32) \
        if len(idx) else np.zeros(0, np.int32)
    # initial period: first non-DST type, else type 0 (RFC 8536 §3.2)
    init = 0
    for utoff, isdst in ttinfo:
        if not isdst:
            init = utoff
            break
    else:
        if ttinfo:
            init = ttinfo[0][0]
    return trans, offs, init


@lru_cache(maxsize=64)
def load_transitions(tz: str) -> Tuple[np.ndarray, np.ndarray]:
    """(trans_utc_micros int64[n+1], offsets_micros int64[n+1]) with a
    sentinel first row covering times before the first transition."""
    if tz in ("UTC", "Z", "GMT", "Etc/UTC", "Etc/GMT"):
        return (np.array([np.iinfo(np.int64).min], np.int64),
                np.zeros(1, np.int64))
    trans, offs, init = _parse_tzif(_tzfile(tz))
    t = np.concatenate([[np.iinfo(np.int64).min // 2], trans * 1_000_000])
    o = np.concatenate([[init], offs.astype(np.int64)]) * 1_000_000
    return t.astype(np.int64), o.astype(np.int64)


@lru_cache(maxsize=64)
def utc_to_wall_tables(tz: str):
    return load_transitions(tz)


@lru_cache(maxsize=64)
def wall_to_utc_tables(tz: str):
    """Thresholds in WALL time: trans[i] + max(off[i-1], off[i]) gives
    Java's earlier-offset-at-overlap / shift-through-gap semantics."""
    t, o = load_transitions(tz)
    if len(t) == 1:
        return t, o
    prev = np.concatenate([[o[0]], o[:-1]])
    thresh = t + np.maximum(prev, o)
    thresh[0] = t[0]
    # enforce monotonicity (pathological zones)
    thresh = np.maximum.accumulate(thresh)
    return thresh.astype(np.int64), o
