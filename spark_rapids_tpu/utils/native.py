"""Loader for the native host runtime (native/srtpu_native.cpp).

Builds on first use with the in-image toolchain (g++), loads via ctypes
(no pybind11 in the image), and degrades gracefully to the numpy paths
when unavailable. The JNI-boundary analog of the reference
(SURVEY.md §2.8): Python orchestrates, C++ does the host hot loops.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

__all__ = ["native_lib", "pack_validity", "unpack_validity",
           "gather_strings_host", "HostArena"]

_LIB = None
_TRIED = False
_LOCK = threading.Lock()
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def native_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        so = os.path.join(_ROOT, "native", "build", "libsrtpu_native.so")
        if not os.path.exists(so):
            try:
                subprocess.run(["make", "-C",
                                os.path.join(_ROOT, "native")],
                               check=True, capture_output=True,
                               timeout=120)
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        lib.srtpu_pack_validity.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
        lib.srtpu_unpack_validity.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
        lib.srtpu_gather_strings.restype = ctypes.c_int64
        lib.srtpu_gather_strings.argtypes = [ctypes.c_void_p] * 2 + [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_void_p]
        lib.srtpu_arena_create.restype = ctypes.c_void_p
        lib.srtpu_arena_create.argtypes = [ctypes.c_int64]
        lib.srtpu_arena_alloc.restype = ctypes.c_void_p
        lib.srtpu_arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.srtpu_arena_reset.argtypes = [ctypes.c_void_p]
        lib.srtpu_arena_used.restype = ctypes.c_int64
        lib.srtpu_arena_used.argtypes = [ctypes.c_void_p]
        lib.srtpu_arena_destroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def _cptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.c_void_p)


def pack_validity(bools: np.ndarray) -> np.ndarray:
    lib = native_lib()
    b = np.ascontiguousarray(bools, np.uint8)
    if lib is None:
        return np.packbits(b.astype(np.bool_), bitorder="little")
    out = np.empty((len(b) + 7) // 8, np.uint8)
    lib.srtpu_pack_validity(_cptr(b), len(b), _cptr(out))
    return out


def unpack_validity(bits: np.ndarray, n: int) -> np.ndarray:
    lib = native_lib()
    bits = np.ascontiguousarray(bits, np.uint8)
    if lib is None:
        return np.unpackbits(bits, bitorder="little")[:n].astype(np.bool_)
    out = np.empty(n, np.uint8)
    lib.srtpu_unpack_validity(_cptr(bits), n, _cptr(out))
    return out.astype(np.bool_)


def gather_strings_host(data: np.ndarray, offsets: np.ndarray,
                        sel: np.ndarray):
    """Dense host-side string gather (CPU-bridge / serializer path)."""
    lib = native_lib()
    sel = np.ascontiguousarray(sel, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int32)
    data = np.ascontiguousarray(data, np.uint8)
    n_out = len(sel)
    if lib is None:
        lens = offsets[sel + 1] - offsets[sel]
        new_off = np.zeros(n_out + 1, np.int32)
        np.cumsum(lens, out=new_off[1:])
        out = np.empty(int(new_off[-1]), np.uint8)
        for i, r in enumerate(sel):
            out[new_off[i]:new_off[i + 1]] = data[offsets[r]:offsets[r + 1]]
        return out, new_off
    total_cap = int((offsets[sel + 1] - offsets[sel]).sum())
    out = np.empty(max(total_cap, 1), np.uint8)
    new_off = np.empty(n_out + 1, np.int32)
    lib.srtpu_gather_strings(_cptr(data), _cptr(offsets), _cptr(sel),
                             n_out, _cptr(out), _cptr(new_off))
    return out[:int(new_off[-1])], new_off


class HostArena:
    """Aligned bump-allocator region (RMM host pool analog)."""

    def __init__(self, size: int):
        lib = native_lib()
        self._lib = lib
        self._arena = lib.srtpu_arena_create(size) if lib else None
        self.size = size
        if lib and not self._arena:
            raise MemoryError(f"arena of {size} bytes")

    def alloc_array(self, count: int, dtype=np.uint8):
        """Allocate `count` ELEMENTS of dtype from the arena; None when
        full (caller falls back to heap). Arrays are valid until reset()/
        close() — callers must copy out (e.g. device_put) before that."""
        dtype = np.dtype(dtype)
        nbytes = int(count) * dtype.itemsize
        if self._arena is None:
            return np.empty(count, dtype)
        p = self._lib.srtpu_arena_alloc(self._arena, nbytes)
        if not p:
            return None
        buf = (ctypes.c_uint8 * nbytes).from_address(p)
        return np.frombuffer(buf, dtype=dtype)

    def reset(self):
        if self._arena is not None:
            self._lib.srtpu_arena_reset(self._arena)

    @property
    def used(self) -> int:
        if self._arena is None:
            return 0
        return self._lib.srtpu_arena_used(self._arena)

    def close(self):
        if self._arena is not None:
            self._lib.srtpu_arena_destroy(self._arena)
            self._arena = None
