"""Operator metrics with levels, analog of GpuMetric
(reference: sql-plugin/.../GpuMetrics.scala:377 ESSENTIAL/MODERATE/DEBUG).

Timer-skew caveat: jax dispatch is ASYNC — by default `timer` measures
the time to *enqueue* device work, not to execute it; execution lands on
whichever downstream operator first blocks (usually the D2H fetch at the
plan root). With `spark.rapids.tpu.sql.metrics.sync` on (ExecContext
passes `sync=True`), the timer joins the device stream before stopping:
it enqueues a trivial op and `block_until_ready`s it, which on an
in-order compute stream waits for everything the timed block dispatched.
That yields debug-grade per-operator execution times at the cost of
pipelining; see docs/observability.md.
"""
from __future__ import annotations

import time
from contextlib import contextmanager

from ..runtime import racedep

ESSENTIAL = 0
MODERATE = 1
DEBUG = 2

__all__ = ["MetricSet", "ESSENTIAL", "MODERATE", "DEBUG"]


def _stream_barrier():
    """Join the device stream: dispatch a trivial op and block on it.
    Device execution streams are in-order, so this returns only after
    every previously dispatched kernel completes."""
    try:
        import jax
        import jax.numpy as jnp
        # tpulint: allow[block-sync] this IS the sql.metrics.sync gate
        jax.block_until_ready(jnp.zeros((), jnp.int32) + 1)
    except Exception:
        pass


class MetricSet:
    """Thread-safe: partitions update operator metrics concurrently."""

    def __init__(self, sync: bool = False):
        from ..runtime import lockdep
        self._values = {}
        self._levels = {}
        self._lock = lockdep.lock("MetricSet._lock")
        self._sync = sync

    def add(self, name: str, amount, level: int = MODERATE):
        with self._lock:
            racedep.note_access("MetricSet._values", name, write=True)
            self._values[name] = self._values.get(name, 0) + amount
            self._levels[name] = level

    def set(self, name: str, value, level: int = MODERATE):
        with self._lock:
            racedep.note_access("MetricSet._values", name, write=True)
            self._values[name] = value
            self._levels[name] = level

    def get(self, name: str, default=0):
        with self._lock:
            racedep.note_access("MetricSet._values", name)
            return self._values.get(name, default)

    @contextmanager
    def timer(self, name: str, level: int = MODERATE):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if self._sync:
                _stream_barrier()
            self.add(name, time.perf_counter() - t0, level)

    def snapshot(self, max_level: int = DEBUG):
        # iterating _values while a partition worker resizes it raises
        # RuntimeError; snapshot under the same lock add/set hold
        with self._lock:
            racedep.note_access("MetricSet._values")
            return {k: v for k, v in self._values.items()
                    if self._levels.get(k, MODERATE) <= max_level}

    def __repr__(self):
        return f"MetricSet({self._values})"
