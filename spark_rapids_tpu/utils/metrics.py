"""Operator metrics with levels, analog of GpuMetric
(reference: sql-plugin/.../GpuMetrics.scala:377 ESSENTIAL/MODERATE/DEBUG).
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager

ESSENTIAL = 0
MODERATE = 1
DEBUG = 2

__all__ = ["MetricSet", "ESSENTIAL", "MODERATE", "DEBUG"]


class MetricSet:
    """Thread-safe: partitions update operator metrics concurrently."""

    def __init__(self):
        self._values = {}
        self._levels = {}
        self._lock = threading.Lock()

    def add(self, name: str, amount, level: int = MODERATE):
        with self._lock:
            self._values[name] = self._values.get(name, 0) + amount
            self._levels[name] = level

    def set(self, name: str, value, level: int = MODERATE):
        with self._lock:
            self._values[name] = value
            self._levels[name] = level

    def get(self, name: str, default=0):
        return self._values.get(name, default)

    @contextmanager
    def timer(self, name: str, level: int = MODERATE):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0, level)

    def snapshot(self, max_level: int = DEBUG):
        return {k: v for k, v in self._values.items()
                if self._levels.get(k, MODERATE) <= max_level}

    def __repr__(self):
        return f"MetricSet({self._values})"
