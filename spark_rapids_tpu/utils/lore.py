"""LORE: operator-level dump & replay for debugging.

(reference: lore/GpuLore.scala:30-70 + lore/dump.scala / replay.scala,
docs/dev/lore.md.) Every physical operator gets a stable LORE id at plan
time; ids selected via `spark.rapids.tpu.sql.lore.idsToDump` dump their
INPUT batches as parquet under the dump path, so a failing operator can be
re-executed in isolation with `load_input()` + the DataFrame API.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional

from ..exec.base import ExecContext, TpuExec

__all__ = ["assign_lore_ids", "apply_lore_dump", "load_input",
           "LoreDumpExec"]


def assign_lore_ids(root: TpuExec) -> None:
    """Stable pre-order ids, like GpuLore.tagForLore."""
    counter = [0]

    def walk(node: TpuExec):
        counter[0] += 1
        node.lore_id = counter[0]
        for c in node.children:
            walk(c)

    walk(root)


class LoreDumpExec(TpuExec):
    """Pass-through operator that dumps every batch it forwards."""

    def __init__(self, child: TpuExec, dump_dir: str, lore_id: int,
                 child_idx: int):
        super().__init__([child], child.schema)
        self.dump_dir = dump_dir
        self.lore_id = -lore_id  # not a selectable id itself
        self._base = os.path.join(dump_dir, f"loreId-{lore_id}",
                                  f"input-{child_idx}")
        self._counter = 0

    def num_partitions(self, ctx):
        return self.children[0].num_partitions(ctx)

    def execute_partition(self, ctx, pid):
        import pyarrow as pa
        import pyarrow.parquet as pq
        import numpy as np
        import jax
        os.makedirs(self._base, exist_ok=True)
        for batch in self.children[0].execute_partition(ctx, pid):
            from ..columnar.column import Column
            from ..utils.transfer import fetch
            host = fetch([c.device_buffers()
                          for c in batch.table.columns] + [batch.row_mask])
            # tpulint: allow[host-sync] `host` is fetched above
            mask = np.asarray(host[-1])[:batch.num_rows]
            arrs = [Column.arrow_from_host(c.dtype, c.length, b)
                    for c, b in zip(batch.table.columns, host[:-1])]
            at = pa.Table.from_arrays(arrs, names=list(batch.table.names))
            if not mask.all():
                at = at.filter(pa.array(mask))
            fname = os.path.join(
                self._base, f"part-{pid}-batch-{self._counter}.parquet")
            pq.write_table(at, fname)
            self._counter += 1
            yield batch


def apply_lore_dump(root: TpuExec, conf) -> TpuExec:
    """Wrap children of selected operators with dump pass-throughs."""
    from ..config import LORE_DUMP_IDS, LORE_DUMP_PATH
    ids_str = conf.get(LORE_DUMP_IDS)
    if not ids_str:
        return root
    wanted = {int(x) for x in str(ids_str).split(",") if x.strip()}
    dump_path = conf.get(LORE_DUMP_PATH)
    meta = {}

    def walk(node: TpuExec):
        if getattr(node, "lore_id", None) in wanted:
            meta[node.lore_id] = node.describe()
            node.children = [
                LoreDumpExec(c, dump_path, node.lore_id, i)
                for i, c in enumerate(node.children)]
        for c in node.children:
            walk(c)

    walk(root)
    if meta:
        os.makedirs(dump_path, exist_ok=True)
        with open(os.path.join(dump_path, "lore-meta.json"), "w") as f:
            json.dump({str(k): v for k, v in meta.items()}, f, indent=2)
    return root


def load_input(session, dump_path: str, lore_id: int, child_idx: int = 0):
    """Reload a dumped operator input as a DataFrame for replay."""
    base = os.path.join(dump_path, f"loreId-{lore_id}",
                        f"input-{child_idx}")
    return session.read.parquet(base)
