"""Tracing / profiling hooks.

NVTX-range analog (reference: NvtxWithMetrics.scala, docs/dev/nvtx_profiling
.md): named ranges show up in the XLA/Perfetto profiler timeline; the
built-in profiler capture (reference: profiler.scala CUPTI Profiler) maps
to jax.profiler traces written to a directory viewable in Perfetto/
TensorBoard.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

import jax

__all__ = ["range_annotation", "start_profile", "stop_profile"]


@contextmanager
def range_annotation(name: str):
    """NVTX-range analog; nests, shows in profiler timelines."""
    with jax.profiler.TraceAnnotation(name):
        yield


_active = {"dir": None}


def start_profile(out_dir: str):
    jax.profiler.start_trace(out_dir)
    _active["dir"] = out_dir
    return out_dir


def stop_profile() -> Optional[str]:
    if _active["dir"] is None:
        return None
    jax.profiler.stop_trace()
    d, _active["dir"] = _active["dir"], None
    return d
