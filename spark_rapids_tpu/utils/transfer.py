"""Device<->host transfer helpers.

On tunneled TPU runtimes each D2H copy pays a large fixed latency; issuing
`copy_to_host_async` on every leaf before `device_get` overlaps those
latencies (measured ~6x on a 6-leaf fetch). This is the engine's single
D2H chokepoint — all exports and host syncs go through `fetch`.
"""
from __future__ import annotations

import jax

__all__ = ["fetch", "fetch_int"]


def fetch(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    for leaf in leaves:
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is not None:
            try:
                copy_async()
            except Exception:
                pass
    # tpulint: allow[host-sync] the single blessed D2H chokepoint
    return jax.device_get(tree)


def fetch_int(x) -> int:
    return int(fetch(x))
