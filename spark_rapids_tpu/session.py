"""Session + DataFrame: the host-facing API that drives TPU execution.

In the reference, Spark provides this surface and the plugin rewrites plans
underneath (Plugin.scala:56 ColumnarOverrideRules). Standalone round-1: the
DataFrame builds logical plans directly and `collect()` runs
plan -> TpuOverrides-style planner -> TPU physical plan. Method names track
pyspark.sql.DataFrame so workloads port mechanically.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .columnar.table import Schema
from .config import TpuConf
from .exec.base import ExecContext
from .exec.nodes import collect_to_arrow
from .expr.expressions import Expression, col, lit
from .expr.aggregates import AggExpr
from .functions import _to_expr
from .plan import logical as L
from .plan.planner import Planner

__all__ = ["TpuSession", "DataFrame"]

# per-process counter uniquifying the hidden right-side key renames of
# name-based joins (see DataFrame.join)
_JOIN_RENAME_COUNTER = [0]

_QM_LOCK = __import__("threading").Lock()

# reentrancy guard: a nested action on a thread that already holds an
# admission grant (e.g. a runtime-filter subquery collected inside a
# parent query) runs under the OUTER query's handle instead of asking
# the scheduler for a second grant (which could deadlock at
# maxConcurrentQueries=1)
_ACTION_TLS = __import__("threading").local()


class TpuSession:
    _active: Optional["TpuSession"] = None

    def __init__(self, conf: Optional[Dict] = None):
        self.conf = TpuConf(conf)
        self.read = DataFrameReader(self)
        # path of the most recent query's event log (set by the profiler
        # wrapper when sql.eventLog.enabled)
        self.last_event_log: Optional[str] = None
        TpuSession._active = self
        from .config import RETRY_COVERAGE_ENABLED
        from .memory.diagnostics import enable_retry_coverage
        enable_retry_coverage(bool(self.conf.get(RETRY_COVERAGE_ENABLED)))
        from .runtime import faults, ledger, lockdep, racedep
        lockdep.maybe_enable_from_conf(self.conf)
        ledger.maybe_enable_from_conf(self.conf)
        racedep.maybe_enable_from_conf(self.conf)
        # conf-carried fault plan (sql.debug.faults.plan) activates here
        # so distributed fragments — executors rebuild TpuSession(conf)
        # — inject under the same plan as the driver
        faults.install_from_conf(self.conf)

    @staticmethod
    def builder_get_or_create(conf: Optional[Dict] = None) -> "TpuSession":
        if TpuSession._active is None:
            TpuSession(conf)
        return TpuSession._active

    def set_conf(self, key, value):
        # tpulint: allow[unlocked-shared-write] conf snapshots are immutable; readers see the old or new frozen conf, never a torn one
        self.conf = self.conf.set(key, value)

    def cluster_manager(self):
        """Lazily start the driver/executor runtime (cluster/driver.py)
        when spark.rapids.tpu.cluster.executors > 0."""
        from .config import CLUSTER_EXECUTORS, CLUSTER_HEARTBEAT_TIMEOUT
        cm = getattr(self, "_cluster", None)
        if cm is None:
            from .cluster import ClusterManager
            cm = ClusterManager(
                self.conf.get(CLUSTER_EXECUTORS),
                heartbeat_timeout=self.conf.get(
                    CLUSTER_HEARTBEAT_TIMEOUT))
            cm.start()
            self._cluster = cm
            import atexit
            atexit.register(cm.shutdown)
        return cm

    def query_manager(self):
        """Lazily build the concurrent query service (service/): every
        action routes through it for admission, fair scheduling,
        cancellation, and deadlines (docs/service.md)."""
        import threading
        mgr = getattr(self, "_query_manager", None)
        if mgr is None:
            with _QM_LOCK:
                mgr = getattr(self, "_query_manager", None)
                if mgr is None:
                    from .service.query_manager import QueryManager
                    mgr = QueryManager(self.conf)
                    self._query_manager = mgr
                    # admission-awareness for the background compile
                    # pool: speculative (warm-pack) compiles defer
                    # while any admitted query is running; weakref so
                    # the hook never outlives session.stop()
                    import weakref

                    from .runtime import compile_pool
                    ref = weakref.ref(mgr)

                    def _busy(_ref=ref):
                        m = _ref()
                        return m is not None and m._running > 0
                    compile_pool.set_busy_hook(_busy)
        return mgr

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """Start the JSON-lines gateway (service/server.py) multiplexing
        client sessions onto this engine process; returns the server
        (its .host/.port carry the bound address)."""
        from .service.server import QueryServer
        # AOT warm pack: when sql.service.warmPack.path is set, replay
        # the recorded key set through the background compile pool
        # before accepting connections — the first client query finds
        # its programs warm (or compiling) instead of paying the full
        # cold tail inline. Advisory: any pack problem logs and serves
        # cold.
        from .runtime import warm_pack
        self._warm_pack_summary = warm_pack.preload(self)
        srv = QueryServer(self, host, port)
        srv.start()
        # multi-host serving fabric: when sql.fleet.directory is set,
        # register this process in the fleet (peer cache tier + sticky
        # routing + warm-state pull from the longest-lived peer); a
        # no-fleet session skips all of it in one conf read
        from . import fleet
        try:
            self._fleet_member = fleet.join(
                self, gateway_addr=(srv.host, srv.port))
        except Exception:
            import logging
            logging.getLogger(__name__).warning(
                "fleet join failed; serving solo", exc_info=True)
            self._fleet_member = None
        return srv

    def save_warm_pack(self, path: Optional[str] = None):
        """Write the warm-pack manifest (recorded SQL + observed
        program signatures) to `path` or sql.service.warmPack.record;
        returns the path written or None when disabled."""
        from .runtime import warm_pack
        return warm_pack.save(self.conf, path)

    def stop(self):
        member = getattr(self, "_fleet_member", None)
        if member is not None:
            try:
                member.leave()
            except Exception:
                pass
            self._fleet_member = None
        cm = getattr(self, "_cluster", None)
        if cm is not None:
            cm.shutdown()
            self._cluster = None
        # pair with query_manager()'s double-checked build: clearing
        # outside _QM_LOCK could interleave with a concurrent build and
        # resurrect a manager the session just tore down
        with _QM_LOCK:
            self._query_manager = None
        if TpuSession._active is self:
            TpuSession._active = None

    # ------------------------------------------------------------------
    def create_dataframe(self, data, schema=None) -> "DataFrame":
        import pyarrow as pa
        if isinstance(data, pa.Table):
            at = data
        elif isinstance(data, dict):
            if schema is not None:
                at = pa.table(data, schema=schema.to_arrow()
                              if isinstance(schema, Schema) else schema)
            else:
                at = pa.table(data)
        else:
            raise TypeError("create_dataframe expects a pyarrow Table or dict")
        return DataFrame(self, L.InMemoryScan(at))

    def sql(self, query: str) -> "DataFrame":
        from .sql.parser import parse_sql
        from .runtime import warm_pack
        warm_pack.note_query(query, self.conf)
        return parse_sql(self, query)


class DataFrameReader:
    def __init__(self, session: TpuSession):
        self._session = session

    def parquet(self, *paths: str, columns=None) -> "DataFrame":
        import glob as _glob
        import os
        expanded: List[str] = []
        for p in paths:
            if os.path.isdir(p):
                expanded.extend(sorted(
                    _glob.glob(os.path.join(p, "*.parquet"))))
            elif any(ch in p for ch in "*?["):
                expanded.extend(sorted(_glob.glob(p)))
            else:
                expanded.append(p)
        return DataFrame(self._session,
                         L.ParquetScan(expanded, columns=columns))

    def csv(self, *paths: str, header=True, schema=None, delimiter=",",
            quote='"', escape="\\", comment=None,
            null_value="") -> "DataFrame":
        """Lazy streaming CSV scan (reference: GpuCSVScan.scala:57);
        schema from a first-block sample unless given."""
        from .exec.text_scan import CsvOptions
        opts = CsvOptions(header=header, delimiter=delimiter, quote=quote,
                          escape=escape, comment=comment,
                          null_value=null_value)
        return DataFrame(self._session,
                         L.TextScan(list(paths), "csv", schema,
                                    options=opts))

    def orc(self, *paths: str) -> "DataFrame":
        """Lazy stripe-streaming ORC scan (reference: GpuOrcScan.scala:78
        PERFILE reader)."""
        return DataFrame(self._session, L.TextScan(list(paths), "orc"))

    def avro(self, *paths: str) -> "DataFrame":
        """Lazy block-streaming Avro scan (reference: GpuAvroScan)."""
        return DataFrame(self._session, L.TextScan(list(paths), "avro"))

    def iceberg(self, path: str, snapshot_id=None,
                as_of_timestamp=None) -> "DataFrame":
        """Iceberg table read: metadata json -> manifest list -> manifests
        -> live parquet files (reference: the iceberg module's
        GpuIcebergParquetScan); supports snapshot time travel."""
        from .io.iceberg import read_iceberg
        return read_iceberg(self._session, path, snapshot_id,
                            as_of_timestamp)

    def delta(self, path: str, version=None) -> "DataFrame":
        from .io.delta import read_delta
        return read_delta(self._session, path, version)

    def json(self, *paths: str, schema=None) -> "DataFrame":
        """Lazy block-streaming JSON-lines scan (reference:
        GpuJsonScan.scala); schema from a first-block sample unless
        given."""
        return DataFrame(self._session,
                         L.TextScan(list(paths), "json", schema))


def _split_join_condition(expr, lschema, rschema):
    """Decompose a join-on expression: (left_keys, right_keys, residual).
    Top-level AND conjuncts of the form left_expr == right_expr become
    equi keys; everything else stays in the residual non-equi condition
    (the reference's extraction in GpuHashJoin + AstUtil)."""
    from .expr.expressions import And, ColumnRef, Eq

    lnames, rnames = set(lschema.names), set(rschema.names)

    def refs(e):
        out = set()
        stack = [e]
        while stack:
            x_ = stack.pop()
            if isinstance(x_, ColumnRef):
                out.add(x_._name if hasattr(x_, "_name") else x_.name)
            stack.extend(getattr(x_, "children", []))
        return out

    def side(e):
        r = refs(e)
        if r and r <= lnames and not (r & rnames):
            return "left"
        if r and r <= rnames and not (r & lnames):
            return "right"
        return None

    def conjuncts(e):
        if isinstance(e, And):
            return conjuncts(e.children[0]) + conjuncts(e.children[1])
        return [e]

    lkeys, rkeys, residual = [], [], None
    for c in conjuncts(expr):
        if isinstance(c, Eq):
            a, b = c.children
            sa, sb = side(a), side(b)
            if sa == "left" and sb == "right":
                lkeys.append(a)
                rkeys.append(b)
                continue
            if sa == "right" and sb == "left":
                lkeys.append(b)
                rkeys.append(a)
                continue
        residual = c if residual is None else (residual & c)
    return lkeys, rkeys, residual


class GroupingID:
    """Marker accepted in rollup/cube agg lists: resolves to the Spark
    grouping_id of the row's grouping set."""

    name = "grouping_id()"

    def alias(self, name):
        from .expr.expressions import Alias
        return Alias(self, name)


class GroupedData:
    def __init__(self, df: "DataFrame", keys: Sequence[Expression],
                 grouping_sets=None):
        self._df = df
        self._keys = list(keys)
        # list of include-masks (one bool per key) or None for plain
        # GROUP BY; reference: GpuExpandExec.scala projections
        self._grouping_sets = grouping_sets

    def _key_names(self):
        names = [getattr(k, "name", None) for k in self._keys]
        if any(n is None for n in names):
            raise ValueError("pandas group transforms need plain column "
                             "keys (got computed expressions)")
        return names

    @staticmethod
    def _out_schema(schema):
        from .columnar import dtypes as _dt
        from .columnar.table import Field, Schema as _Schema
        if isinstance(schema, _Schema):
            return schema
        if isinstance(schema, (list, tuple)):
            return _Schema([Field(n, t) for n, t in schema])
        return _Schema([Field(f.name, _dt.from_arrow(f.type))
                        for f in schema])

    def apply_in_pandas(self, fn, schema) -> "DataFrame":
        """Per-group pandas transform: `fn(pandas.DataFrame) ->
        pandas.DataFrame` runs once per group in a pooled python worker
        (reference: GroupedData.applyInPandas /
        GpuFlatMapGroupsInPandasExec). Groups are repartitioned whole;
        oversized partitions chunk at group boundaries."""
        from .exec.python_exec import _GroupApply
        out = self._out_schema(schema)
        names = self._key_names()
        return DataFrame(self._df._session, L.GroupedMapInPandas(
            self._df._plan, _GroupApply(fn, names), out, names))

    applyInPandas = apply_in_pandas

    def agg_in_pandas(self, _types=None, **named) -> "DataFrame":
        """AggregateInPandas (reference:
        GpuAggregateInPandasExec.scala:51): each kwarg is
        name=(fn, col[, col...]); fn receives pandas Series (one per
        col) for ONE group and returns a scalar. Output: key columns +
        one row per group. Aggregate outputs default to FLOAT64;
        non-float results declare their dtype via
        `_types={name: DataType}`."""
        from .columnar import dtypes as _dt
        from .columnar.table import Field, Schema as _Schema
        from .exec.python_exec import _AggApply
        names = self._key_names()
        aggs = {}
        for out_name, spec in named.items():
            fn = spec[0]
            cols = [getattr(c, "name", c) for c in spec[1:]]
            aggs[out_name] = (fn, cols)
        child_schema = self._df._plan.schema
        fields = [Field(n, child_schema[child_schema.index_of(n)].dtype)
                  for n in names]
        fields += [Field(n, (_types or {}).get(n, _dt.FLOAT64))
                   for n in aggs]
        out = _Schema(fields)
        return DataFrame(self._df._session, L.GroupedMapInPandas(
            self._df._plan, _AggApply(aggs, names), out, names))

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        """Pair two grouped frames for applyInPandas over matching key
        groups (reference: GpuFlatMapCoGroupsInPandasExec)."""
        return CoGroupedData(self, other)

    def agg(self, *aggs, **named_aggs) -> "DataFrame":
        pairs = []
        gid_cols = []
        from .expr.expressions import Alias
        for a in aggs:
            name = getattr(a, "_alias", None) or a.name
            inner = a
            if isinstance(a, Alias):
                name = a._name
                inner = a.child
            if isinstance(inner, GroupingID):
                gid_cols.append(name)
                continue
            if not isinstance(inner, AggExpr):
                raise TypeError(f"not an aggregate: {a!r}")
            pairs.append((name, inner))
        for name, a in named_aggs.items():
            inner = a.child if hasattr(a, "child") and not isinstance(
                a, AggExpr) else a
            pairs.append((name, inner))
        if self._grouping_sets is None:
            if gid_cols:
                raise ValueError("grouping_id() requires rollup/cube/"
                                 "grouping_sets")
            return DataFrame(self._df._session,
                             L.Aggregate(self._df._plan, self._keys,
                                         pairs))
        return self._agg_grouping_sets(pairs, gid_cols)

    def _agg_grouping_sets(self, pairs, gid_cols) -> "DataFrame":
        """ROLLUP/CUBE/GROUPING SETS: Expand (one block per set, excluded
        keys nulled, + grouping_id) then aggregate by
        (keys..., grouping_id), then project user columns."""
        from .expr.expressions import Alias, ColumnRef
        child = self._df._plan
        knames = [f"#gset_k{i}" for i in range(len(self._keys))]
        gid = "#gset_gid"
        expand = L.Expand(child, self._keys, knames,
                          self._grouping_sets, gid)
        gkeys = [ColumnRef(kn) for kn in knames] + [ColumnRef(gid)]
        agg_node = L.Aggregate(expand, gkeys, pairs)
        out = []
        for k, kn in zip(self._keys, knames):
            out.append(Alias(ColumnRef(kn), k.name))
        for nm, _ in pairs:
            out.append(ColumnRef(nm))
        for nm in gid_cols:
            out.append(Alias(ColumnRef(gid), nm))
        return DataFrame(self._df._session, L.Project(agg_node, out))

    def count(self) -> "DataFrame":
        from .expr.aggregates import CountStar
        return self.agg(CountStar().alias("count"))


class CoGroupedData:
    def __init__(self, left: GroupedData, right: GroupedData):
        self._left = left
        self._right = right

    def apply_in_pandas(self, fn, schema) -> "DataFrame":
        """`fn(left_df, right_df) -> pandas.DataFrame` per matching key
        group (either side may be empty)."""
        from .exec.python_exec import _CoGroupApply
        out = GroupedData._out_schema(schema)
        lnames = self._left._key_names()
        rnames = self._right._key_names()
        if len(lnames) != len(rnames):
            raise ValueError("cogroup key counts differ")
        lcols = list(self._left._df.schema.names)
        rcols = list(self._right._df.schema.names)
        wrapper = _CoGroupApply(fn, lnames, rnames, lcols, rcols)
        return DataFrame(self._left._df._session, L.CoGroupInPandas(
            self._left._df._plan, self._right._df._plan, wrapper, out,
            lnames, rnames))

    applyInPandas = apply_in_pandas


class DataFrame:
    def __init__(self, session: TpuSession, plan: L.LogicalPlan):
        self._session = session
        self._plan = plan

    def __del__(self):
        # release long-lived plan resources (mesh-exchange output
        # handles parked for re-execution) when the DataFrame goes away
        try:
            cached = getattr(self, "_cached", None)
            if cached is not None:
                cached[1].release()
        except Exception:
            pass

    # -- plan builders --------------------------------------------------
    @property
    def schema(self) -> Schema:
        return self._plan.schema

    @property
    def columns(self) -> List[str]:
        return self._plan.schema.names

    def select(self, *exprs) -> "DataFrame":
        from .window import WindowExpr
        from .expr.expressions import Alias, ColumnRef
        from .expr.collection_exprs import Explode
        es = [_to_expr(e) for e in exprs]
        # lift explode/posexplode into a Generate stage (the reference's
        # GenerateExec planning: GpuGenerateExec.scala)
        gens = [(i, (e.child if isinstance(e, Alias) else e), e)
                for i, e in enumerate(es)
                if isinstance(e.child if isinstance(e, Alias) else e,
                              Explode)]
        if gens:
            if len(gens) > 1:
                raise ValueError("only one explode per select")
            i, gen, orig = gens[0]
            from .columnar import dtypes as dt
            bound_child = gen.child.bind(self._plan.schema)
            is_map = isinstance(bound_child.dtype, dt.MapType)
            if is_map:
                names = ["key", "value"]
            else:
                names = [orig._name if isinstance(orig, Alias) else "col"]
            if gen.with_position:
                names = ["pos"] + names
            # generated columns get collision-proof internal names in the
            # Generate schema (a pre-existing 'col'/'key'/'pos' column
            # would otherwise shadow them), then alias back for the user
            internal = [f"#gen{id(gen) & 0xFFFF:04x}_{n}" for n in names]
            from .expr.expressions import Alias as _Alias
            gplan = L.Generate(self._plan, gen, internal)
            repl = [_Alias(ColumnRef(ii), n)
                    for ii, n in zip(internal, names)]
            es2 = es[:i] + repl + es[i + 1:]
            return DataFrame(self._session, L.Project(gplan, es2))
        # extract window expressions into a WindowOp stage (the planner
        # split the reference does in GpuWindowExecMeta)
        wcols, plain = [], []
        for e in es:
            inner = e.child if isinstance(e, Alias) else e
            if isinstance(inner, WindowExpr):
                name = e._name if isinstance(e, Alias) else \
                    f"_w{len(wcols)}"
                wcols.append((name, inner))
                plain.append(ColumnRef(name))
            else:
                plain.append(e)
        if wcols:
            return DataFrame(self._session,
                             L.Project(L.WindowOp(self._plan, wcols),
                                       plain))
        return DataFrame(self._session, L.Project(self._plan, es))

    def with_column(self, name: str, e) -> "DataFrame":
        # route through select() so window-expression extraction applies
        es = [col(n) for n in self.columns if n != name]
        es.append(_to_expr(e).alias(name))
        return self.select(*es)

    withColumn = with_column

    def filter(self, cond) -> "DataFrame":
        return DataFrame(self._session, L.Filter(self._plan, _to_expr(cond)))

    where = filter

    def group_by(self, *keys) -> GroupedData:
        return GroupedData(self, [_to_expr(k) for k in keys])

    groupBy = group_by

    def rollup(self, *keys) -> GroupedData:
        """GROUP BY ROLLUP: (k1..kn), (k1..kn-1), ..., ()."""
        ks = [_to_expr(k) for k in keys]
        n = len(ks)
        sets = [[i < j for i in range(n)] for j in range(n, -1, -1)]
        return GroupedData(self, ks, grouping_sets=sets)

    def cube(self, *keys) -> GroupedData:
        """GROUP BY CUBE: all 2^n key subsets."""
        ks = [_to_expr(k) for k in keys]
        n = len(ks)
        sets = [[not (m >> (n - 1 - i)) & 1 == 1 for i in range(n)]
                for m in range(1 << n)]
        return GroupedData(self, ks, grouping_sets=sets)

    def grouping_sets(self, keys, sets) -> GroupedData:
        """Explicit GROUPING SETS: `sets` is a list of key-name lists
        (subsets of `keys`)."""
        ks = [_to_expr(k) for k in keys]
        names = [k.name for k in ks]
        masks = []
        for s_ in sets:
            want = set(s_)
            unknown = want - set(names)
            if unknown:
                raise ValueError(f"grouping set refers to unknown keys "
                                 f"{sorted(unknown)}")
            masks.append([nm in want for nm in names])
        return GroupedData(self, ks, grouping_sets=masks)

    def agg(self, *aggs, **named) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs, **named)

    def join(self, other: "DataFrame", on=None, how: str = "inner",
             condition=None) -> "DataFrame":
        """Join on equi-key column names (`on`) plus an optional non-equi
        `condition` expression over the combined schema (ambiguous names
        resolve to the left side). With no `on` and a `condition`, a
        broadcast nested-loop join runs (reference:
        GpuBroadcastNestedLoopJoinExecBase.scala)."""
        if isinstance(on, str):
            on = [on]
        if on is None:
            on = []
        if isinstance(on, Expression):
            # decompose: equality conjuncts between the two sides become
            # equi keys, the rest joins the non-equi condition
            lk_x, rk_x, extra = _split_join_condition(
                on, self._plan.schema, other._plan.schema)
            if condition is not None:
                extra = condition if extra is None else (extra & condition)
            return self._join_positional(other, [], how, lk_x, rk_x,
                                         condition=extra)
        if not (isinstance(on, (list, tuple))
                and all(isinstance(c, str) for c in on)):
            raise TypeError("join `on` must be column name(s) or an "
                            "expression")
        lk = [col(c) for c in on]
        rk = [col(c) for c in on]
        if not on and condition is None and how != "cross":
            raise ValueError("join needs `on` keys or a `condition`")
        if condition is not None or not on:
            # conditions bind positionally over the combined schema;
            # skip the rename machinery (ambiguous names -> left side)
            return self._join_positional(other, list(on), how, lk, rk,
                                         condition=condition)
        if how in ("left_semi", "left_anti"):
            return DataFrame(self._session,
                             L.Join(self._plan, other._plan, lk, rk, how))
        lnames_list = list(self._plan.schema.names)
        rnames_list = list(other._plan.schema.names)
        if (len(set(lnames_list)) < len(lnames_list)
                or len(set(rnames_list)) < len(rnames_list)):
            # a side already carries duplicate column names (e.g. the
            # output of a previous join): name-based projection would
            # collapse the duplicates, so keep the positional form
            return self._join_positional(other, on, how, lk, rk)
        # Rename colliding right-side columns before the join so every
        # name in the joined schema is unique — the post-join projection
        # then stays purely name-based, which keeps the optimizer's
        # column pruning and filter pushdown working above joins.
        from .expr.expressions import Coalesce
        lnames = set(self._plan.schema.names)
        # collision-proof internal names: a unique counter per join keeps
        # the hidden key columns of DIFFERENT joins in one chain distinct,
        # which the join-reorder pass relies on when it flattens a chain
        _JOIN_RENAME_COUNTER[0] += 1
        tag = _JOIN_RENAME_COUNTER[0]
        rename = {f.name: f"__join_r{tag}_{f.name}"
                  for f in other._plan.schema.fields if f.name in lnames}
        rplan = other._plan
        if rename:
            rplan = L.Project(rplan, [
                col(f.name).alias(rename[f.name]) if f.name in rename
                else col(f.name) for f in other._plan.schema.fields])
        rk = [col(rename.get(c, c)) for c in on]
        jplan = L.Join(self._plan, rplan, lk, rk, how)
        # pyspark semantics: the `on` columns appear once, then left rest,
        # then right rest. For right joins take the key from the right
        # side; for full outer coalesce both sides.
        on_set = set(on)
        exprs = []
        for name in on:
            rn = rename.get(name, name)
            if how == "right":
                exprs.append(col(rn).alias(name))
            elif how == "full":
                exprs.append(Coalesce(col(name), col(rn)).alias(name))
            else:
                exprs.append(col(name))
        for f in self._plan.schema.fields:
            if f.name not in on_set:
                exprs.append(col(f.name))
        for f in other._plan.schema.fields:
            if f.name in on_set:
                continue
            rn = rename.get(f.name, f.name)
            exprs.append(col(rn).alias(f.name) if rn != f.name
                         else col(f.name))
        return DataFrame(self._session, L.Project(jplan, exprs))

    def _join_positional(self, other: "DataFrame", on, how, lk, rk,
                         condition=None):
        """Positional (BoundRef) post-join projection: exact for
        duplicate-named inputs, at the cost of disabling name-based
        pruning above this join."""
        from .expr.expressions import BoundRef, Coalesce
        jplan = L.Join(self._plan, other._plan, lk, rk, how,
                       condition=condition)
        if how in ("left_semi", "left_anti"):
            return DataFrame(self._session, jplan)
        nl = len(self._plan.schema.fields)
        on_set = set(on)
        exprs = []
        jschema = jplan.schema
        for name in on:
            li = self._plan.schema.index_of(name)
            ri = nl + other._plan.schema.index_of(name)
            lref = BoundRef(li, jschema[li].dtype, name)
            rref = BoundRef(ri, jschema[ri].dtype, name)
            if how == "right":
                exprs.append(rref)
            elif how == "full":
                exprs.append(Coalesce(lref, rref).alias(name))
            else:
                exprs.append(lref)
        for i, f in enumerate(jschema.fields):
            if f.name in on_set:
                continue
            exprs.append(BoundRef(i, f.dtype, f.name))
        return DataFrame(self._session, L.Project(jplan, exprs))

    def sort(self, *orders, ascending=True) -> "DataFrame":
        sos = []
        for o in orders:
            if isinstance(o, L.SortOrder):
                sos.append(o)
            else:
                sos.append(L.SortOrder(_to_expr(o), ascending))
        return DataFrame(self._session, L.Sort(self._plan, sos))

    orderBy = sort

    def create_or_replace_temp_view(self, name: str):
        from .sql.parser import register_view
        register_view(self._session, name, self)

    createOrReplaceTempView = create_or_replace_temp_view

    def distinct(self) -> "DataFrame":
        ks = [col(n) for n in self.columns]
        return DataFrame(self._session, L.Aggregate(self._plan, ks, []))

    dropDuplicates = distinct

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self._session, L.Limit(self._plan, n))

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._session, L.Union([self._plan, other._plan]))

    def repartition(self, n: int, *keys) -> "DataFrame":
        ks = [_to_expr(k) for k in keys] or None
        return DataFrame(self._session, L.Repartition(self._plan, n, ks))

    def map_in_pandas(self, fn, schema) -> "DataFrame":
        """Apply `fn(pandas.DataFrame) -> pandas.DataFrame` batch-wise in
        a pooled python WORKER PROCESS, batches crossing as Arrow IPC
        (reference: DataFrame.mapInPandas / GpuMapInPandasExec). `fn`
        must be picklable; `schema` is the output schema
        (Schema | list[(name, DataType)] | arrow schema)."""
        from .columnar.table import Field, Schema as _Schema
        from .columnar import dtypes as _dt
        if isinstance(schema, _Schema):
            out = schema
        elif isinstance(schema, (list, tuple)):
            out = _Schema([Field(n, t) for n, t in schema])
        else:  # arrow schema
            out = _Schema([Field(f.name, _dt.from_arrow(f.type))
                           for f in schema])
        return DataFrame(self._session,
                         L.MapInPandas(self._plan, fn, out))

    mapInPandas = map_in_pandas

    def cache(self) -> "DataFrame":
        """Materialize this DataFrame into HBM-resident device batches
        (GpuInMemoryTableScan analog); later queries skip decode + H2D."""
        def body(root, ctx):
            return list(root.execute_all(ctx))
        batches = self._run_action("cache", body)
        return DataFrame(self._session,
                         L.CachedScan(batches, self._plan.schema))

    def uncache(self) -> "DataFrame":
        """Release this DataFrame's cached physical plan (exec nodes,
        their device state, materialized shuffles). The next action
        re-plans from the logical tree — a FRESH execution, which is
        what honest benchmarking times (`bench.py` calls this between
        iterations so repeat runs do not silently reuse resident
        operator state)."""
        cached = self._cached
        if cached is not None:
            try:
                cached[1].release()
            except Exception:
                pass
            self._cached = None
        # uncache promises the NEXT action is a fresh execution — the
        # cross-query result cache must not answer it from a prior run,
        # and in a fleet no PEER may either: invalidate_plan broadcasts
        # the plan fingerprint to every live member (best-effort; the
        # requester-side snapshot re-stat backstops a lost delivery)
        try:
            from .runtime import result_cache
            result_cache.invalidate_plan(self._plan)
        except Exception:
            pass
        return self

    # -- actions --------------------------------------------------------
    _cached: Optional[tuple] = None
    _last_root = None

    def _execute(self, conf=None):
        # Cache the physical plan: exec nodes own their jitted kernels, so
        # re-collecting a DataFrame reuses compiled programs (the analog of
        # Spark's executedPlan reuse). `conf` is the per-query snapshot
        # taken at submission — concurrent queries must not observe a
        # session conf mutated mid-flight.
        if conf is None:
            conf = self._session.conf
        # scan-snapshot staleness: re-stat every pinned data file; a
        # mid-session overwrite drops the cached physical plan (replan
        # rebinds against the new files) and invalidates dependent
        # result-cache entries — stale bytes are never served, cache on
        # or off (io/snapshot.py)
        from .io.snapshot import refresh_plan_snapshots
        changed = refresh_plan_snapshots(self._plan)
        if changed:
            from .runtime import result_cache
            result_cache.invalidate_paths(changed)
            if self._cached is not None:
                try:
                    self._cached[1].release()
                except Exception:
                    pass
                self._cached = None
        if self._cached is not None and self._cached[0] is conf:
            root = self._cached[1]
        else:
            planner = Planner(conf)
            root = planner.plan(self._plan)
            self._cached = (conf, root)
        ctx = ExecContext(conf, self._session)
        return root, ctx

    def _run_action(self, action: str, body):
        """Run one query action through the query service: admission by
        the fair scheduler, a CancelToken + query_id on the ExecContext,
        and the profiler wrapper (query_queued/query_admitted/
        query_start/.../query_end events when sql.eventLog.enabled).
        Runs on the CALLER's thread once admitted; `DataFrame.submit`
        is the async counterpart."""
        import time as _time
        from .runtime import result_cache
        conf = self._session.conf  # per-query conf snapshot
        outer = getattr(_ACTION_TLS, "handle", None)
        # whole-query tier of the cross-query result cache: collects
        # consult it BEFORE admission — a hit is served on the service
        # fast path (no slot consumed, still metered + event-logged)
        token = None
        if action == "collect" and result_cache.enabled(conf):
            t0 = _time.perf_counter()
            hit, token = result_cache.lookup_query(self._plan, conf)
            if hit is not None:
                if outer is None:
                    mgr = self._session.query_manager()
                    handle = mgr.fast_path(plan=self._plan, conf=conf,
                                           action=action, result=hit)
                    from .profiler.event_log import log_fast_path
                    log_fast_path(self._session, conf, handle, action,
                                  hit.num_rows,
                                  _time.perf_counter() - t0)
                self._last_metrics = {"ResultCache": {
                    "resultCacheHits": 1,
                    "numOutputRows": hit.num_rows}}
                return hit
        if outer is not None:
            # nested action (subquery collected inside a parent query):
            # ride the outer grant + token, skip re-admission
            return self._execute_action(action, body, conf,
                                        outer, nested=True,
                                        cache_token=token)
        mgr = self._session.query_manager()
        # service-level transparent retry: a CLASSIFIED-transient
        # failure (is_transient_error — injected faults, FetchFailed,
        # executor loss; never cancellation/deadline/user errors)
        # re-admits the query as a fresh attempt, with the FIRST
        # attempt's deadline still binding. Each attempt is its own
        # query_id, so admission accounting, the event log, and the
        # resource-ledger per-query balance check all see it whole.
        from .config import SERVICE_MAX_QUERY_RETRIES
        from .runtime.faults import is_transient_error, note_recovery
        max_retries = int(conf.get(SERVICE_MAX_QUERY_RETRIES))
        attempt = 0
        deadline = None      # original deadline, binding across retries
        retry_of = None
        while True:
            timeout = None
            if deadline is not None:
                timeout = max(deadline - _time.monotonic(), 1e-3)
            handle = mgr.open_query(plan=self._plan, conf=conf,
                                    action=action, timeout=timeout)
            if deadline is None:
                deadline = handle.token.deadline
            try:
                out = self._execute_action(action, body, conf, handle,
                                           cache_token=token,
                                           retry_of=retry_of)
            except BaseException as e:
                mgr.close_query(handle, error=e)
                if (attempt < max_retries and is_transient_error(e)
                        and (deadline is None
                             or _time.monotonic() < deadline)):
                    attempt += 1
                    note_recovery("query_retries")
                    retry_of = {"attempt": attempt,
                                "prior_query_id": handle.query_id,
                                "error": repr(e)}
                    continue
                raise
            mgr.close_query(handle, result=out)
            return out

    def submit(self, action: str = "collect", pool=None, timeout=None):
        """Async action through the query service: returns a QueryHandle
        immediately; `handle.result()` blocks for the arrow table (or
        re-raises). The gateway and the throughput bench submit here."""
        if action != "collect":
            raise ValueError("submit() supports the 'collect' action")
        import time as _time
        from .exec.nodes import collect_to_arrow as _collect
        from .runtime import result_cache
        mgr = self._session.query_manager()
        conf = self._session.conf

        token = None
        if result_cache.enabled(conf):
            t0 = _time.perf_counter()
            hit, token = result_cache.lookup_query(self._plan, conf)
            if hit is not None:
                # cache fast path: answered without an admission slot;
                # handle.result() returns immediately
                handle = mgr.fast_path(plan=self._plan, conf=conf,
                                       action="collect", pool=pool,
                                       result=hit)
                from .profiler.event_log import log_fast_path
                log_fast_path(self._session, conf, handle, "collect",
                              hit.num_rows, _time.perf_counter() - t0)
                return handle

        # the admitted body runs on a QueryManager worker thread, so
        # the submitter's fleet member (thread-local) must be captured
        # HERE and re-entered there — a multi-member process would
        # otherwise publish gateway B's results as member A
        from .fleet import context as _fleet_ctx
        member = _fleet_ctx.active_member()

        def run(handle):
            if member is None:
                return self._execute_action(
                    "collect", lambda root, ctx: _collect(root, ctx),
                    conf, handle, cache_token=token)
            with _fleet_ctx.scoped(member):
                return self._execute_action(
                    "collect", lambda root, ctx: _collect(root, ctx),
                    conf, handle, cache_token=token)

        return mgr.submit(run, plan=self._plan, conf=conf,
                          action="collect", pool=pool, timeout=timeout)

    def _execute_action(self, action: str, body, conf, handle,
                        nested: bool = False, cache_token=None,
                        retry_of=None):
        """The admitted half of an action: plan (or reuse the cached
        physical tree), execute under the profiler wrapper, then attach
        the per-query XLA/semaphore/queue-wait accounting to the root
        node's MetricSet. On ANY failure — including cooperative
        cancellation — the physical plan is released deterministically
        (exchange handles, spill files, parked device buffers) instead
        of waiting for GC."""
        from .profiler import tracing, xla_stats
        from .profiler.event_log import profile_query
        from .service.query_manager import _query_scope
        # distributed tracing: one trace per query (trace_id ==
        # query_id). A nested action joins the enclosing query's trace
        # (the outer action installed its context on this thread);
        # otherwise the sampling decision is taken here, before
        # planning, so the plan span is part of the trace.
        tc = tracing.current() if nested else (
            tracing.start_trace(handle.query_id, conf)
            if handle is not None else None)
        rsp = None
        if tc is not None and not nested:
            # open the root span BEFORE planning so the plan span and
            # the back-dated admission wait parent under it — the trace
            # is one rooted tree, not a forest of top-level siblings
            # tpulint: allow[span-leak] query root span: ended by tracing.finish() in this action's finally (idempotent close-out)
            rsp = tracing.open_span("query", "query", tc, action=action)
            tc = tracing.TraceContext(tc.trace_id, rsp.span_id, True)
            if handle is not None:
                tracing.record_queue_span(tc, handle.queue_wait_ms,
                                          pool=handle.pool)
        if tc is not None:
            with tracing.span("plan", "plan", tc):
                root, ctx = self._execute(conf)
        else:
            root, ctx = self._execute(conf)
        ctx.trace = tc
        if handle is not None:
            ctx.cancel = handle.token
            ctx.query_id = handle.query_id
            mgr = getattr(self._session, "_query_manager", None)
            if mgr is not None:
                ctx.sem_priority = mgr.scheduler.priority_of(handle)
        if rsp is not None:
            ctx._root_span = rsp
        # stage-ahead compilation: submit this tree's programs whose
        # signatures were observed before (earlier query or warm-pack
        # seed) to the background pool; downstream stage programs
        # compile while upstream stages execute. Best-effort, never
        # blocks the launch.
        from .runtime import compile_pool
        _cpool = compile_pool.get_pool(conf)
        if _cpool is not None:
            from .exec.base import prewarm_tree
            try:
                # under use(): the pool snapshots the submitter's trace
                # context so background compiles land in this trace
                with tracing.use(ctx.trace):
                    prewarm_tree(root, _cpool,
                                 handle.query_id if handle else None)
            except Exception:
                pass
        sem = getattr(self._session, "_semaphore", None)
        sem_acq0 = sem.metrics["acquires"] if sem is not None else 0
        xla0 = xla_stats.snapshot()
        from .runtime import ledger as _ledger
        lg = _ledger.ledger()
        lease_acq0 = (lg.report()["kinds"].get("staging_lease", {})
                      .get("acquires", 0) if lg is not None else 0)
        _ACTION_TLS.handle = handle if not nested else \
            getattr(_ACTION_TLS, "handle", None)
        from .runtime import result_cache
        rc_on = result_cache.enabled(conf)
        rc0 = result_cache.stats() if rc_on else None
        try:
            with _query_scope(handle.query_id if handle else "?"), \
                    tracing.use(ctx.trace):
                with profile_query(self._session, root, ctx, action,
                                   handle=None if nested else handle) as w:
                    if retry_of and w is not None:
                        # this attempt is a service-level transparent
                        # retry of a transient failure; link it to the
                        # prior attempt's query_id in the event log
                        w.emit("query_retry", action=action, **retry_of)
                    try:
                        # AQE stage driver: materialize shuffle stages
                        # bottom-up and replan (coalesce / skew-split /
                        # join demotion) between stage completion and
                        # consumer launch. Decisions are re-served on a
                        # cached root so every run's event log is
                        # self-contained. Errors (cancellation
                        # included) propagate — a stage that ran IS
                        # query execution.
                        from .plan.aqe import run_stage_driver
                        decisions = run_stage_driver(root, ctx, conf)
                        if decisions and w is not None:
                            w.emit("aqe_replan", action=action,
                                   decisions=decisions)
                        out = body(root, ctx)
                        # observed-cardinality harvest: close the AQE
                        # feedback loop (plan/stats.py calibration
                        # table); advisory, never fails the query
                        from .plan.stats import harvest_calibration
                        try:
                            harvest_calibration(root, ctx)
                        except Exception:
                            pass
                        if rc_on:
                            # a successful run feeds BOTH cache tiers:
                            # tagged exchange map outputs (fragment
                            # misses from planning) and, for collects,
                            # the whole-query arrow result
                            try:
                                result_cache.harvest_fragments(root, ctx)
                            except Exception:
                                pass
                            if cache_token is not None:
                                result_cache.put_query(cache_token, out,
                                                       conf)
                    finally:
                        # recovery events queued mid-execution
                        # (degrade_to_host and friends) drain into the
                        # query's event log even when the run failed
                        if w is not None and ctx.pending_events:
                            for ev in ctx.pending_events:
                                kw = dict(ev)
                                name = kw.pop("event")
                                try:
                                    w.emit(name, **kw)
                                except Exception:
                                    pass
                            ctx.pending_events = []
                        ctx.close()
        except BaseException:
            try:
                root.release()
            except Exception:
                pass
            if self._cached is not None and self._cached[1] is root:
                self._cached = None
            # cooperative prewarm cancellation: a dead query's queued
            # stage-ahead compiles are dropped (a task already
            # compiling finishes — the result is cached for a retry)
            if handle is not None and _cpool is not None:
                try:
                    _cpool.cancel_query(handle.query_id)
                except Exception:
                    pass
            raise
        finally:
            if not nested:
                _ACTION_TLS.handle = None
                # event-log-off fallback: the profiler wrapper normally
                # drains the trace (and emits trace_span records);
                # without it the trace must still close so EXPLAIN
                # ANALYZE gets its summary and the buffers drain
                try:
                    tracing.finish(ctx)
                except Exception:
                    pass
        # per-query XLA accounting rides the root node's MetricSet so it
        # flows into last_metrics() / EXPLAIN ANALYZE / op_metrics events
        xla1 = xla_stats.snapshot()
        rm = ctx.metrics_for(root._op_id)
        rm.add("xlaCompiles", int(xla1["compiles"] - xla0["compiles"]))
        rm.add("xlaDispatches",
               int(xla1["dispatches"] - xla0["dispatches"]))
        rm.add("programCacheHits",
               int(xla1.get("program_cache_hits", 0)
                   - xla0.get("program_cache_hits", 0)))
        rm.add("programCacheMisses",
               int(xla1.get("program_cache_misses", 0)
                   - xla0.get("program_cache_misses", 0)))
        # compile-tail accounting: wall ms spent in XLA compilation
        # attributed to this action (sync misses on this thread plus
        # background prewarms that completed during it) and how many of
        # those compiles ran off the dispatch path
        cms = (xla1.get("program_cache_compile_ms", 0.0)
               - xla0.get("program_cache_compile_ms", 0.0))
        if cms:
            rm.add("compileMs", round(cms, 3))
        bg = int(xla1.get("program_cache_background_compiles", 0)
                 - xla0.get("program_cache_background_compiles", 0))
        if bg:
            rm.add("backgroundCompiles", bg)
        if handle is not None and not nested:
            rm.add("queueWaitMs", round(handle.queue_wait_ms, 3))
        # critical-path decomposition of this action's wall clock
        # (profiler/critical_path.py): per-edge percentage shares ride
        # the root MetricSet so EXPLAIN ANALYZE prints criticalPath=
        summ = getattr(ctx, "trace_summary", None)
        if summ:
            for c, pct in summ["share_pct"].items():
                if pct:
                    rm.add(f"criticalPathShare.{c}", pct)
        if rc_on:
            # per-action cache accounting on the root MetricSet (flows
            # into EXPLAIN ANALYZE / op_metrics); global-counter diffs,
            # so concurrent queries' events can interleave — counters,
            # not invariants
            rc1 = result_cache.stats()
            for metric, counter in (
                    ("resultCacheHits", "result_cache_hits"),
                    ("resultCacheMisses", "result_cache_misses"),
                    ("resultCacheFragmentHits",
                     "result_cache_fragment_hits"),
                    ("resultCacheEvictions", "result_cache_evictions"),
                    ("resultCacheInvalidationEvents",
                     "result_cache_invalidations")):
                d = int(rc1[counter] - rc0[counter])
                if d:
                    rm.add(metric, d)
            if cache_token is not None:
                # this action's own whole-query lookup missed (it was
                # counted in _run_action, before the rc0 snapshot)
                rm.add("resultCacheMisses", 1)
        sem = getattr(self._session, "_semaphore", None)
        if sem is not None:
            acq = sem.metrics["acquires"] - sem_acq0
            if acq:
                rm.add("semaphoreAcquires", int(acq))
        if lg is not None:
            # resource-ledger accounting on the root MetricSet (flows
            # into EXPLAIN ANALYZE): lease traffic this action plus the
            # per-query balance verdict — global-counter diffs, like the
            # cache counters above
            rep = lg.report()
            sk = rep["kinds"].get("staging_lease", {})
            d = int(sk.get("acquires", 0) - lease_acq0)
            if d:
                rm.add("ledgerLeaseAcquires", d)
            rm.add("ledgerPeakLeases", int(sk.get("peakOutstanding", 0)))
            rm.add("ledgerBalanced", int(bool(rep["balanceOk"])))
        self._last_root = root
        self._last_metrics = {op: ms.snapshot(ctx.metrics_level)
                              for op, ms in ctx.metrics.items()}
        return out

    def to_arrow(self):
        return self._run_action(
            "collect", lambda root, ctx: collect_to_arrow(root, ctx))

    def last_metrics(self):
        """Per-operator metrics of the most recent action (GpuMetric
        analog; levels per spark.rapids.tpu.sql.metrics.level)."""
        return getattr(self, "_last_metrics", {})

    def to_jax(self):
        """Zero-copy export of the result as device arrays — the
        ColumnarRdd analog (reference: sql-plugin-api ColumnarRdd,
        zero-copy GPU handoff to ML/XGBoost). Returns
        {column: (data, validity)} of jax Arrays already resident in
        HBM; fixed-width columns only (strings keep Arrow export)."""
        from .columnar import dtypes as _dt
        from .ops.concat import concat_cvs, concat_masks
        from .ops.gather import compact
        for f in self.schema.fields:
            if f.dtype.is_variable_width or f.dtype.is_nested:
                raise TypeError(
                    f"to_jax exports fixed-width columns; {f.name} is "
                    f"{f.dtype.simple_name()} (use to_arrow)")
        def body(root, ctx):
            out = []
            for pid in range(root.num_partitions(ctx)):
                out.extend(root.execute_partition(ctx, pid))
            return out

        batches = self._run_action("to_jax", body)
        if not batches:
            import jax.numpy as jnp
            return {f.name: (jnp.zeros(0, f.dtype.np_dtype),
                             jnp.zeros(0, jnp.bool_))
                    for f in self.schema.fields}
        cvs = [concat_cvs([b.cvs()[i] for b in batches],
                          self.schema.fields[i].dtype)
               for i in range(len(self.schema.fields))]
        mask = concat_masks([b.row_mask for b in batches])
        from .utils.transfer import fetch_int
        dense, count = compact(cvs, mask)
        n = fetch_int(count)
        return {f.name: (c.data[:n], c.validity[:n])
                for f, c in zip(self.schema.fields, dense)}

    def collect(self) -> List[tuple]:
        at = self.to_arrow()
        cols = [at.column(i).to_pylist() for i in range(at.num_columns)]
        return list(zip(*cols)) if cols else []

    def to_pydict(self) -> Dict[str, list]:
        return self.to_arrow().to_pydict()

    def count(self) -> int:
        from .expr.aggregates import CountStar
        df = DataFrame(self._session,
                       L.Aggregate(self._plan, [], [("count", CountStar())]))
        return df.collect()[0][0]

    def explain(self, mode: str = "ALL"):
        """Print (and return) the plan. Modes: ALL / NOT_ON_TPU show
        TPU-placement tagging with per-node lore ids (plus static-audit
        findings); VALIDATE renders the plan auditor's full verdict tree
        (ok / will_fallback / will_not_work / recompile_risk per node,
        docs/static_analysis.md) WITHOUT executing anything; ANALYZE
        runs the query and renders the tree annotated with runtime
        metrics (rows/batches/op-time/shuffle/spill per node, top time
        sinks flagged) — the SQL-UI metric display analog."""
        mode_u = str(mode).upper()
        if mode_u == "ANALYZE":
            return self._explain_analyze()
        old = self._session.conf
        planner = Planner(old.set("spark.rapids.tpu.sql.explain", mode_u))
        planner.plan(self._plan)
        return "\n".join(planner.last_explain)

    def _explain_analyze(self) -> str:
        from .profiler.analyze import render_analyze
        from .profiler.event_log import op_metrics_records, plan_tree
        # drop (and release) any cached physical plan: stateful operators
        # in a previously executed plan (a materialized
        # ShuffleExchangeExec) would short-circuit re-execution, leaving
        # every operator below them metric-less — ANALYZE must measure a
        # full fresh run
        self.uncache()
        self.to_arrow()
        root = self._last_root
        recs = op_metrics_records(root, self._last_metrics)
        by_lore = {r["lore_id"]: r["metrics"] for r in recs}
        text = render_analyze(plan_tree(root), by_lore,
                              title="== EXPLAIN ANALYZE ==")
        print(text)
        return text

    def write_parquet(self, path: str, **kw):
        from .io.parquet import write_parquet
        write_parquet(self, path, **kw)

    def write_delta(self, path: str, mode: str = "append") -> int:
        from .io.delta import write_delta
        return write_delta(self, path, mode)

    @property
    def write(self):
        """Builder-style writer: df.write.mode(...).partitionBy(...)
        .parquet/orc/csv/json/hive_text/delta(path) (reference:
        GpuFileFormatWriter surface)."""
        from .io.writer import DataFrameWriter
        return DataFrameWriter(self)

    def _iter_partition_tables(self):
        """Stream the result partition-by-partition as compacted host
        arrow tables (shared by every file writer). Writers hold their
        admission grant for the generator's whole lifetime (the query
        service's open/close pair brackets the stream)."""
        import pyarrow as pa
        from .exec.nodes import _batch_to_arrow
        from .profiler.event_log import profile_query
        outer = getattr(_ACTION_TLS, "handle", None)
        mgr = self._session.query_manager() if outer is None else None
        conf = self._session.conf
        handle = outer if outer is not None else mgr.open_query(
            plan=self._plan, conf=conf, action="write")
        root, ctx = self._execute(conf)
        ctx.cancel = handle.token
        ctx.query_id = handle.query_id
        from .profiler import tracing
        tc = tracing.current() if outer is not None else \
            tracing.start_trace(handle.query_id, conf)
        ctx.trace = tc
        if tc is not None and outer is None:
            # root first, so the back-dated admission wait parents
            # under it (same rooted-tree shape as _execute_action)
            # tpulint: allow[span-leak] query root span: ended by tracing.finish() in the write path's finally
            rsp = tracing.open_span("query", "query", tc, action="write")
            ctx._root_span = rsp
            ctx.trace = tracing.TraceContext(tc.trace_id, rsp.span_id,
                                             True)
            tracing.record_queue_span(ctx.trace, handle.queue_wait_ms,
                                      pool=handle.pool)
        try:
            with tracing.use(ctx.trace), \
                    profile_query(self._session, root, ctx, "write",
                                  handle=None if outer else handle) as w:
                try:
                    from .plan.aqe import run_stage_driver
                    decisions = run_stage_driver(root, ctx, conf)
                    if decisions and w is not None:
                        w.emit("aqe_replan", action="write",
                               decisions=decisions)
                    for pid in range(root.num_partitions(ctx)):
                        ctx.check_cancel()
                        tables = [_batch_to_arrow(b)
                                  for b in root.execute_partition(ctx, pid)]
                        if tables:
                            yield pa.concat_tables(tables)
                    from .plan.stats import harvest_calibration
                    try:
                        harvest_calibration(root, ctx)
                    except Exception:
                        pass
                finally:
                    ctx.close()
        except BaseException as e:
            try:
                root.release()
            except Exception:
                pass
            if self._cached is not None and self._cached[1] is root:
                self._cached = None
            if mgr is not None:
                # an abandoned generator is a clean early stop, not a
                # query failure
                mgr.close_query(handle, error=None if isinstance(
                    e, GeneratorExit) else e)
            raise
        else:
            if mgr is not None:
                mgr.close_query(handle)
        finally:
            # profile_query normally finishes the trace with the true
            # wall clock; this is the event-log-off fallback
            try:
                tracing.finish(ctx)
            except Exception:
                pass
        self._last_root = root
        self._last_metrics = {op: ms.snapshot(ctx.metrics_level)
                              for op, ms in ctx.metrics.items()}
