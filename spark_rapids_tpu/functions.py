"""Public column-function surface (`import spark_rapids_tpu.functions as F`).

Mirrors pyspark.sql.functions naming for the subset the engine supports, so
workloads port with an import swap. (reference expression inventory:
GpuOverrides.scala:933-4258.)
"""
from __future__ import annotations

from .expr import aggregates as _agg
from .expr.expressions import (Abs, CaseWhen, Cast, Coalesce, ColumnRef,
                               EqNullSafe, Expression, Greatest, If, In,
                               IsNaN, IsNull, Least, Literal, MathUnary,
                               Negate, Pmod, Round, col, lit)

__all__ = [
    "col", "lit", "expr_sum", "sum", "count", "countDistinct", "min", "max",
    "avg", "mean", "first", "last", "when", "coalesce", "isnull", "isnan",
    "abs", "sqrt", "exp", "log", "log10", "log2", "floor", "ceil", "round",
    "greatest", "least", "pmod", "negate", "signum",
]


def sum(e):  # noqa: A001 - match pyspark naming
    return _agg.Sum(_to_expr(e))


def count(e):
    if isinstance(e, str) and e == "*":
        return _agg.CountStar()
    return _agg.Count(_to_expr(e))


def countDistinct(e):
    raise NotImplementedError("count distinct lands with distinct-agg rewrite")


def min(e):  # noqa: A001
    return _agg.Min(_to_expr(e))


def max(e):  # noqa: A001
    return _agg.Max(_to_expr(e))


def avg(e):
    return _agg.Avg(_to_expr(e))


mean = avg


def first(e, ignorenulls=False):
    return _agg.First(_to_expr(e), ignorenulls)


def last(e, ignorenulls=False):
    return _agg.Last(_to_expr(e), ignorenulls)


def _to_expr(e) -> Expression:
    if isinstance(e, Expression):
        return e
    if isinstance(e, str):
        return col(e)
    return lit(e)


class _WhenBuilder:
    def __init__(self, branches):
        self._branches = branches

    def when(self, cond, value):
        return _WhenBuilder(self._branches + [(_to_expr(cond),
                                               _to_expr(value))])

    def otherwise(self, value):
        return CaseWhen(self._branches, _to_expr(value))

    # allow using the builder directly as an expression (no ELSE -> null)
    def __getattr__(self, item):
        return getattr(CaseWhen(self._branches, None), item)


def when(cond, value):
    return _WhenBuilder([(_to_expr(cond), _to_expr(value))])


def coalesce(*exprs):
    return Coalesce(*[_to_expr(e) for e in exprs])


def isnull(e):
    return IsNull(_to_expr(e))


def isnan(e):
    return IsNaN(_to_expr(e))


def abs(e):  # noqa: A001
    return Abs(_to_expr(e))


def negate(e):
    return Negate(_to_expr(e))


def _math(name):
    def fn(e):
        return MathUnary(name, _to_expr(e))
    fn.__name__ = name
    return fn


sqrt = _math("sqrt")
exp = _math("exp")
log = _math("log")
log10 = _math("log10")
log2 = _math("log2")
floor = _math("floor")
ceil = _math("ceil")
signum = _math("signum")


def round(e, scale=0):  # noqa: A001
    return Round(_to_expr(e), scale)


def greatest(*es):
    return Greatest(*[_to_expr(e) for e in es])


def least(*es):
    return Least(*[_to_expr(e) for e in es])


def pmod(a, b):
    return Pmod(_to_expr(a), _to_expr(b))
