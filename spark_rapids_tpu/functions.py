"""Public column-function surface (`import spark_rapids_tpu.functions as F`).

Mirrors pyspark.sql.functions naming for the subset the engine supports, so
workloads port with an import swap. (reference expression inventory:
GpuOverrides.scala:933-4258.)
"""
from __future__ import annotations

from .expr import aggregates as _agg
from .expr import string_exprs as _se
from .expr import datetime_exprs as _de
from .expr.udf import udf, df_udf  # noqa: F401  (public re-exports)
from .expr.expressions import (Abs, CaseWhen, Cast, Coalesce, ColumnRef,
                               EqNullSafe, Expression, Greatest, If, In,
                               IsNaN, IsNull, Least, Literal, MathUnary,
                               Negate, Pmod, Round, col, lit)

__all__ = [
    "col", "lit", "expr_sum", "sum", "count", "countDistinct", "min", "max",
    "avg", "mean", "first", "last", "when", "coalesce", "isnull", "isnan",
    "abs", "sqrt", "exp", "log", "log10", "log2", "floor", "ceil", "round",
    "greatest", "least", "pmod", "negate", "signum",
    "length", "upper", "lower", "substring", "concat", "contains",
    "startswith", "endswith", "like",
    "udf",
    "year", "month", "dayofmonth", "dayofweek", "dayofyear", "quarter",
    "hour", "minute", "second", "date_add", "date_sub", "datediff",
    "last_day", "to_date", "to_timestamp",
]


def sum(e):  # noqa: A001 - match pyspark naming
    return _agg.Sum(_to_expr(e))


def count(e):
    if isinstance(e, str) and e == "*":
        return _agg.CountStar()
    return _agg.Count(_to_expr(e))


def countDistinct(e):
    return _agg.CountDistinct(_to_expr(e))


count_distinct = countDistinct


def approx_count_distinct(e, rsd: float = 0.05):
    return _agg.ApproxCountDistinct(_to_expr(e), rsd)


def percentile(e, percentages, frequency=1):
    return _agg.Percentile(_to_expr(e), percentages)


def bloom_filter_agg(e, estimated_items: int = 1_000_000,
                     num_bits: int = None):
    """Builds a Bloom filter over the column (reference:
    GpuBloomFilterAggregate); returns BinaryType. Probe with
    might_contain."""
    return _agg.BloomFilterAggregate(_to_expr(e), estimated_items,
                                     num_bits)


def might_contain(filter_e, value_e):
    """Membership probe against a bloom_filter_agg result (reference:
    GpuBloomFilterMightContain)."""
    from .expr.hash_expr import BloomFilterMightContain
    return BloomFilterMightContain(_to_expr(filter_e), _to_expr(value_e))


def percentile_approx(e, percentages, accuracy: int = 10000):
    return _agg.ApproxPercentile(_to_expr(e), percentages, accuracy)


def median(e):
    return _agg.Median(_to_expr(e))


def min(e):  # noqa: A001
    return _agg.Min(_to_expr(e))


def max(e):  # noqa: A001
    return _agg.Max(_to_expr(e))


def avg(e):
    return _agg.Avg(_to_expr(e))


mean = avg


def stddev(e):
    return _agg.Stddev(_to_expr(e))


stddev_samp = stddev


def variance(e):
    return _agg.Variance(_to_expr(e))


var_samp = variance


def first(e, ignorenulls=False):
    return _agg.First(_to_expr(e), ignorenulls)


def last(e, ignorenulls=False):
    return _agg.Last(_to_expr(e), ignorenulls)


def _to_expr(e) -> Expression:
    if isinstance(e, Expression):
        return e
    if isinstance(e, str):
        return col(e)
    return lit(e)


class _WhenBuilder:
    def __init__(self, branches):
        self._branches = branches

    def when(self, cond, value):
        return _WhenBuilder(self._branches + [(_to_expr(cond),
                                               _to_expr(value))])

    def otherwise(self, value):
        return CaseWhen(self._branches, _to_expr(value))

    # allow using the builder directly as an expression (no ELSE -> null)
    def __getattr__(self, item):
        return getattr(CaseWhen(self._branches, None), item)


def when(cond, value):
    return _WhenBuilder([(_to_expr(cond), _to_expr(value))])


def coalesce(*exprs):
    return Coalesce(*[_to_expr(e) for e in exprs])


def isnull(e):
    return IsNull(_to_expr(e))


def isnan(e):
    return IsNaN(_to_expr(e))


def abs(e):  # noqa: A001
    return Abs(_to_expr(e))


def negate(e):
    return Negate(_to_expr(e))


def _math(name):
    def fn(e):
        return MathUnary(name, _to_expr(e))
    fn.__name__ = name
    return fn


sqrt = _math("sqrt")
exp = _math("exp")
log = _math("log")
log10 = _math("log10")
log2 = _math("log2")
floor = _math("floor")
ceil = _math("ceil")
signum = _math("signum")


def round(e, scale=0):  # noqa: A001
    return Round(_to_expr(e), scale)


def greatest(*es):
    return Greatest(*[_to_expr(e) for e in es])


def least(*es):
    return Least(*[_to_expr(e) for e in es])


def pmod(a, b):
    return Pmod(_to_expr(a), _to_expr(b))


def length(e):
    return _se.Length(_to_expr(e))


def upper(e):
    return _se.Upper(_to_expr(e))


def lower(e):
    return _se.Lower(_to_expr(e))


def substring(e, start, length=None):
    return _se.Substring(_to_expr(e), start, length)


def concat(*es):
    return _se.ConcatStr(*[_to_expr(e) for e in es])


def _to_pattern(p):
    # pattern args are LITERALS, not column references
    return Literal(p) if isinstance(p, (str, bytes)) else _to_expr(p)


def contains(e, pattern):
    return _se.Contains(_to_expr(e), _to_pattern(pattern))


def startswith(e, pattern):
    return _se.StartsWith(_to_expr(e), _to_pattern(pattern))


def endswith(e, pattern):
    return _se.EndsWith(_to_expr(e), _to_pattern(pattern))


def like(e, pattern: str):
    return _se.Like(_to_expr(e), pattern)


def year(e):
    return _de.Year(_to_expr(e))


def month(e):
    return _de.Month(_to_expr(e))


def dayofmonth(e):
    return _de.DayOfMonth(_to_expr(e))


def dayofweek(e):
    return _de.DayOfWeek(_to_expr(e))


def dayofyear(e):
    return _de.DayOfYear(_to_expr(e))


def quarter(e):
    return _de.Quarter(_to_expr(e))


def hour(e):
    return _de.Hour(_to_expr(e))


def minute(e):
    return _de.Minute(_to_expr(e))


def second(e):
    return _de.Second(_to_expr(e))


def date_add(e, days):
    return _de.DateAdd(_to_expr(e), _to_expr(days))


def date_sub(e, days):
    return _de.DateSub(_to_expr(e), _to_expr(days))


def datediff(end, start):
    return _de.DateDiff(_to_expr(end), _to_expr(start))


def last_day(e):
    return _de.LastDay(_to_expr(e))


def to_date(e):
    return _de.ToDate(_to_expr(e))


def to_timestamp(e):
    return _de.ToTimestamp(_to_expr(e))


def trim(e):
    return _se.Trim(_to_expr(e))


def ltrim(e):
    return _se.Trim(_to_expr(e), left=True, right=False)


def rtrim(e):
    return _se.Trim(_to_expr(e), left=False, right=True)


def reverse(e):
    return _se.Reverse(_to_expr(e))


def instr(e, sub):
    return _se.Instr(_to_expr(e), _to_pattern(sub))


def locate(sub, e):
    return _se.Instr(_to_expr(e), _to_pattern(sub))


def bitwise_and(a, b):
    from .expr.expressions import BitwiseAnd
    return BitwiseAnd(_to_expr(a), _to_expr(b))


def bitwise_or(a, b):
    from .expr.expressions import BitwiseOr
    return BitwiseOr(_to_expr(a), _to_expr(b))


def bitwise_xor(a, b):
    from .expr.expressions import BitwiseXor
    return BitwiseXor(_to_expr(a), _to_expr(b))


def bitwise_not(a):
    from .expr.expressions import BitwiseNot
    return BitwiseNot(_to_expr(a))


def shiftleft(a, b):
    from .expr.expressions import ShiftLeft
    return ShiftLeft(_to_expr(a), _to_expr(b))


def shiftright(a, b):
    from .expr.expressions import ShiftRight
    return ShiftRight(_to_expr(a), _to_expr(b))


def pow(a, b):  # noqa: A001
    from .expr.expressions import Pow
    return Pow(_to_expr(a), _to_expr(b))


def atan2(a, b):
    from .expr.expressions import Atan2
    return Atan2(_to_expr(a), _to_expr(b))


def hash(*cols):  # noqa: A001 - pyspark naming
    from .expr.hash_expr import Murmur3Hash
    return Murmur3Hash([_to_expr(c) for c in cols])


def xxhash64(*cols):
    from .expr.hash_expr import XxHash64
    return XxHash64([_to_expr(c) for c in cols])


def hive_hash(*cols):
    from .expr.hash_expr import HiveHash
    return HiveHash([_to_expr(c) for c in cols])


def lpad(e, length, pad=" "):
    return _se.Pad(_to_expr(e), length, pad, left=True)


def rpad(e, length, pad=" "):
    return _se.Pad(_to_expr(e), length, pad, left=False)


def repeat(e, n):
    return _se.Repeat(_to_expr(e), n)


def concat_ws(sep, *es):
    return _se.ConcatWs(sep, *[_to_expr(e) for e in es])


def rlike(e, pattern: str):
    from .expr.regex_exprs import RLike
    return RLike(_to_expr(e), pattern)


def regexp_extract(e, pattern: str, idx: int = 0):
    from .expr.regex_exprs import RegexpExtract
    return RegexpExtract(_to_expr(e), pattern, idx)


def regexp_replace(e, pattern: str, replacement: str):
    from .expr.regex_exprs import RegexpReplace
    return RegexpReplace(_to_expr(e), pattern, replacement)


# ----------------------------------------------------------------------
# collections (arrays / maps / structs) + higher-order functions
# (reference: collectionOperations.scala, complexTypeCreator.scala,
#  higherOrderFunctions.scala rules in GpuOverrides)
# ----------------------------------------------------------------------
def array(*es):
    from .expr import collection_exprs as _ce
    return _ce.CreateArray([_to_expr(e) for e in es])


def struct(*es):
    from .expr import collection_exprs as _ce
    from .expr.expressions import Alias
    names, children = [], []
    for i, e in enumerate(es):
        ex = _to_expr(e)
        if isinstance(ex, Alias):
            names.append(ex.name)
            children.append(ex.child)
        elif isinstance(ex, ColumnRef):
            names.append(ex.name)
            children.append(ex)
        else:
            names.append(f"col{i + 1}")
            children.append(ex)
    return _ce.CreateNamedStruct(names, children)


def named_struct(*pairs):
    from .expr import collection_exprs as _ce
    names = [pairs[i] for i in range(0, len(pairs), 2)]
    children = [_to_expr(pairs[i]) for i in range(1, len(pairs), 2)]
    return _ce.CreateNamedStruct(names, children)


def get_field(e, name: str):
    from .expr import collection_exprs as _ce
    return _ce.GetStructField(_to_expr(e), name)


def size(e):
    from .expr import collection_exprs as _ce
    return _ce.Size(_to_expr(e))


def element_at(e, key):
    from .expr import collection_exprs as _ce
    return _ce.ElementAt(_to_expr(e), key)


def array_contains(e, value):
    from .expr import collection_exprs as _ce
    return _ce.ArrayContains(_to_expr(e), value)


def array_min(e):
    from .expr import collection_exprs as _ce
    return _ce.ArrayMin(_to_expr(e))


def array_max(e):
    from .expr import collection_exprs as _ce
    return _ce.ArrayMax(_to_expr(e))


def sort_array(e, asc: bool = True):
    from .expr import collection_exprs as _ce
    return _ce.SortArray(_to_expr(e), asc)


def map_keys(e):
    from .expr import collection_exprs as _ce
    return _ce.MapKeys(_to_expr(e))


def map_values(e):
    from .expr import collection_exprs as _ce
    return _ce.MapValues(_to_expr(e))


def explode(e):
    from .expr import collection_exprs as _ce
    return _ce.Explode(_to_expr(e))


def explode_outer(e):
    from .expr import collection_exprs as _ce
    g = _ce.Explode(_to_expr(e))
    g.outer = True
    return g


def posexplode(e):
    from .expr import collection_exprs as _ce
    return _ce.PosExplode(_to_expr(e))


def posexplode_outer(e):
    from .expr import collection_exprs as _ce
    g = _ce.PosExplode(_to_expr(e))
    g.outer = True
    return g


def transform(e, fn):
    from .expr import collection_exprs as _ce
    return _ce.ArrayTransform(_to_expr(e), fn)


def filter(e, fn):  # noqa: A001 - pyspark naming
    from .expr import collection_exprs as _ce
    return _ce.ArrayFilter(_to_expr(e), fn)


def exists(e, fn):
    from .expr import collection_exprs as _ce
    return _ce.ArrayExists(_to_expr(e), fn)


def forall(e, fn):
    from .expr import collection_exprs as _ce
    return _ce.ArrayForAll(_to_expr(e), fn)


def aggregate(e, zero, merge):
    from .expr import collection_exprs as _ce
    return _ce.ArrayAggregate(_to_expr(e), zero, merge)


def collect_list(e):
    return _agg.CollectList(_to_expr(e))


def collect_set(e):
    return _agg.CollectSet(_to_expr(e))


def from_utc_timestamp(e, tz: str):
    return _de.FromUTCTimestamp(_to_expr(e), tz)


def to_utc_timestamp(e, tz: str):
    return _de.ToUTCTimestamp(_to_expr(e), tz)


# -- window functions (pyspark-style re-exports) -----------------------
def row_number():
    from . import window as _w
    return _w.row_number()


def rank():
    from . import window as _w
    return _w.rank()


def dense_rank():
    from . import window as _w
    return _w.dense_rank()


def percent_rank():
    from . import window as _w
    return _w.percent_rank()


def cume_dist():
    from . import window as _w
    return _w.cume_dist()


def ntile(n: int):
    from . import window as _w
    return _w.ntile(n)


def lag(e, offset: int = 1, default=None):
    from . import window as _w
    return _w.lag(_to_expr(e), offset, default)


def lead(e, offset: int = 1, default=None):
    from . import window as _w
    return _w.lead(_to_expr(e), offset, default)


def first_value(e):
    from . import window as _w
    return _w.first_value(_to_expr(e))


def last_value(e):
    from . import window as _w
    return _w.last_value(_to_expr(e))


def nth_value(e, n: int):
    from . import window as _w
    return _w.nth_value(_to_expr(e), n)


def grouping_id():
    """Marker for rollup/cube agg lists: the grouping-set id column
    (reference: Spark grouping_id / GpuExpandExec projections)."""
    from .session import GroupingID
    return GroupingID()


# -- JSON / URL --------------------------------------------------------
def get_json_object(e, path: str):
    from .expr.json_exprs import GetJsonObject
    return GetJsonObject(_to_expr(e), path)


def from_json(e, schema):
    from .expr.json_exprs import FromJson
    return FromJson(_to_expr(e), schema)


def to_json(e):
    from .expr.json_exprs import ToJson
    return ToJson(_to_expr(e))


def parse_url(e, part: str, key=None):
    from .expr.json_exprs import ParseUrl
    return ParseUrl(_to_expr(e), part, key)
