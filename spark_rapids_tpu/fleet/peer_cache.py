"""Peer cache server + client: the wire layer of the cluster cache
tier.

Each fleet member runs one of these servers (the block-server shape
from cluster/blocks.py — length-prefixed Arrow-IPC frames via
cluster/rpc.py, a daemon accept loop, one thread per connection) and
serves three verbs:

  fetch {key}     -> hit {meta, _arrow: [tables...]} | miss {}
  inv   {mode, arg} -> ok {}   (apply a peer's invalidation locally)
  warm  {}        -> warm {manifest, calibration}  (cold-join pull)
  ping  {}        -> ok {}

What a member SERVES is its export store: a byte-bounded LRU of the
query results and exchange fragments its local result cache stored,
held BY REFERENCE (the same immutable pyarrow tables — exporting costs
an index entry, not a copy). Serving from a separate store rather than
reading the process-global result cache directly is what lets two
in-process members behave like two processes under test: each member
only ever answers with results IT computed.

Soundness does not depend on this wire: cache keys embed scan-snapshot
fingerprints (io/snapshot.py), and every requester re-stats its plan's
files before computing the key it asks for — a peer still holding an
entry for overwritten files holds it under a key nobody will ever
request again. The `inv` verb (and the broadcast feeding it) is
hygiene: it frees stale bytes promptly and keeps the export index from
serving entries whose files the requester would immediately reject.
On top of the key discipline, a fetched entry's recorded snapshot is
re-stat'd ON THE REQUESTER before acceptance — a stale entry that
slipped past both layers (the chaos harness manufactures this race) is
rejected, counted, and recomputed locally.

Fault injection: every client fetch/broadcast attempt passes the
`peer.fetch` point (runtime/faults.py), and transient failures retry
on bounded backoff (runtime/backoff.py) before the consult degrades —
byte-identically — to local recompute.
"""
from __future__ import annotations

import socket
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..cluster.rpc import RpcClosed, recv_msg, send_msg
from ..runtime import lockdep

__all__ = ["PeerFetchFailed", "ExportStore", "PeerCacheServer",
           "fetch_entry", "send_invalidate", "pull_warm_state"]


class PeerFetchFailed(ConnectionError):
    """A peer-cache fetch failed. Subclasses ConnectionError so
    faults.is_transient_error classifies it without a special case;
    `transient=False` marks structural replies (peer answered 'miss' is
    NOT an error; a protocol violation is, and retrying won't fix it)."""

    def __init__(self, msg: str, addr=None, transient: bool = True):
        super().__init__(msg)
        self.addr = tuple(addr) if addr else None
        self.transient = transient


# ---------------------------------------------------------------------
# export store
# ---------------------------------------------------------------------
class ExportStore:
    """Byte-bounded LRU of (key -> value, meta) a member serves to
    peers. Values are the result cache's own immutable objects
    (pa.Table for the query tier, the fragment record for the fragment
    tier); `meta` carries tier/paths/snapshot so the server can build a
    wire reply and apply path-prefix invalidations without touching the
    value."""

    def __init__(self, max_bytes: int):
        self._lock = lockdep.lock("Fleet.ExportStore._lock")
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._bytes = 0
        self.max_bytes = int(max_bytes)

    def put(self, key, value, nbytes: int, meta: dict) -> None:
        if nbytes > self.max_bytes:
            return
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            while self._entries and \
                    self._bytes + nbytes > self.max_bytes:
                _, (v, nb, m) = self._entries.popitem(last=False)
                self._bytes -= nb
            self._entries[key] = (value, int(nbytes), meta)
            self._bytes += int(nbytes)

    def get(self, key):
        """(value, meta) or None; touches LRU recency."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return None
            self._entries.move_to_end(key)
            return ent[0], ent[2]

    def drop_paths(self, paths) -> int:
        """Drop every entry whose meta paths intersect `paths`."""
        pset = set(paths)
        with self._lock:
            doomed = [k for k, (v, nb, m) in self._entries.items()
                      if pset.intersection(m.get("paths") or ())]
            for k in doomed:
                _, nb, _ = self._entries.pop(k)
                self._bytes -= nb
        return len(doomed)

    def drop_prefix(self, prefix: str) -> int:
        with self._lock:
            doomed = [k for k, (v, nb, m) in self._entries.items()
                      if any(p.startswith(prefix)
                             for p in (m.get("paths") or ()))]
            for k in doomed:
                _, nb, _ = self._entries.pop(k)
                self._bytes -= nb
        return len(doomed)

    def drop_plan_fp(self, pfp) -> int:
        with self._lock:
            doomed = [k for k, (v, nb, m) in self._entries.items()
                      if m.get("plan_fp") == pfp]
            for k in doomed:
                _, nb, _ = self._entries.pop(k)
                self._bytes -= nb
        return len(doomed)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0


# ---------------------------------------------------------------------
# wire encoding of cached values
# ---------------------------------------------------------------------
def _encode(value, meta: dict) -> Tuple[dict, List]:
    """(payload_meta, tables) for one export entry. Query tier ships
    the table; fragment tier ships the non-empty partitions plus a
    presence mask (None partitions reconstruct on the far side)."""
    out = {k: meta[k] for k in ("tier", "paths", "snapshot", "plan_fp")
           if k in meta}
    if meta.get("tier") == "fragment":
        mask = [t is not None for t in value.tables]
        out["mask"] = mask
        out["pstats"] = list(value.pstats)
        return out, [t for t in value.tables if t is not None]
    return out, [value]


def _decode(payload: dict):
    """(tier, value, meta) from a `hit` reply; value is a pa.Table for
    the query tier, (tables, pstats) for the fragment tier."""
    tables = payload.get("_arrow") or []
    meta = {k: payload[k] for k in ("tier", "paths", "snapshot",
                                    "plan_fp") if k in payload}
    tier = meta.get("tier", "query")
    if tier == "fragment":
        it = iter(tables)
        full = [next(it) if present else None
                for present in payload.get("mask", ())]
        return tier, (full, list(payload.get("pstats", ()))), meta
    if not tables:
        raise PeerFetchFailed("hit reply carried no table",
                              transient=False)
    return tier, tables[0], meta


# ---------------------------------------------------------------------
# server
# ---------------------------------------------------------------------
class PeerCacheServer:
    """One member's cache/warm-state server. Instantiable (NOT a
    process singleton like the shuffle block server) because the test
    and chaos harnesses run several members per process; `member` is
    the owning FleetMember — `inv` and `warm` delegate to it."""

    def __init__(self, member, host: str = "0.0.0.0"):
        self.member = member
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, 0))
        self._listener.listen(16)
        self.port = self._listener.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"tpu-fleet-peer-{self.port}")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name="tpu-fleet-peer-conn").start()

    def _serve_conn(self, sock: socket.socket):
        try:
            while True:
                kind, payload = recv_msg(sock)
                if kind == "fetch":
                    self._serve_fetch(sock, payload)
                elif kind == "inv":
                    n = self.member.apply_invalidation(
                        payload.get("mode"), payload.get("arg"))
                    send_msg(sock, "ok", {"dropped": n})
                elif kind == "warm":
                    send_msg(sock, "warm",
                             self.member.warm_state_payload())
                elif kind == "ping":
                    send_msg(sock, "ok", {})
                else:
                    return
        except (RpcClosed, OSError):
            pass
        finally:
            sock.close()

    def _serve_fetch(self, sock, payload):
        ent = self.member.export.get(tuple_key(payload.get("key")))
        if ent is None:
            send_msg(sock, "miss", {})
            return
        value, meta = ent
        out, tables = _encode(value, meta)
        send_msg(sock, "hit", out, tables=tables)


def tuple_key(key):
    """Cache keys are nested tuples; pickle round-trips them intact,
    but normalize defensively so a list-shaped key from a foreign
    client still indexes."""
    if isinstance(key, list):
        return tuple(tuple_key(k) for k in key)
    return key


# ---------------------------------------------------------------------
# client
# ---------------------------------------------------------------------
def _request(addr: Tuple[str, int], kind: str, payload: dict,
             timeout: float):
    """One request/response exchange, faults.peer.fetch instrumented.
    Transient socket failures raise PeerFetchFailed(transient=True)."""
    from ..runtime import faults
    if faults.ACTIVE:
        faults.hit("peer.fetch", op=kind)
    try:
        sock = socket.create_connection(tuple(addr), timeout=timeout)
    except OSError as e:
        raise PeerFetchFailed(f"connect {addr}: {e!r}",
                              addr=addr) from e
    try:
        sock.settimeout(timeout)
        send_msg(sock, kind, payload)
        return recv_msg(sock)
    except (RpcClosed, OSError) as e:
        raise PeerFetchFailed(f"{kind} from {addr}: {e!r}",
                              addr=addr) from e
    finally:
        sock.close()


def _retrying(addr, kind, payload, timeout, retries, backoff_ms,
              seed_extra=0):
    """Bounded-retry wrapper shared by fetch and invalidation sends;
    deterministic jitter seeded per (addr, verb) so concurrent callers
    de-synchronize (the fetch_blocks discipline)."""
    import time as _time

    from ..profiler import tracing
    from ..runtime.backoff import backoff_delays
    from ..runtime.faults import is_transient_error, note_recovery
    seed = (hash((tuple(addr), kind)) ^ seed_extra) & 0xFFFFFFFF
    delays = backoff_delays(retries, backoff_ms, seed=seed)
    attempt = 0
    while True:
        try:
            return _request(addr, kind, payload, timeout)
        except Exception as e:
            # PeerFetchFailed carries its own transience verdict;
            # anything else (an injected peer.fetch fault — FetchFailed,
            # InjectedFault) goes through the engine classifier, so the
            # chaos harness exercises the same retry loop real socket
            # failures do
            if isinstance(e, PeerFetchFailed):
                transient = e.transient
            else:
                transient = is_transient_error(e)
            if not transient or attempt >= retries:
                raise
            d = delays[attempt]
            attempt += 1
            note_recovery("peer_fetch_retries")
            t0 = _time.perf_counter()
            _time.sleep(d)
            tracing.record_wait_span(
                "fleet.peer_backoff", "backoff",
                (_time.perf_counter() - t0) * 1e3, attempt=attempt)


def fetch_entry(addr: Tuple[str, int], key, timeout: float = 5.0,
                retries: int = 2, backoff_ms: float = 20.0):
    """Ask one peer for a cache entry. Returns (tier, value, meta) or
    None on a miss; raises PeerFetchFailed after the bounded retries.
    The span covers the whole attempt — connect, transfer, and any
    injected peer.fetch delay — which is how a slow peer becomes the
    critical path's peer_fetch edge."""
    from ..profiler import tracing
    with tracing.span("fleet.peer_fetch", "peer_fetch",
                      peer=f"{addr[0]}:{addr[1]}"):
        kind, payload = _retrying(addr, "fetch", {"key": key}, timeout,
                                  retries, backoff_ms,
                                  seed_extra=hash(repr(key)))
    if kind == "miss":
        return None
    if kind != "hit":
        raise PeerFetchFailed(f"peer {addr} answered {kind!r}",
                              addr=addr, transient=False)
    return _decode(payload)


def send_invalidate(addr: Tuple[str, int], mode: str, arg,
                    timeout: float = 5.0, retries: int = 1,
                    backoff_ms: float = 20.0) -> bool:
    """Deliver one invalidation to one peer; True on ack. Best-effort
    by contract — the caller counts failures and moves on (the
    snapshot-key discipline keeps a missed delivery sound)."""
    try:
        kind, _ = _retrying(addr, "inv", {"mode": mode, "arg": arg},
                            timeout, retries, backoff_ms)
        return kind == "ok"
    except Exception:
        # injected faults included: an undelivered broadcast is counted
        # by the caller and covered by the snapshot-key discipline
        return False


def pull_warm_state(addr: Tuple[str, int],
                    timeout: float = 30.0) -> Optional[Dict]:
    """Fetch a donor peer's warm-state payload (warm-pack manifest +
    calibration table). None on any failure — cold-join warm-up is
    advisory, exactly like a missing warm pack on disk."""
    try:
        kind, payload = _retrying(addr, "warm", {}, timeout,
                                  retries=1, backoff_ms=50.0)
    except Exception:
        return None
    return payload if kind == "warm" else None
