"""File-backed peer directory + rendezvous hashing.

Membership is a directory of JSON files (`sql.fleet.directory`), one
per live member, written atomically (tmp + rename) at join and removed
at leave. Every member — and the bench/test harness — discovers the
fleet by listing that directory: no coordinator, no gossip protocol,
and a crashed process leaves at worst one stale file that liveness
probing (pid check on this host) or a failed fetch skims off. This is
the same posture as the shuffle block store: the data plane is
peer-to-peer, the control plane is O(metadata).

Placement is rendezvous (highest-random-weight) hashing over
`(peer_id, key)` digests: every member independently computes the same
preference ORDER for a key, and a membership change reassigns only the
keys whose top choice was the departed/joined peer — the property that
keeps fingerprint-sticky routing (and the peer-cache owner guess)
stable while processes churn.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import List, Optional

__all__ = ["PeerInfo", "PeerDirectory", "rendezvous_order"]


class PeerInfo:
    """One member's registration record."""

    __slots__ = ("peer_id", "host", "port", "gw_host", "gw_port", "pid",
                 "started")

    def __init__(self, peer_id: str, host: str, port: int,
                 gw_host: Optional[str] = None,
                 gw_port: Optional[int] = None,
                 pid: Optional[int] = None,
                 started: Optional[float] = None):
        self.peer_id = peer_id
        self.host = host
        self.port = int(port)
        self.gw_host = gw_host
        self.gw_port = gw_port
        self.pid = pid if pid is not None else os.getpid()
        self.started = float(started if started is not None
                             else time.time())

    @property
    def addr(self):
        """The peer-cache server address."""
        return (self.host, self.port)

    @property
    def gateway(self):
        """The JSON-lines gateway address (None for a headless member
        that serves only the cache tier)."""
        if self.gw_host is None or self.gw_port is None:
            return None
        return (self.gw_host, int(self.gw_port))

    def to_dict(self) -> dict:
        return {"peer_id": self.peer_id, "host": self.host,
                "port": self.port, "gw_host": self.gw_host,
                "gw_port": self.gw_port, "pid": self.pid,
                "started": self.started}

    @classmethod
    def from_dict(cls, d: dict) -> "PeerInfo":
        return cls(d["peer_id"], d["host"], d["port"],
                   gw_host=d.get("gw_host"), gw_port=d.get("gw_port"),
                   pid=d.get("pid"), started=d.get("started"))

    def __repr__(self):
        return (f"PeerInfo({self.peer_id!r}, {self.host}:{self.port}, "
                f"gw={self.gateway}, pid={self.pid})")


def _alive(info: PeerInfo) -> bool:
    """Best-effort liveness: the registering pid still exists on this
    host. A pid we cannot signal (another uid, or a genuinely remote
    host whose registration carries a foreign pid space) counts as
    alive — a wrong 'alive' costs one failed fetch, a wrong 'dead'
    silently shrinks the fleet."""
    if info.pid is None:
        return True
    try:
        os.kill(info.pid, 0)
        return True
    except ProcessLookupError:
        return False
    except OSError:
        return True


class PeerDirectory:
    """The membership view over one registration directory."""

    def __init__(self, root: str):
        self.root = root

    def _path(self, peer_id: str) -> str:
        # peer ids are host:port strings; ':' is path-safe on posix but
        # keep the filename tame anyway
        return os.path.join(self.root,
                            peer_id.replace(":", "_") + ".json")

    def register(self, info: PeerInfo) -> str:
        os.makedirs(self.root, exist_ok=True)
        path = self._path(info.peer_id)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(info.to_dict(), f)
        os.replace(tmp, path)
        return path

    def deregister(self, peer_id: str) -> None:
        try:
            os.unlink(self._path(peer_id))
        except OSError:
            pass

    def peers(self, live_only: bool = True) -> List[PeerInfo]:
        """Every registered member, registration-file order-independent
        (sorted by peer_id for determinism). Corrupt/half-written files
        are skipped — registration is atomic, so these are crash
        leftovers, not protocol states."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.root, name),
                          encoding="utf-8") as f:
                    info = PeerInfo.from_dict(json.load(f))
            except Exception:
                continue
            if live_only and not _alive(info):
                continue
            out.append(info)
        out.sort(key=lambda p: p.peer_id)
        return out

    def oldest_peer(self, exclude: str = None) -> Optional[PeerInfo]:
        """The designated warm-state donor: the longest-lived live
        member (it has seen the most queries — the warmest caches and
        calibration tables in the fleet)."""
        cands = [p for p in self.peers() if p.peer_id != exclude]
        if not cands:
            return None
        return min(cands, key=lambda p: (p.started, p.peer_id))


def _weight(peer_id: str, key_repr: str) -> int:
    h = hashlib.blake2b(f"{peer_id}|{key_repr}".encode("utf-8"),
                        digest_size=8)
    return int.from_bytes(h.digest(), "big")


def rendezvous_order(key, peer_ids) -> List[str]:
    """Peer ids sorted by highest-random-weight for `key` (any
    repr-stable value — plan fingerprints are tuples of primitives).
    Index 0 is the key's owner; later entries are the stable fallback
    order a router spills along and a cache consult probes."""
    kr = repr(key)
    return sorted(peer_ids, key=lambda pid: _weight(pid, kr),
                  reverse=True)
