"""Multi-host serving fabric: N service processes as one logical
service.

Three coupled pieces (docs/fleet.md has the full protocol):

- a cluster cache tier (`peer_cache.py`): on a local result-cache
  miss, consult the rendezvous-ordered owning peers for the same
  snapshot-embedding key, fetch the Arrow bytes, and re-stat the
  shipped snapshot before accepting — with invalidation broadcast as
  hygiene and the snapshot-key discipline as the soundness floor;
- fingerprint-sticky routing (`router.py`, the `route` gateway verb):
  repeat queries land on the process whose caches are warm for them,
  with fleet-wide per-tenant admission;
- warm-state publication (`member.py`): a joining process pulls the
  warm-pack manifest and calibration table from the longest-lived
  peer before taking traffic.

Joining is one call — `serve()` does it when `sql.fleet.directory` is
set — and everything degrades to single-process behavior when the
fleet is unreachable: a failed fetch is a local recompute, a lost
broadcast is caught by snapshot re-stat, a missing donor is a cold
start.
"""
from __future__ import annotations

from . import context
from .directory import PeerDirectory, PeerInfo, rendezvous_order
from .member import FleetMember, install_dispatcher, join
from .peer_cache import ExportStore, PeerCacheServer, PeerFetchFailed
from .router import RouteRejected, Router

__all__ = [
    "context", "PeerDirectory", "PeerInfo", "rendezvous_order",
    "FleetMember", "install_dispatcher", "join",
    "ExportStore", "PeerCacheServer", "PeerFetchFailed",
    "RouteRejected", "Router", "reset",
]


def reset() -> None:
    """Test/module-boundary teardown: leave + detach every member this
    process knows about (the default plus any scoped ones are owned by
    their creators; this handles the common single-default case) and
    uninstall the result-cache dispatcher."""
    m = context.default_member()
    if m is not None:
        try:
            m.leave()
        except Exception:
            pass
    context.reset()
    from ..runtime import result_cache
    result_cache.set_peer_tier(None)
