"""Bench/test fleet worker: one real service process in the fabric.

`bench.py --concurrent --fleet N` launches N of these via
`python -m spark_rapids_tpu.fleet.worker`; each builds a session,
registers the shared parquet views, starts the gateway (which joins
the fleet named by --fleet-dir), prints one READY line with its
addresses, and serves until stdin closes. Keeping the entry in-tree
(rather than inline -c scripts in bench.py) makes the worker
importable from tests and keeps the bench honest: workers are real
interpreters with cold program caches, not forked copies of a warm
parent.
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="spark_rapids_tpu.fleet.worker")
    ap.add_argument("--fleet-dir", required=True,
                    help="peer directory root (shared across workers)")
    ap.add_argument("--view", action="append", default=[],
                    metavar="NAME=PARQUET_PATH",
                    help="register a parquet path as a temp view")
    ap.add_argument("--conf", action="append", default=[],
                    metavar="KEY=VALUE", help="extra session conf")
    args = ap.parse_args(argv)

    from .. import TpuSession
    from ..config import FLEET_DIRECTORY, RESULT_CACHE_ENABLED
    s = TpuSession()
    s.set_conf(FLEET_DIRECTORY.key, args.fleet_dir)
    s.set_conf(RESULT_CACHE_ENABLED.key, True)
    for kv in args.conf:
        k, _, v = kv.partition("=")
        s.set_conf(k, v)
    for kv in args.view:
        name, _, path = kv.partition("=")
        s.read.parquet(path).create_or_replace_temp_view(name)

    srv = s.serve()
    member = getattr(s, "_fleet_member", None)
    ready = {"host": srv.host, "port": srv.port,
             "peer_id": member.peer_id if member else None,
             "warm": getattr(member, "warm_summary", None)}
    sys.stdout.write("READY " + json.dumps(ready) + "\n")
    sys.stdout.flush()

    # serve until the parent closes our stdin (bench teardown) — no
    # signal handling needed, and an orphaned worker exits on its own
    for _line in sys.stdin:
        if _line.strip() == "stop":
            break
    try:
        if member is not None:
            member.leave()
        srv.close()
        s.stop()
    except Exception:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
