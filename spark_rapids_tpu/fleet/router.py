"""Fingerprint-sticky front-end routing with fleet-wide admission.

Any member's gateway can answer the `route` verb: given a query's plan
fingerprint, every member independently computes the same rendezvous
order over the live gateway-bearing peers, so "which process is warm
for this query" needs no shared state — the answer IS the hash. Index
0 is the sticky choice (its result cache, program cache, and
calibration tables have seen this fingerprint before, or will own it
from now on); the router spills down the order only when the sticky
peer is saturated, and the spill target is itself stable, so even the
overflow lands warm.

Admission is the fleet analog of the per-pool DRR caps: a per-tenant
in-flight ceiling across ALL peers (one tenant cannot occupy every
backend) and a per-peer ceiling that converts "sticky" into "sticky
until saturated". Both are lease-based: `route` grants a lease, the
client reports `route_done`, and leases expire on a lazy TTL so a
crashed client cannot permanently consume a tenant's budget.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

from ..runtime import lockdep
from .directory import rendezvous_order

__all__ = ["RouteRejected", "Router"]

#: leases older than this are presumed abandoned (client crashed
#: between route and route_done) and reclaimed lazily on the next route
_LEASE_TTL_SECS = 600.0


class RouteRejected(Exception):
    """Fleet-wide admission refused the query (tenant over its
    in-flight cap, or no live gateway peers)."""

    def __init__(self, reason: str, tenant: str = None):
        super().__init__(reason)
        self.reason = reason
        self.tenant = tenant


class Router:
    """Routing + admission state for one gateway process."""

    def __init__(self, member, conf=None):
        self.member = member
        self._lock = lockdep.lock("Fleet.Router._lock")
        self._leases: Dict[str, tuple] = {}   # id -> (peer, tenant, ts)
        self._peer_inflight: Dict[str, int] = {}
        self._tenant_inflight: Dict[str, int] = {}
        self._seq = 0
        self._stats = {"fleet_route_sticky": 0, "fleet_route_spill": 0,
                       "fleet_route_rejected": 0}
        conf = conf if conf is not None else member.conf
        from ..config import (FLEET_PEER_MAX_INFLIGHT,
                              FLEET_TENANT_MAX_INFLIGHT)
        self.tenant_cap = int(conf.get(FLEET_TENANT_MAX_INFLIGHT))
        self.peer_cap = int(conf.get(FLEET_PEER_MAX_INFLIGHT))

    # -- lease bookkeeping (all under _lock) ---------------------------
    def _expire_locked(self, now: float) -> None:
        doomed = [lid for lid, (_, _, ts) in self._leases.items()
                  if now - ts > _LEASE_TTL_SECS]
        for lid in doomed:
            self._release_locked(lid)

    def _release_locked(self, lease_id: str) -> bool:
        ent = self._leases.pop(lease_id, None)
        if ent is None:
            return False
        peer, tenant, _ = ent
        for table, k in ((self._peer_inflight, peer),
                         (self._tenant_inflight, tenant)):
            n = table.get(k, 0) - 1
            if n > 0:
                table[k] = n
            else:
                table.pop(k, None)
        return True

    # -- the route decision --------------------------------------------
    def route(self, plan_fp, tenant: str = "default") -> dict:
        """Pick the serving peer for `plan_fp`, grant a lease. Returns
        {peer_id, host, port, sticky, lease}; host/port are the chosen
        peer's GATEWAY. Raises RouteRejected on admission failure."""
        from ..profiler import telemetry
        peers = [p for p in self.member.peers(include_self=True)
                 if p.gateway is not None]
        if not peers:
            self._bump("fleet_route_rejected")
            telemetry.counter("fleet_route_rejected").inc()
            raise RouteRejected("no live gateway peers", tenant)
        by_id = {p.peer_id: p for p in peers}
        order = rendezvous_order(plan_fp, list(by_id))
        now = time.monotonic()
        with self._lock:
            self._expire_locked(now)
            if self.tenant_cap > 0 and \
                    self._tenant_inflight.get(tenant, 0) >= \
                    self.tenant_cap:
                self._stats["fleet_route_rejected"] += 1
                telemetry.counter("fleet_route_rejected").inc()
                raise RouteRejected("tenant over in-flight cap", tenant)
            chosen, sticky = order[0], True
            if self.peer_cap > 0:
                for pid in order:
                    if self._peer_inflight.get(pid, 0) < self.peer_cap:
                        chosen, sticky = pid, (pid == order[0])
                        break
                # every peer saturated: stay sticky — queueing on the
                # warm peer beats a cold compile on a busy one
            self._seq += 1
            lease = f"{self.member.peer_id}#{self._seq}"
            self._leases[lease] = (chosen, tenant, now)
            self._peer_inflight[chosen] = \
                self._peer_inflight.get(chosen, 0) + 1
            self._tenant_inflight[tenant] = \
                self._tenant_inflight.get(tenant, 0) + 1
            self._stats["fleet_route_sticky" if sticky
                        else "fleet_route_spill"] += 1
        telemetry.counter("fleet_route_sticky" if sticky
                          else "fleet_route_spill").inc()
        gw = by_id[chosen].gateway
        return {"peer_id": chosen, "host": gw[0], "port": gw[1],
                "sticky": sticky, "lease": lease}

    def done(self, lease_id: str) -> bool:
        """Client-side completion: release the lease's admission slots."""
        with self._lock:
            return self._release_locked(lease_id)

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["fleet_route_leases"] = len(self._leases)
            out["fleet_route_tenants"] = len(self._tenant_inflight)
        return out

    def _bump(self, key: str) -> None:
        with self._lock:
            self._stats[key] += 1
