"""Fleet member resolution: which FleetMember owns the work on this
thread.

A real deployment has exactly ONE member per process (`fleet.join`
installs it as the process default), but the test suite and the chaos
harness run two or three members inside one process to exercise the
wire paths without spawning interpreters. The thread-local override is
what makes that honest: work scoped to member B consults B's peer view
and publishes into B's export store even though A lives in the same
process.

This module is intentionally stdlib-only — `session.py` and
`runtime/result_cache.py` import it on hot paths, and it must never
drag the fleet wire machinery (sockets, pyarrow) into processes that
never join a fleet. Resolution is two attribute reads and out.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

__all__ = ["active_member", "default_member", "set_default", "scoped",
           "reset"]

_TLS = threading.local()
_DEFAULT = None          # the process's joined member (fleet.join)
_LOCK = threading.Lock()


def set_default(member) -> None:
    """Install/clear the process-default member (one per process in a
    real deployment; `None` detaches)."""
    global _DEFAULT
    with _LOCK:
        _DEFAULT = member


def default_member():
    return _DEFAULT


def active_member():
    """The member owning work on THIS thread: the scoped override when
    one is installed, else the process default."""
    m = getattr(_TLS, "member", None)
    return m if m is not None else _DEFAULT


@contextmanager
def scoped(member):
    """Pin `member` as this thread's active member for the duration —
    the bridge onto query-manager worker threads (DataFrame.submit
    captures the submitter's member and re-enters this scope inside the
    admitted body) and the multi-member test/chaos harness."""
    prev = getattr(_TLS, "member", None)
    _TLS.member = member
    try:
        yield member
    finally:
        _TLS.member = prev


def reset() -> None:
    """Drop the process default and THIS thread's override (module-
    boundary teardown in tests/conftest.py)."""
    global _DEFAULT
    with _LOCK:
        _DEFAULT = None
    _TLS.member = None
