"""FleetMember: one process's seat in the serving fabric.

A member owns (a) the export store + peer cache server it answers the
fleet from, (b) its registration in the file-backed peer directory,
and (c) the client side of the tier: consult on local result-cache
miss, publish on local store, invalidation broadcast on local drop,
and the cold-join warm-state pull. `fleet.join(session)` builds one,
installs it as the process default (fleet/context.py) and wires the
dispatcher into runtime/result_cache.py; `member.leave()` undoes all
of it.

Everything here is advisory with respect to query results: a dead
peer, a lost broadcast, an injected peer.fetch fault, or a stale entry
all degrade to exactly what a fleet of one does — local recompute over
re-stat'd snapshots, byte-identical.
"""
from __future__ import annotations

import logging
import time
from typing import Optional

from ..runtime import lockdep
from . import context as fleet_context
from .directory import PeerDirectory, PeerInfo, rendezvous_order
from .peer_cache import (ExportStore, PeerCacheServer, PeerFetchFailed,
                         fetch_entry, pull_warm_state, send_invalidate)

__all__ = ["FleetMember", "join", "install_dispatcher"]

log = logging.getLogger(__name__)

#: live-peer listing cache TTL: consult fires per cache miss, and the
#: directory is a filesystem listing — 500ms staleness costs at most
#: one failed fetch against a just-departed peer
_PEERS_TTL_SECS = 0.5

_STAT_KEYS = (
    "fleet_peer_hits", "fleet_peer_misses", "fleet_peer_fetch_failures",
    "fleet_peer_stale_rejected", "fleet_publishes",
    "fleet_inv_broadcasts", "fleet_inv_broadcast_failures",
    "fleet_inv_applied", "fleet_warm_pulls", "fleet_warm_served",
)


def _telemetry():
    from ..profiler import telemetry
    return telemetry


class FleetMember:
    """One member: export store + server + peer-facing client logic."""

    def __init__(self, session, conf, directory_root: str,
                 gateway_addr=None, advertise_host: str = None,
                 warm_pull: bool = None):
        from ..config import (FLEET_ADVERTISE_HOST, FLEET_CONSULT_FANOUT,
                              FLEET_EXPORT_MAX_BYTES,
                              FLEET_FETCH_BACKOFF_MS,
                              FLEET_FETCH_RETRIES,
                              FLEET_FETCH_TIMEOUT_SECS,
                              FLEET_INVALIDATE_RETRIES, FLEET_WARM_PULL)
        self.session = session
        self.conf = conf
        self.directory = PeerDirectory(directory_root)
        self._slock = lockdep.lock("Fleet.Member._slock")
        self.stats = {k: 0 for k in _STAT_KEYS}
        self._fanout = max(1, int(conf.get(FLEET_CONSULT_FANOUT)))
        self._timeout = float(conf.get(FLEET_FETCH_TIMEOUT_SECS))
        self._retries = int(conf.get(FLEET_FETCH_RETRIES))
        self._backoff_ms = float(conf.get(FLEET_FETCH_BACKOFF_MS))
        self._inv_retries = int(conf.get(FLEET_INVALIDATE_RETRIES))
        self._warm_pull = (bool(conf.get(FLEET_WARM_PULL))
                           if warm_pull is None else bool(warm_pull))
        self.export = ExportStore(int(conf.get(FLEET_EXPORT_MAX_BYTES)))
        self.server = PeerCacheServer(self)
        host = advertise_host or str(
            conf.get(FLEET_ADVERTISE_HOST) or "127.0.0.1")
        gw_host, gw_port = (gateway_addr or (None, None))
        self.info = PeerInfo(f"{host}:{self.server.port}", host,
                             self.server.port, gw_host=gw_host,
                             gw_port=gw_port)
        self.peer_id = self.info.peer_id
        self.warm_summary = None
        self._peers_cache = (0.0, [])
        self._closed = False
        self.directory.register(self.info)

    # -- membership -----------------------------------------------------
    def peers(self, include_self: bool = False):
        """Live peers, briefly cached (the consult path calls this per
        local miss)."""
        now = time.monotonic()
        ts, cached = self._peers_cache
        if now - ts > _PEERS_TTL_SECS:
            cached = self.directory.peers()
            self._peers_cache = (now, cached)
        if include_self:
            return list(cached)
        return [p for p in cached if p.peer_id != self.peer_id]

    def refresh_peers(self) -> None:
        self._peers_cache = (0.0, [])

    def leave(self) -> None:
        """Deregister, stop serving, detach from the process default.
        Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.directory.deregister(self.peer_id)
        self.server.close()
        self.export.clear()
        if fleet_context.default_member() is self:
            fleet_context.set_default(None)

    # -- cache tier: consult / publish ---------------------------------
    def consult(self, key, paths=()):
        """Peer-tier lookup after a local result-cache miss: probe the
        key's rendezvous-ordered owners (fanout-bounded). Returns
        (tier, value, meta) or None; never raises — every failure mode
        is a miss."""
        peers = self.peers()
        if not peers:
            return None
        by_id = {p.peer_id: p for p in peers}
        order = rendezvous_order(key, list(by_id))
        t = _telemetry()
        t0 = time.perf_counter()
        for pid in order[:self._fanout]:
            peer = by_id[pid]
            try:
                got = fetch_entry(peer.addr, key,
                                  timeout=self._timeout,
                                  retries=self._retries,
                                  backoff_ms=self._backoff_ms)
            except Exception as e:
                from ..service.query_manager import QueryCancelled
                if isinstance(e, QueryCancelled):
                    # a cancelled/timed-out query must die, not probe
                    # the next peer
                    raise
                # socket failure, protocol violation, or an injected
                # peer.fetch fault that exhausted its retries: all
                # degrade identically — this peer is a miss
                self._bump("fleet_peer_fetch_failures")
                t.counter("fleet_peer_fetch_failures").inc()
                continue
            if got is None:
                continue
            tier, value, meta = got
            if not self._snapshot_current(meta.get("snapshot")):
                # the stale-invalidation race: the owner missed (or has
                # not yet applied) an invalidation for files that
                # changed under it — reject and recompute locally
                self._bump("fleet_peer_stale_rejected")
                continue
            self._bump("fleet_peer_hits")
            t.counter("fleet_peer_hits").inc()
            t.histogram("fleet_peer_fetch_ms").observe(
                (time.perf_counter() - t0) * 1e3)
            return tier, value, meta
        self._bump("fleet_peer_misses")
        t.counter("fleet_peer_misses").inc()
        return None

    @staticmethod
    def _snapshot_current(snap) -> bool:
        """Requester-side re-stat of the snapshot the entry was
        published under. `None` (owner skipped snapshotting a huge
        path set) defers to the key-embedded snapshot discipline."""
        if not snap:
            return True
        from ..io.snapshot import snapshot_current
        try:
            return snapshot_current(tuple(
                (p, mt, sz) for p, mt, sz in snap))
        except Exception:
            return False

    def publish(self, key, value, nbytes: int, tier: str, paths,
                plan_fp=None) -> None:
        """Export a locally stored cache entry so peers can fetch it.
        By reference — no copy; the snapshot recorded here is what a
        fetching peer re-stats before accepting the bytes."""
        from ..io.snapshot import scan_snapshot
        paths = tuple(paths or ())
        snap = scan_snapshot(paths) if 0 < len(paths) <= 64 else None
        self.export.put(key, value, int(nbytes),
                        {"tier": tier, "paths": paths,
                         "snapshot": snap, "plan_fp": plan_fp})
        self._bump("fleet_publishes")

    # -- invalidation ---------------------------------------------------
    def broadcast_invalidate(self, mode: str, arg) -> int:
        """Gossip one invalidation to every live peer (best-effort,
        bounded retry per peer). Also applies it to our OWN export
        store — an entry we just invalidated locally must not keep
        being served to the fleet. Returns peers acked."""
        self._drop_export(mode, arg)
        acked = 0
        t = _telemetry()
        for peer in self.peers():
            ok = send_invalidate(peer.addr, mode, arg,
                                 timeout=self._timeout,
                                 retries=self._inv_retries,
                                 backoff_ms=self._backoff_ms)
            if ok:
                acked += 1
            else:
                self._bump("fleet_inv_broadcast_failures")
                t.counter("fleet_inv_broadcast_failures").inc()
        self._bump("fleet_inv_broadcasts")
        t.counter("fleet_inv_broadcasts").inc()
        return acked

    def apply_invalidation(self, mode: str, arg) -> int:
        """Server side of `inv`: drop matching LOCAL result-cache
        entries (propagate=False — the origin already told everyone)
        and matching export entries."""
        from ..runtime import result_cache
        n = self._drop_export(mode, arg)
        if mode == "prefix":
            n += result_cache.invalidate_prefix(str(arg),
                                                propagate=False)
        elif mode == "paths":
            n += result_cache.invalidate_paths(list(arg or ()),
                                               propagate=False)
        elif mode == "plan_fp":
            n += result_cache.invalidate_plan_fp(arg)
        self._bump("fleet_inv_applied")
        _telemetry().counter("fleet_inv_applied").inc()
        return n

    def _drop_export(self, mode: str, arg) -> int:
        if mode == "prefix":
            return self.export.drop_prefix(str(arg))
        if mode == "paths":
            return self.export.drop_paths(arg or ())
        if mode == "plan_fp":
            return self.export.drop_plan_fp(
                _normalize_fp(arg))
        return 0

    # -- warm-state publication ----------------------------------------
    def warm_state_payload(self) -> dict:
        """What a joining peer pulls from us: the in-memory warm-pack
        manifest (recorded SQL + stable observed program specs, host-
        fingerprint-gated on the RECEIVING side) and the calibration
        table."""
        from ..plan.stats import export_calibration
        from ..runtime import warm_pack
        self._bump("fleet_warm_served")
        return {"manifest": warm_pack.build_manifest(self.conf),
                "calibration": export_calibration()}

    def pull_warm_state(self) -> dict:
        """Cold-join warm-up: pull from the designated donor (the
        longest-lived live peer) and apply. Advisory — any failure
        returns a skipped summary and the member serves cold."""
        summary = {"status": "skipped"}
        if not self._warm_pull:
            self.warm_summary = summary
            return summary
        donor = self.directory.oldest_peer(exclude=self.peer_id)
        if donor is None:
            self.warm_summary = summary
            return summary
        payload = pull_warm_state(donor.addr, timeout=self._timeout * 6)
        if not payload:
            self.warm_summary = summary
            return summary
        from ..plan.stats import import_calibration
        from ..runtime import warm_pack
        imported = 0
        try:
            imported = import_calibration(payload.get("calibration"))
        except Exception:
            log.warning("fleet: calibration import from %s failed",
                        donor.peer_id, exc_info=True)
        summary = {"status": "ok", "donor": donor.peer_id,
                   "calibration_imported": imported}
        manifest = payload.get("manifest")
        if manifest:
            summary["preload"] = warm_pack.preload_manifest(
                self.session, manifest)
        self._bump("fleet_warm_pulls")
        self.warm_summary = summary
        return summary

    # -- introspection --------------------------------------------------
    def _bump(self, key: str, n: int = 1) -> None:
        with self._slock:
            self.stats[key] += n

    def snapshot(self) -> dict:
        with self._slock:
            out = dict(self.stats)
        out.update({f"fleet_export_{k}": v
                    for k, v in self.export.stats().items()})
        out["fleet_peer_id"] = self.peer_id
        out["fleet_peers_live"] = len(self.peers(include_self=True))
        return out


def _normalize_fp(fp):
    """Plan fingerprints are nested tuples; they ride the wire through
    pickle intact, but normalize list-shaped ones defensively."""
    if isinstance(fp, list):
        return tuple(_normalize_fp(x) for x in fp)
    return fp


# ---------------------------------------------------------------------
# the result-cache dispatcher + join()
# ---------------------------------------------------------------------
class _Dispatcher:
    """What runtime/result_cache.py holds: resolves the thread's active
    member per call, so one process can host several members (tests)
    while the common case stays two attribute reads + None check."""

    @staticmethod
    def consult(key, paths=()):
        m = fleet_context.active_member()
        return m.consult(key, paths) if m is not None else None

    @staticmethod
    def publish(key, value, nbytes, tier, paths, plan_fp=None):
        m = fleet_context.active_member()
        if m is not None:
            m.publish(key, value, nbytes, tier, paths, plan_fp=plan_fp)

    @staticmethod
    def broadcast(mode, arg):
        m = fleet_context.active_member()
        if m is not None:
            m.broadcast_invalidate(mode, arg)


_DISPATCHER = _Dispatcher()


def install_dispatcher() -> None:
    """Idempotently wire the fleet tier into the result cache and
    register the pull gauges. Safe to call with no member joined —
    every dispatch no-ops on a None active member."""
    from ..profiler import telemetry
    from ..runtime import result_cache
    result_cache.set_peer_tier(_DISPATCHER)

    def _fleet_gauges():
        m = fleet_context.default_member()
        if m is None:
            return {}
        return {k: v for k, v in m.snapshot().items()
                if isinstance(v, (int, float))}

    telemetry.register_gauge_fn("fleet", _fleet_gauges)


def join(session, gateway_addr=None) -> Optional[FleetMember]:
    """Join the fleet named by sql.fleet.directory: start the peer
    cache server, register, install as process default, and pull warm
    state from the designated donor. Returns None (and changes
    nothing) when no fleet directory is configured."""
    from ..config import FLEET_DIRECTORY
    conf = session.conf
    root = str(conf.get(FLEET_DIRECTORY) or "").strip()
    if not root:
        return None
    # idempotent per process: serve() after an explicit join (or a
    # second serve()) must not register a phantom second member. A
    # late-arriving gateway address upgrades the existing registration.
    existing = fleet_context.default_member()
    if existing is not None and not existing._closed:
        if gateway_addr is not None and existing.info.gateway is None:
            existing.info.gw_host, existing.info.gw_port = gateway_addr
            existing.directory.register(existing.info)
            existing.refresh_peers()
        return existing
    member = FleetMember(session, conf, root, gateway_addr=gateway_addr)
    install_dispatcher()
    fleet_context.set_default(member)
    try:
        member.pull_warm_state()
    except Exception:
        log.warning("fleet: warm-state pull failed; serving cold",
                    exc_info=True)
    return member
