"""Shared interprocedural machinery for the static auditors.

Three passes need the same whole-tree model: the concurrency auditor
(analysis/concurrency.py, deadlock shapes), the resource-lifetime
auditor (analysis/lifetime.py, acquire/release shapes), and the
data-race auditor (analysis/races.py, Eraser-style locksets). This
module owns the parts they share:

- the module walk (`build_model`): every function/method in the tree
  becomes a `FuncInfo` with its synchronization events, call edges and
  per-``class.attr`` access sites;
- resource inventory: threading.Lock/RLock/Condition/Semaphore
  creations (class-keyed: ``ShuffleExchangeExec._lock``),
  ``lockdep.lock("K")`` factories, TpuSemaphore permits, bounded pools
  (keyed by ``thread_name_prefix``), queues;
- call resolution (`Model.resolve_ref`): lexical scope chain for
  nested defs, module-local and imported engine functions,
  self-methods, and the unique-method heuristic with the
  ``_NO_RESOLVE`` polymorphic blocklist;
- pool-worker / thread-target resolution (``Model.pools[*].workers``,
  ``Model.thread_targets``) — the thread-context roots every pass
  derives worker reachability from;
- memoized interprocedural event summaries (`Model.summarize`) with
  held-sets composed across resolvable calls;
- the shared allow-marker filter (``# tpulint: allow[rule] reason``)
  and per-file marker cache (`filter_markers`).

Static analysis of Python is necessarily approximate. Calls are
propagated only when unambiguous (self-methods, module-local and
imported engine functions, uniquely-named methods); polymorphic names
(``execute_partition`` et al) are skipped — the runtime witnesses
(lockdep/ledger/racedep) cover the dynamic side.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .lint_rules import MARKER_RE

__all__ = ["PERMIT", "Event", "PoolInfo", "FuncInfo", "Model",
           "build_model", "filter_markers"]

PERMIT = "TpuSemaphore.permit"

_SUMMARY_CAP = 400

# attribute-call names never resolved by the unique-method heuristic:
# polymorphic across the operator tree or too generic to trust
_NO_RESOLVE = {
    "execute_partition", "execute_all", "num_partitions", "describe",
    "release", "close", "get", "set", "add", "put", "append", "items",
    "values", "keys", "pop", "update", "start", "join", "cancel",
    "check", "read", "write", "send", "recv", "result", "submit",
    "wait", "acquire", "done", "copy", "extend", "clear", "sort",
    "split", "strip", "format", "encode", "decode", "timer", "info",
    "debug", "warning", "error", "flush", "seek", "tell", "next",
    # names shared with stdlib/pyarrow objects: gc.collect(),
    # Event.is_set(), schema.to_arrow(), table.filter(), ...
    "collect", "is_set", "to_arrow", "exists", "filter", "count",
    "index", "insert", "remove", "discard", "shutdown", "status",
    "tolist", "item", "reshape", "astype", "mkdir", "unlink",
}

_LOCKY = ("lock", "cond", "mutex")

#: container-mutating method names: `self.attr.append(x)` mutates the
#: shared container (GIL-atomic per call, but shared state) and
#: `self.attr[k].append(x)` is a read-modify-write through the slot
_MUTATORS = {"append", "extend", "add", "update", "pop", "popitem",
             "remove", "discard", "insert", "clear", "move_to_end"}

#: assignment sources that make an attr write a queue/Future hand-off
#: (the object is itself the synchronization point) rather than raw
#: shared-state mutation
_HANDOFF_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                  "deque", "Event", "Barrier", "Future",
                  "ThreadPoolExecutor"}

#: sink method names through which `self` escapes during __init__
#: (publish-before-init detection: registries, queues, pools)
_PUBLISH_SINKS = {"append", "add", "put", "register", "submit"}


def _ctor_name(call: ast.Call) -> Optional[str]:
    """Class name when `call` looks like a constructor (Name func with
    a capitalized stem, underscore-private included: `_Parser(...)`)."""
    f = call.func
    if isinstance(f, ast.Name):
        stem = f.id.lstrip("_")
        if stem[:1].isupper():
            return f.id
    return None


def _last_name(expr) -> Optional[str]:
    """Trailing identifier of a Name/Attribute/Call chain."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Call):
        return _last_name(expr.func)
    return None


def _is_locky(name: Optional[str]) -> bool:
    if not name:
        return False
    low = name.lower()
    return any(t in low for t in _LOCKY) or low == "_mu"


def _is_semish(expr) -> bool:
    n = _last_name(expr)
    return bool(n) and "sem" in n.lower() and "semaphore" not in (
        n,)  # TpuSemaphore class ref itself is not an instance


def _is_riderish(expr) -> bool:
    n = _last_name(expr)
    return bool(n) and "rider" in n.lower()


class Event:
    """One synchronization- or access-relevant action at a source site.

    `kind` is one of: acquire | release | wait | sync | submit (the
    synchronization stream consumed by the concurrency auditor), or
    read | write | rmw | checkact | publish (the per-``class.attr``
    access stream consumed by the race auditor — kept in
    ``FuncInfo.accesses``, never in ``FuncInfo.events``, so the
    summary caps of the two passes cannot starve each other)."""

    __slots__ = ("kind", "line", "col", "desc", "blocking", "resource",
                 "pool", "wclass", "exempt")

    def __init__(self, kind: str, line: int, col: int, desc: str,
                 blocking: bool = False, resource: Optional[str] = None,
                 pool: Optional[str] = None, wclass: str = "",
                 exempt: frozenset = frozenset()):
        self.kind = kind
        self.line = line
        self.col = col
        self.desc = desc
        self.blocking = blocking
        self.resource = resource
        self.pool = pool
        self.wclass = wclass      # future | queue | sem | cond | socket
        # for access events: aug | subscript | method:<name> | handoff..
        self.exempt = exempt      # held keys this wait releases


class PoolInfo:
    """A bounded executor, keyed by worker-thread name prefix."""

    __slots__ = ("key", "mod", "path", "line", "workers", "sites")

    def __init__(self, key: str, mod: str, path: str, line: int):
        self.key = key
        self.mod = mod
        self.path = path
        self.line = line
        self.workers: List[Tuple[str, tuple]] = []  # (owner fid, ref)
        self.sites: List[int] = []


class FuncInfo:
    """Per-function facts: events with lexical held-sets, call edges,
    attribute-access sites."""

    __slots__ = ("fid", "path", "mod", "cls", "name", "qual", "line",
                 "events", "calls", "nested", "parent", "accesses")

    def __init__(self, fid: str, path: str, mod: str,
                 cls: Optional[str], name: str, line: int,
                 parent: Optional[str] = None):
        self.fid = fid
        self.path = path
        self.mod = mod
        self.cls = cls
        self.name = name
        self.qual = f"{cls}.{name}" if cls else name
        self.line = line
        self.parent = parent      # enclosing function's fid (nested defs)
        self.events: List[Tuple[Event, frozenset]] = []
        self.calls: List[Tuple[tuple, int, frozenset]] = []
        self.nested: Dict[str, str] = {}
        # per-`class.attr` access events (read/write/rmw/checkact/
        # publish) with the lexically-held lockset at the site
        self.accesses: List[Tuple[Event, frozenset]] = []


class Model:
    """Whole-tree facts the rules run against."""

    def __init__(self):
        self.funcs: Dict[str, FuncInfo] = {}
        self.resources: Dict[str, str] = {}       # key -> kind
        self.resource_sites: Dict[str, List[Tuple[str, int]]] = {}
        self.cond_pairs: Dict[str, Optional[str]] = {}
        self.attr_res: Dict[Tuple[str, str], str] = {}  # (cls, attr) -> key
        self.pools: Dict[str, PoolInfo] = {}
        self.module_fns: Dict[Tuple[str, str], str] = {}
        self.methods: Dict[Tuple[str, str, str], str] = {}
        self.methods_by_name: Dict[str, List[str]] = {}
        self.imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        self.thread_targets: List[Tuple[str, tuple, Optional[str]]] = []
        self.lines: Dict[str, List[str]] = {}     # relpath -> source lines
        # class name -> constructor-site escape shapes ("local" =
        # assigned to a plain local, "recv" = temporary method
        # receiver, "stored"/"escaped" = reaches shared state): the
        # race auditor's instance-confinement evidence
        self.ctors: Dict[str, List[str]] = {}
        self._summaries: Dict[str, list] = {}

    # -- registration --------------------------------------------------
    def add_resource(self, key: str, kind: str, path: str, line: int):
        self.resources.setdefault(key, kind)
        self.resource_sites.setdefault(key, []).append((path, line))

    def add_func(self, fn: FuncInfo):
        self.funcs[fn.fid] = fn
        if fn.cls is None and "." not in fn.name:
            self.module_fns.setdefault((fn.mod, fn.name), fn.fid)
        if fn.cls is not None:
            self.methods.setdefault((fn.mod, fn.cls, fn.name), fn.fid)
            self.methods_by_name.setdefault(fn.name, []).append(fn.fid)

    # -- call resolution -----------------------------------------------
    def resolve_ref(self, fn: FuncInfo, ref: tuple) -> Optional[str]:
        kind, name = ref
        if kind == "local":
            # lexical scope chain: own nested defs, then enclosing
            # functions' (siblings like map_one called from
            # map_partition, both nested in _ensure_shuffled)
            cur: Optional[FuncInfo] = fn
            while cur is not None:
                if name in cur.nested:
                    return cur.nested[name]
                cur = self.funcs.get(cur.parent) if cur.parent else None
            fid = self.module_fns.get((fn.mod, name))
            if fid is not None:
                return fid
            imp = self.imports.get(fn.mod, {}).get(name)
            if imp is not None:
                return self.module_fns.get(imp)
            return None
        if kind == "self":
            if fn.cls is not None:
                fid = self.methods.get((fn.mod, fn.cls, name))
                if fid is not None:
                    return fid
            return self._unique_method(name)
        if kind == "attr":
            return self._unique_method(name)
        return None

    def _unique_method(self, name: str) -> Optional[str]:
        if name in _NO_RESOLVE or name.startswith("__"):
            return None
        cands = self.methods_by_name.get(name, ())
        return cands[0] if len(cands) == 1 else None

    # -- interprocedural summaries --------------------------------------
    def summarize(self, fid: str, _stack: Optional[set] = None) -> list:
        """All (event, held-keys, site-fid) pairs realizable by calling
        `fid`, with held-sets relative to its entry. Memoized; recursion
        cut at the in-progress set; capped at _SUMMARY_CAP entries."""
        if fid in self._summaries:
            return self._summaries[fid]
        stack = _stack if _stack is not None else set()
        if fid in stack:
            return []
        stack.add(fid)
        fn = self.funcs[fid]
        out: List[tuple] = []
        for ev, held in fn.events:
            out.append((ev, held, fid))
        for ref, _line, held in fn.calls:
            callee = self.resolve_ref(fn, ref)
            if callee is None or callee == fid:
                continue
            for ev, add_held, site in self.summarize(callee, stack):
                out.append((ev, held | add_held, site))
                if len(out) >= _SUMMARY_CAP:
                    break
            if len(out) >= _SUMMARY_CAP:
                break
        stack.discard(fid)
        out = out[:_SUMMARY_CAP]
        self._summaries[fid] = out
        return out

    def reachable_from(self, roots: List[str]) -> Set[str]:
        seen = set(roots)
        work = list(roots)
        while work:
            fid = work.pop()
            fn = self.funcs.get(fid)
            if fn is None:
                continue
            for ref, _line, _held in fn.calls:
                callee = self.resolve_ref(fn, ref)
                if callee is not None and callee not in seen:
                    seen.add(callee)
                    work.append(callee)
        return seen

    def snippet(self, path: str, line: int) -> str:
        lines = self.lines.get(path, ())
        if 1 <= line <= len(lines):
            return lines[line - 1].strip()
        return ""


# ---------------------------------------------------------------------
# module scanning
# ---------------------------------------------------------------------
_THREADING_LOCKS = {"Lock": "lock", "RLock": "rlock",
                    "Condition": "cond", "Semaphore": "sem",
                    "BoundedSemaphore": "sem"}


def _threading_ctor(call: ast.Call) -> Optional[str]:
    """'lock'/'rlock'/'cond'/'sem' when `call` constructs a threading
    primitive (threading.Lock(), Lock(), ...)."""
    f = call.func
    name = None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "threading":
        name = f.attr
    elif isinstance(f, ast.Name):
        name = f.id
    return _THREADING_LOCKS.get(name) if name else None


def _lockdep_factory(call: ast.Call) -> Optional[Tuple[str, str]]:
    """(key, kind) for lockdep.lock("K") / lockdep.rlock("K")."""
    f = call.func
    attr = None
    if isinstance(f, ast.Attribute) and _last_name(f.value) == "lockdep":
        attr = f.attr
    elif isinstance(f, ast.Name) and f.id in ("lock", "rlock"):
        attr = f.id
    if attr in ("lock", "rlock") and call.args and \
            isinstance(call.args[0], ast.Constant) and \
            isinstance(call.args[0].value, str):
        return call.args[0].value, attr
    return None


def _is_pool_ctor(call: ast.Call) -> bool:
    return _last_name(call.func) == "ThreadPoolExecutor"


def _kw(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class _Scanner:
    """One source module -> FuncInfos + resources + pools in the model."""

    def __init__(self, model: Model, mod: str, path: str, src: str):
        self.model = model
        self.mod = mod
        self.path = path
        self.tree = ast.parse(src)
        model.lines[path] = src.splitlines()

    def scan(self):
        imap = self.model.imports.setdefault(self.mod, {})
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                src = self._resolve_import(node)
                if src is not None:
                    for a in node.names:
                        imap[a.asname or a.name] = (src, a.name)
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self._scan_fn(sub, node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_fn(node, None)
            elif isinstance(node, ast.Assign):
                self._module_assign(node)

    def _resolve_import(self, node: ast.ImportFrom) -> Optional[str]:
        """Dotted engine-module path for `from .x import y` relative to
        this module; absolute engine imports pass through."""
        mod = node.module or ""
        if node.level == 0:
            if mod.startswith("spark_rapids_tpu."):
                return mod[len("spark_rapids_tpu."):]
            return None
        parts = self.mod.split(".")
        # level 1 = sibling package level, 2 = one package up, ...
        base = parts[:len(parts) - node.level]
        return ".".join(base + mod.split(".")) if mod else None

    def _module_assign(self, node: ast.Assign):
        if not (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            return
        name = node.targets[0].id
        kind = _threading_ctor(node.value)
        if kind is not None:
            key = f"{self.mod}.{name}"
            self.model.add_resource(key, kind, self.path, node.lineno)
            if kind == "cond":
                self.model.cond_pairs[key] = None
            return
        ld = _lockdep_factory(node.value)
        if ld is not None:
            self.model.add_resource(ld[0], ld[1], self.path, node.lineno)
            return
        cn = _ctor_name(node.value)
        if cn is not None:
            # module-level singleton: shared by construction
            self.model.ctors.setdefault(cn, []).append("escaped")

    def _scan_fn(self, node, cls: Optional[str],
                 parent: Optional[FuncInfo] = None) -> FuncInfo:
        qual = node.name if cls is None else f"{cls}.{node.name}"
        if parent is not None:
            qual = f"{parent.qual}.<{node.name}>"
        fid = f"{self.mod}:{qual}"
        fn = FuncInfo(fid, self.path, self.mod, cls, node.name,
                      node.lineno,
                      parent=parent.fid if parent is not None else None)
        fn.qual = qual
        self.model.add_func(fn)
        _FnWalker(self, fn, cls).walk(node.body)
        return fn


class _FnWalker:
    """Statement walk of one function body, carrying the lexical
    held-resource stack and emitting events / call edges / accesses."""

    def __init__(self, scanner: _Scanner, fn: FuncInfo,
                 cls: Optional[str]):
        self.sc = scanner
        self.model = scanner.model
        self.fn = fn
        self.cls = cls
        self.held: List[str] = []
        self.pool_vars: Dict[str, str] = {}    # local name -> pool key
        self.fut_pools: Dict[str, str] = {}    # future var -> pool key
        self.queue_vars: Set[str] = set()
        self.local_res: Dict[str, str] = {}    # local name -> resource
        self.ctor_vars: Dict[str, str] = {}    # local name -> class name
        self._ctor_seen: Set[int] = set()      # Call node ids recorded

    # -- helpers -------------------------------------------------------
    def _snap(self) -> frozenset:
        return frozenset(self.held)

    def _emit(self, ev: Event):
        self.fn.events.append((ev, self._snap()))

    def _call_edge(self, ref: tuple, line: int):
        self.fn.calls.append((ref, line, self._snap()))

    def _push(self, key: str, line: int, col: int, desc: str):
        self._emit(Event("acquire", line, col, desc, resource=key))
        self.held.append(key)

    def _pop(self, key: str):
        for i in range(len(self.held) - 1, -1, -1):
            if self.held[i] == key:
                del self.held[i]
                return

    def _pool_key_for(self, call: ast.Call, line: int) -> str:
        pref = _kw(call, "thread_name_prefix")
        if isinstance(pref, ast.Constant) and isinstance(pref.value, str) \
                and pref.value:
            key = pref.value
        else:
            key = f"{self.sc.mod}.{self.fn.name}.pool@{line}"
        p = self.model.pools.get(key)
        if p is None:
            p = PoolInfo(key, self.sc.mod, self.fn.path, line)
            self.model.pools[key] = p
        p.sites.append(line)
        return key

    # -- attribute-access recording (race auditor's input) -------------
    def _self_attr(self, expr) -> Optional[str]:
        """Attr name when `expr` is a `self.X` access in a method."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and self.cls is not None:
            return expr.attr
        return None

    def _access(self, kind: str, line: int, col: int, attr: str,
                wclass: str = ""):
        # lock attributes are resources, not data: their consistency is
        # the concurrency auditor's domain
        if _is_locky(attr) or (self.cls, attr) in self.model.attr_res:
            return
        self.fn.accesses.append((Event(
            kind, line, col, self.model.snippet(self.fn.path, line),
            resource=f"{self.cls}.{attr}", wclass=wclass), self._snap()))

    def _record_store(self, tgt, line: int, wclass: str = ""):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._record_store(el, line, wclass)
            return
        a = self._self_attr(tgt)
        if a is not None:
            self._access("write", line, tgt.col_offset, a, wclass)
            return
        if isinstance(tgt, ast.Subscript):
            a = self._self_attr(tgt.value)
            if a is not None:
                self._access("write", line, tgt.col_offset, a,
                             wclass or "subscript")

    def _is_handoff_value(self, val) -> bool:
        """True when an attr write's source is a queue/Future/pool
        hand-off: the assigned object is itself the synchronization
        point (or the value was received through one)."""
        if not isinstance(val, ast.Call):
            return False
        if _is_pool_ctor(val):
            return True
        n = _last_name(val.func)
        if n in _HANDOFF_CTORS:
            return True
        if isinstance(val.func, ast.Attribute) and \
                val.func.attr in ("submit", "result", "get"):
            return True
        return False

    def _checkact(self, s: ast.If):
        """check-then-act shapes: `if k not in self.d: self.d[k] = ...`
        and `if self.x is None: self.x = ...` (lazy memo)."""
        t = s.test
        if isinstance(t, ast.BoolOp) and isinstance(t.op, ast.And) \
                and t.values:
            # `if self._arena is None and native_lib() is not None:`
            # still checks-then-acts on the leading condition
            t = t.values[0]
        if not (isinstance(t, ast.Compare) and len(t.ops) == 1):
            return
        if isinstance(t.ops[0], ast.NotIn):
            a = self._self_attr(t.comparators[0])
            if a is not None and self._stores_subscript(s.body, a):
                self._access("checkact", s.lineno, s.col_offset, a,
                             "notin")
        elif isinstance(t.ops[0], ast.Is) and \
                isinstance(t.comparators[0], ast.Constant) and \
                t.comparators[0].value is None:
            a = self._self_attr(t.left)
            if a is not None and self._stores_attr(s.body, a):
                self._access("checkact", s.lineno, s.col_offset, a,
                             "isnone")

    def _stores_subscript(self, body, attr: str) -> bool:
        for st in body:
            for n in ast.walk(st):
                if isinstance(n, ast.Assign):
                    for tgt in n.targets:
                        if isinstance(tgt, ast.Subscript) and \
                                self._self_attr(tgt.value) == attr:
                            return True
        return False

    def _stores_attr(self, body, attr: str) -> bool:
        for st in body:
            for n in ast.walk(st):
                if isinstance(n, ast.Assign):
                    for tgt in n.targets:
                        if self._self_attr(tgt) == attr:
                            return True
        return False

    # -- resource resolution -------------------------------------------
    def resolve_resource(self, expr) -> Optional[str]:
        """Resource key for a lock-ish expression, or None."""
        if isinstance(expr, ast.Name):
            n = expr.id
            if n in self.local_res:
                return self.local_res[n]
            key = f"{self.sc.mod}.{n}"
            if key in self.model.resources:
                return key
            if _is_locky(n):
                self.model.add_resource(key, "lock", self.fn.path, 0)
                return key
            return None
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and self.cls is not None:
                key = self.model.attr_res.get((self.cls, attr))
                if key is not None:
                    return key
                key = f"{self.cls}.{attr}"
                if key in self.model.resources:
                    return key
                if _is_locky(attr):
                    self.model.add_resource(key, "lock", self.fn.path, 0)
                    return key
                return None
            # foreign attribute: unique suffix across the registry
            if _is_locky(attr):
                cands = [k for k in self.model.resources
                         if k.endswith(f".{attr}")]
                if len(cands) == 1:
                    return cands[0]
                owner = _last_name(expr.value) or "ext"
                key = f"{owner}.{attr}"
                self.model.add_resource(key, "lock", self.fn.path, 0)
                return key
        return None

    def resolve_with_item(self, expr, line: int) -> Optional[str]:
        """Resource a `with` item holds for its body, or None."""
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Attribute):
                if f.attr == "hold" and _is_semish(f.value):
                    return PERMIT
                if f.attr == "step" and _is_riderish(f.value):
                    return PERMIT
            return None
        return self.resolve_resource(expr)

    # -- statement walk -------------------------------------------------
    def walk(self, stmts):
        for s in stmts:
            self.stmt(s)

    def stmt(self, s):
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sub = self.sc._scan_fn(s, self.cls, parent=self.fn)
            self.fn.nested[s.name] = sub.fid
            return
        if isinstance(s, (ast.ClassDef, ast.Lambda)):
            return
        if isinstance(s, ast.With):
            self._with(s)
            return
        if isinstance(s, ast.Try):
            self.walk(s.body)
            for h in s.handlers:
                self.walk(h.body)
            self.walk(s.orelse)
            self.walk(s.finalbody)
            return
        if isinstance(s, (ast.If, ast.While)):
            # snapshot BEFORE the test: a non-blocking acquire in the
            # test (if sem.try_acquire(): ...) holds for the BODY but
            # must not leak past the branch (PermitRider's alternating
            # ride/real-permit loop would otherwise read as a cycle)
            snap = list(self.held)
            if isinstance(s, ast.If):
                self._checkact(s)
            self.exprs(s.test, s.lineno)
            self.walk(s.body)
            self.held = list(snap)
            self.walk(s.orelse)
            self.held = snap
            return
        if isinstance(s, ast.For):
            snap = list(self.held)
            self.exprs(s.iter, s.lineno)
            self.walk(s.body)
            self.held = list(snap)
            self.walk(s.orelse)
            self.held = snap
            return
        if isinstance(s, ast.Assign):
            self._assign(s)
            return
        if isinstance(s, ast.AugAssign):
            a = self._self_attr(s.target)
            if a is not None:
                self._access("rmw", s.lineno, s.target.col_offset, a,
                             "aug")
            elif isinstance(s.target, ast.Subscript):
                a = self._self_attr(s.target.value)
                if a is not None:
                    self._access("rmw", s.lineno, s.target.col_offset,
                                 a, "aug-subscript")
            self.exprs(s.value, s.lineno)
            return
        if isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self._record_store(s.target, s.lineno)
                self.exprs(s.value, s.lineno)
            return
        if isinstance(s, (ast.Expr, ast.Return, ast.Assert, ast.Raise)):
            val = getattr(s, "value", None)
            if val is None and isinstance(s, ast.Raise):
                val = s.exc
            if val is not None:
                self.exprs(val, s.lineno)
            return
        # everything else: still sweep for calls in child expressions
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self.exprs(child, s.lineno)
            elif isinstance(child, ast.stmt):
                self.stmt(child)

    def _with(self, s: ast.With):
        pushed: List[str] = []
        for item in s.items:
            ce = item.context_expr
            if isinstance(ce, ast.Call) and _is_pool_ctor(ce):
                key = self._pool_key_for(ce, ce.lineno)
                if isinstance(item.optional_vars, ast.Name):
                    self.pool_vars[item.optional_vars.id] = key
                continue
            r = self.resolve_with_item(ce, ce.lineno)
            if r is not None:
                self._push(r, ce.lineno, ce.col_offset,
                           self.model.snippet(self.fn.path, ce.lineno))
                pushed.append(r)
            else:
                self.exprs(ce, s.lineno)
        self.walk(s.body)
        for r in reversed(pushed):
            self._pop(r)

    def _assign(self, s: ast.Assign):
        tgt = s.targets[0] if len(s.targets) == 1 else None
        val = s.value
        if isinstance(val, ast.Call):
            kind = _threading_ctor(val)
            ld = _lockdep_factory(val)
            if kind is not None or ld is not None:
                self._register_lock(tgt, val, kind, ld, s.lineno)
                return
            if _is_pool_ctor(val) and isinstance(tgt, ast.Name):
                self.pool_vars[tgt.id] = self._pool_key_for(val,
                                                            val.lineno)
                return
            if _last_name(val.func) in ("Queue", "SimpleQueue",
                                        "LifoQueue") and \
                    isinstance(tgt, ast.Name):
                self.queue_vars.add(tgt.id)
                return
            # fut = pool.submit(...) keeps the pool association
            if isinstance(val.func, ast.Attribute) and \
                    val.func.attr == "submit" and isinstance(tgt, ast.Name):
                pk = self._submit(val)
                if pk is not None:
                    self.fut_pools[tgt.id] = pk
                    return
        cname = _ctor_name(val) if isinstance(val, ast.Call) else None
        if cname is not None:
            shape = ("local" if isinstance(tgt, ast.Name) else "stored")
            self.model.ctors.setdefault(cname, []).append(shape)
            self._ctor_seen.add(id(val))
            if shape == "local":
                self.ctor_vars[tgt.id] = cname
        elif isinstance(val, ast.Name) and val.id in self.ctor_vars:
            # a locally-constructed instance stored into an attribute
            # or container escapes its creating thread
            for t in s.targets:
                if not isinstance(t, ast.Name):
                    self.model.ctors.setdefault(
                        self.ctor_vars[val.id], []).append("stored")
        wclass = "handoff" if self._is_handoff_value(val) else ""
        for t in s.targets:
            self._record_store(t, s.lineno, wclass)
        # publish-before-init: `REGISTRY[k] = self` (or any non-self
        # container slot) inside __init__ makes the instance visible to
        # other threads before construction completes
        if self.fn.name == "__init__" and isinstance(val, ast.Name) \
                and val.id == "self":
            for t in s.targets:
                if isinstance(t, ast.Subscript) and \
                        self._self_attr(t.value) is None:
                    sink = _last_name(t.value) or "?"
                    self._access("publish", s.lineno, t.col_offset,
                                 sink, "store")
        self.exprs(val, s.lineno)

    def _register_lock(self, tgt, call: ast.Call, kind, ld, line: int):
        if ld is not None:
            key, kind = ld
        elif isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self" \
                and self.cls is not None:
            key = f"{self.cls}.{tgt.attr}"
        elif isinstance(tgt, ast.Name):
            key = f"{self.sc.mod}.{self.fn.name}.{tgt.id}"
            self.local_res[tgt.id] = key
        else:
            return
        self.model.add_resource(key, kind, self.fn.path, line)
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self" \
                and self.cls is not None:
            self.model.attr_res[(self.cls, tgt.attr)] = key
        if kind == "cond":
            paired = None
            if call.args:
                paired = self.resolve_resource(call.args[0])
            self.model.cond_pairs[key] = paired

    # -- expression / call classification -------------------------------
    def exprs(self, expr, line: int):
        skip: Set[int] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self.call(node)
                f = node.func
                if isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Call):
                    cn = _ctor_name(f.value)
                    if cn is not None and id(f.value) not in \
                            self._ctor_seen:
                        # `_Parser(src).parse()`: a temporary receiver
                        # stays on the constructing thread
                        self._ctor_seen.add(id(f.value))
                        self.model.ctors.setdefault(cn, []).append(
                            "recv")
                cn = _ctor_name(node)
                if cn is not None and id(node) not in self._ctor_seen:
                    self._ctor_seen.add(id(node))
                    self.model.ctors.setdefault(cn, []).append(
                        "escaped")
                if isinstance(f, ast.Attribute):
                    recv = f.value
                    if isinstance(recv, ast.Name) and recv.id == "self":
                        skip.add(id(f))   # self.method(): not a data read
                        continue
                    a = self._self_attr(recv)
                    if a is not None and f.attr in _MUTATORS:
                        # self.attr.append(x): shared-container mutation
                        self._access("write", node.lineno,
                                     node.col_offset, a,
                                     f"method:{f.attr}")
                        skip.add(id(recv))
                    elif isinstance(recv, ast.Subscript):
                        a2 = self._self_attr(recv.value)
                        if a2 is not None and f.attr in _MUTATORS:
                            # self.attr[k].append(x): slot RMW
                            self._access("rmw", node.lineno,
                                         node.col_offset, a2,
                                         f"method:{f.attr}")
                            skip.add(id(recv.value))
                    if self.fn.name == "__init__" and \
                            f.attr in _PUBLISH_SINKS and \
                            self._self_attr(f.value) is None and \
                            any(isinstance(arg, ast.Name)
                                and arg.id == "self"
                                for arg in node.args):
                        self._access("publish", node.lineno,
                                     node.col_offset,
                                     _last_name(f.value) or "?",
                                     f"sink:{f.attr}")
            elif isinstance(node, ast.Attribute) and \
                    id(node) not in skip and \
                    isinstance(node.ctx, ast.Load):
                a = self._self_attr(node)
                if a is not None:
                    self._access("read", node.lineno, node.col_offset, a)

    def call(self, c: ast.Call):
        f = c.func
        line, col = c.lineno, c.col_offset
        desc = self.model.snippet(self.fn.path, line)
        # nested functions passed as arguments (with_retry(batch,
        # map_one)) run with the caller's held-set: edge them —
        # checking the whole lexical scope chain
        for arg in c.args:
            if isinstance(arg, ast.Name):
                cur = self.fn
                while cur is not None:
                    if arg.id in cur.nested:
                        self._call_edge(("local", arg.id), line)
                        break
                    cur = (self.model.funcs.get(cur.parent)
                           if cur.parent else None)
        if isinstance(f, ast.Name):
            name = f.id
            if name == "fetch":
                self._emit(Event("sync", line, col, desc))
            elif name == "as_completed":
                self._emit(Event("wait", line, col, desc, blocking=True,
                                 wclass="future"))
            elif name == "recv_msg":
                self._emit(Event("wait", line, col, desc, blocking=True,
                                 wclass="socket"))
            elif name in ("Thread",):
                self._thread(c)
            elif name not in ("print", "len", "range", "isinstance",
                              "int", "float", "str", "list", "dict",
                              "set", "tuple", "max", "min", "sorted",
                              "enumerate", "zip", "super", "getattr",
                              "hasattr", "setattr", "iter", "next",
                              "type", "repr", "id", "abs", "sum",
                              "round", "bool", "bytes", "open",
                              "frozenset", "divmod", "map", "filter",
                              "any", "all", "vars", "callable"):
                self._call_edge(("local", name), line)
            return
        if not isinstance(f, ast.Attribute):
            return
        attr = f.attr
        base = f.value
        if attr == "Thread" and _last_name(base) == "threading":
            self._thread(c)
            return
        if attr == "acquire":
            self._acquire(c, base, line, col, desc)
            return
        if attr == "try_acquire" and _is_semish(base):
            self._emit(Event("acquire", line, col, desc, resource=PERMIT))
            self.held.append(PERMIT)
            return
        if attr == "release":
            r = (PERMIT if _is_semish(base) or _is_riderish(base)
                 else self.resolve_resource(base))
            if r is not None:
                self._emit(Event("release", line, col, desc, resource=r))
                self._pop(r)
            return
        if attr == "result":
            bn = _last_name(base)
            futish = bn in self.fut_pools or (
                bn and ("fut" in bn.lower() or bn == "f"))
            if futish:
                blocking = not c.args and not c.keywords
                self._emit(Event("wait", line, col, desc,
                                 blocking=blocking, wclass="future",
                                 pool=self.fut_pools.get(bn)))
            return
        if attr == "as_completed":
            self._emit(Event("wait", line, col, desc, blocking=True,
                             wclass="future"))
            return
        if attr == "submit":
            self._submit(c)
            return
        if attr == "map":
            bn = _last_name(base)
            if bn in self.pool_vars:
                pk = self.pool_vars[bn]
                if c.args:
                    self._worker(pk, c.args[0])
                self._emit(Event("submit", line, col, desc, pool=pk))
                self._emit(Event("wait", line, col, desc, blocking=True,
                                 wclass="future", pool=pk))
            return
        if attr == "get":
            bn = _last_name(base)
            if bn in self.queue_vars or (
                    bn and (bn in ("q", "queue") or bn.endswith("_q")
                            or bn.endswith("_queue"))):
                blocking = not c.args and _kw(c, "timeout") is None \
                    and _kw(c, "block") is None
                self._emit(Event("wait", line, col, desc,
                                 blocking=blocking, wclass="queue"))
            return
        if attr == "wait":
            self._wait(c, base, line, col, desc)
            return
        if attr in ("recv", "recvall", "recv_into", "accept", "recv_msg"):
            self._emit(Event("wait", line, col, desc, blocking=True,
                             wclass="socket"))
            return
        if attr == "block_until_ready" or attr == "device_get":
            self._emit(Event("sync", line, col, desc))
            return
        if isinstance(base, ast.Name) and base.id == "self":
            self._call_edge(("self", attr), line)
            return
        self._call_edge(("attr", attr), line)

    def _acquire(self, c, base, line, col, desc):
        blocking = True
        if c.args and isinstance(c.args[0], ast.Constant) and \
                c.args[0].value in (False, 0):
            blocking = False
        bl = _kw(c, "blocking")
        if isinstance(bl, ast.Constant) and bl.value in (False, 0):
            blocking = False
        if _kw(c, "timeout") is not None:
            blocking = False
        if _is_semish(base):
            # TpuSemaphore.acquire: blocking device admission (its
            # internal token poll does not bound the wait for a permit)
            self._emit(Event("wait", line, col, desc, blocking=blocking,
                             wclass="sem", resource=PERMIT))
            self._emit(Event("acquire", line, col, desc, resource=PERMIT))
            self.held.append(PERMIT)
            return
        r = self.resolve_resource(base)
        if r is not None:
            if blocking:
                self._push(r, line, col, desc)
            else:
                self._emit(Event("acquire", line, col, desc, resource=r))
                self.held.append(r)

    def _wait(self, c, base, line, col, desc):
        blocking = not c.args and _kw(c, "timeout") is None
        exempt: frozenset = frozenset()
        r = self.resolve_resource(base)
        if r is not None and self.model.resources.get(r) == "cond":
            # Condition.wait releases its lock while parked
            paired = self.model.cond_pairs.get(r)
            exempt = frozenset(k for k in (r, paired) if k)
        self._emit(Event("wait", line, col, desc, blocking=blocking,
                         wclass="cond" if exempt else "event",
                         exempt=exempt))

    def _submit(self, c: ast.Call) -> Optional[str]:
        f = c.func
        base = f.value
        bn = _last_name(base)
        pk = self.pool_vars.get(bn)
        if pk is None and isinstance(base, ast.Call):
            # _build_pool().submit(...): resolve through the factory
            ref = ("local", _last_name(base.func) or "")
            callee = self.model.resolve_ref(self.fn, ref)
            pk = f"factory:{_last_name(base.func)}" \
                if callee is None else None
            if callee is not None:
                pk = self._factory_pool(callee)
        if pk is None and bn and "pool" in bn.lower():
            pk = f"{self.sc.mod}.{bn}"
        if pk is None:
            return None
        if c.args:
            self._worker(pk, c.args[0])
        self._emit(Event("submit", c.lineno, c.col_offset,
                         self.model.snippet(self.fn.path, c.lineno),
                         pool=pk))
        return pk

    def _factory_pool(self, fid: str) -> Optional[str]:
        """Pool key created inside a factory function (e.g.
        _build_pool): the unique pool whose creation site is in it."""
        fn = self.model.funcs.get(fid)
        if fn is None:
            return None
        cands = [k for k, p in self.model.pools.items()
                 if p.mod == fn.mod and any(
                     fn.line <= ln for ln in p.sites)]
        return cands[0] if len(cands) == 1 else None

    def _worker(self, pool_key: str, arg):
        ref = None
        if isinstance(arg, ast.Name):
            ref = ("local", arg.id)
        elif isinstance(arg, ast.Attribute) and \
                isinstance(arg.value, ast.Name) and arg.value.id == "self":
            ref = ("self", arg.attr)
        if ref is not None:
            p = self.model.pools.get(pool_key)
            if p is None:
                p = PoolInfo(pool_key, self.sc.mod, self.fn.path, 0)
                self.model.pools[pool_key] = p
            p.workers.append((self.fn.fid, ref))

    def _thread(self, c: ast.Call):
        tgt = _kw(c, "target")
        name = _kw(c, "name")
        ref = None
        if isinstance(tgt, ast.Name):
            ref = ("local", tgt.id)
        elif isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
            ref = ("self", tgt.attr)
        nm = name.value if isinstance(name, ast.Constant) and \
            isinstance(name.value, str) else None
        if ref is not None:
            self.model.thread_targets.append((self.fn.fid, ref, nm))
        # a Thread construction is a spawn point like pool.submit:
        # writes that lexically precede the function's first spawn are
        # single-threaded (the race auditor's init-before-first-submit
        # exemption); pool=None keeps pool-self-wait indifferent
        self._emit(Event("submit", c.lineno, c.col_offset,
                         self.model.snippet(self.fn.path, c.lineno),
                         wclass="thread"))


# ---------------------------------------------------------------------
# model building
# ---------------------------------------------------------------------
def _iter_py(paths: List[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        files.append(os.path.join(dirpath, fn))
        else:
            files.append(p)
    return files


def _mod_name(path: str, rel_to: Optional[str]) -> str:
    rel = os.path.relpath(path, rel_to) if rel_to else path
    rel = rel.replace(os.sep, "/")
    stem = rel[:-3] if rel.endswith(".py") else rel
    parts = [p for p in stem.split("/") if p]
    if parts and parts[0] == "spark_rapids_tpu":
        parts = parts[1:]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "root"


def build_model(paths: List[str],
                rel_to: Optional[str] = None) -> Model:
    model = Model()
    for f in _iter_py(paths):
        rel = (os.path.relpath(f, rel_to) if rel_to else f)
        rel = rel.replace(os.sep, "/")
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        try:
            _Scanner(model, _mod_name(f, rel_to), rel, src).scan()
        except SyntaxError:
            continue
    return model


# ---------------------------------------------------------------------
# shared allow-marker filtering
# ---------------------------------------------------------------------
def _allowed(markers: Dict[int, Tuple[Set[str], bool]], rule: str,
             line: int) -> bool:
    for ln in (line, line - 1):
        m = markers.get(ln)
        if m and rule in m[0]:
            return True
    return False


def _file_markers(lines: List[str]) -> Dict[int, Tuple[Set[str], bool]]:
    markers: Dict[int, Tuple[Set[str], bool]] = {}
    for i, raw in enumerate(lines, start=1):
        m = MARKER_RE.search(raw)
        if m:
            rules = {r.strip() for r in m.group(1).split(",")
                     if r.strip()}
            markers[i] = (rules, bool(m.group(2).strip()))
    return markers


def filter_markers(model: Model, violations: list) -> list:
    """Drop violations whose site (or the line above) carries an
    inline `# tpulint: allow[rule] reason` marker."""
    out = []
    marker_cache: Dict[str, Dict[int, Tuple[Set[str], bool]]] = {}
    for v in violations:
        mk = marker_cache.get(v.path)
        if mk is None:
            mk = _file_markers(model.lines.get(v.path, []))
            marker_cache[v.path] = mk
        if not _allowed(mk, v.rule, v.line):
            out.append(v)
    return out
