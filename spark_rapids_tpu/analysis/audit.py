"""Plan-time static auditor: NOT_ON_TPU verdict tagging before execution.

The GpuOverrides explain discipline, completed (reference:
GpuOverrides.scala tagging + `spark.rapids.sql.explain=NOT_ON_GPU`): a
pre-execution walk over the tagged/bound plan that propagates
schema/dtype information through every node, checks each bound
expression against the TypeSig registry (including the AUDIT_CHECKS
kernel-truth refinements that are narrower than the binders), and tags
every node with a structured verdict:

  ok              runs on TPU as compiled device programs
  will_fallback   runs, but on the host CPU (host_fallback interpreter,
                  python_exec worker, pure_callback host eval)
  will_not_work   will fail at runtime (unregistered expression,
                  dtype the kernels cannot actually handle — e.g. a
                  decimal128 two-limb buffer entering the double-math
                  path); with `sql.audit.strict` these raise a plan-time
                  UnsupportedExpr carrying the lore id + node path
  recompile_risk  shapes/dtypes escaping the power-of-two bucketing or
                  weak-typing discipline — each occurrence compiles a
                  fresh XLA program

Surfaced via `df.explain("VALIDATE")`, the ALL/NOT_ON_TPU explain modes,
and a `plan_audit` event in the profiler event log (keyed by lore id).
"""
from __future__ import annotations

from typing import Callable, List, Optional

from ..expr.expressions import Expression, UnsupportedExpr

__all__ = ["Verdict", "AuditReport", "audit_plan", "OK", "WILL_FALLBACK",
           "WILL_NOT_WORK", "RECOMPILE_RISK"]

OK = "ok"
WILL_FALLBACK = "will_fallback"
WILL_NOT_WORK = "will_not_work"
RECOMPILE_RISK = "recompile_risk"

# severity order for a node's summary tag
_RANK = {OK: 0, RECOMPILE_RISK: 1, WILL_FALLBACK: 2, WILL_NOT_WORK: 3}
_TAG = {OK: "*", RECOMPILE_RISK: "~", WILL_FALLBACK: "!cpu",
        WILL_NOT_WORK: "!!"}

# bound-tree infrastructure nodes that deliberately carry no signature
_INFRA = {"BoundRef", "NamedLambdaVariable", "Alias"}

# expressions that bind on TPU but evaluate on the host CPU bridge
# (their registry notes say "runs via CPU bridge")
_HOST_BRIDGE = {"FromJson", "ToJson", "ParseUrl"}


class Verdict:
    """One finding on one plan node."""

    __slots__ = ("kind", "reason", "node", "path", "lore_id")

    def __init__(self, kind: str, reason: str, node: str, path: str,
                 lore_id: Optional[int]):
        self.kind = kind
        self.reason = reason
        self.node = node
        self.path = path
        self.lore_id = lore_id

    def to_dict(self) -> dict:
        return {"kind": self.kind, "reason": self.reason,
                "node": self.node, "path": self.path,
                "lore_id": self.lore_id}

    def describe(self) -> str:
        lore = f" [loreId={self.lore_id}]" if self.lore_id is not None \
            else ""
        return f"{self.kind}{lore} {self.path}: {self.reason}"

    def __repr__(self):
        return f"Verdict({self.describe()})"


class AuditReport:
    """All non-ok findings plus a renderable per-node verdict tree."""

    def __init__(self, findings: List[Verdict], tree_lines: List[str],
                 node_count: int):
        self.findings = findings
        self.tree_lines = tree_lines
        self.node_count = node_count
        # descriptions of the whole-stage fusion groups the planner
        # formed AFTER this audit ran (plan/fusion.py fills this in)
        self.fusion_groups: List[str] = []

    def of_kind(self, kind: str) -> List[Verdict]:
        return [v for v in self.findings if v.kind == kind]

    @property
    def ok(self) -> bool:
        return not self.of_kind(WILL_NOT_WORK)

    def lines(self) -> List[str]:
        """The VALIDATE explain rendering: the verdict-tagged plan tree
        followed by one line per finding."""
        out = ["== PLAN AUDIT =="]
        out.extend(self.tree_lines)
        if self.findings:
            out.append("-- findings --")
            out.extend(v.describe() for v in self.findings)
        else:
            out.append("-- no findings: plan runs fully on TPU --")
        if self.fusion_groups:
            out.append("-- fused stages --")
            out.extend(self.fusion_groups)
        return out

    def render(self) -> str:
        return "\n".join(self.lines())

    def to_events(self) -> List[dict]:
        """JSON-able findings for the `plan_audit` event-log record."""
        return [v.to_dict() for v in self.findings]

    def raise_if_blocked(self):
        """Strict mode: any will_not_work verdict fails the plan NOW,
        with the lore id + node path of every blocked site — not 40s
        into the query with an opaque XLA error."""
        blocked = self.of_kind(WILL_NOT_WORK)
        if blocked:
            raise UnsupportedExpr(
                "plan audit: " + "; ".join(v.describe() for v in blocked))


def _is_pow2(n) -> bool:
    return isinstance(n, int) and n > 0 and (n & (n - 1)) == 0


def _audit_expr(expr, role: str, add: Callable[[str, str], None],
                seen_ids=None):
    """Walk one bound expression tree, checking every node against the
    registry (coverage + primary-input dtype) and the AUDIT_CHECKS
    kernel-truth refinements."""
    from ..plan import typesig
    if expr is None or not isinstance(expr, Expression):
        return
    if seen_ids is None:
        seen_ids = set()
    if id(expr) in seen_ids:       # diamond-shared subtrees audit once
        return
    seen_ids.add(id(expr))
    name = type(expr).__name__
    kids = getattr(expr, "children", None) or []
    ent = typesig.SIGS.get(name)
    if ent is None and name not in _INFRA:
        add(WILL_NOT_WORK,
            f"unregistered expression {name} in {role}: no TypeSig "
            f"registration — device support unknown (register it in "
            f"plan/typesig.py, or with an explicit permissive sig + "
            f"note)")
    cdt = getattr(kids[0], "dtype", None) if kids else None
    if ent is not None and cdt is not None and not ent[0].supports(cdt):
        add(WILL_NOT_WORK,
            f"{name} in {role} does not support input type {cdt} "
            f"(supported: {ent[0].describe()})")
    reason = typesig.audit_check(name, cdt)
    if reason is not None:
        add(WILL_NOT_WORK, f"{name} in {role} over {cdt}: {reason}")
    if name in _HOST_BRIDGE:
        add(WILL_FALLBACK,
            f"{name} in {role} runs via the CPU bridge (host row "
            f"interpreter)")
    if name == "PyUDF":
        add(WILL_FALLBACK,
            f"python UDF {getattr(expr, 'name', '?')!r} in {role} was "
            f"not AST-compiled: evaluates via jax.pure_callback (device "
            f"program suspends per batch for host evaluation)")
    if name == "Literal":
        import numpy as _np
        v = getattr(expr, "value", None)
        if isinstance(v, (_np.generic, _np.ndarray)):
            add(RECOMPILE_RISK,
                f"non-weak-typed literal {v!r} ({type(v).__name__}) in "
                f"{role}: numpy-typed constants carry a strong dtype "
                f"into the trace and can promote operand dtypes, "
                f"splitting the XLA compile cache — use a plain Python "
                f"literal")
    for c in kids:
        _audit_expr(c, role, add, seen_ids)
    # a bound WindowExpr carries bound partition keys / sort orders in
    # its spec, outside .children
    spec = getattr(expr, "spec", None)
    if name == "WindowExpr" and spec is not None:
        for k in getattr(spec, "partition_keys", []) or []:
            _audit_expr(k, f"{role} partition key", add, seen_ids)
        for o in getattr(spec, "orders", []) or []:
            _audit_expr(getattr(o, "expr", None), f"{role} order key",
                        add, seen_ids)


def _bound_exprs(node):
    """Yield (role, bound expression) pairs for every expression a
    logical node carries, by node type."""
    from ..plan import logical as L
    if isinstance(node, L.Project):
        for e, b in zip(node.exprs, node.bound):
            if b is not None:
                yield f"Project expr {e.name!r}", b
    elif isinstance(node, L.Filter):
        if node.bound is not None:
            yield "Filter condition", node.bound
    elif isinstance(node, L.Aggregate):
        for k in node.bound_keys:
            yield f"Aggregate key {k.name!r}", k
        for n, a in node.bound_aggs:
            yield f"Aggregate agg {n!r}", a
    elif isinstance(node, L.Expand):
        for k in node.bound_keys:
            yield f"Expand key {k.name!r}", k
    elif isinstance(node, L.Join):
        for k in node.bound_left_keys or []:
            yield f"Join left key {k.name!r}", k
        for k in node.bound_right_keys or []:
            yield f"Join right key {k.name!r}", k
        if node.bound_condition is not None:
            yield "Join condition", node.bound_condition
    elif isinstance(node, L.Sort):
        for o in node.bound_orders:
            yield f"Sort key {o.expr!r}", o.expr
    elif isinstance(node, L.WindowOp):
        for n, w in node.bound:
            yield f"WindowOp column {n!r}", w
    elif isinstance(node, L.Generate):
        yield "Generate generator", node.bound
    elif isinstance(node, L.Repartition):
        for k in node.bound_keys or []:
            yield f"Repartition key {k.name!r}", k


def _audit_parquet_scan(node, add: Callable[[str, str], None]):
    """Plan-time device-decode audit of a ParquetScan: read the FIRST
    file's footer (cheap, metadata only) and report, per selected
    column, why the device decode path would fall back to host pyarrow
    — codec / physical type / encoding / nested — so 'why did this
    scan fall back' is answerable before running anything. Best-effort:
    unreadable files stay silent (the runtime path re-checks)."""
    try:
        import pyarrow.parquet as pq

        from ..io.parquet_device import fallback_reasons
        pf = pq.ParquetFile(node.paths[0])
        if pf.metadata.num_row_groups == 0:
            return
        cols = (node.columns if node.columns is not None
                else [f.name for f in node.schema.fields])
        for name, (cat, detail) in fallback_reasons(pf, 0,
                                                    cols).items():
            add(WILL_FALLBACK,
                f"scan device-decode fallback ({cat}): column "
                f"'{name}' decodes on host pyarrow — {detail}")
    except Exception:
        return


def _audit_node(meta, path: str, depth: int, findings: List[Verdict],
                tree_lines: List[str], conf, counter: List[int]):
    from ..plan import logical as L
    counter[0] += 1
    node = meta.node
    lore = getattr(meta.exec_node, "lore_id", None)
    local: List[Verdict] = []

    def add(kind: str, reason: str):
        local.append(Verdict(kind, reason, node.node_name(), path, lore))

    # planner tagging verdicts (RapidsMeta willNotWork / host analogs)
    for r in meta.reasons:
        add(WILL_NOT_WORK, r)
    for r in meta.host_reasons:
        add(WILL_FALLBACK, f"host fallback: {r}")
    # operators that are host/python by construction
    if isinstance(node, (L.MapInPandas, L.GroupedMapInPandas,
                         L.CoGroupInPandas)):
        add(WILL_FALLBACK,
            "python_exec: rows cross to a pooled python worker process "
            "as Arrow IPC (device pipeline breaks at this node)")
    if isinstance(node, L.ParquetScan):
        _audit_parquet_scan(node, add)
    # every bound expression the node carries
    for role, b in _bound_exprs(node):
        _audit_expr(b, role, add)

    findings.extend(local)
    worst = max((v.kind for v in local), key=_RANK.get, default=OK)
    lore_tag = f" [loreId={lore}]" if lore is not None else ""
    line = f"{'  ' * depth}{_TAG[worst]}{lore_tag} {node.describe()}"
    if local:
        line += "  <-- " + "; ".join(
            f"{v.kind}: {v.reason}" for v in local)
    tree_lines.append(line)
    many = len(meta.children) > 1
    for i, c in enumerate(meta.children):
        step = f"{i}:{c.node.node_name()}" if many else c.node.node_name()
        _audit_node(c, f"{path}/{step}", depth + 1, findings, tree_lines,
                    conf, counter)


def audit_plan(meta, conf) -> AuditReport:
    """Audit a tagged (and, when conversion succeeded, converted)
    PlanMeta tree. Safe to run on every plan(): a pure tree walk, no
    device work."""
    findings: List[Verdict] = []
    tree_lines: List[str] = []
    counter = [0]
    # plan-wide recompile checks: capacities escaping the power-of-two
    # bucketing (columnar/column.py bucket_capacity) compile one XLA
    # program per distinct batch shape
    from ..config import BATCH_SIZE_ROWS, MAX_READER_BATCH_SIZE_ROWS
    root_name = meta.node.node_name()
    root_lore = getattr(meta.exec_node, "lore_id", None)
    for entry, label in ((BATCH_SIZE_ROWS, "sql.batchSizeRows"),
                         (MAX_READER_BATCH_SIZE_ROWS,
                          "sql.reader.batchSizeRows")):
        n = conf.get(entry)
        if n and not _is_pow2(n):
            findings.append(Verdict(
                RECOMPILE_RISK,
                f"conf {label}={n} is not a power of two: full batches "
                f"take capacities outside the power-of-two buckets "
                f"(columnar/column.py bucket_capacity), so XLA compiles "
                f"a fresh program per operator for that shape",
                root_name, root_name, root_lore))
    _audit_node(meta, root_name, 0, findings, tree_lines, conf, counter)
    return AuditReport(findings, tree_lines, counter[0])
