"""tpulint: AST rules for the engine's own JAX discipline.

The engine's performance contract is enforced by convention at ~60
hand-audited call sites: device->host syncs go through
`utils/transfer.fetch` (async-overlapped, the single D2H chokepoint),
`block_until_ready` lives only inside the conf-gated metric timers, and
jit-traced code keeps shapes bucketed and literals weak-typed. This
module turns those conventions into machine-checked rules (consumed by
`tools/tpulint.py` and the tier-1 `tests/test_lint_clean.py`):

  host-sync        np.asarray / jax.device_get / .item() in a module
                   that imports jax — an implicit device->host sync that
                   bypasses the fetch() chokepoint and serializes the
                   dispatch pipeline
  block-sync       block_until_ready outside the conf-gated metric
                   timers (utils/metrics.py `sql.metrics.sync`)
  jit-static-shape a jit-traced function building shapes from a traced
                   parameter (missing static_argnums) or from a closure
                   capture (every distinct value compiles a fresh XLA
                   program)
  strong-literal   numpy-typed scalar constants materialized inside
                   traced code (jnp.array(0.5), np.float32(2)): strong
                   dtypes defeat weak-type promotion and can split the
                   compile cache — plain Python literals stay weak
  donate-missing   a jit-traced consume-and-replace function (returns
                   `param.at[...].set(...)`) without donate_argnums:
                   XLA cannot reuse the input buffer
  jit-instance     a `jax.jit(...)` call inside an exec/ operator method
                   (assigned to `self.*` or a per-instance memo dict):
                   the program dies with the instance, so a fresh
                   same-shaped query re-compiles — route through
                   `runtime/program_cache.cached_program` (class-level
                   `@jax.jit` decorators are already process-global and
                   are not flagged)
  ctx-cancel       an exec/ batch loop over execute_partition /
                   execute_all whose body never calls
                   `ctx.check_cancel()`: a cancelled or timed-out query
                   would run the operator to completion instead of
                   stopping at the next batch boundary (the query
                   service's cooperative-cancellation contract)
  fp-unstable-attr a plan/ or exec/ node attribute visible to the
                   structural fingerprints (plan/reuse.node_fp,
                   runtime/program_cache.expr_fp) assigned from a
                   process-global counter, id(), uuid, or a clock:
                   same-shaped plans stop deduplicating and the
                   cross-query caches miss forever. Identity attrs must
                   be fingerprint-skipped names (`_op_id`, `lore_id`,
                   `_cached`, `_jit*`, `_*_cache`) or underscore-private
  retry-swallows-cancel
                   a broad `except Exception` (or bare except) inside a
                   retry loop whose handler neither re-raises nor
                   consults the cancellation/transience classifiers:
                   the loop would eat QueryCancelled/KeyboardInterrupt
                   and retry a query the user already killed — retry
                   handlers must re-raise, or route through
                   is_transient_error/is_oom_error/check_cancel
  span-leak        a tracing span opened imperatively
                   (`tracing.open_span(...)`) whose result is never
                   `.end()`-ed in a `finally` (and not handed to the
                   caller): a leaked span never records — Span only
                   emits on end — and every child opened under it
                   mis-parents, so the trace silently loses that edge.
                   `with tracing.span(...)` closes itself and is the
                   preferred shape; deferred-close root spans (ended by
                   `tracing.finish`) carry allow markers
  allow-no-reason  a `# tpulint: allow[...]` marker without a reason —
                   every accepted violation must say why

Intentional sites carry an inline marker on the flagged line (or the
line above):

    x = np.asarray(buf)  # tpulint: allow[host-sync] buf is already host

Everything else lands in the committed baseline
(`tools/tpulint_baseline.json`) or fails the run.
"""
from __future__ import annotations

import ast
import builtins
import json
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

__all__ = ["Violation", "RULES", "lint_source", "lint_file",
           "lint_paths", "load_baseline", "diff_baseline",
           "baseline_entries"]

MARKER_RE = re.compile(
    r"#\s*tpulint:\s*allow\[([A-Za-z0-9_,\- ]+)\]\s*(.*)")

_BUILTINS = set(dir(builtins))

# shape-constructing callables whose first positional argument is a shape
_SHAPE_CTORS = {"zeros", "ones", "full", "empty", "arange"}
# numpy scalar-dtype constructors that produce strong-typed constants
_STRONG_CTORS = {"float16", "float32", "float64", "int8", "int16",
                 "int32", "int64", "uint8", "uint16", "uint32", "uint64",
                 "bool_", "array", "asarray"}


class Violation:
    __slots__ = ("path", "line", "col", "rule", "message", "snippet")

    def __init__(self, path: str, line: int, col: int, rule: str,
                 message: str, snippet: str):
        self.path = path
        self.line = line
        self.col = col
        self.rule = rule
        self.message = message
        self.snippet = snippet

    def key(self) -> Tuple[str, str, str]:
        """Line numbers shift; identity is (file, rule, code text)."""
        return (self.path, self.rule, self.snippet)

    def describe(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule}: "
                f"{self.message}")

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "message": self.message,
                "snippet": self.snippet}

    def __repr__(self):
        return f"Violation({self.describe()})"


class _ModuleCtx:
    """Per-module facts the rules share: import aliases + markers."""

    def __init__(self, tree: ast.Module, lines: List[str], path: str):
        self.tree = tree
        self.lines = lines
        self.path = path
        self.np_aliases: Set[str] = set()
        self.jnp_aliases: Set[str] = set()
        self.jax_aliases: Set[str] = set()
        self.from_jax: Set[str] = set()       # from jax import jit, ...
        self.module_names: Set[str] = set()
        for node in tree.body:
            self._top_level(node)
        # alias collection must also see function-local imports
        # (several engine modules do `import jax` inside a method)
        for node in ast.walk(tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._collect_imports(node)
        self.imports_jax = bool(self.jax_aliases or self.jnp_aliases
                                or self.from_jax)
        # line -> (set of allowed rules, has_reason)
        self.markers: Dict[int, Tuple[Set[str], bool]] = {}
        for i, raw in enumerate(lines, start=1):
            m = MARKER_RE.search(raw)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.markers[i] = (rules, bool(m.group(2).strip()))

    def _collect_imports(self, node):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = a.asname or a.name.split(".")[0]
                self.module_names.add(name)
                if a.name == "numpy":
                    self.np_aliases.add(a.asname or "numpy")
                elif a.name == "jax.numpy":
                    self.jnp_aliases.add(a.asname or "jax")
                elif a.name == "jax" or a.name.startswith("jax."):
                    self.jax_aliases.add(a.asname or "jax")
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                self.module_names.add(a.asname or a.name)
            if node.module == "jax":
                self.from_jax.update(a.asname or a.name
                                     for a in node.names)
            elif node.module == "jax.numpy":
                self.jnp_aliases.update(
                    a.asname or a.name for a in node.names
                    if a.name == "numpy")

    def _top_level(self, node):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            pass          # handled by the _collect_imports walk
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            self.module_names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        self.module_names.add(n.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(node.target, ast.Name):
                self.module_names.add(node.target.id)
        elif isinstance(node, (ast.If, ast.Try)):
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.stmt):
                    self._top_level(sub)

    def allowed(self, rule: str, line: int) -> bool:
        for ln in (line, line - 1):
            ent = self.markers.get(ln)
            if ent and (rule in ent[0] or "all" in ent[0]):
                return True
        return False

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


def _call_root(func) -> Optional[str]:
    """'np' for np.asarray, 'jax' for jax.device_get, None otherwise."""
    if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                      ast.Name):
        return func.value.id
    return None


class _JitInfo:
    __slots__ = ("is_jit", "static_names", "has_donate")

    def __init__(self, is_jit, static_names, has_donate):
        self.is_jit = is_jit
        self.static_names = static_names
        self.has_donate = has_donate


def _jit_info(fn: ast.FunctionDef, ctx: _ModuleCtx) -> _JitInfo:
    """Detect @jax.jit / @jit / @partial(jax.jit, ...) decoration and
    resolve static/donated parameter names."""
    params = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]

    def is_jit_ref(e) -> bool:
        if isinstance(e, ast.Name):
            return e.id == "jit" and ("jit" in ctx.from_jax
                                      or "jit" in ctx.module_names)
        return (isinstance(e, ast.Attribute) and e.attr == "jit"
                and isinstance(e.value, ast.Name)
                and e.value.id in ctx.jax_aliases)

    for dec in fn.decorator_list:
        call = None
        if is_jit_ref(dec):
            return _JitInfo(True, set(), False)
        if isinstance(dec, ast.Call):
            if is_jit_ref(dec.func):
                call = dec
            elif (isinstance(dec.func, ast.Name)
                  and dec.func.id == "partial" and dec.args
                  and is_jit_ref(dec.args[0])):
                call = dec
            elif (isinstance(dec.func, ast.Attribute)
                  and dec.func.attr == "partial" and dec.args
                  and is_jit_ref(dec.args[0])):
                call = dec
        if call is None:
            continue
        static: Set[str] = set()
        has_donate = False
        for kw in call.keywords:
            if kw.arg in ("static_argnums", "static_argnames"):
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant):
                        if isinstance(n.value, int) \
                                and 0 <= n.value < len(params):
                            static.add(params[n.value])
                        elif isinstance(n.value, str):
                            static.add(n.value)
            elif kw.arg in ("donate_argnums", "donate_argnames"):
                has_donate = True
        return _JitInfo(True, static, has_donate)
    return _JitInfo(False, set(), False)


def _local_names(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                     ast.Store):
            out.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(node.name)
            out.update(a.arg for a in (node.args.posonlyargs
                                       + node.args.args
                                       + node.args.kwonlyargs))
            if node.args.vararg:
                out.add(node.args.vararg.arg)
            if node.args.kwarg:
                out.add(node.args.kwarg.arg)
        elif isinstance(node, ast.comprehension):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            out.add(node.name)
    return out


def _shape_position_names(fn: ast.FunctionDef,
                          ctx: _ModuleCtx) -> Iterator[Tuple[str, int,
                                                             int]]:
    """Names appearing where a value becomes a SHAPE inside `fn`: the
    first argument of jnp.zeros/ones/full/empty/arange (and .reshape
    args), or slice bounds."""
    ctors = ctx.jnp_aliases | ctx.np_aliases

    def names_in(e):
        # `x.shape[0]`-derived values are static under jit — skip the
        # whole subtree of shape-like attribute accesses
        if isinstance(e, ast.Attribute) and e.attr in (
                "shape", "size", "ndim", "dtype"):
            return
        if isinstance(e, ast.Name):
            yield e
            return
        for child in ast.iter_child_nodes(e):
            yield from names_in(child)

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id in ctors
                    and f.attr in _SHAPE_CTORS and node.args):
                for n in names_in(node.args[0]):
                    yield n.id, n.lineno, n.col_offset
            elif isinstance(f, ast.Attribute) and f.attr == "reshape":
                for a in node.args:
                    for n in names_in(a):
                        yield n.id, n.lineno, n.col_offset
        elif isinstance(node, ast.Slice):
            for part in (node.lower, node.upper):
                if part is not None:
                    for n in names_in(part):
                        yield n.id, n.lineno, n.col_offset


# ---------------------------------------------------------------------
# rules: fn(ctx) -> iterator of (line, col, rule, message)
# ---------------------------------------------------------------------
def rule_host_sync(ctx: _ModuleCtx):
    if not ctx.imports_jax:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        root = _call_root(f)
        if isinstance(f, ast.Attribute) and f.attr == "asarray" \
                and root in ctx.np_aliases:
            yield (node.lineno, node.col_offset, "host-sync",
                   "np.asarray on a (potential) device array is an "
                   "implicit blocking D2H sync — route through "
                   "utils/transfer.fetch (async-overlapped) or mark "
                   "the site if the input is already host memory")
        elif ((isinstance(f, ast.Attribute) and f.attr == "device_get"
               and root in ctx.jax_aliases)
              or (isinstance(f, ast.Name)
                  and f.id == "device_get"
                  and "device_get" in ctx.from_jax)):
            yield (node.lineno, node.col_offset, "host-sync",
                   "jax.device_get blocks without overlapping the D2H "
                   "copies — use utils/transfer.fetch")
        elif isinstance(f, ast.Attribute) and f.attr == "item" \
                and not node.args and not node.keywords:
            yield (node.lineno, node.col_offset, "host-sync",
                   ".item() on a device array is a per-element "
                   "blocking sync — use utils/transfer.fetch_int or "
                   "batch the fetch")


def rule_block_sync(ctx: _ModuleCtx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name == "block_until_ready":
            yield (node.lineno, node.col_offset, "block-sync",
                   "block_until_ready stalls the dispatch pipeline; it "
                   "belongs only inside the conf-gated metric timers "
                   "(utils/metrics.py, sql.metrics.sync)")


def rule_jit_static_shape(ctx: _ModuleCtx):
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        info = _jit_info(fn, ctx)
        if not info.is_jit:
            continue
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)}
        locals_ = _local_names(fn) | params
        seen: Set[Tuple[str, int]] = set()
        for name, line, col in _shape_position_names(fn, ctx):
            if (name, line) in seen:
                continue
            seen.add((name, line))
            if name in info.static_names:
                continue
            if name in params:
                yield (line, col, "jit-static-shape",
                       f"jit-traced function {fn.name!r} builds a shape "
                       f"from parameter {name!r} without declaring it "
                       f"in static_argnums/static_argnames")
            elif name not in locals_ and name not in ctx.module_names \
                    and name not in _BUILTINS:
                yield (line, col, "jit-static-shape",
                       f"jit-traced function {fn.name!r} bakes closure "
                       f"capture {name!r} into a shape: every distinct "
                       f"value compiles a fresh XLA program (acceptable "
                       f"only for power-of-two-bucketed capacities — "
                       f"mark the site with the bucketing reason)")


def rule_strong_literal(ctx: _ModuleCtx):
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        if not _jit_info(fn, ctx).is_jit:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            root = _call_root(f)
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _STRONG_CTORS
                    and root in (ctx.jnp_aliases | ctx.np_aliases)):
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            if (len(node.args) == 1
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, (int, float))
                    and not isinstance(node.args[0].value, bool)):
                yield (node.lineno, node.col_offset, "strong-literal",
                       f"strong-typed scalar constant "
                       f"{ctx.snippet(node.lineno)[:40]!r} inside "
                       f"jit-traced {fn.name!r}: defeats weak-type "
                       f"promotion and can split the compile cache — "
                       f"use a plain Python literal")


def rule_donate_missing(ctx: _ModuleCtx):
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        info = _jit_info(fn, ctx)
        if not info.is_jit or info.has_donate:
            continue
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args)}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            v = node.value
            # return <param>.at[...].set/add/...(...)
            if (isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Attribute)
                    and v.func.attr in ("set", "add", "max", "min",
                                        "multiply")
                    and isinstance(v.func.value, ast.Subscript)
                    and isinstance(v.func.value.value, ast.Attribute)
                    and v.func.value.value.attr == "at"
                    and isinstance(v.func.value.value.value, ast.Name)
                    and v.func.value.value.value.id in params):
                p = v.func.value.value.value.id
                yield (node.lineno, node.col_offset, "donate-missing",
                       f"jit-traced {fn.name!r} consumes and replaces "
                       f"parameter {p!r} (returns {p}.at[...]."
                       f"{v.func.attr}) without donate_argnums: XLA "
                       f"allocates a second buffer instead of updating "
                       f"in place")


def rule_jit_instance(ctx: _ModuleCtx):
    """Flag non-decorator `jax.jit(...)` calls lexically inside an
    operator method (first parameter `self`) in exec/ modules: the
    jitted program is owned by one exec instance, so an identical
    fresh query tree re-traces and re-compiles it. The process-global
    `runtime/program_cache.cached_program` is the replacement. Class-
    level `@jax.jit` staticmethod decorators are a single process-wide
    program already and are excluded (decorators are not Call
    expressions in a method body)."""
    if not re.search(r"(^|/)exec/", ctx.path):
        return

    # decorator expressions (incl. partial(jax.jit, ...)) are exempt
    dec_nodes: Set[int] = set()
    for fn in ast.walk(ctx.tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in fn.decorator_list:
                for n in ast.walk(dec):
                    dec_nodes.add(id(n))

    def is_jit_call(node) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "jit" \
                and isinstance(f.value, ast.Name) \
                and f.value.id in ctx.jax_aliases:
            return True
        return (isinstance(f, ast.Name) and f.id == "jit"
                and "jit" in ctx.from_jax)

    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        args = fn.args.posonlyargs + fn.args.args
        if not args or args[0].arg != "self":
            continue
        for node in ast.walk(fn):
            if id(node) in dec_nodes or not is_jit_call(node):
                continue
            yield (node.lineno, node.col_offset, "jit-instance",
                   f"jax.jit inside exec method {fn.name!r} builds a "
                   f"per-instance program: a fresh same-shaped query "
                   f"re-compiles it — use runtime/program_cache."
                   f"cached_program so the trace is shared process-"
                   f"globally")


def rule_ctx_cancel(ctx: _ModuleCtx):
    """Flag exec/ batch loops (`for ... in <x>.execute_partition(...)`
    or `.execute_all(...)`) whose body never polls the cooperative
    cancel token: the query service (service/query_manager.py) can only
    stop a query at sites that call `ctx.check_cancel()`, so a loop
    without one turns cancel/deadline into a no-op for that operator.
    Comprehension-shaped collectors are not flagged (they cannot host a
    statement; their inner operators carry the checkpoints). Scope:
    exec/ operators plus the AQE stage driver (plan/aqe.py), whose
    replan loop sits between stage barriers and must stay
    cancellable."""
    if not re.search(r"(^|/)(exec/|plan/aqe\.py$)", ctx.path):
        return

    def pulls_batches(e) -> bool:
        for n in ast.walk(e):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ("execute_partition",
                                        "execute_all"):
                return True
        return False

    def body_checks(stmts) -> bool:
        for s in stmts:
            for n in ast.walk(s):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "check_cancel":
                    return True
        return False

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.For) and pulls_batches(node.iter) \
                and not body_checks(node.body):
            yield (node.lineno, node.col_offset, "ctx-cancel",
                   "batch loop over execute_partition/execute_all "
                   "never polls the cancel token: a cancelled or "
                   "timed-out query runs this operator to completion — "
                   "add ctx.check_cancel() at the top of the loop body")


def rule_pool_cancel(ctx: _ModuleCtx):
    """Flag exec/ worker functions handed to a thread pool
    (`<pool>.submit(worker, ...)`) whose body never polls the
    cooperative cancel token: a cancelled query joins the pool's
    futures, so a worker that never calls `ctx.check_cancel()` (or a
    `check_cancel`-polling helper) keeps running map/build work to
    completion after the cancel — the pool drain blocks on it and the
    query's resources stay pinned for the full phase. Scope: exec/
    operators plus the AQE stage driver (plan/aqe.py)."""
    if not re.search(r"(^|/)(exec/|plan/aqe\.py$)", ctx.path):
        return

    submitted: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "submit" and node.args:
            target = node.args[0]
            if isinstance(target, ast.Name):
                submitted.add(target.id)
            elif isinstance(target, ast.Attribute):
                submitted.add(target.attr)

    if not submitted:
        return

    def polls_cancel(fn) -> bool:
        for n in ast.walk(fn):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "check_cancel":
                return True
        return False

    for fn in ast.walk(ctx.tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and fn.name in submitted and not polls_cancel(fn):
            yield (fn.lineno, fn.col_offset, "pool-cancel",
                   f"worker {fn.name!r} is submitted to a thread pool "
                   f"but never polls the cancel token: a cancelled "
                   f"query blocks on the pool drain while this worker "
                   f"runs its whole loop — poll ctx.check_cancel() "
                   f"inside the worker")


#: attribute names the fingerprints skip by contract — identity fields
#: allowed to hold counter values (program_cache.expr_fp skips `_jit*`,
#: `_*_cache`, and these names; plan/reuse.node_fp skips every
#: underscore-prefixed attr)
_FP_SKIPPED_ATTRS = ("_op_id", "lore_id", "_cached")
#: callable last-names whose result differs per process/call: anything
#: they feed into a fingerprint-visible attr splits the caches
_UNSTABLE_CALLS = {"id", "uuid1", "uuid4", "time", "monotonic",
                   "perf_counter", "time_ns", "monotonic_ns", "random",
                   "randint", "token_hex", "urandom", "getrandbits"}
#: next(<counter-ish>) arg name fragments that mark a process-global
#: counter (next(iter(batches)) is data, not identity — not flagged)
_COUNTERISH = ("id", "count", "counter", "seq")


def _fp_exempt_attr(attr: str) -> bool:
    """True when the structural fingerprints skip this attribute name
    (the documented expr_fp/node_fp contract), so unstable values are
    fine there."""
    if attr in _FP_SKIPPED_ATTRS:
        return True
    if attr.startswith("_jit"):
        return True
    if attr.startswith("_") and attr.endswith("_cache"):
        return True
    return False


def _unstable_value(rhs) -> Optional[str]:
    """Describe the first process-unstable expression in `rhs`, or
    None when the value is structural."""
    for n in ast.walk(rhs):
        if not isinstance(n, ast.Call):
            continue
        fname = None
        if isinstance(n.func, ast.Name):
            fname = n.func.id
        elif isinstance(n.func, ast.Attribute):
            fname = n.func.attr
        if fname in _UNSTABLE_CALLS:
            return f"{fname}(...)"
        if fname == "next" and n.args:
            arg = n.args[0]
            argname = None
            if isinstance(arg, ast.Name):
                argname = arg.id
            elif isinstance(arg, ast.Attribute):
                argname = arg.attr
            if argname and any(frag in argname.lower()
                               for frag in _COUNTERISH):
                return f"next({argname})"
    return None


def rule_fp_unstable_attr(ctx: _ModuleCtx):
    """Flag `self.<attr> = <unstable>` in plan/ and exec/ node classes
    where <attr> is visible to the structural fingerprints
    (plan/reuse.node_fp fingerprints every public attr;
    runtime/program_cache.expr_fp additionally sees private attrs that
    are not `_jit*` / `_*_cache` / explicitly skipped) and <unstable>
    draws from a process-global counter, id(), uuid, a clock, or a
    random source. Such attrs make structurally identical plans hash
    differently, silently disabling exchange reuse, the program cache,
    and the cross-query result cache. Identity bookkeeping belongs in
    the fingerprint-skipped names (`_op_id`, `lore_id`, `_cached`,
    `_jit*`, `_*_cache`)."""
    if not re.search(r"(^|/)(plan|exec)/", ctx.path):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            if _fp_exempt_attr(tgt.attr):
                continue
            desc = _unstable_value(node.value)
            if desc is not None:
                yield (node.lineno, node.col_offset, "fp-unstable-attr",
                       f"attribute {tgt.attr!r} is visible to the "
                       f"structural fingerprints (expr_fp/node_fp) but "
                       f"is assigned the process-unstable value {desc}: "
                       f"identical plans stop fingerprint-equal and "
                       f"every cross-query cache misses — rename it to "
                       f"a fingerprint-skipped name (_op_id/lore_id/"
                       f"_cached/_jit*/_*_cache) or derive it "
                       f"structurally")


def rule_unstable_program_key(ctx: _ModuleCtx):
    """Flag `cached_program(..., key=<unstable>)` where the key draws
    from id(), a clock, a uuid, a random source, or a process-global
    counter. The program-cache key IS the sharing contract: an
    unstable component makes every structurally identical site compile
    its own program (cache always misses), and excludes the site from
    warm-pack manifests — keys that cannot match across processes are
    dropped at record time (runtime/warm_pack.py). A site whose program
    genuinely depends on unkeyable instance state must spell it
    `key=("id", id(self))` AND carry an allow marker explaining why,
    like the documented per-instance fallbacks do."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = None
        if isinstance(node.func, ast.Name):
            fname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        if fname != "cached_program":
            continue
        for kw in node.keywords:
            if kw.arg != "key" or kw.value is None:
                continue
            desc = _unstable_value(kw.value)
            if desc is not None:
                yield (node.lineno, node.col_offset,
                       "unstable-program-key",
                       f"cached_program key= contains the process-"
                       f"unstable value {desc}: the entry can never be "
                       f"shared across instances or recorded in a warm "
                       f"pack — derive the key from structural "
                       f"fingerprints (expr_fp/stage_fingerprint/"
                       f"chunk counts) or mark the documented "
                       f"('id', id(self)) fallback with an allow "
                       f"marker")


def rule_mesh_program_key(ctx: _ModuleCtx):
    """Flag shard_map/mesh programs in exec/ that are not built through
    `cached_program()` with a mesh-topology-bearing key. A collective
    program's lowering bakes in the mesh topology — replica groups, ICI
    routing, the device target — so a key that omits
    `mesh_topology_key(...)` lets two topologies share one cache entry:
    the second mesh silently dispatches a program compiled for the
    first (wrong replica groups at best, an XLA runtime error at
    worst), and warm packs recorded on one topology preload into
    processes that can never run them. Every function that traces a
    `shard_map` must register it via `cached_program(..., key=(
    mesh_topology_key(n, axis), ...))`."""
    if not re.search(r"(^|/)exec/", ctx.path):
        return

    def outer_funcs(body):
        for n in body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield n
            elif isinstance(n, ast.ClassDef):
                yield from outer_funcs(n.body)

    def called_name(call: ast.Call) -> Optional[str]:
        if isinstance(call.func, ast.Name):
            return call.func.id
        if isinstance(call.func, ast.Attribute):
            return call.func.attr
        return None

    for fn in outer_funcs(ctx.tree.body):
        smaps = [n for n in ast.walk(fn) if isinstance(n, ast.Call)
                 and called_name(n) in ("shard_map", "_shard_map")]
        if not smaps:
            continue
        cps = [n for n in ast.walk(fn) if isinstance(n, ast.Call)
               and called_name(n) == "cached_program"]
        if not cps:
            yield (smaps[0].lineno, smaps[0].col_offset,
                   "mesh-program-key",
                   f"{fn.name} traces a shard_map program without "
                   f"cached_program(): the collective compiles outside "
                   f"the program cache — invisible to warm packs, the "
                   f"compile pool, and the topology-keying contract")
            continue
        mesh_keyed = False
        for cp in cps:
            for kw in cp.keywords:
                if kw.arg == "key" and kw.value is not None and any(
                        isinstance(n, ast.Call)
                        and called_name(n) == "mesh_topology_key"
                        for n in ast.walk(kw.value)):
                    mesh_keyed = True
        if not mesh_keyed:
            yield (smaps[0].lineno, smaps[0].col_offset,
                   "mesh-program-key",
                   f"{fn.name} registers a shard_map program whose "
                   f"cached_program key= lacks mesh_topology_key(): "
                   f"two mesh topologies would share one cache entry "
                   f"and a warm pack recorded on one would preload "
                   f"into the other — lead the key with "
                   f"mesh_topology_key(n_devices, axis_name)")


#: identifiers whose presence in a broad retry handler shows the author
#: thought about cancellation/transience classification (the classifier
#: helpers, the cancel exception types, and the token itself)
_CANCEL_AWARE_NAMES = {"QueryCancelled", "QueryTimedOut",
                       "KeyboardInterrupt", "GeneratorExit",
                       "CancelToken", "check_cancel",
                       "is_oom_error", "is_transient_error"}
#: a loop (or its enclosing function) is retry-shaped when any bound
#: name smells like retry machinery
_RETRYISH_RE = re.compile(r"retr(y|ies)|attempt|backoff", re.IGNORECASE)


def rule_retry_swallows_cancel(ctx: _ModuleCtx):
    """Flag a broad `except Exception` / `except BaseException` / bare
    `except` inside a retry-shaped loop (the enclosing function or any
    name in the loop matches retry/attempt/backoff) whose handler body
    neither contains a `raise` nor references any cancellation-aware
    name (QueryCancelled, KeyboardInterrupt, CancelToken, check_cancel,
    is_oom_error, is_transient_error). Such a handler retries
    EVERYTHING — including a cancellation the user already issued or a
    deadline the service already enforced — turning "kill this query"
    into "run it max_retries more times". Retry handlers must re-raise
    on the non-transient path or classify before continuing."""

    def broad(h: ast.ExceptHandler) -> bool:
        if h.type is None:
            return True
        elts = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        return any(isinstance(e, ast.Name)
                   and e.id in ("Exception", "BaseException")
                   for e in elts)

    def handler_aware(h: ast.ExceptHandler) -> bool:
        for s in h.body:
            for n in ast.walk(s):
                if isinstance(n, ast.Raise):
                    return True
                if isinstance(n, ast.Name) \
                        and n.id in _CANCEL_AWARE_NAMES:
                    return True
                if isinstance(n, ast.Attribute) \
                        and n.attr in _CANCEL_AWARE_NAMES:
                    return True
        return False

    def retryish(loop, fn_name: Optional[str]) -> bool:
        if fn_name and _RETRYISH_RE.search(fn_name):
            return True
        for n in ast.walk(loop):
            if isinstance(n, ast.Name) and _RETRYISH_RE.search(n.id):
                return True
            if isinstance(n, ast.Attribute) \
                    and _RETRYISH_RE.search(n.attr):
                return True
        return False

    seen: Set[Tuple[int, int]] = set()

    def visit(node, fn_name: Optional[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_name = node.name
        if isinstance(node, (ast.While, ast.For)) \
                and retryish(node, fn_name):
            for n in ast.walk(node):
                if not isinstance(n, ast.Try):
                    continue
                for h in n.handlers:
                    pos = (h.lineno, h.col_offset)
                    if pos in seen:
                        continue
                    if broad(h) and not handler_aware(h):
                        seen.add(pos)
                        yield (h.lineno, h.col_offset,
                               "retry-swallows-cancel",
                               "broad except inside a retry loop "
                               "neither re-raises nor consults a "
                               "cancellation/transience classifier: "
                               "a cancelled or timed-out query would "
                               "be retried instead of dying — "
                               "re-raise QueryCancelled/"
                               "KeyboardInterrupt (or classify with "
                               "is_transient_error) before retrying")
        for child in ast.iter_child_nodes(node):
            yield from visit(child, fn_name)

    yield from visit(ctx.tree, None)


def rule_span_leak(ctx: _ModuleCtx):
    """Flag `open_span(...)` results that are not provably closed: no
    `<name>.end()` inside the finalbody of a try in the same function,
    and the span is not returned to the caller. `with tracing.span(...)`
    closes itself and is never flagged; a discarded or
    attribute-stashed open_span() is always flagged (nothing in scope
    can reliably end it). Scope: the whole engine tree except
    profiler/tracing.py, which defines the API."""
    if re.search(r"(^|/)profiler/tracing\.py$", ctx.path):
        return

    def open_span_call(expr):
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                f = n.func
                nm = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                if nm == "open_span":
                    return n
        return None

    # enclosing-function map: a statement's close obligations are
    # discharged within its own function scope
    func_of = {}

    def _tag(node, fn):
        for child in ast.iter_child_nodes(node):
            func_of[child] = fn
            _tag(child, child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.Lambda)) else fn)

    _tag(ctx.tree, None)

    def _ends_or_returns(fn_node, name) -> bool:
        root = fn_node if fn_node is not None else ctx.tree
        for n in ast.walk(root):
            if isinstance(n, ast.Try):
                for s in n.finalbody:
                    for m in ast.walk(s):
                        if (isinstance(m, ast.Call)
                                and isinstance(m.func, ast.Attribute)
                                and m.func.attr == "end"
                                and isinstance(m.func.value, ast.Name)
                                and m.func.value.id == name):
                            return True
            elif isinstance(n, ast.Return) and n.value is not None:
                for m in ast.walk(n.value):
                    if isinstance(m, ast.Name) and m.id == name:
                        return True
        return False

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            call = open_span_call(node.value)
            targets = node.targets
        elif isinstance(node, ast.Expr):
            call = open_span_call(node.value)
            targets = None
        else:
            continue
        if call is None:
            continue
        if targets is not None and len(targets) == 1 \
                and isinstance(targets[0], ast.Name):
            name = targets[0].id
            if _ends_or_returns(func_of.get(node), name):
                continue
            yield (call.lineno, call.col_offset, "span-leak",
                   f"span `{name}` from open_span() has no `.end()` in "
                   "a finally and is not returned: a leaked span never "
                   "records and its children mis-parent — end it in a "
                   "finally or use `with tracing.span(...)`")
        else:
            yield (call.lineno, call.col_offset, "span-leak",
                   "open_span() result discarded or stored where no "
                   "finally can end it — bind it to a local closed in "
                   "a finally, or use `with tracing.span(...)`")


RULES = {
    "host-sync": rule_host_sync,
    "block-sync": rule_block_sync,
    "jit-static-shape": rule_jit_static_shape,
    "strong-literal": rule_strong_literal,
    "donate-missing": rule_donate_missing,
    "jit-instance": rule_jit_instance,
    "ctx-cancel": rule_ctx_cancel,
    "pool-cancel": rule_pool_cancel,
    "retry-swallows-cancel": rule_retry_swallows_cancel,
    "fp-unstable-attr": rule_fp_unstable_attr,
    "unstable-program-key": rule_unstable_program_key,
    "mesh-program-key": rule_mesh_program_key,
    "span-leak": rule_span_leak,
}


def lint_source(src: str, path: str = "<string>",
                rules=None) -> List[Violation]:
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Violation(path, e.lineno or 0, 0, "parse-error", str(e),
                          "")]
    lines = src.splitlines()
    ctx = _ModuleCtx(tree, lines, path)
    out: List[Violation] = []
    for name, fn in (rules or RULES).items():
        for line, col, rule, msg in (fn(ctx) or ()):
            if ctx.allowed(rule, line):
                continue
            out.append(Violation(path, line, col, rule, msg,
                                 ctx.snippet(line)))
    # a bare allow marker hides a violation without saying why
    for ln, (rnames, has_reason) in sorted(ctx.markers.items()):
        if not has_reason:
            out.append(Violation(
                path, ln, 0, "allow-no-reason",
                f"allow[{','.join(sorted(rnames))}] marker without a "
                f"reason — say why the site is intentional",
                ctx.snippet(ln)))
    out.sort(key=lambda v: (v.line, v.col, v.rule))
    return out


def lint_file(path: str, rel_to: Optional[str] = None) -> List[Violation]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    rel = os.path.relpath(path, rel_to) if rel_to else path
    return lint_source(src, rel.replace(os.sep, "/"))


def lint_paths(paths: List[str],
               rel_to: Optional[str] = None) -> List[Violation]:
    out: List[Violation] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        else:
            out.append(p)
    violations: List[Violation] = []
    for f in out:
        violations.extend(lint_file(f, rel_to))
    return violations


# ---------------------------------------------------------------------
# baseline: accepted pre-existing violations, each with a reason
# ---------------------------------------------------------------------
def load_baseline(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return list(data.get("entries", []))


def baseline_entries(violations: List[Violation],
                     reason: str = "") -> dict:
    return {"version": 1,
            "entries": [{"path": v.path, "rule": v.rule,
                         "snippet": v.snippet, "reason": reason}
                        for v in violations]}


def diff_baseline(violations: List[Violation],
                  baseline: List[dict]
                  ) -> Tuple[List[Violation], List[dict]]:
    """(new violations not in the baseline, stale baseline entries no
    longer observed). Matching is by (path, rule, snippet) with
    multiplicity, so line drift does not churn the baseline."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in baseline:
        k = (e.get("path", ""), e.get("rule", ""), e.get("snippet", ""))
        budget[k] = budget.get(k, 0) + 1
    new: List[Violation] = []
    for v in violations:
        k = v.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            new.append(v)
    stale = []
    for e in baseline:
        k = (e.get("path", ""), e.get("rule", ""), e.get("snippet", ""))
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            stale.append(e)
    return new, stale
