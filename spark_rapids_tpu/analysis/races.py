"""Static data-race auditor: Eraser-style lockset analysis.

The concurrency auditor (deadlocks) and the lifetime auditor (leaks)
leave a third fatal class uncovered: unsynchronized access to shared
mutable state. The engine runs every query across ~15 named thread
pools while promising byte-identical results; a single unlocked
read-modify-write on a shared counter or a check-then-act slot
creation in a shuffle map can silently break that. This pass is the
static half of the race tooling (the runtime half is
runtime/racedep.py): an Eraser-style lockset analysis over the model
built by analysis/core.py.

Access model
------------
core.py's walker records every ``self.attr`` access in every method
with the lexically-held lockset: plain reads, stores (including
``self.attr[k] = v`` and container mutators like
``self.attr.append(x)``), read-modify-writes (``self.x += 1``,
``self.attr[k].append(x)``), check-then-act shapes (``if k not in
self.d: self.d[k] = ...`` / ``if self.x is None: self.x = ...``) and
``self``-escapes during ``__init__``. Accesses are composed
interprocedurally from thread ROOTS — functions nobody calls, pool
worker targets (resolved from ``pool.submit(fn)`` exactly as the
concurrency auditor resolves them) and ``threading.Thread`` targets —
so an access site's lockset is the INTERSECTION over every realizable
path to it, and its thread-context set is the union of root contexts
(``query`` for caller-thread code, ``pool:<prefix>`` per named pool,
``thread:<name>`` per dedicated thread). A pool context is inherently
multi-threaded: one pool reaching an attr already means concurrent
access.

Rules
-----
  unlocked-shared-write  attr written from >= 2 contexts (or written
                         in one and read in another) with an empty
                         lockset intersection across the accesses
  compound-rmw           ``self.x += 1`` / ``self.d[k].append(v)`` on
                         a shared attr outside any lock — the GIL
                         makes each bytecode atomic, not the
                         read-modify-write
  check-then-act         ``if k not in self.d: self.d[k] = ...`` /
                         ``if self.x is None: self.x = ...`` on shared
                         state without a lock: two threads both pass
                         the check
  publish-before-init    ``self`` stored into a cross-thread-visible
                         structure (registry slot, queue, pool) before
                         all fields are assigned in ``__init__``

Exemptions (principled, not noise suppression)
----------------------------------------------
  init-before-first-submit  writes in ``__init__`` (and ``_init*``
                            helpers), or writes that lexically precede
                            the function's first pool submission:
                            nothing else can run yet
  immutable-after-publish   attrs whose every write is init-phase:
                            concurrent reads of frozen state are fine
  queue/Future hand-off     attrs assigned from Queue/Executor/Future
                            constructors or ``.get()``/``.result()``:
                            the object IS the synchronization point
  lockdep-guarded           a non-empty lockset intersection (plain or
                            lockdep-wrapped locks) is the fix, not a
                            finding

Remaining intentional sites carry the shared inline marker::

    self._hits += 1  # tpulint: allow[compound-rmw] stats are advisory

Violations share lint_rules' (path, rule, snippet) identity; the
baseline (tools/tpulint_races_baseline.json) is committed EMPTY and
`tools/tpulint.py --races --check` keeps it that way.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from .core import (Model, PERMIT, build_model, filter_markers)
from .lint_rules import Violation

__all__ = ["RACE_RULES", "analyze_model", "analyze_paths"]

RACE_RULES = ("unlocked-shared-write", "compound-rmw", "check-then-act",
              "publish-before-init")

#: per-function cap on composed access entries (same role as
#: core._SUMMARY_CAP for synchronization events; accesses are denser
#: because every `self.attr` read counts, so the cap is higher)
_ACCESS_CAP = 800


# ---------------------------------------------------------------------
# thread contexts
# ---------------------------------------------------------------------
def _worker_roots(model: Model) -> Dict[str, Set[str]]:
    """fid -> context labels for resolved pool-worker / Thread
    targets."""
    roots: Dict[str, Set[str]] = {}
    for pkey, pool in model.pools.items():
        for owner_fid, ref in pool.workers:
            owner = model.funcs.get(owner_fid)
            fid = model.resolve_ref(owner, ref) if owner else None
            if fid is not None:
                roots.setdefault(fid, set()).add(f"pool:{pkey}")
    for owner_fid, ref, nm in model.thread_targets:
        owner = model.funcs.get(owner_fid)
        fid = model.resolve_ref(owner, ref) if owner else None
        if fid is not None:
            roots.setdefault(fid, set()).add(f"thread:{nm or ref[1]}")
    return roots


def _contexts(model: Model,
              wroots: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
    """fid -> every thread context that can execute it. Worker contexts
    propagate through each worker target's call closure; everything
    reachable from non-worker functions additionally runs on the
    caller ('query') thread."""
    ctx: Dict[str, Set[str]] = {fid: set() for fid in model.funcs}
    by_label: Dict[str, List[str]] = {}
    for fid, labels in wroots.items():
        for lb in labels:
            by_label.setdefault(lb, []).append(fid)
    for lb, roots in sorted(by_label.items()):
        for fid in model.reachable_from(roots):
            if fid in ctx:
                ctx[fid].add(lb)
    main_roots = [fid for fid in model.funcs if fid not in wroots]
    for fid in model.reachable_from(main_roots):
        if fid in ctx:
            ctx[fid].add("query")
    return ctx


def _roots(model: Model, wroots: Dict[str, Set[str]]) -> List[str]:
    """Realization roots: true entry points (no static caller), every
    worker/thread target, plus any function left uncovered (methods
    only reachable through polymorphic calls the resolver skips)."""
    called: Set[str] = set()
    for fn in model.funcs.values():
        for ref, _line, _held in fn.calls:
            callee = model.resolve_ref(fn, ref)
            if callee is not None:
                called.add(callee)
    roots = [fid for fid in model.funcs if fid not in called]
    roots += [fid for fid in wroots if fid in called]
    covered = model.reachable_from(roots)
    roots += [fid for fid in model.funcs if fid not in covered]
    return roots


# ---------------------------------------------------------------------
# interprocedural access composition
# ---------------------------------------------------------------------
def _summarize_accesses(model: Model, fid: str, memo: dict,
                        _stack: Optional[set] = None) -> list:
    """(access-event, held-keys, site-fid) realizable by calling `fid`,
    held-sets relative to its entry — core.Model.summarize over the
    access stream instead of the synchronization stream."""
    if fid in memo:
        return memo[fid]
    stack = _stack if _stack is not None else set()
    if fid in stack:
        return []
    stack.add(fid)
    fn = model.funcs[fid]
    out: List[tuple] = []
    for ev, held in fn.accesses:
        out.append((ev, held, fid))
    for ref, _line, held in fn.calls:
        callee = model.resolve_ref(fn, ref)
        if callee is None or callee == fid:
            continue
        for ev, add_held, site in _summarize_accesses(model, callee,
                                                      memo, stack):
            out.append((ev, held | add_held, site))
            if len(out) >= _ACCESS_CAP:
                break
        if len(out) >= _ACCESS_CAP:
            break
    stack.discard(fid)
    out = out[:_ACCESS_CAP]
    memo[fid] = out
    return out


class _Site:
    """One access site with facts merged across every realization."""

    __slots__ = ("ev", "fid", "held", "ctxs", "init", "handoff",
                 "pre_submit")

    def __init__(self, ev, fid, held, ctxs):
        self.ev = ev
        self.fid = fid
        self.held = set(held)     # lockset INTERSECTION across paths
        self.ctxs = set(ctxs)     # context UNION across paths
        self.init = False
        self.handoff = ev.wclass == "handoff"
        self.pre_submit = False


def _locks(model: Model, held) -> Set[str]:
    """Mutual-exclusion members of a held-set (permits are counted
    admission, not exclusion). A Condition constructed over a lock IS
    that lock: canonicalize through cond_pairs so `with self._cond:`
    and `with self._lock:` intersect non-empty."""
    out = set()
    for h in held:
        if h == PERMIT:
            continue
        out.add(model.cond_pairs.get(h) or h)
    return out


def _in_init(model: Model, fid: str) -> bool:
    """True when `fid` is __init__ / an _init* helper, or nested in
    one (construction-phase code: single-threaded by contract)."""
    fn = model.funcs.get(fid)
    while fn is not None:
        if fn.name == "__init__" or fn.name.startswith("_init"):
            return True
        fn = model.funcs.get(fn.parent) if fn.parent else None
    return False


def _first_submit_line(model: Model, fid: str) -> Optional[int]:
    fn = model.funcs.get(fid)
    if fn is None:
        return None
    lines = [ev.line for ev, _h in fn.events if ev.kind == "submit"]
    return min(lines) if lines else None


def _confined_classes(model: Model,
                      wroots: Dict[str, Set[str]]) -> Set[str]:
    """Classes whose instances are thread-confined: every observed
    constructor site is a plain local assignment or a temporary method
    receiver, and no method of the class is a pool-worker/Thread
    target. Many contexts can run `_Parser.next` — each on its own
    per-call instance; that is not sharing."""
    rootcls: Set[str] = set()
    for fid in wroots:
        fn = model.funcs.get(fid)
        if fn is not None and fn.cls:
            rootcls.add(fn.cls)
    out: Set[str] = set()
    for (_mod, cls, _name) in model.methods:
        if cls in rootcls or cls in out:
            continue
        shapes = model.ctors.get(cls)
        if shapes and all(sh in ("local", "recv") for sh in shapes):
            out.add(cls)
    return out


def _shared(ctxs: Set[str]) -> bool:
    """Two distinct contexts, or any pool context (a pool's own
    workers already race each other)."""
    return len(ctxs) >= 2 or any(c.startswith("pool:") for c in ctxs)


def _collect_sites(model: Model, wroots: Dict[str, Set[str]]
                   ) -> Dict[tuple, _Site]:
    ctx = _contexts(model, wroots)
    memo: dict = {}
    sites: Dict[tuple, _Site] = {}
    for root in _roots(model, wroots):
        rctx = ctx.get(root) or {"query"}
        for ev, held, site_fid in _summarize_accesses(model, root, memo):
            path = model.funcs[site_fid].path
            k = (path, ev.line, ev.col, ev.kind, ev.resource, ev.wclass)
            s = sites.get(k)
            if s is None:
                sites[k] = _Site(ev, site_fid, held, rctx)
            else:
                s.held &= set(held)
                s.ctxs |= rctx
    for s in sites.values():
        s.init = _in_init(model, s.fid)
        if not s.init:
            first = _first_submit_line(model, s.fid)
            if first is not None and s.ev.line < first:
                s.pre_submit = True
    return sites


# ---------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------
def analyze_model(model: Model) -> List[Violation]:
    wroots = _worker_roots(model)
    sites = _collect_sites(model, wroots)
    confined = _confined_classes(model, wroots)
    out: List[Violation] = []
    seen: Set[tuple] = set()

    def add(path: str, line: int, col: int, rule: str, msg: str):
        k = (path, line, rule)
        if k in seen:
            return
        seen.add(k)
        out.append(Violation(path, line, col, rule, msg,
                             model.snippet(path, line)))

    # group per class.attr
    attrs: Dict[str, List[_Site]] = {}
    for s in sites.values():
        if s.ev.kind == "publish":
            continue
        attrs.setdefault(s.ev.resource, []).append(s)

    for key in sorted(attrs):
        if key.split(".", 1)[0] in confined:
            continue
        acc = attrs[key]
        # queue/Future/Event hand-off: the attr holds a synchronization
        # object (assigned from a Queue/Executor/Future constructor or
        # received through .get()/.result()); mutating method calls on
        # it (`self._idle.clear()`) are synchronized operations
        if any(s.ev.wclass == "handoff" for s in acc):
            continue
        ctxs: Set[str] = set()
        for s in acc:
            ctxs |= s.ctxs
        shared = _shared(ctxs)

        writes = [s for s in acc if s.ev.kind in ("write", "rmw")]
        eff_writes = sorted(
            (s for s in writes
             if not (s.init or s.handoff or s.pre_submit)),
            key=lambda s: (s.ev.line, s.ev.col))
        # immutable-after-publish / init-only / pure hand-off: no
        # post-construction raw write -> nothing to race on
        if eff_writes and shared:
            racy = eff_writes + sorted(
                (s for s in acc
                 if s.ev.kind in ("read", "checkact") and not s.init),
                key=lambda s: (s.ev.line, s.ev.col))
            lockset = _locks(model, racy[0].held)
            for s in racy[1:]:
                lockset &= _locks(model, s.held)
            if not lockset:
                # anchor at the first UNLOCKED access (write preferred)
                # so the finding — and any allow-marker — lands on the
                # site missing the lock, not on a correctly-locked
                # write whose counterpart read is the actual hazard
                unlocked = [s for s in racy if not _locks(model, s.held)]
                w = next((s for s in unlocked
                          if s.ev.kind in ("write", "rmw")),
                         unlocked[0] if unlocked else eff_writes[0])
                wr = w.ev.kind in ("write", "rmw")
                # counterpart: a write when the anchor is a read, any
                # other access when the anchor is a write
                other = next(
                    (s for s in racy
                     if s is not w
                     and (wr or s.ev.kind in ("write", "rmw"))), w)
                fn = model.funcs[w.fid]
                verb = "written" if wr else "read"
                add(fn.path, w.ev.line, w.ev.col,
                    "unlocked-shared-write",
                    f"{key} is {verb} unlocked in {fn.qual} and "
                    f"accessed from contexts {sorted(ctxs)} with no "
                    f"common lock (counterpart at "
                    f"{model.funcs[other.fid].path}:{other.ev.line}) — "
                    f"guard every access with one lock, or make the "
                    f"attr immutable after construction")

        if not shared:
            continue
        for s in sorted(acc, key=lambda s: (s.ev.line, s.ev.col)):
            if s.init or s.handoff or s.pre_submit:
                continue
            if _locks(model, s.held):
                continue
            fn = model.funcs[s.fid]
            if s.ev.kind == "rmw":
                add(fn.path, s.ev.line, s.ev.col, "compound-rmw",
                    f"read-modify-write of shared {key} in {fn.qual} "
                    f"({s.ev.wclass}) outside any lock — the GIL does "
                    f"not make `+=`/slot-mutation atomic; contexts "
                    f"{sorted(ctxs)} can interleave and lose updates")
            elif s.ev.kind == "checkact":
                add(fn.path, s.ev.line, s.ev.col, "check-then-act",
                    f"check-then-act on shared {key} in {fn.qual} "
                    f"({s.ev.wclass}) without a lock — two contexts "
                    f"({sorted(ctxs)}) can both pass the check and "
                    f"double-create/overwrite the slot; hold a lock "
                    f"across test and store (or use setdefault)")

    # publish-before-init: self escapes __init__ before the last field
    # assignment (another thread can observe a half-built instance)
    for fid in sorted(model.funcs):
        fn = model.funcs[fid]
        if fn.name != "__init__":
            continue
        pubs = [ev for ev, _h in fn.accesses if ev.kind == "publish"]
        if not pubs:
            continue
        field_writes = [ev for ev, _h in fn.accesses
                        if ev.kind in ("write", "rmw")]
        last = max((ev.line for ev in field_writes), default=0)
        for pub in pubs:
            if pub.line < last:
                add(fn.path, pub.line, pub.col, "publish-before-init",
                    f"{fn.qual} publishes `self` into "
                    f"`{pub.resource.split('.', 1)[1]}` "
                    f"({pub.wclass}) at line {pub.line} before its "
                    f"last field assignment at line {last} — another "
                    f"thread can observe a half-constructed instance; "
                    f"publish as the final statement")

    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


# ---------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------
def analyze_paths(paths: List[str], rel_to: Optional[str] = None,
                  model: Optional[Model] = None) -> List[Violation]:
    """Build the model, run the race rules, drop marker-allowed sites.
    Violations share lint_rules' (path, rule, snippet) identity, so
    tpulint's baseline/diff machinery applies unchanged."""
    model = model or build_model(paths, rel_to)
    return filter_markers(model, analyze_model(model))
