"""Static resource-lifetime auditor: acquire/release shape analysis.

The engine's hardest shipped bugs were lifetime bugs, not logic bugs:
PR 4 released a staging-pool lease before `block_until_ready`, letting
queued XLA kernels read recycled host memory. This pass models the
engine's typed acquire/release resources —

  PinnedStagingPool leases     x = pool.acquire(n)   / x.release()
  SpillStore handles           x = store.add_batch(b)/ x.close()
  Device/Host byte reservations  mgr.reserve(n)      / mgr.release(n)
  TpuSemaphore permits / rider slots  sem.acquire()  / sem.release()

— and reports four fatal shapes as tpulint Violations (same identity,
marker and baseline machinery as lint_rules.py / concurrency.py):

  leak-on-exception     an acquisition whose release is not dominated
                        by try/finally or a context manager, and that
                        never escapes to an owner (returned, stored,
                        registered for cleanup): any raise between —
                        including cancel-checkpoint exits — leaks it.
  double-release        the same resource released twice on some path.
  use-after-release     the resource (or a buffer derived from it via
                        .view()/.array/frombuffer aliasing) flows into
                        a call after its release on some path.
  release-before-sync   a lease whose buffer fed a jnp/jax op released
                        with no intervening block_until_ready/fetch —
                        the exact PR 4 race (archived under
                        tests/fixtures/lifetime/ and re-detected).
  unbalanced-transfer   a tracked resource handed across a thread/pool
                        boundary (pool.submit / Thread target) whose
                        resolved worker has no protected release of
                        the corresponding parameter: nobody owns it on
                        the worker's error path.

Call resolution for unbalanced-transfer reuses analysis/core.py's Model
(lexical-scope chain, unique-method heuristic); allow-markers
(`# tpulint: allow[rule] reason`) and the JSON baseline flow through
tools/tpulint.py --lifetime exactly like the other analyzers.

The analysis is per-function and intentionally conservative: a
resource that escapes its acquiring function (ownership transfer to a
handle list, a cleanup registry, the caller) is not second-guessed —
interprocedural balance is the runtime ledger's job
(runtime/ledger.py), which this pass pairs with.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from .core import (Model, _allowed, _file_markers, _is_riderish,
                   _is_semish, _iter_py, _last_name, _mod_name,
                   build_model)
from .lint_rules import Violation

__all__ = ["LIFETIME_RULES", "analyze_paths", "analyze_source"]

LIFETIME_RULES = ("leak-on-exception", "double-release",
                  "use-after-release", "release-before-sync",
                  "unbalanced-transfer")

#: attribute names whose access on a lease creates an aliasing derived
#: value (the PR 4 race flows through exactly these)
_ALIAS_ATTRS = ("array", "view")
#: call names that propagate aliasing from an argument to the result
_ALIAS_CALLS = ("frombuffer", "asarray", "memoryview", "ascontiguousarray")
#: calls that act as a device-sync barrier for release-before-sync
_SYNC_CALLS = ("block_until_ready", "fetch")


def _root_name(expr) -> Optional[str]:
    """Leftmost identifier of a Name/Attribute/Call chain."""
    while isinstance(expr, (ast.Attribute, ast.Call)):
        expr = expr.func if isinstance(expr, ast.Call) else expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _is_poolish(expr) -> bool:
    n = _last_name(expr)
    if not n:
        return False
    low = n.lower()
    return "pool" in low or "staging" in low


def _is_mgrish(expr) -> bool:
    n = _last_name(expr)
    if not n:
        return False
    low = n.lower()
    return low in ("dm", "hm") or "mgr" in low or "manager" in low


def _acquisition(call) -> Optional[Tuple[str, str]]:
    """(kind, site-tag) when `call` acquires a tracked handle-like
    resource bound to a variable; None otherwise."""
    if not isinstance(call, ast.Call) or not isinstance(
            call.func, ast.Attribute):
        return None
    base, attr = call.func.value, call.func.attr
    if attr == "acquire" and _is_poolish(base):
        return "staging-lease", f"{_last_name(base)}.acquire"
    if attr == "add_batch":
        return "spill-handle", f"{_last_name(base)}.add_batch"
    return None


class _Rec:
    """Per-variable lifetime state inside one function walk."""

    __slots__ = ("var", "kind", "line", "tag", "released", "rel_line",
                 "protected", "escaped", "fed", "synced", "reported")

    def __init__(self, var: str, kind: str, line: int, tag: str):
        self.var = var
        self.kind = kind
        self.line = line
        self.tag = tag
        self.released = False     # released on SOME path walked so far
        self.rel_line = 0
        self.protected = False    # some release sits in a finalbody /
        # the acquisition is a with-item
        self.escaped = False      # ownership transferred out
        self.fed = False          # buffer flowed into a jnp/jax op
        self.synced = True        # block_until_ready seen since feed
        self.reported = set()     # rules already emitted for this var

    def copy(self) -> "_Rec":
        r = _Rec(self.var, self.kind, self.line, self.tag)
        r.released, r.rel_line = self.released, self.rel_line
        r.protected, r.escaped = self.protected, self.escaped
        r.fed, r.synced = self.fed, self.synced
        r.reported = self.reported   # shared: one report per var
        return r


class _PairRec:
    """One acquire half of a paired-call resource (byte reservation,
    permit): `base.reserve(n)` / `sem.acquire()` matched against a
    later `base.release(...)` in the same function."""

    __slots__ = ("base", "kind", "line", "released", "protected")

    def __init__(self, base: str, kind: str, line: int):
        self.base = base
        self.kind = kind
        self.line = line
        self.released = False
        self.protected = False


class _FnLifetime:
    """Sequential walk of one function body with some-path branch
    semantics: If/Try branches are walked on copies and merged with
    union (a release on SOME path arms use-after/double-release on the
    code that follows)."""

    def __init__(self, auditor: "_ModuleAuditor", funcdef, cls_name):
        self.a = auditor
        self.fn = funcdef
        self.cls = cls_name
        self.recs: Dict[str, _Rec] = {}
        self.derived: Dict[str, str] = {}    # alias var -> lease var
        self.pairs: List[_PairRec] = []
        self.in_finally = False

    # -- expression helpers -------------------------------------------
    def _names_in(self, node) -> set:
        return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}

    def _lease_roots(self, node) -> set:
        """Tracked lease vars referenced by `node`, through derived
        aliases."""
        roots = set()
        for nm in self._names_in(node):
            if nm in self.recs:
                roots.add(nm)
            elif nm in self.derived:
                roots.add(self.derived[nm])
        return roots

    def _alias_source(self, value) -> Optional[str]:
        """Lease var that `value` aliases (one hop through _ALIAS_ATTRS
        / _ALIAS_CALLS), or None."""
        # slicing an aliasing view still aliases the same memory
        while isinstance(value, ast.Subscript):
            value = value.value
        if isinstance(value, ast.Attribute) and value.attr in _ALIAS_ATTRS:
            root = value.value
            if isinstance(root, ast.Name):
                return self._resolve_lease(root.id)
        if isinstance(value, ast.Call):
            fname = _last_name(value.func)
            # jnp.asarray(...) yields a DEVICE value: its hazard is
            # covered by release-before-sync (feed tracking), not by
            # host-alias use-after-release
            if _root_name(value.func) in self.a.jax_aliases:
                return None
            if fname in _ALIAS_CALLS or fname in _ALIAS_ATTRS:
                for arg in value.args:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name):
                            src = self._resolve_lease(sub.id)
                            if src is not None:
                                return src
        return None

    def _resolve_lease(self, name: str) -> Optional[str]:
        if name in self.recs:
            return name
        return self.derived.get(name)

    # -- event handlers ------------------------------------------------
    def _emit(self, rule: str, line: int, col: int, msg: str):
        self.a.emit(rule, line, col, msg)

    def _note_release(self, var: str, node):
        rec = self.recs.get(var)
        if rec is None:
            return
        if rec.released and "double-release" not in rec.reported:
            rec.reported.add("double-release")
            self._emit(
                "double-release", node.lineno, node.col_offset,
                f"{rec.kind} `{var}` (acquired line {rec.line} via "
                f"{rec.tag}) released again — already released on a "
                f"path through line {rec.rel_line}")
        if rec.fed and not rec.synced \
                and "release-before-sync" not in rec.reported:
            rec.reported.add("release-before-sync")
            self._emit(
                "release-before-sync", node.lineno, node.col_offset,
                f"{rec.kind} `{var}` fed a jnp/jax op but is released "
                f"with no block_until_ready on the outputs: dispatch "
                f"is async and jnp.asarray can alias the host buffer "
                f"zero-copy, so queued kernels read the recycled "
                f"buffer (the PR 4 staging race)")
        rec.released = True
        rec.rel_line = node.lineno
        if self.in_finally:
            rec.protected = True

    def _release_target(self, call) -> Optional[str]:
        """Var released by `call`: x.release() / x.close() /
        pool.release(x)."""
        if not isinstance(call.func, ast.Attribute):
            return None
        base, attr = call.func.value, call.func.attr
        if attr in ("release", "close") and isinstance(base, ast.Name) \
                and base.id in self.recs and not call.args:
            return base.id
        if attr == "release" and call.args \
                and isinstance(call.args[0], ast.Name) \
                and call.args[0].id in self.recs:
            return call.args[0].id
        return None

    def _scan_calls(self, stmt):
        """Order-independent per-statement scan: releases, feeds,
        sync barriers, escapes, pair events, transfers."""
        released_vars = set()
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            fname = _last_name(node.func)
            # sync barrier clears every pending feed
            if fname in _SYNC_CALLS:
                for rec in self.recs.values():
                    rec.synced = True
            tgt = self._release_target(node)
            if tgt is not None:
                self._note_release(tgt, node)
                released_vars.add(tgt)
                continue
            # paired-call resources --------------------------------
            if isinstance(node.func, ast.Attribute):
                base, attr = node.func.value, node.func.attr
                bname = _last_name(base)
                if attr in ("reserve", "force_reserve") \
                        and _is_mgrish(base):
                    self.pairs.append(_PairRec(
                        bname, "reservation", node.lineno))
                elif attr == "acquire" and (
                        _is_semish(base) or _is_riderish(base)):
                    self.pairs.append(_PairRec(
                        bname, "permit", node.lineno))
                elif attr == "release" and node.args \
                        and _is_mgrish(base):
                    self._close_pair(bname)
                elif attr == "release" and not node.args and (
                        _is_semish(base) or _is_riderish(base)):
                    self._close_pair(bname)
                elif attr == "submit" and len(node.args) >= 2:
                    self._check_transfer(node, node.args[0],
                                         node.args[1:])
            if fname == "Thread":
                tref = kargs = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        tref = kw.value
                    elif kw.arg == "args" and isinstance(
                            kw.value, (ast.Tuple, ast.List)):
                        kargs = kw.value.elts
                if tref is not None and kargs:
                    self._check_transfer(node, tref, kargs)
        # jnp/jax feeds and use/escape detection, after releases so a
        # release statement itself is not a "use"
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                root = _root_name(node.func)
                if root in self.a.jax_aliases \
                        and _last_name(node.func) not in _SYNC_CALLS:
                    for var in self._lease_roots(node):
                        rec = self.recs[var]
                        rec.fed = True
                        rec.synced = False
                self._scan_escapes(node)
        # use-after-release: any reference to a released lease (or a
        # derived alias) outside the release call itself
        for var in self._lease_roots(stmt):
            rec = self.recs[var]
            if rec.released and var not in released_vars \
                    and "use-after-release" not in rec.reported:
                rec.reported.add("use-after-release")
                self._emit(
                    "use-after-release", stmt.lineno, stmt.col_offset,
                    f"{rec.kind} `{var}` used after its release on a "
                    f"path through line {rec.rel_line}: the buffer may "
                    f"already be recycled by the next lease")

    def _close_pair(self, base: Optional[str]):
        for p in self.pairs:
            if p.base == base and not p.released:
                p.released = True
                p.protected = p.protected or self.in_finally
                return

    def _scan_escapes(self, call: ast.Call):
        """Bare lease names handed to a call (append to a handle list,
        cleanup registration, constructor capture) transfer ownership —
        the leak rule must not second-guess the new owner."""
        if self._release_target(call) is not None:
            return
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for sub in ast.walk(arg):
                name = None
                if isinstance(sub, ast.Name):
                    name = sub.id
                elif isinstance(sub, ast.Attribute) \
                        and sub.attr in ("release", "close") \
                        and isinstance(sub.value, ast.Name):
                    name = sub.value.id  # ctx.add_cleanup(x.release)
                if name in self.recs:
                    self.recs[name].escaped = True

    def _check_transfer(self, call, fn_ref, args):
        """unbalanced-transfer: a tracked resource passed to a worker
        whose resolved body has no finally-protected release of the
        receiving parameter."""
        passed = []   # (arg position, var)
        for i, arg in enumerate(args):
            if isinstance(arg, ast.Name) and arg.id in self.recs:
                passed.append((i, arg.id))
        if not passed:
            return
        worker = self.a.resolve_worker(self.fn, fn_ref)
        for pos, var in passed:
            rec = self.recs[var]
            rec.escaped = True   # the worker owns it now — if it can
            if worker is None:
                continue         # unresolvable: trust the transfer
            param = self._worker_param(worker, pos)
            if param is None or self._worker_releases(worker, param):
                continue
            if "unbalanced-transfer" not in rec.reported:
                rec.reported.add("unbalanced-transfer")
                self._emit(
                    "unbalanced-transfer", call.lineno, call.col_offset,
                    f"{rec.kind} `{var}` handed across a thread/pool "
                    f"boundary to `{worker.name}` which never releases "
                    f"parameter `{param}` under try/finally: nobody "
                    f"owns it on the worker's error path")

    @staticmethod
    def _worker_param(worker, pos: int) -> Optional[str]:
        args = [a.arg for a in worker.args.args]
        if args and args[0] in ("self", "cls"):
            args = args[1:]
        return args[pos] if pos < len(args) else None

    @staticmethod
    def _worker_releases(worker, param: str) -> bool:
        for node in ast.walk(worker):
            if not isinstance(node, ast.Try):
                continue
            for fin in node.finalbody:
                for sub in ast.walk(fin):
                    if isinstance(sub, ast.Call) and isinstance(
                            sub.func, ast.Attribute):
                        base, attr = sub.func.value, sub.func.attr
                        if attr in ("release", "close") and isinstance(
                                base, ast.Name) and base.id == param:
                            return True
                        if attr == "release" and any(
                                isinstance(a, ast.Name)
                                and a.id == param for a in sub.args):
                            return True
        # `with param:` / `with closing(param):` also owns it
        for node in ast.walk(worker):
            if isinstance(node, ast.With):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Name) and sub.id == param:
                            return True
        return False

    # -- statement walk ------------------------------------------------
    def run(self):
        self.walk(self.fn.body)
        # function-end verdicts ---------------------------------------
        for rec in self.recs.values():
            # a more specific finding already covers this var: don't
            # stack the generic leak report on top
            if rec.escaped or rec.reported:
                continue
            if not rec.released:
                rec.reported.add("leak-on-exception")
                self._emit(
                    "leak-on-exception", rec.line, 0,
                    f"{rec.kind} `{rec.var}` acquired via {rec.tag} is "
                    f"never released or transferred in this function: "
                    f"it leaks on every path")
            elif not rec.protected:
                rec.reported.add("leak-on-exception")
                self._emit(
                    "leak-on-exception", rec.line, 0,
                    f"{rec.kind} `{rec.var}` acquired via {rec.tag} is "
                    f"released on the straight-line path only — no "
                    f"try/finally or context manager, so any exception "
                    f"(including a cancel-checkpoint exit) between "
                    f"acquire and release leaks it")
        for p in self.pairs:
            if p.released and not p.protected:
                self._emit(
                    "leak-on-exception", p.line, 0,
                    f"{p.kind} acquired on `{p.base}` is released on "
                    f"the straight-line path only — no try/finally, so "
                    f"an exception (including a cancel-checkpoint "
                    f"exit) between acquire and release leaks it")

    def walk(self, stmts):
        for stmt in stmts:
            self.stmt(stmt)

    def _bind(self, targets, value):
        """Assignment: new acquisitions, alias propagation, rebinds."""
        simple = [t.id for t in targets if isinstance(t, ast.Name)]
        acq = _acquisition(value) if isinstance(value, ast.Call) else None
        if acq and len(simple) == 1:
            kind, tag = acq
            var = simple[0]
            self.recs[var] = _Rec(var, kind, value.lineno, tag)
            self.derived = {d: r for d, r in self.derived.items()
                            if r != var}
            return
        src = self._alias_source(value) if value is not None else None
        for var in simple:
            if src is not None and src != var:
                self.derived[var] = src
            else:
                # rebinding kills prior tracking for this name
                self.recs.pop(var, None)
                self.derived.pop(var, None)
        # storing a lease into an attribute/subscript is an escape
        for t in targets:
            if isinstance(t, (ast.Attribute, ast.Subscript)) \
                    and value is not None:
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Name) and sub.id in self.recs:
                        self.recs[sub.id].escaped = True

    def _snapshot(self):
        return ({v: r.copy() for v, r in self.recs.items()},
                dict(self.derived), list(self.pairs))

    def _merge(self, branches):
        """Union merge of branch outcomes (some-path semantics)."""
        base_recs: Dict[str, _Rec] = {}
        base_derived: Dict[str, str] = {}
        for recs, derived, _pairs in branches:
            for v, r in recs.items():
                cur = base_recs.get(v)
                if cur is None:
                    base_recs[v] = r.copy()
                else:
                    cur.released = cur.released or r.released
                    cur.rel_line = max(cur.rel_line, r.rel_line)
                    cur.protected = cur.protected or r.protected
                    cur.escaped = cur.escaped or r.escaped
                    cur.fed = cur.fed or r.fed
                    cur.synced = cur.synced and r.synced
            base_derived.update(derived)
        self.recs = base_recs
        self.derived = base_derived

    def stmt(self, stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs audited as their own functions
        if isinstance(stmt, ast.Assign):
            self._scan_calls(stmt)
            self._bind(stmt.targets, stmt.value)
            return
        if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            self._scan_calls(stmt)
            if stmt.value is not None:
                self._bind([stmt.target], stmt.value)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                acq = _acquisition(item.context_expr)
                if acq and isinstance(item.optional_vars, ast.Name):
                    kind, tag = acq
                    var = item.optional_vars.id
                    rec = _Rec(var, kind, item.context_expr.lineno, tag)
                    rec.protected = True   # __exit__ owns the release
                    self.recs[var] = rec
                else:
                    self._scan_calls(stmt)
            self.walk(stmt.body)
            for item in stmt.items:
                acq = _acquisition(item.context_expr)
                if acq and isinstance(item.optional_vars, ast.Name):
                    var = item.optional_vars.id
                    if var in self.recs:
                        self._note_release(var, stmt)
            return
        if isinstance(stmt, ast.If):
            self._scan_calls(stmt.test)
            snap = self._snapshot()
            self.walk(stmt.body)
            b1 = self._snapshot()
            self.recs, self.derived, self.pairs = (
                {v: r.copy() for v, r in snap[0].items()},
                dict(snap[1]), snap[2])
            self.walk(stmt.orelse)
            b2 = self._snapshot()
            self.pairs = b1[2] + [p for p in b2[2] if p not in b1[2]]
            self._merge([b1, b2])
            return
        if isinstance(stmt, (ast.For, ast.While)):
            if isinstance(stmt, ast.For):
                self._scan_calls(stmt.iter)
                self._bind([stmt.target], None)
            else:
                self._scan_calls(stmt.test)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self.walk(stmt.body)
            snap = self._snapshot()
            branches = [snap]
            for handler in stmt.handlers:
                self.recs, self.derived = (
                    {v: r.copy() for v, r in snap[0].items()},
                    dict(snap[1]))
                # a release inside an except handler is deliberate
                # error-path compensation (release-then-reraise): it
                # counts as protection, like a finalbody release
                was = self.in_finally
                self.in_finally = True
                self.walk(handler.body)
                self.in_finally = was
                branches.append(self._snapshot())
            self._merge(branches)
            self.walk(stmt.orelse)
            was = self.in_finally
            self.in_finally = True
            self.walk(stmt.finalbody)
            self.in_finally = was
            return
        if isinstance(stmt, ast.Return) or (
                isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, (ast.Yield, ast.YieldFrom))):
            # returned / yielded resources belong to the caller now
            self._scan_calls(stmt)
            val = stmt.value
            if val is not None:
                for sub in ast.walk(val):
                    if isinstance(sub, ast.Name) and sub.id in self.recs:
                        self.recs[sub.id].escaped = True
            return
        self._scan_calls(stmt)


class _ModuleAuditor:
    """Per-module driver: collects jnp/jax aliases and function ASTs,
    runs _FnLifetime over every def, resolves transfer workers through
    the concurrency Model."""

    def __init__(self, model: Optional[Model], mod: str, path: str,
                 src: str):
        self.model = model
        self.mod = mod
        self.path = path
        self.tree = ast.parse(src)
        self.lines = src.splitlines()
        self.violations: List[Violation] = []
        self.jax_aliases = set()
        self.fn_by_line: Dict[int, ast.AST] = {}
        self._cur_line = 0
        self._collect()

    def _collect(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    root = alias.name.split(".")[0]
                    if root == "jax":
                        self.jax_aliases.add(alias.asname or root)
            elif isinstance(node, ast.ImportFrom):
                if node.module and node.module.split(".")[0] == "jax":
                    for alias in node.names:
                        self.jax_aliases.add(alias.asname or alias.name)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.fn_by_line[node.lineno] = node

    def emit(self, rule: str, line: int, col: int, msg: str):
        snippet = ""
        if 1 <= line <= len(self.lines):
            snippet = self.lines[line - 1].strip()
        self.violations.append(Violation(
            self.path, line, col, rule, msg, snippet))

    # -- worker resolution (concurrency Model reuse) -------------------
    def resolve_worker(self, funcdef, fn_ref) -> Optional[ast.AST]:
        """AST of the function `fn_ref` names, via the concurrency
        model's scope-chain + unique-method resolution."""
        if isinstance(fn_ref, ast.Lambda):
            return None
        if isinstance(fn_ref, ast.Name):
            ref = ("local", fn_ref.id)
        elif isinstance(fn_ref, ast.Attribute):
            kind = ("self" if isinstance(fn_ref.value, ast.Name)
                    and fn_ref.value.id == "self" else "attr")
            ref = (kind, fn_ref.attr)
        else:
            return None
        if self.model is None:
            return None
        owner = self._model_fn(funcdef.lineno)
        if owner is None:
            return None
        fid = self.model.resolve_ref(owner, ref)
        if fid is None:
            return None
        callee = self.model.funcs.get(fid)
        if callee is None or callee.mod != self.mod:
            return None   # cross-module worker: out of scope here
        return self.fn_by_line.get(callee.line)

    def _model_fn(self, line: int):
        for fn in self.model.funcs.values():
            if fn.mod == self.mod and fn.line == line:
                return fn
        return None

    def run(self) -> List[Violation]:
        self._visit(self.tree.body, None)
        return self.violations

    def _visit(self, body, cls_name):
        for node in body:
            if isinstance(node, ast.ClassDef):
                self._visit(node.body, node.name)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                _FnLifetime(self, node, cls_name).run()
                self._visit(node.body, cls_name)


# ---------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------
def analyze_source(src: str, path: str = "<mem>", mod: str = "mem",
                   model: Optional[Model] = None) -> List[Violation]:
    """Audit one module's source (unit-test surface). Marker-allowed
    sites are dropped, like analyze_paths."""
    try:
        auditor = _ModuleAuditor(model, mod, path, src)
    except SyntaxError:
        return []
    out = []
    markers = _file_markers(src.splitlines())
    for v in sorted(auditor.run(), key=lambda v: (v.line, v.col, v.rule)):
        if not _allowed(markers, v.rule, v.line):
            out.append(v)
    return out


def analyze_paths(paths: List[str], rel_to: Optional[str] = None,
                  model: Optional[Model] = None) -> List[Violation]:
    """Build the concurrency call-resolution model over `paths`, run
    the lifetime pass per module, drop marker-allowed sites. Violations
    share lint_rules' (path, rule, snippet) identity, so the tpulint
    baseline/diff machinery applies unchanged."""
    model = model or build_model(paths, rel_to)
    out: List[Violation] = []
    for f in _iter_py(paths):
        rel = (os.path.relpath(f, rel_to) if rel_to else f)
        rel = rel.replace(os.sep, "/")
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        out.extend(analyze_source(
            src, path=rel, mod=_mod_name(f, rel_to), model=model))
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out
