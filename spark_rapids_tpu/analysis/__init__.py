"""Static analysis for the engine: the plan-time auditor (NOT_ON_TPU
verdict tagging, analysis/audit.py), the AST rules behind the
`tpulint` engine linter (analysis/lint_rules.py), the interprocedural
concurrency auditor (analysis/concurrency.py), and the
resource-lifetime auditor (analysis/lifetime.py).

The passes make the engine's safety contracts machine-checked instead
of reviewer folklore: the auditor walks a bound physical plan BEFORE
execution and predicts where it will fall back, fail, or recompile;
the linter walks the engine's own source and flags sync/recompile
hazards (implicit device->host syncs, shape-baking jit closures,
dtype-promotion traps, missing buffer donation, fingerprint-unstable
node attrs); the concurrency auditor proves deadlock-shape properties
over locks/pools/semaphores (runtime twin: runtime/lockdep.py); the
lifetime auditor proves acquire/release properties over staging
leases, permits, spill handles, and byte reservations (runtime twin:
runtime/ledger.py).
"""
from .audit import (AuditReport, Verdict, audit_plan, OK, WILL_FALLBACK,
                    WILL_NOT_WORK, RECOMPILE_RISK)

__all__ = ["AuditReport", "Verdict", "audit_plan", "OK", "WILL_FALLBACK",
           "WILL_NOT_WORK", "RECOMPILE_RISK"]
