"""Static analysis for the engine: the plan-time auditor (NOT_ON_TPU
verdict tagging, analysis/audit.py) and the AST rules behind the
`tpulint` engine linter (analysis/lint_rules.py).

Both passes make the engine's safety contracts machine-checked instead
of reviewer folklore: the auditor walks a bound physical plan BEFORE
execution and predicts where it will fall back, fail, or recompile; the
linter walks the engine's own source and flags sync/recompile hazards
(implicit device->host syncs, shape-baking jit closures, dtype-promotion
traps, missing buffer donation).
"""
from .audit import (AuditReport, Verdict, audit_plan, OK, WILL_FALLBACK,
                    WILL_NOT_WORK, RECOMPILE_RISK)

__all__ = ["AuditReport", "Verdict", "audit_plan", "OK", "WILL_FALLBACK",
           "WILL_NOT_WORK", "RECOMPILE_RISK"]
