"""Static concurrency auditor: lock/pool wait-graph analysis.

PR 8 shipped two real deadlocks that only a 300s broadcast timeout and
faulthandler dumps made debuggable: a bounded-pool future wait-cycle
(a broadcast build whose subtree submitted ANOTHER build to the same
4-worker pool and blocked on its future — every worker parked behind
itself) and permit starvation (collect threads pinning every
TpuSemaphore permit while exchange map workers blocked inside
`sem.acquire` forever). Both are SHAPES, detectable from source. This
module is the static half of the concurrency tooling (the runtime half
is runtime/lockdep.py). The interprocedural machinery — module walk,
resource inventory, call resolution, pool-worker targets, summaries —
lives in analysis/core.py (shared with the lifetime and data-race
auditors); this module owns the deadlock rules:

  lock-order-cycle  two code paths acquire the same class-keyed
                    resources in opposite orders (ABBA); reported once
                    per strongly-connected component of the static
                    order graph
  wait-under-lock   a BLOCKING (untimed) wait — Future.result,
                    as_completed, queue get, semaphore acquire, socket
                    recv — while holding a lock or a TpuSemaphore
                    permit; bounded/timed waits are polls and exempt,
                    as is Condition.wait on a held condition (it
                    releases that lock while parked)
  pool-self-wait    a function reachable from a bounded pool's own
                    workers that submits to that pool and blocks on a
                    future (the q2 bug class — flagged regardless of
                    timeout: the PR 8 cycle HAD a 300s timeout)
  sync-under-lock   a device sync (fetch / block_until_ready /
                    device_get) under a held lock: every other thread
                    needing the lock now waits on device latency

Static analysis of Python is necessarily approximate. Calls are
propagated only when unambiguous (self-methods, module-local and
imported engine functions, uniquely-named methods); polymorphic names
(`execute_partition` et al) are skipped, so permits that flow through
`next(iterator)` are invisible — the runtime witness covers the
dynamic side. Intentional sites carry the same inline marker tpulint
uses::

    f.result()  # tpulint: allow[wait-under-lock] PermitRider ...
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from .lint_rules import MARKER_RE, Violation  # noqa: F401  (re-export)
# Shared machinery: the model/walker/marker layer moved to core.py when
# the data-race auditor joined. Names are re-imported here so existing
# importers (lifetime.py historically, tests, tools) keep working.
from .core import (  # noqa: F401  (re-exports are part of the API)
    PERMIT, Event, FuncInfo, Model, PoolInfo, _allowed, _file_markers,
    _is_locky, _is_riderish, _is_semish, _iter_py, _last_name,
    _mod_name, _NO_RESOLVE, _SUMMARY_CAP, build_model, filter_markers)

__all__ = ["CONC_RULES", "PERMIT", "Model", "build_model",
           "analyze_model", "analyze_paths", "inventory"]

CONC_RULES = ("lock-order-cycle", "wait-under-lock", "pool-self-wait",
              "sync-under-lock")


# ---------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------
def _lock_kinds(model: Model, held: frozenset) -> Set[str]:
    """Held keys that are actual mutual-exclusion locks (not permits)."""
    return {h for h in held
            if h != PERMIT
            and model.resources.get(h, "lock") in ("lock", "rlock",
                                                   "cond")}


def _rule_sites(model: Model):
    """(event, effective-held, site-fn) deduped per site with the
    SMALLEST observed held-set (so a site only reachable lock-free is
    not flagged off a rarer locked path ... the other way around: the
    smallest non-empty qualifying held-set wins for messaging)."""
    best: Dict[tuple, tuple] = {}
    for fid in list(model.funcs):
        for ev, held, site in model.summarize(fid):
            site_fn = model.funcs[site]
            k = (site_fn.path, ev.line, ev.kind, ev.wclass)
            cur = best.get(k)
            if cur is None or len(held) > len(cur[1]):
                # keep the LARGEST held-set: rules flag hazards on any
                # realizable path, and the message should name them
                best[k] = (ev, held, site_fn)
    return best.values()


def analyze_model(model: Model) -> List[Violation]:
    out: List[Violation] = []
    seen: Set[tuple] = set()

    def add(path: str, line: int, col: int, rule: str, msg: str):
        k = (path, line, rule)
        if k in seen:
            return
        seen.add(k)
        out.append(Violation(path, line, col, rule, msg,
                             model.snippet(path, line)))

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for ev, held, fn in _rule_sites(model):
        if ev.kind == "acquire" and ev.resource is not None:
            for h in held:
                if h != ev.resource:
                    edges.setdefault((h, ev.resource), (fn.path, ev.line))
        if ev.kind == "sync":
            locks = _lock_kinds(model, held)
            if locks:
                add(fn.path, ev.line, ev.col, "sync-under-lock",
                    f"device sync in {fn.qual} while holding "
                    f"{sorted(locks)} — every thread needing the lock "
                    f"now waits on device latency; move the fetch "
                    f"outside the critical section")
        if ev.kind == "wait" and ev.blocking:
            eff = (held - ev.exempt) - {ev.resource or ""}
            locks = _lock_kinds(model, eff)
            permit = PERMIT in eff
            if locks or permit:
                what = []
                if locks:
                    what.append(f"lock(s) {sorted(locks)}")
                if permit:
                    what.append("a TpuSemaphore permit")
                add(fn.path, ev.line, ev.col, "wait-under-lock",
                    f"blocking {ev.wclass or 'wait'} in {fn.qual} while "
                    f"holding {' and '.join(what)} — anything needing "
                    f"the held resource (or a starved permit waiter) "
                    f"deadlocks against this wait; use a timed poll or "
                    f"release first")

    # pool-self-wait: a future wait on pool P in code reachable from
    # P's own workers, where the same reachable region submits to P
    for pkey, pool in model.pools.items():
        roots = []
        for owner_fid, ref in pool.workers:
            owner = model.funcs.get(owner_fid)
            if owner is None:
                continue
            fid = model.resolve_ref(owner, ref)
            if fid is not None:
                roots.append(fid)
        if not roots:
            continue
        for fid in sorted(model.reachable_from(roots)):
            ents = model.summarize(fid)
            submits = any(e.kind == "submit" and e.pool == pkey
                          for e, _h, _s in ents)
            if not submits:
                continue
            for ev, _held, site in ents:
                if ev.kind == "wait" and ev.wclass == "future" and \
                        ev.pool in (None, pkey):
                    sf = model.funcs[site]
                    add(sf.path, ev.line, ev.col, "pool-self-wait",
                        f"{sf.qual} waits on a future of bounded pool "
                        f"'{pkey}' and is reachable from that pool's "
                        f"own workers — with every worker parked "
                        f"behind itself only a timeout breaks the "
                        f"cycle (the PR 8 q2 deadlock shape); run the "
                        f"nested work inline (cf. on_build_pool())")

    # lock-order cycles: SCCs of the static order graph
    for cyc in _cycles(edges):
        path, line = edges[(cyc[0], cyc[1 % len(cyc)])]
        add(path, line, 0, "lock-order-cycle",
            f"lock-order cycle {' -> '.join(cyc + [cyc[0]])}: two "
            f"paths acquire these resources in opposite orders; "
            f"pick one global order")

    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def _cycles(edges: Dict[Tuple[str, str], tuple]) -> List[List[str]]:
    """Cyclic SCCs (size > 1) of the order graph, deterministic order.
    Self-edges never occur (acquire skips h == resource): same-class
    nesting, e.g. chained ShuffleExchangeExec instances, is benign."""
    succ: Dict[str, List[str]] = {}
    nodes: Set[str] = set()
    for a, b in edges:
        if a == b:
            continue
        succ.setdefault(a, []).append(b)
        nodes.add(a)
        nodes.add(b)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str):
        work = [(v, iter(sorted(succ.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(sorted(succ.get(w, ())))))
                    advanced = True
                    break
                elif w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)
    return sccs


# ---------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------
def analyze_paths(paths: List[str], rel_to: Optional[str] = None,
                  model: Optional[Model] = None) -> List[Violation]:
    """Build the model, run the rules, drop marker-allowed sites.
    Violations share lint_rules' (path, rule, snippet) identity, so the
    tpulint baseline/diff machinery applies unchanged."""
    model = model or build_model(paths, rel_to)
    return filter_markers(model, analyze_model(model))


def inventory(model: Model) -> dict:
    """Resource/pool/thread-role inventory for --json and the docs."""
    waits = syncs = 0
    for fn in model.funcs.values():
        for ev, _h in fn.events:
            if ev.kind == "wait":
                waits += 1
            elif ev.kind == "sync":
                syncs += 1
    roles = {}
    for pkey, pool in model.pools.items():
        names = []
        for owner_fid, ref in pool.workers:
            owner = model.funcs.get(owner_fid)
            fid = model.resolve_ref(owner, ref) if owner else None
            names.append(fid or f"{ref[0]}:{ref[1]}")
        roles[pkey] = sorted(set(names))
    threads = {}
    for owner_fid, ref, nm in model.thread_targets:
        owner = model.funcs.get(owner_fid)
        fid = model.resolve_ref(owner, ref) if owner else None
        threads[nm or f"<unnamed {ref[1]}>"] = fid or f"{ref[0]}:{ref[1]}"
    return {
        "resources": {k: {"kind": model.resources[k],
                          "sites": model.resource_sites.get(k, [])}
                      for k in sorted(model.resources)},
        "pools": roles,
        "thread_targets": threads,
        "functions": len(model.funcs),
        "wait_sites": waits,
        "sync_sites": syncs,
    }
