"""Hash-join execution (inner/left/right/full/semi/anti/cross).

Reference: GpuShuffledHashJoinExec.scala:167 + GpuHashJoin.scala (gather-map
join over cudf hash tables) and GpuBroadcastNestedLoopJoinExecBase for
cross. TPU-first redesign under the static-shape regime:

  1. BUILD: concat the right side into one device table.
  2. Per stream batch, COUNT phase (one XLA program): sort the combined
     (build + stream) keys — radix-normalized, NaN/null aware — derive
     equality segments, count joinable build rows per segment, and for
     every stream row its match count. Matching rows of a segment are
     contiguous in combined-sorted space, so a (segment start, j) pair
     addresses the j-th match directly.
  3. Host-sync ONLY the total match count -> bucket the output capacity
     (the cudf analog returns gather-map sizes the same way).
  4. EXPAND phase (second XLA program, shape keyed by output bucket):
     searchsorted over the per-row offsets builds the left/right gather
     maps; gather payload columns from both sides.

Semi/anti joins skip phases 3-4 entirely — they are a mask update on the
stream batch. Right/full outer track per-build-row matched flags across
stream batches and emit unmatched build rows in a final batch.

Null join keys never match (SQL equi-join); NaN keys match NaN per Spark.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.column import bucket_capacity
from ..columnar.table import Schema
from ..expr.expressions import EmitCtx, Expression
from ..ops import sortkeys as sk
from ..ops.concat import concat_cvs, concat_masks
from ..ops.kernel_utils import CV
from ..utils.transfer import fetch_int
from ..profiler import xla_stats
from .base import ExecContext, TpuExec
from .batch import DeviceBatch
from .nodes import make_table

__all__ = ["HashJoinExec", "NestedLoopJoinExec"]



def _null_cvs(fields, cap):
    """All-null columns for outer-join extension rows (flat dtypes;
    nested children TODO alongside nested outer-join payload support)."""
    from ..columnar.column import alloc_shape
    out = []
    for f in fields:
        np_dt = f.dtype.np_dtype or jnp.int8
        out.append(CV(jnp.zeros(alloc_shape(f.dtype, cap), np_dt),
                      jnp.zeros(cap, jnp.bool_),
                      jnp.zeros(cap + 1, jnp.int32)
                      if f.dtype.is_variable_width else None))
    return out


class HashJoinExec(TpuExec):
    # the stream side collapses into the probe pre-stage program
    # (_stream_batches); the fusion pass leaves that prefix alone
    fuses_child_chain = True

    def __init__(self, left: TpuExec, right: TpuExec,
                 bound_left_keys: Sequence[Expression],
                 bound_right_keys: Sequence[Expression], how: str,
                 schema: Schema, per_partition: bool = False,
                 condition: Optional[Expression] = None):
        """per_partition: both children are hash-partitioned on the join
        keys (exchanges below us), so each partition joins independently —
        the distributed shuffled-join topology (reference:
        GpuShuffledHashJoinExec.scala:167).

        condition: extra non-equi predicate bound over the COMBINED
        (left ++ right) schema, evaluated on candidate pairs after the
        equi-key expansion (the reference compiles these to cudf AST
        expressions, AstUtil.scala; here the expression fuses into the
        pair-evaluation program)."""
        super().__init__([left, right], schema)
        self.lkeys = list(bound_left_keys)
        self.rkeys = list(bound_right_keys)
        self.how = how
        self.per_partition = per_partition
        self.condition = condition
        self._count_cache = {}
        self._expand_cache = {}
        from ..runtime.program_cache import expr_fp, exprs_fp
        # shared program-cache key material: same keys/type/condition
        # from a different DataFrame reuse every join program
        self._fp = (exprs_fp(self.lkeys), exprs_fp(self.rkeys), how,
                    expr_fp(condition) if condition is not None
                    else None)
        # probe-side pre-projection: the fusable stream-side chain
        # collapses into one pre-stage program per stream batch
        # (resolved lazily at first execute, see UngroupedAggExec)
        self._base_left = None
        self._lstages = None
        self._n_fused = 0
        self._pre_jit = None

    def num_partitions(self, ctx):
        if self.per_partition:
            return self.children[0].num_partitions(ctx)
        return 1

    def describe(self):
        mode = "distributed" if self.per_partition else "single"
        fused = f", fused_stages={self._n_fused}" if self._n_fused else ""
        return f"HashJoinExec[{self.how}, {mode}{fused}]"

    def _resolve_fusion(self, ctx):
        if self._base_left is None:
            from ..config import STAGE_FUSION_ENABLED
            from .base import collapse_fusable
            if ctx.conf.get(STAGE_FUSION_ENABLED):
                self._base_left, self._lstages, self._n_fused = \
                    collapse_fusable(self.children[0])
            else:
                self._base_left, self._n_fused = self.children[0], 0
            if self._n_fused:
                from ..runtime.program_cache import cached_program
                # tpulint: allow[fp-unstable-attr,unstable-program-key] id(self) is the documented per-instance fallback key: unshared, never falsely shared, excluded from warm packs
                self._pre_jit = cached_program(
                    self._lstages, cls=type(self).__name__, tag="pre",
                    key=getattr(self._lstages, "_stage_fp",
                                ("inst", id(self))))

    def _stream_batches(self, ctx, pid):
        """Probe-side input with the fusable left chain applied as one
        pre-stage program per batch (the probe-side pre-projection)."""
        self._resolve_fusion(ctx)
        base = self._base_left
        for lpid in ([pid] if self.per_partition
                     else range(base.num_partitions(ctx))):
            for b in base.execute_partition(ctx, lpid):
                ctx.check_cancel()
                if self._n_fused:
                    cvs2, mask2 = self._pre_jit(b.cvs(), b.row_mask)
                    xla_stats.count_dispatch()
                    b = DeviceBatch(
                        make_table(self.children[0].schema, cvs2,
                                   b.num_rows),
                        b.num_rows, mask2, b.capacity)
                yield b

    # ------------------------------------------------------------------
    @staticmethod
    def _concat_batches(batches, schema: Schema):
        if not batches:
            cvs = [CV(jnp.zeros(128, f.dtype.np_dtype or jnp.int8),
                      jnp.zeros(128, jnp.bool_),
                      jnp.zeros(129, jnp.int32)
                      if f.dtype.is_variable_width else None)
                   for f in schema.fields]
            return cvs, jnp.zeros(128, jnp.bool_)
        ncols = len(batches[0].table.columns)
        if len(batches) == 1:
            return batches[0].cvs(), batches[0].row_mask
        cvs = [concat_cvs([b.cvs()[i] for b in batches],
                          schema.fields[i].dtype)
               for i in range(ncols)]
        mask = concat_masks([b.row_mask for b in batches])
        return cvs, mask

    def _collect_side(self, ctx, child, key_exprs, pids=None):
        batches = []
        for pid in (pids if pids is not None
                    else range(child.num_partitions(ctx))):
            batches.extend(child.execute_partition(ctx, pid))
        return self._concat_batches(batches, child.schema)

    def _key_nchunks(self, bkey_cvs, bmask, skey_cvs, smask):
        ncs = []
        for i, ke in enumerate(self.lkeys):
            if isinstance(ke.dtype, (dt.StringType, dt.BinaryType)):
                ncs.append(max(sk.string_nchunks(bkey_cvs[i], bmask),
                               sk.string_nchunks(skey_cvs[i], smask)))
            else:
                ncs.append(0)
        return tuple(ncs)

    # ---- single-key fast path: sorted build + searchsorted probe -------
    # The build side sorts ONCE per join (not once per stream batch): keys
    # normalize to a monotone uint64 radix word, invalid keys pin to
    # UINT64_MAX (sorted last, excluded by clipping ranges to n_valid), and
    # each stream batch probes with two binary searches — O(S log B) per
    # batch instead of a combined (B+S) sort (reference contrast:
    # GpuHashJoin.scala builds a hash table once; this is the TPU-sortable
    # equivalent).
    @staticmethod
    def _single_key_u64(kcv: CV, dtype: dt.DataType):
        """Monotone uint64 key, or None when the dtype needs >1 array."""
        arrs = sk.order_keys(kcv, dtype)
        if len(arrs) != 1:
            return None
        a = arrs[0]
        if a.dtype == jnp.uint8 or a.dtype == jnp.uint32:
            return a.astype(jnp.uint64)
        if a.dtype == jnp.int64:
            return a.astype(jnp.uint64) ^ jnp.uint64(1 << 63)
        if a.dtype == jnp.int32:
            return (a.astype(jnp.int64).astype(jnp.uint64)
                    ^ jnp.uint64(1 << 63))
        if a.dtype == jnp.int8 or a.dtype == jnp.int16:
            return (a.astype(jnp.int64).astype(jnp.uint64)
                    ^ jnp.uint64(1 << 63))
        return None

    def _fast_path_ok(self):
        if len(self.rkeys) != 1:
            return False
        d = self.rkeys[0].dtype
        if isinstance(d, dt.DecimalType) and d.is_decimal128:
            return False   # two-limb keys need the generic path
        return not (d.is_variable_width or d.is_nested
                    or isinstance(d, dt.DoubleType))

    def _build_sorted(self, bkey_cvs, bmask):
        """jitted once per build capacity (cached in _count_cache):
        returns (sorted ukeys with invalids pinned MAX, perm sorted->orig,
        n_valid)."""
        key = ("buildsort", bmask.shape[0])
        fn = self._count_cache.get(key)
        if fn is None:
            def fn_(kcv, mask):
                ukey = self._single_key_u64(kcv, self.rkeys[0].dtype)
                valid = mask & kcv.validity
                pinned = jnp.where(valid, ukey,
                                   jnp.uint64(0xFFFFFFFFFFFFFFFF))
                inv = jnp.logical_not(valid).astype(jnp.uint8)
                perm = sk.lexsort([inv, pinned])
                return pinned[perm], perm.astype(jnp.int32), \
                    jnp.sum(valid.astype(jnp.int32))
            from ..runtime.program_cache import cached_program
            fn = cached_program(fn_, cls=type(self).__name__,
                                tag="buildsort", key=self._fp)
            self._count_cache[key] = fn
        return fn(bkey_cvs[0], bmask)

    # ---- direct-address (perfect-hash) build: no sort at all -----------
    # When the single int key's value span fits a bounded table (TPC-H
    # surrogate keys are dense), build = two scatters, probe = two
    # gathers: O(n) linear passes instead of XLA's single-threaded
    # O(n log n) sort (~0.5s at 1M rows on CPU). Falls back to the sorted
    # path per-batch only when a stream row has >1 match AND the join
    # needs pair enumeration.
    _DIRECT_SPAN_FACTOR = 8
    _DIRECT_SPAN_MIN = 1 << 22

    def _try_build_direct(self, bkey_cvs, bmask, cap_b):
        """Returns {'R', 'kmin', 'kmax', 'cnt_t', 'idx_t'} or None."""
        from ..utils.transfer import fetch
        key = ("keyrange", cap_b)
        rfn = self._count_cache.get(key)
        if rfn is None:
            def rfn_(kcv, mask):
                ukey = self._single_key_u64(kcv, self.rkeys[0].dtype)
                valid = mask & kcv.validity
                kmin = jnp.min(jnp.where(valid, ukey,
                                         jnp.uint64(0xFFFFFFFFFFFFFFFF)))
                kmax = jnp.max(jnp.where(valid, ukey, jnp.uint64(0)))
                return kmin, kmax, jnp.sum(valid.astype(jnp.int32))
            from ..runtime.program_cache import cached_program
            rfn = cached_program(rfn_, cls=type(self).__name__,
                                 tag="keyrange", key=self._fp)
            self._count_cache[key] = rfn
        kmin_d, kmax_d, nv_d = rfn(bkey_cvs[0], bmask)
        kmin, kmax, nv = (int(v) for v in fetch((kmin_d, kmax_d, nv_d)))
        if nv == 0:
            return None
        span = kmax - kmin + 1
        if span > max(self._DIRECT_SPAN_FACTOR * cap_b,
                      self._DIRECT_SPAN_MIN):
            return None
        R = bucket_capacity(span)
        bkey = ("directbuild", R, cap_b)
        bfn = self._count_cache.get(bkey)
        if bfn is None:
            def bfn_(kcv, mask, kmin_dev):
                ukey = self._single_key_u64(kcv, self.rkeys[0].dtype)
                valid = mask & kcv.validity
                d = (ukey - kmin_dev).astype(jnp.int64)
                off = jnp.where(valid, jnp.clip(d, 0, R), R)
                cnt_t = jnp.zeros(R + 1, jnp.int32).at[off].add(
                    valid.astype(jnp.int32))
                idx_t = jnp.zeros(R + 1, jnp.int32).at[off].max(
                    jnp.arange(cap_b, dtype=jnp.int32))
                return cnt_t, idx_t
            from ..runtime.program_cache import cached_program
            bfn = cached_program(bfn_, cls=type(self).__name__,
                                 tag="directbuild",
                                 key=self._fp + (R,))
            self._count_cache[bkey] = bfn
        cnt_t, idx_t = bfn(bkey_cvs[0], bmask, kmin_d)
        return {"R": R, "kmin": kmin_d, "kmax": kmax_d,
                "cnt_t": cnt_t, "idx_t": idx_t}

    def _direct_probe(self, direct, skcv, smask, cap_s):
        R = direct["R"]
        key = ("directprobe", R, cap_s)
        fn = self._count_cache.get(key)
        if fn is None:
            def fn_(cnt_t, idx_t, kmin, kmax, skcv, smask):
                ukey_s = self._single_key_u64(skcv, self.lkeys[0].dtype)
                joinable = smask & skcv.validity
                in_r = joinable & (ukey_s >= kmin) & (ukey_s <= kmax)
                d = (ukey_s - kmin).astype(jnp.int64)
                poff = jnp.where(in_r, jnp.clip(d, 0, R), R)
                cnt = cnt_t[poff].astype(jnp.int64)
                bidx = idx_t[poff]
                return cnt, bidx
            from ..runtime.program_cache import cached_program
            fn = cached_program(fn_, cls=type(self).__name__,
                                tag="directprobe",
                                key=self._fp + (R,))
            self._count_cache[key] = fn
        return fn(direct["cnt_t"], direct["idx_t"], direct["kmin"],
                  direct["kmax"], skcv, smask)

    def _probe_fn(self, cap_b, cap_s):
        """Per-stream-batch count phase against the sorted build keys."""
        def fn(sorted_ukey, n_valid, skcv, smask):
            ukey_s = self._single_key_u64(skcv, self.lkeys[0].dtype)
            joinable = smask & skcv.validity
            lo = jnp.searchsorted(sorted_ukey, ukey_s, side="left")
            hi = jnp.searchsorted(sorted_ukey, ukey_s, side="right")
            lo = jnp.minimum(lo, n_valid)
            hi = jnp.minimum(hi, n_valid)
            cnt = jnp.where(joinable, (hi - lo).astype(jnp.int64), 0)
            offsets = jnp.cumsum(cnt) - cnt
            total = jnp.sum(cnt)
            # matched build positions (right/full outer): range-mark via
            # +1/-1 diff then prefix sum over sorted build space
            diff = jnp.zeros(cap_b + 1, jnp.int32)
            add_lo = jnp.where(joinable, lo, cap_b)
            add_hi = jnp.where(joinable, hi, cap_b)
            diff = diff.at[add_lo].add(1).at[add_hi].add(-1)
            touched = jnp.cumsum(diff[:-1]) > 0
            return (cnt, offsets, total, lo.astype(jnp.int64), touched)
        return fn

    @staticmethod
    @jax.jit
    def _matched_from_touched(bperm, touched, n_valid, acc):
        pos_ok = jnp.arange(touched.shape[0]) < n_valid
        upd = jnp.zeros_like(acc).at[bperm].max(touched & pos_ok)
        return acc | upd

    # ---- single-match (FK-join) output stats + gather index -----------
    # When no stream row has more than one match — every build-unique
    # dimension join (TPC-H's dominant shape) — the expand phase is a
    # no-op permutation: the stream side passes through UNTOUCHED (zero
    # copy, mask update only) and the build payload gathers at stream
    # capacity. One probe-stat fetch decides the path per batch.
    @staticmethod
    @jax.jit
    def _probe_stats(cnt, smask):
        matched = (cnt > 0) & smask
        eff = jnp.where(smask & (cnt == 0), 1, cnt)
        return (jnp.sum(cnt), jnp.sum(eff),
                jnp.sum(matched.astype(jnp.int64)), jnp.max(cnt))

    @staticmethod
    @jax.jit
    def _fk_gather_idx(cnt, bstart, perm, smask, n_build):
        matched = (cnt > 0) & smask
        pos = jnp.clip(bstart, 0, perm.shape[0] - 1).astype(jnp.int32)
        rg = jnp.clip(perm[pos], 0, n_build - 1).astype(jnp.int32)
        return rg, matched

    def _fk_output(self, m, batch, scvs, bcvs, rg, matched, smask,
                   n_matched, n_eff, cap_s):
        """Single-match join output: stream columns pass through IN
        PLACE (holey mask — num_rows stays the positional upper bound),
        build payload gathered by the per-row match index."""
        new_mask = matched if self.how == "inner" else smask
        out_cvs = list(scvs) + self._gather_cols(bcvs, rg, matched)
        tbl = make_table(self.schema, out_cvs, batch.num_rows)
        m.add("numOutputRows",
              n_matched if self.how == "inner" else n_eff)
        m.add("numOutputBatches", 1)
        return ("batch", DeviceBatch(tbl, batch.num_rows, new_mask,
                                     cap_s))

    # ---- phase 1+2: combined sort & count (jitted) --------------------
    def _count_fn(self, nchunks, cap_b, cap_s):
        def fn(bkeys, bmask, skeys, smask):
            nk = len(self.rkeys)
            joinable_b = bmask
            joinable_s = smask
            comb_keys: List = []
            for i in range(nk):
                kb, ks_ = bkeys[i], skeys[i]
                joinable_b = joinable_b & kb.validity
                joinable_s = joinable_s & ks_.validity
                comb_keys.append(concat_cvs([kb, ks_], self.rkeys[i].dtype))
            joinable = jnp.concatenate([joinable_b, joinable_s])
            is_build = jnp.concatenate([
                jnp.ones(cap_b, jnp.bool_), jnp.zeros(cap_s, jnp.bool_)])
            arrays = [jnp.logical_not(joinable).astype(jnp.uint8)]
            for i, kcv in enumerate(comb_keys):
                arrays.extend(sk.order_keys(kcv, self.rkeys[i].dtype,
                                            nchunks[i]))
            perm = sk.lexsort(arrays)
            sorted_arrays = [a[perm] for a in arrays]
            boundary = sk.group_boundaries(sorted_arrays)
            seg_ids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
            n = cap_b + cap_s
            jb_sorted = (is_build & joinable)[perm]
            js_sorted = (joinable & ~is_build)[perm]
            seg_bcnt = jax.ops.segment_sum(jb_sorted.astype(jnp.int64),
                                           seg_ids, n)
            seg_scnt = jax.ops.segment_sum(js_sorted.astype(jnp.int64),
                                           seg_ids, n)
            # combined-sorted position of the first joinable build row of
            # each segment (build rows sort before stream rows? not
            # guaranteed -> take min over build rows only)
            pos = jnp.arange(n)
            seg_bstart = jax.ops.segment_min(
                jnp.where(jb_sorted, pos, n), seg_ids, n)
            # per ORIGINAL stream row: its segment & match count
            seg_of_comb = jnp.zeros(n, jnp.int32).at[perm].set(seg_ids)
            seg_of_stream = seg_of_comb[cap_b:]
            cnt = jnp.where(joinable_s, seg_bcnt[seg_of_stream], 0)
            bstart_of_stream = seg_bstart[seg_of_stream]
            # matched flags for build rows (right/full outer)
            matched_comb = jb_sorted & (seg_scnt[seg_ids] > 0)
            matched_orig = jnp.zeros(n, jnp.bool_).at[perm].set(matched_comb)
            matched_b = matched_orig[:cap_b]
            offsets = jnp.cumsum(cnt) - cnt
            total = jnp.sum(cnt)
            return (cnt, offsets, total, bstart_of_stream, perm, matched_b)
        return fn

    # ---- phase 3: expansion (jitted, keyed by out capacity) ------------
    def _expand_fn(self, out_cap, cap_b, with_left_nulls):
        from ..ops.gather import row_of_unit

        def fn(cnt, offsets, bstart_of_stream, perm, smask):
            t = jnp.arange(out_cap, dtype=jnp.int64)
            cap_s = cnt.shape[0]
            # stream row for each output slot (scatter+cummax, not
            # searchsorted — see ops.gather.row_of_unit)
            i = row_of_unit(offsets, cap_s, out_cap).astype(jnp.int64)
            if with_left_nulls:
                # left/full: unmatched live stream rows produce one row
                eff_cnt = jnp.where(smask & (cnt == 0), 1, cnt)
                offs = jnp.cumsum(eff_cnt) - eff_cnt
                i = row_of_unit(offs, cap_s, out_cap).astype(jnp.int64)
                i = jnp.clip(i, 0, cap_s - 1)
                j = t - offs[i]
                matched = cnt[i] > 0
                total = jnp.sum(eff_cnt)
            else:
                i = jnp.clip(i, 0, cap_s - 1)
                j = t - offsets[i]
                matched = cnt[i] > 0
                total = jnp.sum(cnt)
            in_bounds = t < total
            comb_pos = bstart_of_stream[i] + j
            comb_pos = jnp.clip(comb_pos, 0, perm.shape[0] - 1)
            b_orig = perm[comb_pos]           # original combined index
            b_orig = jnp.clip(b_orig, 0, cap_b - 1)
            lgather = i.astype(jnp.int32)
            rgather = b_orig.astype(jnp.int32)
            rvalid = matched & in_bounds
            lvalid = in_bounds
            return lgather, rgather, lvalid, rvalid, total
        return fn

    def _gather_cols(self, cvs, idx, inb):
        """Gather payload columns by idx — join expansion duplicates rows,
        so var-width capacities are re-measured (ops.gather.gather_cols)."""
        from ..ops.gather import gather_cols
        return gather_cols(cvs, idx, inb)

    # ------------------------------------------------------------------
    def execute_partition(self, ctx: ExecContext, pid: int):
        if self.how == "cross":
            yield from self._execute_cross(ctx)
            return
        m = ctx.metrics_for(self._op_id)
        right = self.children[1]
        stream_batches = self._stream_batches(ctx, pid)
        from ..config import (EXCHANGE_ASYNC_BROADCAST,
                              EXCHANGE_BROADCAST_TIMEOUT)
        from .broadcast import BroadcastExchangeExec, on_build_pool
        if (not self.per_partition
                and isinstance(right, BroadcastExchangeExec)
                and ctx.conf.get(EXCHANGE_ASYNC_BROADCAST)
                and not on_build_pool()):
            # async broadcast build (GpuBroadcastExchangeExec model):
            # the build materializes on a background thread while this
            # thread advances the stream side's scan/decode/pre-stage;
            # bounded prefetch so waiting batches don't pin HBM
            right.submit_build(ctx)
            prefetched = []
            while not right.build_done() and len(prefetched) < 2:
                b = next(stream_batches, None)
                if b is None:
                    break
                prefetched.append(b)
            with m.timer("buildTime"):
                bbatches = right.await_build(
                    ctx, ctx.conf.get(EXCHANGE_BROADCAST_TIMEOUT))
            if prefetched:
                import itertools
                stream_batches = itertools.chain(prefetched,
                                                 stream_batches)
        else:
            build_pids = ([pid] if self.per_partition
                          else range(right.num_partitions(ctx)))
            with m.timer("buildTime"):
                bbatches = []
                for bpid in build_pids:
                    bbatches.extend(right.execute_partition(ctx, bpid))

        from ..config import JOIN_BUILD_BUDGET
        budget = ctx.conf.get(JOIN_BUILD_BUDGET)
        total_bytes = sum(b.nbytes for b in bbatches)
        if budget > 0 and total_bytes > budget and self.lkeys:
            yield from self._execute_subpartitioned(
                ctx, m, pid, bbatches, total_bytes, budget,
                stream_batches=stream_batches)
            return

        yield from self._join_pass(ctx, m, bbatches, stream_batches)

    def _join_pass(self, ctx: ExecContext, m, bbatches, stream_batches):
        """One complete hash-join pass: concat the given build batches,
        probe every stream batch, emit unmatched build rows for
        right/full. Called once normally; once per disjoint-key
        sub-partition in the out-of-core path."""
        from .batch import maybe_compact
        left, right = self.children
        with m.timer("buildTime"):
            bbatches = [maybe_compact(b, right.schema) for b in bbatches]
            bcvs, bmask = self._concat_batches(bbatches, right.schema)
            cap_b = bmask.shape[0]
            bctx = EmitCtx(bcvs, cap_b)
            bkey_cvs = [k.emit(bctx) for k in self.rkeys]
        matched_b_acc = jnp.zeros(cap_b, jnp.bool_)
        fast = self._fast_path_ok()
        direct = None
        if fast and self.condition is None and self.how in (
                "inner", "left", "left_semi", "left_anti"):
            with m.timer("buildTime"):
                direct = self._try_build_direct(bkey_cvs, bmask, cap_b)
        if fast and direct is None:
            with m.timer("buildTime"):
                sorted_ukey, bperm, n_valid_b = self._build_sorted(
                    bkey_cvs, bmask)
        elif direct is not None:
            # sorted structures built lazily only if a stream batch needs
            # pair enumeration (duplicate build keys)
            sorted_ukey = bperm = n_valid_b = None

        from ..memory.retry import with_retry

        def probe_one(batch):
            """Idempotent per-stream-batch probe: returns (kind, payload)
            for the caller to yield/accumulate. Split-safe: all join
            semantics here are stream-row-local; matched-build marks
            OR-accumulate."""
            out = list(self._probe_batch(ctx, m, batch, bcvs, bmask,
                                         bkey_cvs, cap_b, fast,
                                         sorted_ukey if fast else None,
                                         bperm if fast else None,
                                         n_valid_b if fast else None,
                                         direct))
            return out

        for batch in stream_batches:
            batch = maybe_compact(batch, left.schema, factor=8)
            for results in with_retry(batch, probe_one):
                for kind, payload in results:
                    if kind == "matched_b":
                        matched_b_acc = matched_b_acc | payload
                    else:
                        yield payload

        if self.how in ("right", "full"):
            unmatched = bmask & ~matched_b_acc
            n_un = fetch_int((jnp.sum(unmatched)))
            if n_un > 0:
                # emit unmatched build rows with null left columns
                out_cvs = _null_cvs(left.schema.fields, cap_b)
                out_cvs += [CV(cv.data, cv.validity & unmatched, cv.offsets)
                            for cv in bcvs]
                tbl = make_table(self.schema, out_cvs, cap_b)
                yield DeviceBatch(tbl, cap_b, unmatched, cap_b)

    # ---- out-of-core: disjoint-key sub-partition loop ------------------
    def _subpartition_fn(self, key_exprs, S: int, seed: int = 0xAB5):
        """Device program extracting hash sub-partition `b` of a batch:
        rows whose join-key hash lands in bucket b compact to the front
        (GpuSubPartitionHashJoin.scala:617 rehash, TPU-style). `seed`
        varies per recursion level — re-splitting with the same seed
        would put every row back into one bucket."""
        from ..ops.gather import compact
        from ..ops.hash import partition_ids
        key_dtypes = [k.dtype for k in key_exprs]

        def fn(cvs, mask, b):
            cap = mask.shape[0]
            ectx = EmitCtx(cvs, cap)
            key_cvs = [k.emit(ectx) for k in key_exprs]
            pids = partition_ids(key_cvs, key_dtypes, S, seed=seed)
            mask_b = mask & (pids == b)
            out_cvs, count = compact(cvs, mask_b)
            return out_cvs, count
        from ..runtime.program_cache import cached_program, exprs_fp
        return cached_program(fn, cls=type(self).__name__, tag="subpart",
                              key=(exprs_fp(key_exprs), S, seed))

    def _subpart_fns(self, S: int, seed: int):
        """Cached (build-side, stream-side) sub-partition programs."""
        kb = ("subpart", "b", S, seed)
        ks = ("subpart", "s", S, seed)
        if kb not in self._count_cache:
            self._count_cache[kb] = self._subpartition_fn(
                self.rkeys, S, seed)
            self._count_cache[ks] = self._subpartition_fn(
                self.lkeys, S, seed)
        return self._count_cache[kb], self._count_cache[ks]

    def _shrink_batch(self, schema: Schema, out_cvs, nlive: int):
        """Slice a compacted (live-prefix) batch down to a bucketed
        capacity; nested columns keep their capacity (offset/child
        re-slicing is not worth the complexity here)."""
        from ..ops.gather import take_strings as _ts
        cap = out_cvs[0].validity.shape[0] if out_cvs else 128
        if any(cv.children for cv in out_cvs):
            tbl = make_table(schema, out_cvs, nlive)
            return DeviceBatch(tbl, nlive, jnp.arange(cap) < nlive, cap)
        new_cap = min(bucket_capacity(max(nlive, 1)), cap)
        cvs2 = []
        idx = jnp.arange(new_cap)
        inb = idx < nlive
        for cv in out_cvs:
            if cv.offsets is not None:
                nbytes = fetch_int(cv.offsets[nlive]) if nlive else 0
                bcap = min(bucket_capacity(max(nbytes, 1)),
                           cv.data.shape[0])
                cvs2.append(_ts(cv, idx, in_bounds=inb,
                                out_data_capacity=bcap))
            else:
                cvs2.append(CV(cv.data[:new_cap], cv.validity[:new_cap]))
        tbl = make_table(schema, cvs2, nlive)
        return DeviceBatch(tbl, nlive, inb, new_cap)

    # deepest sub-partition recursion (reference allows repeated
    # repartition, GpuSubPartitionHashJoin.scala:617)
    _MAX_SUBPART_DEPTH = 10

    def _split_both(self, ctx, m, S: int, seed: int, build_batches,
                    stream_batches):
        """Split build + stream batch iterators into S disjoint-key
        spillable piles. On error, closes everything parked so far (the
        OOC path must not leak under the very memory pressure it exists
        to handle). Returns (piles_b, bytes_b, piles_s)."""
        from ..memory.spill import spill_store
        store = spill_store(ctx.conf)
        left, right = self.children
        bfn, sfn = self._subpart_fns(S, seed)
        piles_b: List[List] = [[] for _ in range(S)]
        bytes_b = [0] * S
        piles_s: List[List] = [[] for _ in range(S)]
        try:
            with m.timer("buildTime"):
                for b in build_batches:
                    for s in range(S):
                        out_cvs, cnt = bfn(b.cvs(), b.row_mask,
                                           jnp.int32(s))
                        nlive = fetch_int(cnt)
                        if nlive == 0:
                            continue
                        sb = self._shrink_batch(right.schema, out_cvs,
                                                nlive)
                        bytes_b[s] += sb.nbytes
                        piles_b[s].append(store.add_batch(sb, priority=7))
            for batch in stream_batches:
                with m.timer("opTime"):
                    for s in range(S):
                        out_cvs, cnt = sfn(batch.cvs(), batch.row_mask,
                                           jnp.int32(s))
                        nlive = fetch_int(cnt)
                        if nlive == 0:
                            continue
                        sb = self._shrink_batch(left.schema, out_cvs,
                                                nlive)
                        piles_s[s].append(store.add_batch(sb, priority=7))
        except BaseException:
            for pile in piles_b + piles_s:
                for h in pile:
                    h.close()
            raise
        return piles_b, bytes_b, piles_s

    def _run_buckets(self, ctx, m, piles_b, bytes_b, piles_s,
                     budget: int, depth: int):
        """Dispatch each disjoint-key bucket through _join_bucket,
        closing every pile handle on generator exit (including consumer
        abandonment — close() is idempotent with the per-bucket
        finally)."""
        try:
            for s in range(len(piles_b)):
                yield from self._join_bucket(ctx, m, piles_b[s],
                                             piles_s[s], bytes_b[s],
                                             budget, depth)
        finally:
            for pile in piles_b + piles_s:
                for h in pile:
                    h.close()

    @staticmethod
    def _drain(handles):
        for h in handles:
            b = h.materialize()
            h.close()
            yield b

    def _execute_subpartitioned(self, ctx: ExecContext, m, pid, bbatches,
                                total_bytes: int, budget: int,
                                stream_batches=None):
        """Build side exceeds its budget: rehash BOTH sides into S
        disjoint-key sub-partitions parked as spillable piles, then run
        an independent join pass per sub-partition, RECURSIVELY
        re-splitting any sub-partition whose build still exceeds the
        budget (fresh hash seed per level). Keys are disjoint across
        buckets, so every join type decomposes exactly (reference:
        GpuSubPartitionHashJoin.scala:617 — 16-bucket
        repartition-and-loop)."""
        S = 2
        while S < 16 and total_bytes > S * budget:
            S *= 2
        m.add("numSubPartitions", S)

        piles_b, bytes_b, piles_s = self._split_both(
            ctx, m, S, 0xAB5, bbatches,
            stream_batches if stream_batches is not None
            else self._stream_batches(ctx, pid))
        del bbatches
        yield from self._run_buckets(ctx, m, piles_b, bytes_b, piles_s,
                                     budget, depth=1)

    def _join_bucket(self, ctx, m, bhandles, shandles, bbytes: int,
                     budget: int, depth: int):
        """Join one disjoint-key sub-partition held as spillable piles.
        Re-splits recursively while the build exceeds the budget; build
        handles stay OPEN (reservation counted) for the whole pass and
        close in a finally, so accounting reflects resident memory and
        abandoned generators leak nothing."""
        if bbytes > budget and depth < self._MAX_SUBPART_DEPTH:
            S = 2
            while S < 16 and bbytes > S * budget:
                S *= 2
            seed = (0xAB5 ^ (depth * 0x9E3779B9)) & 0x7FFFFFFF
            piles_b, bytes_b, piles_s = self._split_both(
                ctx, m, S, seed, self._drain(bhandles),
                self._drain(shandles))
            if max(bytes_b) >= bbytes:
                # degenerate (one dominant key): the split didn't shrink
                # the biggest bucket — stop recursing below, join as-is
                depth = self._MAX_SUBPART_DEPTH
            m.add("numSubPartRecursions", 1)
            yield from self._run_buckets(ctx, m, piles_b, bytes_b,
                                         piles_s, budget, depth + 1)
            return

        # terminal: one join pass. Handles stay open while their batches
        # are live (ADVICE r3: closing early releases the DeviceManager
        # reservation during the most memory-intensive phase).
        try:
            builds = [h.materialize() for h in bhandles]

            def stream_s():
                for h in shandles:
                    yield h.materialize()

            yield from self._join_pass(ctx, m, builds, stream_s())
        finally:
            for h in bhandles:
                h.close()
            for h in shandles:
                h.close()

    def _probe_batch(self, ctx, m, batch, bcvs, bmask, bkey_cvs, cap_b,
                     fast, sorted_ukey, bperm, n_valid_b, direct=None):
        """One stream batch through count/probe + expand. Yields
        ("matched_b", mask) and ("batch", DeviceBatch) items. Idempotent
        (retry/split safe): all semantics are stream-row-local and
        matched-build marks OR-accumulate in the caller."""
        with m.timer("opTime"):
            scvs, smask = batch.cvs(), batch.row_mask
            cap_s = batch.capacity
            sctx = EmitCtx(scvs, cap_s)
            skey_cvs = [k.emit(sctx) for k in self.lkeys]
            if direct is not None:
                from ..utils.transfer import fetch
                cnt, bidx = self._direct_probe(direct, skey_cvs[0], smask,
                                               cap_s)
                if self.how == "left_semi":
                    yield ("batch", DeviceBatch(
                        batch.table, batch.num_rows,
                        smask & (cnt > 0), cap_s))
                    return
                if self.how == "left_anti":
                    yield ("batch", DeviceBatch(
                        batch.table, batch.num_rows,
                        smask & (cnt == 0), cap_s))
                    return
                n_total, n_eff, n_matched, max_cnt = (
                    int(v) for v in fetch(self._probe_stats(cnt, smask)))
                if max_cnt <= 1:
                    if self.how == "inner" and n_matched == 0:
                        return
                    matched = (cnt > 0) & smask
                    rg = jnp.clip(bidx, 0, cap_b - 1)
                    yield self._fk_output(m, batch, scvs, bcvs, rg,
                                          matched, smask, n_matched,
                                          n_eff, cap_s)
                    return
                # duplicate build keys in this batch's match set: promote
                # to the sorted fast path (built once, reused)
                if "sorted" not in direct:
                    direct["sorted"] = self._build_sorted(bkey_cvs, bmask)
                sorted_ukey, bperm, n_valid_b = direct["sorted"]
            if fast:
                pkey = ("probe", cap_b, cap_s)
                pfn = self._count_cache.get(pkey)
                if pfn is None:
                    from ..runtime.program_cache import cached_program
                    pfn = cached_program(
                        self._probe_fn(cap_b, cap_s),
                        cls=type(self).__name__, tag="probe",
                        key=self._fp + (cap_b, cap_s))
                    self._count_cache[pkey] = pfn
                (cnt, offsets, total, bstart,
                 touched) = pfn(sorted_ukey, n_valid_b, skey_cvs[0],
                                smask)
                xla_stats.count_dispatch()
                perm = bperm
                if self.how in ("right", "full") and \
                        self.condition is None:
                    yield ("matched_b", self._matched_from_touched(
                        bperm, touched, n_valid_b,
                        jnp.zeros(cap_b, jnp.bool_)))
            else:
                nchunks = self._key_nchunks(bkey_cvs, bmask,
                                            skey_cvs, smask)
                ckey = (nchunks, cap_b, cap_s)
                cfn = self._count_cache.get(ckey)
                if cfn is None:
                    from ..runtime.program_cache import cached_program
                    cfn = cached_program(
                        self._count_fn(nchunks, cap_b, cap_s),
                        cls=type(self).__name__, tag="count",
                        key=self._fp + (nchunks, cap_b, cap_s))
                    self._count_cache[ckey] = cfn
                (cnt, offsets, total, bstart, perm,
                 matched_b) = cfn(bkey_cvs, bmask, skey_cvs, smask)
                xla_stats.count_dispatch()
                if self.how in ("right", "full") and \
                        self.condition is None:
                    yield ("matched_b", matched_b)
            if self.condition is not None:
                yield from self._probe_cond(m, batch, scvs, smask, cap_s,
                                            bcvs, cap_b, cnt, offsets,
                                            total, bstart, perm)
                return
            if self.how == "left_semi":
                yield ("batch", DeviceBatch(batch.table, batch.num_rows,
                                            smask & (cnt > 0), cap_s))
                return
            if self.how == "left_anti":
                yield ("batch", DeviceBatch(batch.table, batch.num_rows,
                                            smask & (cnt == 0), cap_s))
                return
            from ..utils.transfer import fetch
            n_total, n_eff, n_matched, max_cnt = (
                int(v) for v in fetch(self._probe_stats(cnt, smask)))
            with_left_nulls = self.how in ("left", "full")
            if max_cnt <= 1 and self.how in ("inner", "left"):
                # FK fast path: stream columns pass through unchanged
                if self.how == "inner" and n_matched == 0:
                    return
                rg, matched = self._fk_gather_idx(cnt, bstart, perm,
                                                  smask, cap_b)
                yield self._fk_output(m, batch, scvs, bcvs, rg, matched,
                                      smask, n_matched, n_eff, cap_s)
                return
            n_out = n_eff if with_left_nulls else n_total
            if n_out == 0:
                return
            out_cap = bucket_capacity(n_out)
            ekey = (out_cap, cap_b, cap_s, with_left_nulls)
            efn = self._expand_cache.get(ekey)
            if efn is None:
                from ..runtime.program_cache import cached_program
                efn = cached_program(
                    self._expand_fn(out_cap, cap_b, with_left_nulls),
                    cls=type(self).__name__, tag="expand",
                    key=self._fp + (out_cap, cap_b, with_left_nulls))
                self._expand_cache[ekey] = efn
            lg, rg, lvalid, rvalid, _ = efn(cnt, offsets, bstart, perm,
                                            smask)
            xla_stats.count_dispatch()
            out_cvs = self._gather_cols(scvs, lg, lvalid)
            out_cvs += self._gather_cols(bcvs, rg, rvalid)
            tbl = make_table(self.schema, out_cvs, n_out)
        m.add("numOutputRows", n_out)
        m.add("numOutputBatches", 1)
        yield ("batch", DeviceBatch(tbl, n_out,
                                    jnp.arange(out_cap) < n_out, out_cap))

    # ------------------------------------------------------------------
    def _probe_cond(self, m, batch, scvs, smask, cap_s, bcvs, cap_b,
                    cnt, offsets, total, bstart, perm):
        """Conditional-join path: expand pure candidate pairs from the
        equi keys, evaluate the bound non-equi condition on the gathered
        pair columns, then derive per-stream-row and per-build-row match
        state from the PASSING pairs only. Outer-side null extension uses
        seg_matched, not the raw candidate counts."""
        n_out = fetch_int(total)
        seg_matched = jnp.zeros(cap_s, jnp.bool_)
        if n_out > 0:
            out_cap = bucket_capacity(n_out)
            ekey = (out_cap, cap_b, cap_s, False)
            efn = self._expand_cache.get(ekey)
            if efn is None:
                from ..runtime.program_cache import cached_program
                efn = cached_program(
                    self._expand_fn(out_cap, cap_b, False),
                    cls=type(self).__name__, tag="expand",
                    key=self._fp + (out_cap, cap_b, False))
                self._expand_cache[ekey] = efn
            lg, rg, lvalid, rvalid, _ = efn(cnt, offsets, bstart, perm,
                                            smask)
            lcols = self._gather_cols(scvs, lg, lvalid)
            rcols = self._gather_cols(bcvs, rg, rvalid)
            cctx = EmitCtx(lcols + rcols, out_cap)
            ccv = self.condition.emit(cctx)
            pass_ = (lvalid & rvalid & ccv.validity
                     & ccv.data.astype(jnp.bool_))
            seg_matched = seg_matched.at[lg].max(pass_)
            if self.how in ("right", "full"):
                mb = jnp.zeros(cap_b, jnp.bool_).at[rg].max(pass_)
                yield ("matched_b", mb)
            if self.how not in ("left_semi", "left_anti"):
                tbl = make_table(self.schema, lcols + rcols, n_out)
                m.add("numOutputRows", n_out)
                m.add("numOutputBatches", 1)
                yield ("batch", DeviceBatch(tbl, n_out, pass_, out_cap))
        if self.how == "left_semi":
            yield ("batch", DeviceBatch(batch.table, batch.num_rows,
                                        smask & seg_matched, cap_s))
        elif self.how == "left_anti":
            yield ("batch", DeviceBatch(batch.table, batch.num_rows,
                                        smask & ~seg_matched, cap_s))
        elif self.how in ("left", "full"):
            # stream rows with no PASSING pair -> one null-extended row
            null_mask = smask & ~seg_matched
            out_cvs = list(batch.cvs()) + _null_cvs(
                self.children[1].schema.fields, cap_s)
            tbl = make_table(self.schema, out_cvs, batch.num_rows)
            yield ("batch", DeviceBatch(tbl, batch.num_rows, null_mask,
                                        cap_s))

    # ------------------------------------------------------------------
    def _execute_cross(self, ctx: ExecContext):
        m = ctx.metrics_for(self._op_id)
        left, right = self.children
        bcvs, bmask = self._collect_side(ctx, right, [])
        cap_b = bmask.shape[0]
        # densify build side row ids on host once
        bidx = jnp.nonzero(bmask, size=cap_b, fill_value=0)[0]
        n_b = fetch_int((jnp.sum(bmask)))
        for lpid in range(left.num_partitions(ctx)):
            for batch in left.execute_partition(ctx, lpid):
                ctx.check_cancel()
                scvs, smask = batch.cvs(), batch.row_mask
                cap_s = batch.capacity
                sidx = jnp.nonzero(smask, size=cap_s, fill_value=0)[0]
                n_s = fetch_int((jnp.sum(smask)))
                n_out = n_s * n_b
                if n_out == 0:
                    continue
                out_cap = bucket_capacity(n_out)
                t = jnp.arange(out_cap)
                li = sidx[jnp.clip(t // max(n_b, 1), 0, cap_s - 1)]
                ri = bidx[jnp.clip(t % max(n_b, 1), 0, cap_b - 1)]
                inb = t < n_out
                out_cvs = self._gather_cols(scvs, li.astype(jnp.int32), inb)
                out_cvs += self._gather_cols(bcvs, ri.astype(jnp.int32), inb)
                tbl = make_table(self.schema, out_cvs, n_out)
                m.add("numOutputRows", n_out)
                yield DeviceBatch(tbl, n_out, inb, out_cap)


class NestedLoopJoinExec(HashJoinExec):
    """Broadcast nested-loop join: no equi keys, arbitrary condition
    (reference: GpuBroadcastNestedLoopJoinExecBase.scala). The build side
    is collected once; each stream batch crosses against it in bounded
    chunks (stream-slice x full build), the condition evaluates on the
    gathered pair columns, and outer/semi/anti semantics derive from the
    passing pairs exactly as in the conditional hash join."""

    _CHUNK_TARGET = 1 << 20

    def __init__(self, left: TpuExec, right: TpuExec, how: str,
                 schema: Schema, condition: Expression):
        super().__init__(left, right, [], [], how, schema,
                         condition=condition)

    def describe(self):
        return f"NestedLoopJoinExec[{self.how}]"

    def num_partitions(self, ctx):
        return 1

    def execute_partition(self, ctx: ExecContext, pid: int):
        m = ctx.metrics_for(self._op_id)
        left, right = self.children
        with m.timer("buildTime"):
            bcvs, bmask = self._collect_side(ctx, right, [])
            cap_b = bmask.shape[0]
            bidx = jnp.nonzero(bmask, size=cap_b, fill_value=0)[0]
            n_b = fetch_int(jnp.sum(bmask))
        matched_b_acc = jnp.zeros(cap_b, jnp.bool_)
        right_fields = right.schema.fields
        for lpid in range(left.num_partitions(ctx)):
            for batch in left.execute_partition(ctx, lpid):
                ctx.check_cancel()
                scvs, smask = batch.cvs(), batch.row_mask
                cap_s = batch.capacity
                sidx = jnp.nonzero(smask, size=cap_s, fill_value=0)[0]
                n_s = fetch_int(jnp.sum(smask))
                seg_matched = jnp.zeros(cap_s, jnp.bool_)
                if n_b > 0 and n_s > 0:
                    chunk = max(1, self._CHUNK_TARGET // max(n_b, 1))
                    for s0 in range(0, n_s, chunk):
                        k = min(chunk, n_s - s0)
                        n_out = k * n_b
                        out_cap = bucket_capacity(n_out)
                        with m.timer("opTime"):
                            t = jnp.arange(out_cap)
                            li = sidx[jnp.clip(s0 + t // n_b, 0,
                                               cap_s - 1)].astype(
                                jnp.int32)
                            ri = bidx[jnp.clip(t % n_b, 0,
                                               cap_b - 1)].astype(
                                jnp.int32)
                            inb = t < n_out
                            lcols = self._gather_cols(scvs, li, inb)
                            rcols = self._gather_cols(bcvs, ri, inb)
                            cctx = EmitCtx(lcols + rcols, out_cap)
                            ccv = self.condition.emit(cctx)
                            pass_ = (inb & ccv.validity
                                     & ccv.data.astype(jnp.bool_))
                            seg_matched = seg_matched.at[li].max(pass_)
                            if self.how in ("right", "full"):
                                matched_b_acc = \
                                    matched_b_acc.at[ri].max(pass_)
                        if self.how not in ("left_semi", "left_anti"):
                            tbl = make_table(self.schema, lcols + rcols,
                                             n_out)
                            m.add("numOutputBatches", 1)
                            yield DeviceBatch(tbl, n_out, pass_, out_cap)
                if self.how == "left_semi":
                    yield DeviceBatch(batch.table, batch.num_rows,
                                      smask & seg_matched, cap_s)
                elif self.how == "left_anti":
                    yield DeviceBatch(batch.table, batch.num_rows,
                                      smask & ~seg_matched, cap_s)
                elif self.how in ("left", "full"):
                    null_mask = smask & ~seg_matched
                    out_cvs = list(batch.cvs()) + _null_cvs(
                        right_fields, cap_s)
                    tbl = make_table(self.schema, out_cvs,
                                     batch.num_rows)
                    yield DeviceBatch(tbl, batch.num_rows, null_mask,
                                      cap_s)
        if self.how in ("right", "full"):
            unmatched = bmask & ~matched_b_acc
            n_un = fetch_int(jnp.sum(unmatched))
            if n_un > 0:
                out_cvs = _null_cvs(left.schema.fields, cap_b)
                out_cvs += [CV(cv.data, cv.validity & unmatched,
                               cv.offsets) for cv in bcvs]
                tbl = make_table(self.schema, out_cvs, cap_b)
                yield DeviceBatch(tbl, cap_b, unmatched, cap_b)
