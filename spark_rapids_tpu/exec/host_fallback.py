"""Host-fallback execution nodes (the GpuCpuBridge analog).

(reference: GpuCpuBridgeExpression.scala / GpuCpuBridgeThreadPool.scala —
unsupported expressions copy to host rows, evaluate on CPU, and return to
the device; RapidsMeta tags explain why.) A batch round-trips
device -> arrow -> row dicts -> interpreter (expr/host_eval.py) ->
arrow -> device. Slow and proud of it: the alternative is a failed query.
"""
from __future__ import annotations

from typing import List, Optional

import pyarrow as pa

from ..columnar.table import Schema, Table
from ..expr.host_eval import host_eval_rows
from .base import ExecContext, TpuExec
from .batch import DeviceBatch

__all__ = ["HostFilterExec", "HostProjectExec"]


def _batch_rows(batch: DeviceBatch):
    import numpy as np
    import pyarrow.types as pt
    from .nodes import _batch_to_arrow
    at = _batch_to_arrow(batch)
    names = at.schema.names
    cols = []
    for i in range(at.num_columns):
        vals = at.column(i).to_pylist()
        # integers ride as WIDTH-TYPED numpy scalars so interpreter
        # arithmetic wraps like Java/device (int32*int32 wraps at 32
        # bits); plain Python ints would widen unboundedly and diverge
        # from the device result on overflow
        t = at.schema.types[i]
        if pt.is_integer(t):
            np_t = np.dtype(t.to_pandas_dtype()).type
            vals = [None if v is None else np_t(v) for v in vals]
        cols.append(vals)
    rows = [dict(zip(names, vals)) for vals in zip(*cols)] \
        if at.num_rows else []
    return at, rows


class HostFilterExec(TpuExec):
    """Filter whose predicate runs on host rows."""

    def __init__(self, child: TpuExec, condition, reason: str):
        super().__init__([child], child.schema)
        self.condition = condition
        self.reason = reason

    def describe(self):
        return f"HostFilterExec[{self.condition!r}]  (CPU: {self.reason})"

    def execute_partition(self, ctx: ExecContext, pid: int):
        m = ctx.metrics_for(self._op_id)
        for batch in self.children[0].execute_partition(ctx, pid):
            ctx.check_cancel()
            with m.timer("hostEvalTime"):
                at, rows = _batch_rows(batch)
                if not rows:
                    continue
                keep = host_eval_rows(self.condition, rows)
                mask = pa.array([bool(k) if k is not None else False
                                 for k in keep])
                filtered = at.filter(mask)
            if filtered.num_rows == 0:
                continue
            tbl = Table.from_arrow(filtered)
            m.add("numOutputBatches", 1)
            m.add("numOutputRows", filtered.num_rows)
            yield DeviceBatch(tbl, filtered.num_rows)


class HostProjectExec(TpuExec):
    """Project where SOME output expressions run on host rows; supported
    ones still evaluate there too (whole-node fallback, round 2 — the
    reference bridges per-expression)."""

    def __init__(self, child: TpuExec, exprs, schema: Schema, reason: str):
        super().__init__([child], schema)
        self.exprs = list(exprs)
        self.reason = reason

    def describe(self):
        return (f"HostProjectExec[{len(self.exprs)} exprs]  "
                f"(CPU: {self.reason})")

    def execute_partition(self, ctx: ExecContext, pid: int):
        m = ctx.metrics_for(self._op_id)
        for batch in self.children[0].execute_partition(ctx, pid):
            ctx.check_cancel()
            with m.timer("hostEvalTime"):
                at, rows = _batch_rows(batch)
                arrays = []
                from ..columnar.dtypes import to_arrow as dt_to_arrow
                for e, f in zip(self.exprs, self.schema.fields):
                    vals = host_eval_rows(e, rows)
                    arrays.append(pa.array(vals, dt_to_arrow(f.dtype)))
                out = (pa.Table.from_arrays(arrays,
                                            names=list(self.schema.names))
                       if arrays else pa.table({}))
            tbl = Table.from_arrow(out)
            m.add("numOutputBatches", 1)
            m.add("numOutputRows", out.num_rows)
            yield DeviceBatch(tbl, out.num_rows)
