"""Expand: GROUPING SETS / ROLLUP / CUBE row expansion.

(reference: GpuExpandExec.scala — each input row is emitted once per
projection list.) TPU-first: all grouping-set projections are computed in
ONE jitted program and laid out as contiguous blocks of the (static)
output capacity n_sets * cap; excluded keys are the key column with its
validity zeroed (no per-row branching), and the grouping-id column is a
block-constant fill. The aggregation above groups by
(keys..., grouping_id) so subtotal rows can't merge with genuine-null
detail rows.
"""
from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from ..columnar.table import Schema
from ..expr.expressions import EmitCtx, Expression
from ..ops.concat import concat_cvs, concat_masks
from ..ops.kernel_utils import CV
from .base import ExecContext, TpuExec
from .batch import DeviceBatch
from .nodes import make_table

__all__ = ["ExpandExec"]


class ExpandExec(TpuExec):
    def __init__(self, child: TpuExec, bound_keys: Sequence[Expression],
                 include_masks: Sequence[Sequence[bool]], schema: Schema):
        super().__init__([child], schema)
        self.bound_keys = list(bound_keys)
        self.include_masks = [tuple(m) for m in include_masks]
        nk = len(self.bound_keys)
        # Spark grouping_id: bit (nk-1-i) set when key i is EXCLUDED
        self.gids = [
            sum((0 if inc else 1) << (nk - 1 - i)
                for i, inc in enumerate(m)) for m in self.include_masks]
        child_dts = [f.dtype for f in child.schema.fields]
        key_dts = [k.dtype for k in self.bound_keys]

        def _run(cvs, mask):
            cap = mask.shape[0]
            ctx = EmitCtx(list(cvs), cap)
            kcvs = [k.emit(ctx) for k in self.bound_keys]
            n_sets = len(self.include_masks)
            out = []
            for i, cv in enumerate(cvs):
                out.append(concat_cvs([cv] * n_sets, child_dts[i]))
            for i, kcv in enumerate(kcvs):
                # excluded sets get an all-null column with ZEROED
                # buffers: grouping normalizes on (data, validity), so
                # stale data under null would split subtotal groups
                null_cv = CV(
                    jnp.zeros_like(kcv.data), jnp.zeros(cap, jnp.bool_),
                    None if kcv.offsets is None
                    else jnp.zeros_like(kcv.offsets),
                    kcv.children)
                parts = [kcv if m[i] else null_cv
                         for m in self.include_masks]
                out.append(concat_cvs(parts, key_dts[i]))
            gid = jnp.concatenate([jnp.full(cap, g, jnp.int64)
                                   for g in self.gids])
            out.append(CV(gid, jnp.ones(cap * n_sets, jnp.bool_)))
            out_mask = concat_masks([mask] * n_sets)
            return out, out_mask

        from ..runtime.program_cache import cached_program, exprs_fp
        self._jit = cached_program(
            _run, cls="ExpandExec", tag="run",
            key=(exprs_fp(self.bound_keys),
                 tuple(self.include_masks)))

    def describe(self):
        return (f"ExpandExec[{len(self.include_masks)} sets, "
                f"keys={[k.name for k in self.bound_keys]}]")

    def num_partitions(self, ctx):
        return self.children[0].num_partitions(ctx)

    def execute_partition(self, ctx: ExecContext, pid: int):
        m = ctx.metrics_for(self._op_id)
        n_sets = len(self.include_masks)
        for batch in self.children[0].execute_partition(ctx, pid):
            ctx.check_cancel()
            with m.timer("opTime"):
                out, out_mask = self._jit(batch.cvs(), batch.row_mask)
            num = (n_sets - 1) * batch.capacity + batch.num_rows
            m.add("numOutputBatches", 1)
            m.add("numOutputRows", batch.num_rows * n_sets)
            yield DeviceBatch(
                make_table(self.schema, out, num), num, out_mask,
                batch.capacity * n_sets)
