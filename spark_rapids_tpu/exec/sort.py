"""Sort execution.

Analog of GpuSortExec (reference: GpuSortExec.scala:87; SortUtils.scala).
TPU-first: one fused XLA program — radix-normalized order keys (Spark
null ordering + NaN-greatest + descending via bitwise complement),
stable lexsort, then a gather of every payload column. Dead rows sort to
the back. The out-of-core chunked merge path arrives with the spill
framework; round-1 concatenates all input batches on device.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.table import Schema
from ..expr.expressions import EmitCtx
from ..ops import sortkeys as sk
from ..ops.concat import concat_cvs, concat_masks
from ..ops.gather import take
from ..ops.kernel_utils import CV
from ..utils.transfer import fetch_int
from .base import ExecContext, TpuExec
from .batch import DeviceBatch
from .nodes import make_table

__all__ = ["SortExec", "sort_batch_cvs"]


def _order_key_arrays(key_cvs, orders, nchunks):
    arrays = []
    for kcv, o, nc in zip(key_cvs, orders, nchunks):
        vkey = kcv.validity.astype(jnp.uint8)
        arrays.append(vkey if o.nulls_first else ~vkey)
        arrays.extend(sk.order_keys(kcv, o.expr.dtype, nc,
                                    descending=not o.ascending))
    return arrays


def sort_batch_cvs(cvs: Sequence[CV], mask, orders, nchunks):
    """Returns (sorted_cvs, out_mask): live rows dense at the front in
    the requested order. Runs inside jit."""
    cap = mask.shape[0]
    ctx = EmitCtx(list(cvs), cap)
    key_cvs = [o.expr.emit(ctx) for o in orders]
    arrays = [jnp.logical_not(mask).astype(jnp.uint8)]  # dead rows last
    arrays += _order_key_arrays(key_cvs, orders, nchunks)
    perm = sk.lexsort(arrays)
    live_sorted = mask[perm]
    out = [take(cv, perm, in_bounds=live_sorted) for cv in cvs]
    return out, live_sorted


class SortExec(TpuExec):
    def __init__(self, child: TpuExec, bound_orders, schema: Schema):
        super().__init__([child], schema)
        self.orders = list(bound_orders)
        self._jit_cache = {}

    def num_partitions(self, ctx):
        return 1

    def describe(self):
        return f"SortExec[{self.orders}]"

    def _nchunks(self, cvs, mask) -> Tuple[int, ...]:
        ncs = []
        ctx = EmitCtx(list(cvs), mask.shape[0])
        for o in self.orders:
            if isinstance(o.expr.dtype, (dt.StringType, dt.BinaryType)):
                kcv = o.expr.emit(ctx)
                lens = kcv.offsets[1:] - kcv.offsets[:-1]
                lens = jnp.where(mask & kcv.validity, lens, 0)
                ncs.append(sk.nchunks_for_len(
                    max(fetch_int((jnp.max(lens))), 1)))
            else:
                ncs.append(0)
        return tuple(ncs)

    def execute_partition(self, ctx: ExecContext, pid: int):
        m = ctx.metrics_for(self._op_id)
        child = self.children[0]
        batches: List[DeviceBatch] = []
        for cpid in range(child.num_partitions(ctx)):
            batches.extend(child.execute_partition(ctx, cpid))
        if not batches:
            return
        with m.timer("sortTime"):
            if len(batches) == 1:
                cvs, mask = batches[0].cvs(), batches[0].row_mask
            else:
                ncols = len(batches[0].table.columns)
                cvs = [concat_cvs([b.cvs()[i] for b in batches],
                                  self.schema.fields[i].dtype)
                       for i in range(ncols)]
                mask = concat_masks([b.row_mask for b in batches])
            nchunks = self._nchunks(cvs, mask)
            fn = self._jit_cache.get(nchunks)
            if fn is None:
                fn = jax.jit(lambda c, mk: sort_batch_cvs(
                    c, mk, self.orders, nchunks))
                self._jit_cache[nchunks] = fn
            out, out_mask = fn(cvs, mask)
        cap = out_mask.shape[0]
        m.add("numOutputBatches", 1)
        yield DeviceBatch(make_table(self.schema, out, cap), cap, out_mask,
                          cap)
