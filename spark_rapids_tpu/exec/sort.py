"""Sort execution.

Analog of GpuSortExec (reference: GpuSortExec.scala:87; SortUtils.scala).
TPU-first: one fused XLA program — radix-normalized order keys (Spark
null ordering + NaN-greatest + descending via bitwise complement),
stable lexsort, then a gather of every payload column. Dead rows sort to
the back. Inputs collect into spill-store handles (bounded HBM); above
the out-of-core threshold the sort becomes a range exchange over the
handles plus independent per-partition sorts emitted in range order.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.table import Schema
from ..expr.expressions import EmitCtx
from ..ops import sortkeys as sk
from ..ops.concat import concat_cvs, concat_masks
from ..ops.gather import take
from ..ops.kernel_utils import CV
from ..profiler import xla_stats
from ..utils.transfer import fetch_int
from .base import ExecContext, TpuExec
from .batch import DeviceBatch
from .nodes import make_table

__all__ = ["SortExec", "sort_batch_cvs"]


def _order_key_arrays(key_cvs, orders, nchunks):
    arrays = []
    for kcv, o, nc in zip(key_cvs, orders, nchunks):
        vkey = kcv.validity.astype(jnp.uint8)
        arrays.append(vkey if o.nulls_first else ~vkey)
        arrays.extend(sk.order_keys(kcv, o.expr.dtype, nc,
                                    descending=not o.ascending))
    return arrays


def sort_batch_cvs(cvs: Sequence[CV], mask, orders, nchunks):
    """Returns (sorted_cvs, out_mask): live rows dense at the front in
    the requested order. Runs inside jit."""
    cap = mask.shape[0]
    ctx = EmitCtx(list(cvs), cap)
    key_cvs = [o.expr.emit(ctx) for o in orders]
    arrays = [jnp.logical_not(mask).astype(jnp.uint8)]  # dead rows last
    arrays += _order_key_arrays(key_cvs, orders, nchunks)
    perm = sk.lexsort(arrays)
    live_sorted = mask[perm]
    out = [take(cv, perm, in_bounds=live_sorted) for cv in cvs]
    return out, live_sorted


class SortExec(TpuExec):
    """In-core: concat + one fused sort. Out-of-core (input above
    sql.sort.outOfCore.thresholdBytes, single ascending/nulls-first
    leading key): range-exchange the input into ordered spill-file
    partitions, sort each partition independently, emit in partition
    order — bounded device memory (reference: GpuSortExec.scala:44
    out-of-core mode, redesigned around the exchange)."""

    # the collect loop applies the fusable child chain as one pre-stage
    # program per batch (sort keys themselves already emit inside the
    # sort program); the fusion pass leaves the prefix alone
    fuses_child_chain = True

    def __init__(self, child: TpuExec, bound_orders, schema: Schema):
        super().__init__([child], schema)
        self.orders = list(bound_orders)
        self._jit_cache = {}
        # resolved lazily at first execute (see UngroupedAggExec)
        self._base = None
        self._stages = None
        self._n_fused = 0
        self._pre_jit = None

    def _resolve_fusion(self, ctx):
        if self._base is None:
            from ..config import STAGE_FUSION_ENABLED
            from .base import collapse_fusable
            if ctx.conf.get(STAGE_FUSION_ENABLED):
                self._base, self._stages, self._n_fused = collapse_fusable(
                    self.children[0])
            else:
                self._base, self._n_fused = self.children[0], 0
            if self._n_fused:
                from ..runtime.program_cache import cached_program
                # tpulint: allow[fp-unstable-attr,unstable-program-key] id(self) is the documented per-instance fallback key: unshared, never falsely shared, excluded from warm packs
                self._pre_jit = cached_program(
                    self._stages, cls="SortExec", tag="pre",
                    key=getattr(self._stages, "_stage_fp",
                                ("inst", id(self))))

    def num_partitions(self, ctx):
        return 1

    def describe(self):
        fused = f", fused_stages={self._n_fused}" if self._n_fused else ""
        return f"SortExec[{self.orders}{fused}]"

    def _nchunks(self, cvs, mask) -> Tuple[int, ...]:
        ncs = []
        ctx = EmitCtx(list(cvs), mask.shape[0])
        for o in self.orders:
            if isinstance(o.expr.dtype, (dt.StringType, dt.BinaryType)):
                kcv = o.expr.emit(ctx)
                lens = kcv.offsets[1:] - kcv.offsets[:-1]
                lens = jnp.where(mask & kcv.validity, lens, 0)
                ncs.append(sk.nchunks_for_len(
                    max(fetch_int((jnp.max(lens))), 1)))
            else:
                ncs.append(0)
        return tuple(ncs)

    def _ooc_eligible(self, ctx) -> bool:
        from ..config import SORT_OOC_ENABLED
        if not ctx.conf.get(SORT_OOC_ENABLED):
            return False
        o0 = self.orders[0]
        # range boundaries follow ascending natural order with nulls in
        # partition 0; other leading orders fall back to in-core
        return (o0.ascending and o0.nulls_first
                and not isinstance(o0.expr.dtype,
                                   (dt.StringType, dt.BinaryType)))

    def _sort_one_batch(self, ctx, cvs, mask):
        m = ctx.metrics_for(self._op_id)
        with m.timer("sortTime"):
            nchunks = self._nchunks(cvs, mask)
            fn = self._jit_cache.get(nchunks)
            if fn is None:
                from ..runtime.program_cache import (cached_program,
                                                     exprs_fp)
                fn = cached_program(
                    lambda c, mk, _nc=nchunks:
                    sort_batch_cvs(c, mk, self.orders, _nc),
                    cls="SortExec", tag="sort",
                    key=(exprs_fp(self.orders), nchunks))
                self._jit_cache[nchunks] = fn
            out, out_mask = fn(cvs, mask)
        xla_stats.count_dispatch()
        cap = out_mask.shape[0]
        m.add("numOutputBatches", 1)
        return DeviceBatch(make_table(self.schema, out, cap), cap,
                           out_mask, cap)

    def execute_partition(self, ctx: ExecContext, pid: int):
        """Collect the child into spillable handles (the SpillStore keeps
        HBM bounded while we measure the exact input size), then pick
        in-core (one fused sort) or out-of-core (range exchange over the
        handles + per-partition sorts, reference GpuSortExec.scala:44)."""
        from ..config import SORT_OOC_THRESHOLD
        from ..memory.spill import spill_store
        self._resolve_fusion(ctx)
        m = ctx.metrics_for(self._op_id)
        child = self._base
        store = spill_store(ctx.conf)
        handles = []
        total = 0
        try:
            from ..memory.retry import retry_no_split
            from .batch import maybe_compact
            for cpid in range(child.num_partitions(ctx)):
                for batch in child.execute_partition(ctx, cpid):
                    ctx.check_cancel()
                    if self._n_fused:
                        cvs2, mask2 = self._pre_jit(batch.cvs(),
                                                    batch.row_mask)
                        xla_stats.count_dispatch()
                        batch = DeviceBatch(
                            make_table(self.schema, cvs2, batch.num_rows),
                            batch.num_rows, mask2, batch.capacity)
                    batch = maybe_compact(batch, self.schema)
                    handles.append(retry_no_split(
                        lambda b=batch: store.add_batch(b)))
                    total += batch.nbytes
            if not handles:
                return
            thr = ctx.conf.get(SORT_OOC_THRESHOLD)
            if total > thr and self._ooc_eligible(ctx):
                m.add("oocRangePartitions",
                      max(2, int(2 * total // max(thr, 1)) + 1))
                yield from self._execute_out_of_core(ctx, handles, total)
                return
            batches = [h.materialize() for h in handles]
            if len(batches) == 1:
                cvs, mask = batches[0].cvs(), batches[0].row_mask
            else:
                ncols = len(batches[0].table.columns)
                cvs = [concat_cvs([b.cvs()[i] for b in batches],
                                  self.schema.fields[i].dtype)
                       for i in range(ncols)]
                mask = concat_masks([b.row_mask for b in batches])
            yield self._sort_one_batch(ctx, cvs, mask)
        finally:
            for h in handles:
                h.close()

    def _execute_out_of_core(self, ctx: ExecContext, handles, total):
        from ..config import SORT_OOC_THRESHOLD
        from ..exec.exchange import RangeShuffleExchangeExec
        thr = ctx.conf.get(SORT_OOC_THRESHOLD)
        nparts = max(2, int(2 * total // max(thr, 1)) + 1)
        ex = RangeShuffleExchangeExec(
            _HandleScanExec(handles, self.schema), nparts,
            [self.orders[0].expr], self.schema)
        for rp in range(nparts):  # partitions are range-ordered
            for batch in ex.execute_partition(ctx, rp):
                ctx.check_cancel()
                yield self._sort_one_batch(ctx, batch.cvs(),
                                           batch.row_mask)



class _HandleScanExec(TpuExec):
    """Serves spill-store handles as batches, one child partition per
    handle (feeds the out-of-core sort's range exchange)."""

    def __init__(self, handles, schema: Schema):
        super().__init__([], schema)
        self.handles = list(handles)

    def num_partitions(self, ctx):
        return max(1, len(self.handles))

    def execute_partition(self, ctx, pid):
        if pid < len(self.handles):
            yield self.handles[pid].materialize()
