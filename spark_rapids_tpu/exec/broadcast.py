"""Broadcast exchange: collect-once build side for broadcast hash joins.

(reference: GpuBroadcastExchangeExec.scala — the build side materializes
ON A BACKGROUND THREAD bounded by spark.sql.broadcastTimeout, so the
stream side's scan/decode overlaps the build instead of serializing
behind it.) The node owns the materialized build batches, so (a) the
join can kick the build off asynchronously at execute time and block
only when it actually needs the data, and (b) the plan-level reuse pass
(plan/reuse.py) can dedupe structurally identical broadcast subtrees —
both consumers share one materialization under the instance lock.

Timeout semantics: `await_build` degrades, never hangs. Past the conf
deadline it counts `broadcastTimeoutFallbacks` and runs the build
synchronously on the calling thread — if the background future already
started, the instance lock makes that a bounded wait for the in-flight
build (which still polls the cancel token) rather than duplicate work.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from .base import ExecContext, TpuExec

__all__ = ["BroadcastExchangeExec", "on_build_pool"]

_POOL_LOCK = threading.Lock()
_POOL = None

# the bounded build pool's worker-name prefix: on_build_pool() keys off
# it, and runtime/lockdep's check_pool_wait guards await_build with it
BUILD_POOL_PREFIX = "tpu-bcast-build"


def _build_pool():
    """Shared daemon pool for async broadcast builds (a few concurrent
    builds at most: one per broadcast join executing)."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            import concurrent.futures as cf
            _POOL = cf.ThreadPoolExecutor(
                max_workers=4, thread_name_prefix=BUILD_POOL_PREFIX)
        return _POOL


def on_build_pool() -> bool:
    """True when the current thread IS a broadcast-build pool worker.
    A build whose subtree contains another broadcast join must
    materialize that nested build inline: submitting it to the same
    bounded pool and waiting on the future forms a wait cycle (every
    worker parked on a future queued behind itself) that only the
    await timeout can break."""
    return threading.current_thread().name.startswith(BUILD_POOL_PREFIX)


class BroadcastExchangeExec(TpuExec):
    def __init__(self, child: TpuExec, schema):
        super().__init__([child], schema)
        from ..runtime import lockdep
        self._lock = lockdep.rlock("BroadcastExchangeExec._lock")
        self._batches: Optional[List] = None
        self._future = None
        self._future_lock = lockdep.lock(
            "BroadcastExchangeExec._future_lock")
        self._submit_t: Optional[float] = None

    def describe(self):
        return "BroadcastExchangeExec"

    def num_partitions(self, ctx):
        return 1

    # ------------------------------------------------------------------
    def _materialize(self, ctx: ExecContext) -> List:
        with self._lock:
            if self._batches is None:
                m = ctx.metrics_for(self._op_id)
                child = self.children[0]
                out = []
                with m.timer("buildTime"):
                    for bpid in range(child.num_partitions(ctx)):
                        for b in child.execute_partition(ctx, bpid):
                            ctx.check_cancel()
                            out.append(b)
                m.set("numOutputBatches", len(out))
                self._batches = out
            return self._batches

    def build_done(self) -> bool:
        """Whether the materialized build is ready without blocking."""
        # tpulint: allow[unlocked-shared-write] monotonic None->list memo written under _lock; a stale None only reports not-ready
        if self._batches is not None:
            return True
        f = self._future
        return f is not None and f.done()

    def submit_build(self, ctx: ExecContext):
        """Kick the build onto the background pool; idempotent (one
        future per instance, shared by every consumer)."""
        with self._future_lock:
            if self._future is None:
                # tpulint: allow[fp-unstable-attr] runtime timing capture, not plan identity
                self._submit_t = time.perf_counter()
                from ..profiler import tracing
                tc = getattr(ctx, "trace", None) or tracing.current()

                def _build_task():
                    # build runs on a tpu-bcast-build thread: seed it
                    # with the submitting query's trace context
                    ctx.check_cancel()
                    with tracing.use(tc), \
                            tracing.span("broadcast.build",
                                         "pool_task"):
                        return self._materialize(ctx)

                self._future = _build_pool().submit(_build_task)
            return self._future

    def await_build(self, ctx: ExecContext,
                    timeout_secs: float) -> List:
        """Block on the async build, bounded by timeout_secs (0 = wait
        forever). On timeout: count the fallback and run/join the build
        synchronously on this thread — never an unbounded silent hang."""
        import concurrent.futures as cf

        from ..runtime import lockdep
        m = ctx.metrics_for(self._op_id)
        fut = self.submit_build(ctx)
        # the q2 wait-cycle guard, live: blocking on a build future FROM
        # a build worker parks the bounded pool behind itself (join.py's
        # on_build_pool() gate makes this unreachable in practice; the
        # witness proves it stays that way)
        lockdep.check_pool_wait(BUILD_POOL_PREFIX)
        t_await = time.perf_counter()

        def _note_wait():
            # the time the JOIN was blocked on the async build — a
            # pool_wait edge on the critical path (the overlap portion
            # below is free and earns no span)
            waited = time.perf_counter() - t_await
            if waited > 1e-3:
                from ..profiler import tracing
                tracing.record_wait_span("broadcast.await_build",
                                         "pool_wait", waited * 1e3,
                                         ctx)

        try:
            batches = fut.result(timeout_secs if timeout_secs
                                 and timeout_secs > 0 else None)
            _note_wait()
        except cf.TimeoutError:
            _note_wait()
            m.add("broadcastTimeoutFallbacks", 1)
            fut.cancel()  # not-yet-started futures build fresh below
            batches = self._materialize(ctx)
        # build time that ran while the stream side worked: everything
        # between submit and the moment the join blocked on the result
        if self._submit_t is not None:
            overlap = max(0.0, t_await - self._submit_t)
            m.add("broadcastBuildOverlapMs", round(overlap * 1e3, 3))
            self._submit_t = None
        return batches

    # ------------------------------------------------------------------
    def execute_partition(self, ctx: ExecContext, pid: int):
        for b in self._materialize(ctx):
            ctx.check_cancel()
            yield b

    def release(self):
        with self._lock:
            self._batches = None
        with self._future_lock:
            self._future = None
        super().release()
