"""GenerateExec: explode / posexplode (+_outer) over list and map columns.

Reference: sql-plugin/.../GpuGenerateExec.scala (GpuExplode, GpuPosExplode,
outer variants). TPU-first design: per batch, ONE fused program computes the
generator array and its effective per-row fan-out; the output row -> (parent
row, element) map is a searchsorted over the output offsets — the same
static-shape expansion pattern the join count/expand path uses, so the whole
generate is two jitted programs (count, expand) regardless of row count.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.column import bucket_capacity
from ..columnar.table import Schema
from ..expr.expressions import EmitCtx, Expression
from ..ops import gather as ops_gather
from ..ops.kernel_utils import CV
from ..utils.transfer import fetch
from .base import TpuExec
from .batch import DeviceBatch
from .nodes import make_table

__all__ = ["GenerateExec"]


class GenerateExec(TpuExec):
    def __init__(self, child: TpuExec, bound_gen, schema: Schema,
                 outer: bool = False):
        super().__init__([child], schema)
        self.gen = bound_gen                  # bound Explode/PosExplode
        self.outer = outer or bound_gen.outer
        self.with_pos = bound_gen.with_position
        self.is_map = isinstance(bound_gen.child.dtype, dt.MapType)

        def _count(cvs, mask):
            ctx = EmitCtx(cvs, mask.shape[0])
            arr = self.gen.child.emit(ctx)
            lens = (arr.offsets[1:] - arr.offsets[:-1]).astype(jnp.int32)
            lens = jnp.where(arr.validity & mask, lens, 0)
            if self.outer:
                # empty/null arrays on live rows still emit one (null) row
                eff = jnp.where(mask, jnp.maximum(lens, 1), 0)
            else:
                eff = lens
            out_off = jnp.concatenate([
                jnp.zeros(1, jnp.int32), jnp.cumsum(eff).astype(jnp.int32)])
            # var-width output sizing: parent col i repeats eff[i] times
            measures = [ops_gather.repeat_measures(cv, eff) for cv in cvs]
            return arr, lens, out_off, out_off[mask.shape[0]], measures

        from ..runtime.program_cache import cached_program, expr_fp
        self._gen_fp = (expr_fp(self.gen), self.outer, self.with_pos,
                        self.is_map)
        self._count = cached_program(_count, cls="GenerateExec",
                                     tag="count", key=self._gen_fp)
        self._expand_cache = {}

    def describe(self):
        mode = "posexplode" if self.with_pos else "explode"
        if self.outer:
            mode += "_outer"
        return f"GenerateExec[{mode}({self.gen.child!r})]"

    def _expand_fn(self, out_cap: int, caps_key):
        # instance-level memo over program-cache wrappers (the wrappers
        # are cheap; the jitted programs live in the bounded process
        # cache, keyed on generator shape not instance identity)
        cached = self._expand_cache.get((out_cap, caps_key))
        if cached is not None:
            return cached
        return self._build_expand(out_cap, caps_key)

    def _build_expand(self, out_cap: int, caps_key):
        def fn(cvs, mask, arr, lens, out_off):
            cap = mask.shape[0]
            j = jnp.arange(out_cap, dtype=jnp.int32)
            parent = jnp.searchsorted(out_off[1:], j,
                                      side="right").astype(jnp.int32)
            parent = jnp.clip(parent, 0, cap - 1)
            rel = j - out_off[parent]
            total = out_off[cap]
            out_live = j < total
            elem_ok = out_live & (rel < lens[parent]) & arr.validity[parent]
            epos = arr.offsets[:-1][parent] + jnp.where(elem_ok, rel, 0)
            outs: List[CV] = [
                ops_gather.take(cv, parent, out_live,
                                iter(ck) if ck else None)
                for cv, ck in zip(cvs, caps_key)]
            if self.with_pos:
                outs.append(CV(rel, elem_ok))
            if self.is_map:
                st = arr.child
                outs.append(ops_gather.take(st.children[0], epos, elem_ok))
                outs.append(ops_gather.take(st.children[1], epos, elem_ok))
            else:
                outs.append(ops_gather.take(arr.child, epos, elem_ok))
            out_mask = out_live
            return outs, out_mask

        from ..runtime.program_cache import cached_program
        jfn = cached_program(fn, cls="GenerateExec", tag="expand",
                             key=self._gen_fp + (out_cap, caps_key))
        self._expand_cache[(out_cap, caps_key)] = jfn
        return jfn

    def execute_partition(self, ctx, pid):
        m = ctx.metrics_for(self._op_id)
        for batch in self.children[0].execute_partition(ctx, pid):
            ctx.check_cancel()
            with m.timer("opTime"):
                cvs = batch.cvs()
                arr, lens, out_off, total_dev, measures = self._count(
                    cvs, batch.row_mask)
                total, got = fetch((total_dev, measures))
                total = int(total)
                out_cap = bucket_capacity(max(total, 1))
                caps_key = tuple(
                    tuple(bucket_capacity(max(int(v), 1)) for v in ms)
                    for ms in got)
                outs, out_mask = self._expand_fn(out_cap, caps_key)(
                    cvs, batch.row_mask, arr, lens, out_off)
            m.add("numOutputRows", total)
            m.add("numOutputBatches", 1)
            yield DeviceBatch(make_table(self.schema, outs, total),
                              total, out_mask, out_cap)
