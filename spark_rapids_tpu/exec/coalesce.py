"""Batch coalescing (reference: GpuCoalesceBatches.scala:899 + the
CoalesceGoal protocol). Small batches — many-small-files scans, post-shuffle
shards — concatenate on device toward a target row count before flowing
into sort/agg/join, amortizing per-batch dispatch and padding waste.
Batches already at or above half the target pass through untouched; filter
row-masks are compacted away during the concat (the one place the lazy-mask
design materializes)."""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..columnar.column import bucket_capacity
from ..columnar.table import Schema
from ..ops.concat import concat_cvs, concat_masks, pad_cv, pad_mask
from ..ops.gather import compact
from ..utils.transfer import fetch_int
from .base import ExecContext, TpuExec
from .batch import DeviceBatch
from .nodes import make_table

__all__ = ["CoalesceBatchesExec"]


class CoalesceBatchesExec(TpuExec):
    def __init__(self, child: TpuExec, target_rows: int, fan_in: int = 1):
        """fan_in: how many child partitions each output partition drains
        (merging across small files needs cross-partition coalescing)."""
        super().__init__([child], child.schema)
        self.target = target_rows
        self.fan_in = max(1, fan_in)

    def describe(self):
        return (f"CoalesceBatchesExec[target={self.target}, "
                f"fanIn={self.fan_in}]")

    def num_partitions(self, ctx):
        n = self.children[0].num_partitions(ctx)
        return max(1, -(-n // self.fan_in))

    def _flush(self, ctx: ExecContext, pending: List[DeviceBatch]):
        if not pending:
            return None
        m = ctx.metrics_for(self._op_id)
        if len(pending) == 1:
            return pending[0]
        with m.timer("concatTime"):
            ncols = len(pending[0].table.columns)
            cvs = [concat_cvs([b.cvs()[i] for b in pending],
                              self.schema.fields[i].dtype)
                   for i in range(ncols)]
            mask = concat_masks([b.row_mask for b in pending])
            # pad to a power-of-two capacity BEFORE compacting so output
            # shapes stay bucketed (bounds XLA recompilation)
            cap = bucket_capacity(mask.shape[0])
            cvs = [pad_cv(cv, cap) for cv in cvs]
            mask = pad_mask(mask, cap)
            out_cvs, count = compact(cvs, mask)
            m.add("numConcats", 1)
        n = fetch_int(count)
        return DeviceBatch(make_table(self.schema, out_cvs, n), n,
                           jnp.arange(cap) < n, cap)

    def _child_batches(self, ctx, pid):
        child = self.children[0]
        n = child.num_partitions(ctx)
        for cpid in range(pid * self.fan_in,
                          min((pid + 1) * self.fan_in, n)):
            yield from child.execute_partition(ctx, cpid)

    def execute_partition(self, ctx: ExecContext, pid: int):
        pending: List[DeviceBatch] = []
        pending_rows = 0
        for batch in self._child_batches(ctx, pid):
            if batch.num_rows >= self.target // 2 and not pending:
                yield batch  # already big enough: pass through untouched
                continue
            pending.append(batch)
            pending_rows += batch.num_rows
            if pending_rows >= self.target:
                out = self._flush(ctx, pending)
                if out is not None:
                    yield out
                pending, pending_rows = [], 0
        out = self._flush(ctx, pending)
        if out is not None:
            yield out
