"""Bounded worker pool machinery shared by the exchange map sides.

(reference: RapidsShuffleThreadedWriter — the multithreaded shuffle
writer runs map tasks on a bounded pool while the GpuSemaphore still
bounds DEVICE admission.) Two pieces live here:

- `resolve_map_threads`: `sql.exec.exchange.mapThreads` -> an actual
  pool width (0 = auto min(4, cores), clamped to the partition count).
- `PermitRider`: device-admission for map workers that does not
  deadlock against the caller's own TpuSemaphore permit.

The deadlock `PermitRider` exists to avoid: the thread that triggers
`_ensure_shuffled` usually already HOLDS a semaphore permit —
`collect_to_arrow.run_part` acquires around `next(it)`, and advancing
the iterator is exactly what materializes the shuffle. With
`sql.concurrentTpuTasks=1`, map workers blocking on `sem.acquire`
would wait forever on a permit their own caller holds. Worse, with
CHAINED exchanges every real permit can be pinned by other collect
threads that are themselves blocked on this exchange's
materialization lock, so even a pool that rides one permit deadlocks
if the remaining workers block inside `sem.acquire`. Instead, ONE
worker at a time "rides" the caller's already-granted permit and
every other worker polls: grab a real permit only when one is free
(`try_acquire`), otherwise wait briefly for the ride slot. Progress
is guaranteed (worst case the pool serializes on the ridden permit),
and device concurrency never exceeds the configured permits: the
rider slot spends admission the calling task already won.
"""
from __future__ import annotations

import os
import threading
from contextlib import contextmanager

__all__ = ["resolve_map_threads", "PermitRider"]


def resolve_map_threads(ctx, nparts: int) -> int:
    """Pool width for an exchange map side: conf value, 0 = auto
    (min(4, cpu cores)), clamped to the partition count."""
    from ..config import EXCHANGE_MAP_THREADS
    t = ctx.conf.get(EXCHANGE_MAP_THREADS)
    if t is None or int(t) <= 0:
        t = min(4, os.cpu_count() or 1)
    return max(1, min(int(t), max(nparts, 1)))


class PermitRider:
    """Grants map workers device-step admission (see module docstring).

    Usage per device step (a jitted map program + its fetch):

        with rider.step():
            host = with_retry(batch, map_one)

    Waits on real permits accumulate in `waited_secs` for the
    `mapPoolWaitMs` metric.
    """

    # lockdep resource key for the ride slot: the witness sees it as a
    # distinct class-keyed resource so ride-then-lock vs lock-then-ride
    # inversions across map workers are observable
    RIDE = "PermitRider.ride"

    def __init__(self, sem, priority: int = 0, token=None):
        self._sem = sem
        self._priority = priority
        self._token = token
        self._rider = threading.Semaphore(1)
        self._lock = threading.Lock()
        self._waited = 0.0
        self._riding = None      # thread name currently on the ride slot

    @property
    def waited_secs(self) -> float:
        with self._lock:
            return self._waited

    def debug_state(self) -> dict:
        """Held-state introspection for the lockdep dump."""
        with self._lock:
            return {"riding": self._riding, "waitedSecs": self._waited}

    @contextmanager
    def step(self):
        # Admission loop. Never block indefinitely inside
        # `sem.acquire`: under chained exchanges every real permit can
        # be pinned by collect threads that are themselves blocked on
        # this exchange's materialization lock — waiting for one would
        # deadlock the pool. Instead alternate between the ride slot
        # (the caller's already-granted permit, guaranteed to free up
        # each time the riding worker finishes a step) and an
        # opportunistic non-blocking real permit, so the pool degrades
        # to serial-on-one-permit rather than hanging.
        import time
        t0 = time.perf_counter()

        def _record():
            waited = time.perf_counter() - t0
            with self._lock:
                self._waited += waited
            if waited > 1e-3:
                # admission wait that actually stalled this map step:
                # back-dated pool_wait span in the query's trace (the
                # worker thread was seeded via tracing.use)
                from ..profiler import tracing
                tracing.record_wait_span("exchange.pool_admission",
                                         "pool_wait", waited * 1e3)
            return waited

        from ..runtime import ledger, lockdep

        def _ride():
            with self._lock:
                self._riding = threading.current_thread().name
            lockdep.note_acquired(self.RIDE)
            ledger.note_acquire("ride", tag="PermitRider.step")

        def _unride():
            lockdep.note_released(self.RIDE)
            ledger.note_release("ride")
            with self._lock:
                self._riding = None

        while True:
            if self._rider.acquire(blocking=False):
                _ride()
                try:
                    yield _record()
                finally:
                    _unride()
                    self._rider.release()
                return
            if self._sem.try_acquire():
                try:
                    yield _record()
                finally:
                    self._sem.release()
                return
            if self._rider.acquire(timeout=0.05):
                _ride()
                try:
                    yield _record()
                finally:
                    _unride()
                    self._rider.release()
                return
            if self._token is not None:
                self._token.check()
