"""Hash-aggregate execution: ungrouped reductions and grouped aggregation.

Reference algorithm (GpuAggregateExec.scala:863-894): first-pass per-batch
aggregation, then merge passes until one batch remains. TPU-first redesign:
grouping is *sort-based segmented reduction* — radix-normalized keys,
stable lexsort, boundary flags -> segment ids, jax.ops.segment_* reductions
— all static-shape and fused into one XLA program per pass, instead of
cudf's dynamic hash tables. Capacity stays constant through a pass; dead
(filtered/padding) rows sort to the back as their own segments and are
masked out of the output.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.column import bucket_capacity
from ..columnar.table import Schema
from ..expr.aggregates import AggExpr
from ..expr.expressions import EmitCtx, Expression, UnsupportedExpr
from ..ops import sortkeys as sk
from ..ops.concat import concat_cvs, concat_masks, pad_cv, pad_mask
from ..ops.gather import take, take_strings
from ..ops.kernel_utils import CV
from ..profiler import xla_stats
from ..utils.transfer import fetch_int
from .base import ExecContext, TpuExec
from .batch import DeviceBatch
from .nodes import make_table

__all__ = ["UngroupedAggExec", "HashAggregateExec"]

# Hash-bucket first pass: O(n) scatter-reduce into this many buckets per
# round (no sort), with exact per-bucket key verification; rows whose
# bucket is owned by a different key retry the next round under a new
# seed, and any survivors fall back to the sort path. The TPU answer to
# cudf's hash groupby (reference: GpuAggregateExec first pass).
_HASH_BUCKETS = 4096
_HASH_ROUNDS = 2
_HASH_BUCKETS_MAX = 1 << 18


def _hash_buckets_for(cap: int) -> int:
    """Adaptive bucket count: ~cap/4 buckets keeps the load factor low
    enough that two rep-verify rounds absorb high-cardinality batches
    (fixed 4096 buckets sent every >8k-group batch to the sort path —
    q10's 15k customer groups cost 3s/batch there)."""
    b = _HASH_BUCKETS
    target = min(cap // 4, _HASH_BUCKETS_MAX)
    while b < target:
        b <<= 1
    return b


class UngroupedAggExec(TpuExec):
    """Reduction without grouping keys -> one row.

    The filter/project chain below collapses into the update program
    (collapse_fusable) and the cross-batch merge folds in too: ONE jitted
    dispatch per batch instead of one per operator — the whole-stage-fusion
    answer to the reference's per-kernel cudf dispatch (§3.3 hot loop)."""

    # the update program collapses the child chain itself; the fusion
    # pass must not wrap that prefix in a FusedStage (plan/fusion.py)
    fuses_child_chain = True

    def __init__(self, child: TpuExec, agg_names: Sequence[str],
                 bound_aggs: Sequence[AggExpr], schema: Schema):
        super().__init__([child], schema)
        self.agg_names = list(agg_names)
        self.aggs = list(bound_aggs)
        # fusion resolves lazily at first execute: children may be wrapped
        # after plan construction (LORE dump pass-throughs)
        self._base = None
        self._stages = None
        self._n_fused = 0

        def _update(cvs, mask):
            cvs, mask = self._stages(cvs, mask)
            ctx = EmitCtx(cvs, mask.shape[0])
            states = []
            for a in self.aggs:
                if a.child is not None:
                    cv = a.child.emit(ctx)
                else:
                    cv = CV(jnp.zeros(mask.shape[0], jnp.int8),
                            jnp.ones(mask.shape[0], jnp.bool_))
                states.append(a.update(cv, mask))
            return states

        def _update_merge(acc, cvs, mask):
            st = _update(cvs, mask)
            return [a.merge(x, y) for a, x, y in zip(self.aggs, acc, st)]

        def _finalize(states):
            out = []
            for a, s in zip(self.aggs, states):
                v, ok = a.finalize(s)
                if isinstance(v, CV):
                    out.append((v, jnp.reshape(ok, (1,))))
                else:
                    out.append((jnp.reshape(v, (1,) + tuple(v.shape)),
                                jnp.reshape(ok, (1,))))
            return out

        from ..runtime.program_cache import cached_program, exprs_fp
        self._aggs_fp = exprs_fp(self.aggs)
        # update programs inline self._stages, whose fingerprint is only
        # known after _resolve_fusion — built there
        self._raw_update = _update
        self._raw_update_merge = _update_merge
        self._update_jit = None
        self._update_merge_jit = None
        self._finalize_jit = cached_program(
            _finalize, cls="UngroupedAggExec", tag="finalize",
            key=(self._aggs_fp,))

    def num_partitions(self, ctx):
        return 1

    def describe(self):
        fused = f", fused_stages={self._n_fused}" if self._n_fused else ""
        return f"UngroupedAggExec[{self.agg_names}{fused}]"

    def _resolve_fusion(self):
        if self._base is None:
            from .base import collapse_fusable
            self._base, self._stages, self._n_fused = collapse_fusable(
                self.children[0])
            from ..runtime.program_cache import cached_program
            key = (self._aggs_fp,
                   getattr(self._stages, "_stage_fp",
                           ("inst", id(self))))
            self._update_jit = cached_program(
                self._raw_update, cls="UngroupedAggExec", tag="update",
                key=key)
            self._update_merge_jit = cached_program(
                self._raw_update_merge, cls="UngroupedAggExec",
                tag="update_merge", key=key, donate_argnums=(0,))
            self._whole_key = key

    def _whole_input_program(self):
        """ONE dispatch for the whole HBM-resident input: every batch is an
        argument, the per-batch update/merge loop unrolls inside a single
        XLA program, and finalize folds in too — zero per-batch Python
        round-trips (the deepest whole-stage fusion)."""
        def run(batches):
            acc = None
            for cvs, mask in batches:
                cvs2, mask2 = self._stages(list(cvs), mask)
                ctx = EmitCtx(cvs2, mask2.shape[0])
                st = []
                for a in self.aggs:
                    if a.child is not None:
                        cv = a.child.emit(ctx)
                    else:
                        cv = CV(jnp.zeros(mask2.shape[0], jnp.int8),
                                jnp.ones(mask2.shape[0], jnp.bool_))
                    st.append(a.update(cv, mask2))
                acc = st if acc is None else [
                    a.merge(x, y) for a, x, y in zip(self.aggs, acc, st)]
            out = []
            for a, s in zip(self.aggs, acc):
                v, ok = a.finalize(s)
                if isinstance(v, CV):
                    out.append((v, jnp.reshape(ok, (1,))))
                else:
                    out.append((jnp.reshape(v, (1,) + tuple(v.shape)),
                                jnp.reshape(ok, (1,))))
            return out
        from ..runtime.program_cache import cached_program
        return cached_program(run, cls="UngroupedAggExec", tag="whole",
                              key=self._whole_key)

    def _try_whole_input(self, ctx, m):
        """Single-dispatch path for an HBM-resident child; returns
        finalized outputs or None. No copies: batch buffers pass as
        program arguments."""
        from .nodes import CachedScanExec
        if not isinstance(self._base, CachedScanExec):
            return None
        batches = self._base.batches
        if not batches or len(batches) > 64:  # unroll bound
            return None
        if not hasattr(self, "_whole_jit"):
            self._whole_jit = self._whole_input_program()
        args = tuple((tuple(b.cvs()), b.row_mask) for b in batches)
        with m.timer("opTime"):
            out = self._whole_jit(args)
        xla_stats.count_dispatch()
        return out

    def execute_partition(self, ctx: ExecContext, pid: int):
        self._resolve_fusion()
        m = ctx.metrics_for(self._op_id)
        child = self._base
        stacked_out = self._try_whole_input(ctx, m)
        if stacked_out is not None:
            tbl = make_table(self.schema, _pad_one_row(stacked_out), 1)
            m.add("numOutputRows", 1)
            yield DeviceBatch(tbl, 1)
            return
        acc = None
        for cpid in range(child.num_partitions(ctx)):
            for batch in child.execute_partition(ctx, cpid):
                ctx.check_cancel()
                with m.timer("opTime"):
                    if acc is None:
                        acc = self._update_jit(batch.cvs(), batch.row_mask)
                    else:
                        acc = self._update_merge_jit(acc, batch.cvs(),
                                                     batch.row_mask)
                xla_stats.count_dispatch()
        if acc is None:
            # aggregate over empty input still yields one row (stages run
            # over all-dead base-schema columns)
            cvs = [CV(jnp.zeros(128, f.dtype.np_dtype or jnp.int8),
                      jnp.zeros(128, jnp.bool_),
                      jnp.zeros(129, jnp.int32)
                      if f.dtype.is_variable_width else None)
                   for f in self._base.schema.fields]
            acc = self._update_jit(cvs, jnp.zeros(128, jnp.bool_))
            xla_stats.count_dispatch()
        outs = self._finalize_jit(acc)
        xla_stats.count_dispatch()
        tbl = make_table(self.schema, _pad_one_row(outs), 1)
        m.add("numOutputRows", 1)
        yield DeviceBatch(tbl, 1)


def _pad_one_row(outs):
    """1-row (capacity-128-padded) output columns from finalized
    (value, ok) pairs; array-valued finalizes arrive as CVs with
    offsets+child already built."""
    cvs = []
    pad = 128 - 1
    for (v, ok) in outs:
        valid = jnp.concatenate([jnp.reshape(ok, (1,)).astype(jnp.bool_),
                                 jnp.zeros(pad, jnp.bool_)])
        if isinstance(v, CV):
            off = v.offsets
            off_p = jnp.concatenate(
                [off, jnp.full((pad,), off[-1], off.dtype)])
            cvs.append(CV(v.data, valid, off_p, v.children))
        else:
            data = jnp.concatenate(
                [v, jnp.zeros((pad,) + v.shape[1:], v.dtype)])
            cvs.append(CV(data, valid))
    return cvs


def _gather_raw(arr, perm):
    return arr[perm]


def _seg_ident(kind: str, dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if kind == "min" else -jnp.inf
    if dtype == jnp.bool_:
        return kind == "min"
    return jnp.iinfo(dtype).max if kind == "min" else jnp.iinfo(dtype).min


def _seg_reduce(reducer: str, arr, live, seg_ids, num_segments):
    if reducer == "sum":
        x = jnp.where(live, arr, jnp.zeros_like(arr))
        return jax.ops.segment_sum(x, seg_ids, num_segments)
    if reducer == "or":
        x = (live & arr.astype(jnp.bool_)).astype(jnp.int32)
        return jax.ops.segment_max(x, seg_ids, num_segments) > 0
    if reducer == "min":
        x = jnp.where(live, arr, _seg_ident("min", arr.dtype))
        return jax.ops.segment_min(x, seg_ids, num_segments)
    if reducer == "max":
        x = jnp.where(live, arr, _seg_ident("max", arr.dtype))
        return jax.ops.segment_max(x, seg_ids, num_segments)
    raise ValueError(reducer)


_NP2DT = None


def _dtype_for_np(npdt) -> dt.DataType:
    global _NP2DT
    if _NP2DT is None:
        import numpy as np
        _NP2DT = {np.dtype(np.bool_): dt.BOOL, np.dtype(np.int8): dt.INT8,
                  np.dtype(np.int16): dt.INT16, np.dtype(np.int32): dt.INT32,
                  np.dtype(np.int64): dt.INT64,
                  np.dtype(np.float32): dt.FLOAT32,
                  np.dtype(np.float64): dt.FLOAT64}
    import numpy as np
    return _NP2DT[np.dtype(npdt)]


def _packed_eq_arrays(key_cvs, keys, nchunks):
    """Per-column equality key arrays (null flag + order keys) with
    adjacent uint32 chunk words packed into uint64: halves the
    rep-gather + compare count in the hash-pass verify step."""
    out = []
    for kcv, kexpr, nc in zip(key_cvs, keys, nchunks):
        arrs = [jnp.logical_not(kcv.validity).astype(jnp.uint8)]
        arrs += sk.order_keys(kcv, kexpr.dtype, nc)
        packed = []
        i = 0
        while i < len(arrs):
            a = arrs[i]
            if (a.dtype == jnp.uint32 and i + 1 < len(arrs)
                    and arrs[i + 1].dtype == jnp.uint32):
                packed.append((a.astype(jnp.uint64) << 32)
                              | arrs[i + 1].astype(jnp.uint64))
                i += 2
            else:
                packed.append(a)
                i += 1
        out.append(packed)
    return out


def _remix_round(h1, r: int):
    """Round-r bucket hash from the base row hash: integer finalizer
    mix, so only round 0 pays the O(bytes) key walk."""
    if r == 0:
        return h1
    hm = h1.astype(jnp.uint32) ^ jnp.uint32(0x9E3779B9 * r)
    hm = hm * jnp.uint32(0x85EBCA6B)
    hm = hm ^ (hm >> 13)
    return (hm * jnp.uint32(0xC2B2AE35)).astype(jnp.int32)


class HashAggregateExec(TpuExec):
    """Grouped aggregation via segmented reduction over sorted keys.

    Modes (reference: GpuHashAggregateExec partial/final around
    GpuShuffleExchangeExec, GpuAggregateExec.scala:1942):
      complete      — drain every child partition, merge, finalize (1 out).
      per_partition — child is key-partitioned; each partition aggregates
                      independently to final results.
      partial       — per child partition: first-pass + merges, emit ONE
                      batch of (keys..., state columns...) — the
                      exchange-input side; rows shrink to group count
                      BEFORE any shuffle.
      final         — child delivers partial-format batches (post
                      exchange); merge states and finalize.
    The filter chain below collapses into the first-pass program
    (collapse_fusable): one dispatch per input batch."""

    # the first-pass program collapses the child chain itself (filters
    # only: the collapse keeps column ordinals); the fusion pass leaves
    # that prefix alone (plan/fusion.py)
    fuses_child_chain = True
    fusion_require_ordinals = True

    def __init__(self, child: TpuExec, key_names: Sequence[str],
                 bound_keys: Sequence[Expression], agg_names: Sequence[str],
                 bound_aggs: Sequence[AggExpr], schema: Schema,
                 per_partition: bool = False, mode: Optional[str] = None):
        self.mode = mode or ("per_partition" if per_partition
                             else "complete")
        self.key_names = list(key_names)
        self.keys = list(bound_keys)
        self.agg_names = list(agg_names)
        self.aggs = list(bound_aggs)
        for a in self.aggs:
            if a.state_reducers is None:
                raise UnsupportedExpr(
                    f"{a!r} does not support grouped merge")
            if "custom" in a.state_reducers and not hasattr(
                    a, "g_merge_custom"):
                raise UnsupportedExpr(f"{a!r} lacks g_merge_custom")
            if (a.child is not None and a.child.dtype.is_variable_width
                    and type(a).__name__ not in ("Count",)):
                raise UnsupportedExpr(f"{a!r} over variable-width input")
            # First/Last keep batch order only because concat order IS the
            # stable-sort tiebreak; nothing extra needed here

        if self.mode == "partial":
            schema = self._partial_schema(child.schema)
        super().__init__([child], schema)
        # fusion resolves lazily at first execute (see UngroupedAggExec)
        self._base = None
        self._stages = None
        self._n_fused = 0

        self._update_cache = {}
        self._merge_cache = {}
        from ..runtime.program_cache import cached_program, exprs_fp
        # shared program-cache key material: same keys+aggs from a
        # different DataFrame reuse every grouped-agg program
        self._fp = (exprs_fp(self.keys), exprs_fp(self.aggs))
        self._finalize_jit = cached_program(
            self._finalize_fn, cls="HashAggregateExec", tag="finalize",
            key=self._fp)
        hashable = (dt.BooleanType, dt.ByteType, dt.ShortType,
                    dt.IntegerType, dt.DateType, dt.LongType,
                    dt.TimestampType, dt.DecimalType, dt.FloatType,
                    dt.DoubleType, dt.StringType, dt.BinaryType)
        self._hash_ok = (all(isinstance(k.dtype, hashable)
                             for k in self.keys)
                         # an agg whose g_update sorts internally (t-digest)
                         # would defeat the no-sort hash first pass
                         and all(getattr(a, "sort_free_update", True)
                                 for a in self.aggs))
        self._hash_disabled = False

    # -- partial-state wire schema --------------------------------------
    def _state_np_dtypes(self):
        """Infer the flat state array dtypes via abstract evaluation."""
        shapes = []
        for a in self.aggs:
            cap = 128
            shape = (cap,)
            if a.child is not None:
                np_dt = a.child.dtype.np_dtype or jnp.int8
                if isinstance(a.child.dtype, dt.DecimalType) \
                        and a.child.dtype.is_decimal128:
                    shape = (cap, 2)
            else:
                np_dt = jnp.int8
            cv = jax.ShapeDtypeStruct(shape, np_dt)
            vcv = jax.ShapeDtypeStruct((cap,), jnp.bool_)
            seg = jax.ShapeDtypeStruct((cap,), jnp.int32)
            out = jax.eval_shape(
                lambda c, v, s: a.g_update(CV(c, v), v, s, cap),
                cv, vcv, seg)
            shapes.extend([o.dtype for o in out])
        return shapes

    def _partial_schema(self, child_schema: Schema) -> Schema:
        from ..columnar.table import Field
        fields = []
        for nm, k in zip(self.key_names, self.keys):
            fields.append(Field(f"_k_{nm}", k.dtype))
        for si, npdt in enumerate(self._state_np_dtypes()):
            fields.append(Field(f"_s{si}", _dtype_for_np(npdt)))
        return Schema(fields)

    @property
    def _wire_schema(self) -> Schema:
        """Partial-state wire schema — the format buffered partials take
        when parked in the spill store, and the format partial mode
        emits over the exchange."""
        if getattr(self, "_wire_schema_c", None) is None:
            self._wire_schema_c = (self.schema if self.mode == "partial"
                                   else self._partial_schema(None))
        return self._wire_schema_c

    # -- spillable partial buffering (out-of-core aggregation) ----------
    def _park(self, store, part):
        """Wrap a (key_cvs, flat_states, seg_live, cap) partial as a
        partial-format DeviceBatch and register it with the spill store,
        so buffered group state demotes to host/disk under HBM pressure
        instead of dying (reference: GpuAggregateExec buffered batches
        are spillable)."""
        from ..memory.retry import retry_no_split
        ks, st, sl, cap = part
        cvs = list(ks) + [CV(s, jnp.ones(cap, jnp.bool_)) for s in st]
        tbl = make_table(self._wire_schema, cvs, cap)
        # parking reserves device budget: retry-after-spill covers the
        # transient-OOM window (AllocationRetryCoverageTracker keeps
        # this class of site inside the retry discipline)
        return retry_no_split(lambda: store.add_batch(
            DeviceBatch(tbl, cap, sl, cap), priority=8))

    def _unpark(self, h, close=True):
        b = h.materialize()
        if close:
            h.close()
        cvs = b.cvs()
        nkeys = len(self.keys)
        return ([cv for cv in cvs[:nkeys]],
                [cv.data for cv in cvs[nkeys:]], b.row_mask, b.capacity)

    def _bucket_slice_fn(self, K: int, seed: int = 0x5EED):
        """Device program extracting one of K disjoint-key hash buckets
        from a partial: live rows whose key hashes to bucket `b` are
        compacted to the front (the repartition half of the reference's
        GpuAggregateExec.scala:863-894 fallback). `seed` varies per
        recursion level — re-splitting an oversized bucket with the same
        seed would put every row back in one bucket."""
        from ..ops.gather import compact
        from ..ops.hash import partition_ids
        key_dtypes = [k.dtype for k in self.keys]

        def fn(ks, st, sl, b):
            pids = partition_ids(ks, key_dtypes, K, seed=seed)
            mask_b = sl & (pids == b)
            cvs_all = list(ks) + [CV(s, jnp.ones_like(sl)) for s in st]
            out_cvs, count = compact(cvs_all, mask_b)
            nkeys = len(ks)
            return (out_cvs[:nkeys],
                    [cv.data for cv in out_cvs[nkeys:]], count)
        from ..runtime.program_cache import cached_program
        return cached_program(fn, cls="HashAggregateExec", tag="bslice",
                              key=self._fp + (K, seed))

    def _shrink_to(self, ks, st, nlive: int):
        """Slice a live-prefix partial down to a bucketed capacity."""
        new_cap = bucket_capacity(max(nlive, 1))
        cur = ks[0].validity.shape[0] if ks else (
            st[0].shape[0] if st else new_cap)
        new_cap = min(new_cap, cur)
        idx = jnp.arange(new_cap)
        in_bounds = idx < nlive
        ks2 = []
        for kcv in ks:
            if kcv.offsets is not None:
                nbytes = fetch_int(kcv.offsets[nlive]) if nlive else 0
                byte_cap = bucket_capacity(max(nbytes, 1))
                byte_cap = min(byte_cap, kcv.data.shape[0])
                ks2.append(take_strings(kcv, idx, in_bounds=in_bounds,
                                        out_data_capacity=byte_cap))
            else:
                ks2.append(CV(kcv.data[:new_cap], kcv.validity[:new_cap]))
        st2 = [s[:new_cap] for s in st]
        return (ks2, st2, idx < nlive, new_cap)

    def num_partitions(self, ctx):
        if self.mode in ("per_partition", "partial", "final"):
            return self.children[0].num_partitions(ctx)
        return 1

    def describe(self):
        fused = f", fused_stages={self._n_fused}" if self._n_fused else ""
        return (f"HashAggregateExec[{self.mode}, keys={self.key_names}, "
                f"aggs={self.agg_names}{fused}]")

    # -- sort/segment machinery (runs inside jit) ----------------------
    def _sort_and_segment(self, key_cvs, mask, nchunks,
                          allow_host_sort: bool = True):
        cap = mask.shape[0]
        arrays = [jnp.logical_not(mask).astype(jnp.uint8)]  # dead rows last
        for kcv, kexpr, nc in zip(key_cvs, self.keys, nchunks):
            arrays.append(jnp.logical_not(kcv.validity).astype(jnp.uint8))
            arrays.extend(sk.order_keys(kcv, kexpr.dtype, nc))
        perm = sk.lexsort(arrays, allow_host=allow_host_sort)
        sorted_arrays = [a[perm] for a in arrays]
        boundary = sk.group_boundaries(sorted_arrays)
        seg_ids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
        live_sorted = mask[perm]
        seg_live = jax.ops.segment_max(live_sorted.astype(jnp.int32),
                                       seg_ids, cap) > 0
        seg_start = jax.ops.segment_min(jnp.arange(cap), seg_ids, cap)
        src_rows = perm[jnp.clip(seg_start, 0, cap - 1)]
        key_out = [take(kcv, src_rows, in_bounds=seg_live)
                   for kcv in key_cvs]
        return perm, seg_ids, live_sorted, seg_live, key_out

    def _hash_update_fn(self, nchunks, hash_once: bool = False):
        """Sort-free first pass: bucket rows by key hash, verify each row's
        key against its bucket's representative (canonical order-key
        equality — NaN/-0.0/null exact), segment-reduce matching rows, and
        leave collisions to the next round / sort fallback. Returns
        (key_cvs, flat_states, live, n_leftover) with capacity
        _HASH_ROUNDS * _HASH_BUCKETS.

        With `hash_once` (string keys, sql.agg.stringHashKeys.enabled)
        the bucket hash derives from the SAME packed chunk words the
        verify step compares (xxhash64-style fold, ops/hash.py) — one
        byte pass over the string keys total, instead of murmur3's
        second independent walk. Collisions stay exact: a row matches a
        bucket only when the chunk compare against the representative
        passes; hash collisions fall to the next round / sort path."""
        from ..ops.hash import hash_once_rows, murmur3_row_hash

        def fn(cvs, mask):
            cvs, mask = self._stages(cvs, mask)
            cap = mask.shape[0]
            ctx = EmitCtx(cvs, cap)
            key_cvs = [k.emit(ctx) for k in self.keys]
            key_dtypes = [k.dtype for k in self.keys]
            eq_arrays = _packed_eq_arrays(key_cvs, self.keys, nchunks)
            agg_inputs = []
            for a in self.aggs:
                if a.child is not None:
                    agg_inputs.append(a.child.emit(ctx))
                else:
                    agg_inputs.append(CV(jnp.zeros(cap, jnp.int8),
                                         jnp.ones(cap, jnp.bool_)))
            remaining = mask
            rowidx = jnp.arange(cap, dtype=jnp.int32)
            round_keys = []          # per-round rep ROW indices
            round_states = None
            round_live = []
            # hash the full (possibly var-width) keys ONCE; later rounds
            # re-bucket by mixing the base hash with an integer
            # finalizer — O(bytes) work happens a single time
            if hash_once:
                h1 = hash_once_rows(eq_arrays)
            else:
                h1 = murmur3_row_hash(key_cvs, key_dtypes, seed=42)
            for r in range(_HASH_ROUNDS):
                # escalating buckets: round 0 small (low-cardinality
                # batches — the common case — pay only 4096-slot segment
                # ops), later rounds big enough for high-card batches
                B = _HASH_BUCKETS if r == 0 else _hash_buckets_for(cap)
                h = _remix_round(h1, r)
                b = (h.astype(jnp.uint32) % jnp.uint32(B)).astype(jnp.int32)
                repmin = jax.ops.segment_min(
                    jnp.where(remaining, rowidx, cap), b, B)
                has = repmin < cap
                rep = jnp.clip(repmin, 0, cap - 1)
                rep_of_row = rep[b]
                match = remaining
                for arrs in eq_arrays:
                    for arr in arrs:
                        match = match & (arr == arr[rep_of_row])
                states_r = []
                for a, icv in zip(self.aggs, agg_inputs):
                    if icv.offsets is not None:
                        scv = CV(jnp.zeros(cap, jnp.int8), icv.validity)
                    else:
                        scv = icv
                    states_r.append(a.g_update(scv, match, b, B))
                flat_r = [c for s in states_r for c in s]
                round_states = ([[f] for f in flat_r] if round_states is None
                                else [o + [f] for o, f in
                                      zip(round_states, flat_r)])
                # keys are NOT gathered here: only the rep's original ROW
                # INDEX is kept — key materialization (expensive for
                # string keys at B slots) happens once, post-compaction,
                # at live-group scale in update_one
                round_keys.append(rep)
                round_live.append(has)
                remaining = remaining & ~match
            rep_rows = jnp.concatenate(round_keys)
            flat = [jnp.concatenate(parts) for parts in round_states]
            live = jnp.concatenate(round_live)
            leftover = jnp.sum(remaining.astype(jnp.int32))
            n_live = jnp.sum(live.astype(jnp.int32))
            return rep_rows, flat, live, leftover, n_live
        return fn

    def _materialize_hash_partial(self, b, rep_rows, st, sl,
                                  n_live: int):
        """Turn a hash-pass result (rep ROW indices + states + live
        mask over rounds*B slots) into a (keys, states, live, cap)
        partial at bucket_capacity(live). Key columns — expensive for
        strings — gather from the ORIGINAL batch only here, at
        live-group scale, never at bucket scale."""
        from ..ops.gather import compaction_perm, gather_cols
        cap_part = sl.shape[0]
        new_cap = min(bucket_capacity(max(n_live, 1)), cap_part)
        # gather_cols fetches var-width measures internally (host sync),
        # so this stays host-driven; the gathers themselves are jitted
        perm, _ = compaction_perm(sl)
        idx = perm[:new_cap]
        inb = jnp.arange(new_cap) < n_live
        kfn = self._update_cache.get("keyemit")
        if kfn is None:
            def kfn_(cvs, mask):
                cvs2, mask2 = self._stages(cvs, mask)
                ctx = EmitCtx(cvs2, mask2.shape[0])
                return [k.emit(ctx) for k in self.keys]
            from ..runtime.program_cache import cached_program
            kfn = cached_program(kfn_, cls="HashAggregateExec",
                                 tag="keyemit",
                                 key=self._fp + (self._stage_fp,))
            self._update_cache["keyemit"] = kfn
        key_cvs = kfn(b.cvs(), b.row_mask)
        rep2 = rep_rows[idx]
        ks2 = gather_cols(key_cvs, rep2, inb)
        st2 = [s[idx] for s in st]
        return (ks2, st2, inb, new_cap)

    def _update_fn(self, nchunks):
        def fn(cvs, mask):
            cvs, mask = self._stages(cvs, mask)
            cap = mask.shape[0]
            ctx = EmitCtx(cvs, cap)
            key_cvs = [k.emit(ctx) for k in self.keys]
            perm, seg_ids, live, seg_live, key_out = \
                self._sort_and_segment(key_cvs, mask, nchunks)
            states = []
            for a in self.aggs:
                if a.child is not None:
                    cv = a.child.emit(ctx)
                else:
                    cv = CV(jnp.zeros(cap, jnp.int8),
                            jnp.ones(cap, jnp.bool_))
                if cv.offsets is not None:  # var-width: Count uses validity
                    scv = CV(jnp.zeros(cap, jnp.int8), cv.validity[perm])
                else:
                    scv = CV(cv.data[perm], cv.validity[perm])
                states.append(a.g_update(scv, live, seg_ids, cap))
            flat = [c for s in states for c in s]
            return key_out, flat, seg_live
        return fn

    def _merge_fn(self, nchunks):
        def fn(key_cvs, flat_states, mask):
            cap = mask.shape[0]
            perm, seg_ids, live, seg_live, key_out = \
                self._sort_and_segment(key_cvs, mask, nchunks)
            out_flat = []
            i = 0
            for a in self.aggs:
                width = self._state_width(a)
                if "custom" in a.state_reducers:
                    cols = [flat_states[i + j][perm] for j in range(width)]
                    out_flat.extend(a.g_merge_custom(cols, live, seg_ids,
                                                     cap))
                    i += width
                else:
                    for r in a.state_reducers:
                        arr = flat_states[i][perm]
                        out_flat.append(_seg_reduce(r, arr, live, seg_ids,
                                                    cap))
                        i += 1
            return key_out, out_flat, seg_live
        return fn

    @staticmethod
    def _state_width(a) -> int:
        if "custom" in a.state_reducers:
            return a.num_state_cols()
        return len(a.state_reducers)

    def _finalize_fn(self, key_cvs, flat_states, seg_live):
        outs = list(key_cvs)
        i = 0
        for a in self.aggs:
            k = self._state_width(a)
            s = tuple(flat_states[i:i + k])
            i += k
            v, ok = a.finalize(s)
            if isinstance(v, CV):
                # array-valued finalize (t-digest percentile lists):
                # the agg built offsets+child; AND in group liveness
                outs.append(CV(v.data, v.validity & ok & seg_live,
                               v.offsets, v.children))
            else:
                outs.append(CV(v, ok & seg_live))
        return outs

    # ------------------------------------------------------------------
    def _has_string_keys(self) -> bool:
        return any(isinstance(k.dtype, (dt.StringType, dt.BinaryType))
                   for k in self.keys)

    def _nchunks_for(self, key_cvs, mask) -> Tuple[int, ...]:
        """Static string-chunk counts; measures only live+valid rows so
        dead/padding rows cannot inflate the chunk count."""
        ncs = []
        for kcv, kexpr in zip(key_cvs, self.keys):
            if isinstance(kexpr.dtype, (dt.StringType, dt.BinaryType)):
                lens = kcv.offsets[1:] - kcv.offsets[:-1]
                lens = jnp.where(mask & kcv.validity, lens, 0)
                maxlen = fetch_int((jnp.max(lens))) if \
                    lens.shape[0] else 0
                ncs.append(sk.nchunks_for_len(max(maxlen, 1)))
            else:
                ncs.append(0)
        return tuple(ncs)

    def _batch_nchunks(self, batch: DeviceBatch) -> Tuple[int, ...]:
        """nchunks for an input batch without double-evaluating keys: zero
        for non-string keys; string keys that are plain column refs read
        offsets straight off the batch."""
        if not self._has_string_keys():
            return tuple(0 for _ in self.keys)
        from ..expr.expressions import Alias, BoundRef
        cvs = batch.cvs()
        ncs = []
        for k in self.keys:
            if not isinstance(k.dtype, (dt.StringType, dt.BinaryType)):
                ncs.append(0)
                continue
            e = k.child if isinstance(k, Alias) else k
            if isinstance(e, BoundRef):
                kcv = cvs[e.ordinal]
            else:
                kcv = k.emit(EmitCtx(cvs, batch.capacity))
            lens = kcv.offsets[1:] - kcv.offsets[:-1]
            lens = jnp.where(batch.row_mask & kcv.validity, lens, 0)
            maxlen = fetch_int((jnp.max(lens)))
            ncs.append(sk.nchunks_for_len(max(maxlen, 1)))
        return tuple(ncs)

    def _resolve_fusion(self):
        if self._base is None:
            if self.mode in ("complete", "partial", "per_partition"):
                from .base import collapse_fusable
                self._base, self._stages, self._n_fused = collapse_fusable(
                    self.children[0], require_ordinals=True)
            else:
                self._base, self._n_fused = self.children[0], 0
                self._stages = lambda cvs, mask: (cvs, mask)
                self._stages._stage_fp = ("chain",)
            # tpulint: allow[fp-unstable-attr] id(self) is the documented per-instance fallback key: unshared, never falsely shared
            self._stage_fp = getattr(self._stages, "_stage_fp",
                                     ("inst", id(self)))

    # -- whole-input fused path (HBM-cached child, one device program) --
    def _whole_grouped_program(self, nchunks, opt_cap,
                               hash_once: bool = False):
        """ONE program for the entire cached input: per-batch fused
        stages + key/input emit, concat, sort-segment aggregate, compact
        live groups to opt_cap, finalize — plus (count, overflow) so the
        host can detect optimistic-capacity misses in the same round trip
        (the whole-stage answer to the reference's multi-pass
        GpuAggregateExec when groups are few). `hash_once` derives the
        per-round bucket hashes from the equality chunk words (one byte
        pass over string keys; see _hash_update_fn)."""
        from ..ops.gather import take_strings
        from ..ops.hash import hash_once_rows, murmur3_row_hash
        key_dtypes = [k.dtype for k in self.keys]

        def run(batches):
            # per-batch fused stages + key/input emit, then concat
            key_parts = [[] for _ in self.keys]
            in_parts = [[] for _ in self.aggs]
            masks = []
            for cvs, bmask in batches:
                cvs2, mask2 = self._stages(list(cvs), bmask)
                cap_i = mask2.shape[0]
                ectx = EmitCtx(cvs2, cap_i)
                for ki, k in enumerate(self.keys):
                    key_parts[ki].append(k.emit(ectx))
                for ai, a in enumerate(self.aggs):
                    if a.child is not None:
                        in_parts[ai].append(a.child.emit(ectx))
                    else:
                        in_parts[ai].append(
                            CV(jnp.zeros(cap_i, jnp.int8),
                               jnp.ones(cap_i, jnp.bool_)))
                masks.append(mask2)
            key_cvs = [concat_cvs(ps, k.dtype)
                       for ps, k in zip(key_parts, self.keys)]
            mask = concat_masks(masks)
            cap = mask.shape[0]
            agg_inputs = []
            for parts in in_parts:
                vcat = jnp.concatenate([p.validity for p in parts])
                if parts[0].offsets is not None:
                    agg_inputs.append(CV(jnp.zeros(cap, jnp.int8), vcat))
                else:
                    agg_inputs.append(
                        CV(jnp.concatenate([p.data for p in parts]),
                           vcat))
            # hash rounds (sort-free — XLA device sorts at input scale
            # are the slow path on TPU; bucketed segment reduction is
            # O(rounds * n))
            eq_arrays = _packed_eq_arrays(key_cvs, self.keys, nchunks)
            if hash_once:
                h1 = hash_once_rows(eq_arrays)
            else:
                h1 = murmur3_row_hash(key_cvs, key_dtypes, seed=42)
            B = _HASH_BUCKETS
            remaining = mask
            rowidx = jnp.arange(cap, dtype=jnp.int32)
            round_keys = [[] for _ in self.keys]
            round_states = None
            round_live = []
            for r in range(_HASH_ROUNDS):
                h = _remix_round(h1, r)
                b = (h.astype(jnp.uint32)
                     % jnp.uint32(B)).astype(jnp.int32)
                repmin = jax.ops.segment_min(
                    jnp.where(remaining, rowidx, cap), b, B)
                has = repmin < cap
                rep = jnp.clip(repmin, 0, cap - 1)
                rep_of_row = rep[b]
                match = remaining
                for arrs in eq_arrays:
                    for arr in arrs:
                        match = match & (arr == arr[rep_of_row])
                states_r = []
                for a, icv in zip(self.aggs, agg_inputs):
                    scv = (CV(jnp.zeros(cap, jnp.int8), icv.validity)
                           if icv.offsets is not None else icv)
                    states_r.append(a.g_update(scv, match, b, B))
                flat_r = [c for st_ in states_r for c in st_]
                round_states = ([[f] for f in flat_r]
                                if round_states is None
                                else [o + [f] for o, f in
                                      zip(round_states, flat_r)])
                for ki, (kcv, nc) in enumerate(zip(key_cvs, nchunks)):
                    if kcv.offsets is not None:
                        bcap = min(kcv.data.shape[0],
                                   bucket_capacity(max(B * nc * 4, 4)))
                        round_keys[ki].append(take_strings(
                            kcv, rep, in_bounds=has,
                            out_data_capacity=bcap))
                    else:
                        round_keys[ki].append(take(kcv, rep,
                                                   in_bounds=has))
                round_live.append(has)
                remaining = remaining & ~match
            leftover = jnp.sum(remaining.astype(jnp.int32))
            hk = [concat_cvs(parts, kd)
                  for parts, kd in zip(round_keys, key_dtypes)]
            hflat = [jnp.concatenate(parts) for parts in round_states]
            hlive = jnp.concatenate(round_live)
            # same key can surface in several rounds: one small merge
            # (sort over ROUNDS*BUCKETS rows only) unifies them and puts
            # live groups first
            mk, mflat, mlive = self._merge_body(hk, hflat, hlive,
                                                nchunks)
            sel = jnp.arange(opt_cap, dtype=jnp.int32)
            count = jnp.sum(mlive.astype(jnp.int32))
            overflow = (count > opt_cap) | (leftover > 0)
            sl_c = mlive[sel] if mlive.shape[0] > opt_cap else \
                jnp.pad(mlive, (0, opt_cap - mlive.shape[0]))
            ks_c = []
            for kcv, nc in zip(mk, nchunks):
                if kcv.offsets is not None:
                    bcap = min(kcv.data.shape[0],
                               bucket_capacity(max(opt_cap * nc * 4, 4)))
                    ks_c.append(take_strings(kcv, sel, in_bounds=sl_c,
                                             out_data_capacity=bcap))
                else:
                    ks_c.append(take(kcv, sel, in_bounds=sl_c))
            flat_c = [f[sel] for f in mflat]
            outs = self._finalize_fn(ks_c, flat_c, sl_c)
            return outs, sl_c, count, overflow
        return run

    def _merge_body(self, key_cvs, flat_states, mask, nchunks,
                    allow_host_sort: bool = True):
        """In-trace merge (the body of _merge_fn without the jit
        boundary): sort-segment the partial keys, reduce states; live
        groups come out first. `allow_host_sort=False` force-disables
        the host-callback sort — mandatory when tracing inside
        shard_map, where pure_callback would deadlock the collective."""
        cap = mask.shape[0]
        perm, seg_ids, live, seg_live, key_out = \
            self._sort_and_segment(key_cvs, mask, nchunks,
                                   allow_host_sort=allow_host_sort)
        out_flat = []
        i = 0
        for a in self.aggs:
            width = self._state_width(a)
            if "custom" in a.state_reducers:
                cols = [flat_states[i + j][perm] for j in range(width)]
                out_flat.extend(a.g_merge_custom(cols, live, seg_ids,
                                                 cap))
                i += width
            else:
                for r in a.state_reducers:
                    arr = flat_states[i][perm]
                    out_flat.append(_seg_reduce(r, arr, live, seg_ids,
                                                cap))
                    i += 1
        return key_out, out_flat, seg_live

    def _try_whole_input(self, ctx, m):
        """Single-round-trip path: cached child, bounded batch count, no
        retry pressure. Returns a DeviceBatch or None (overflow or
        ineligible)."""
        from ..config import AGG_OPTIMISTIC_GROUPS
        from .nodes import CachedScanExec
        opt_cap = ctx.conf.get(AGG_OPTIMISTIC_GROUPS)
        if (self.mode != "complete" or opt_cap <= 0
                or not self._hash_ok
                or getattr(self, "_whole_disabled", False)
                or not isinstance(self._base, CachedScanExec)):
            return None
        batches = self._base.batches
        if not batches or len(batches) > 64:
            return None
        if not hasattr(self, "_whole_nchunks"):
            ncs = [self._batch_nchunks(b) for b in batches]
            self._whole_nchunks = tuple(max(t) for t in zip(*ncs))
        from ..config import AGG_STRING_HASH_KEYS
        hash_once = (self._has_string_keys()
                     and bool(ctx.conf.get(AGG_STRING_HASH_KEYS)))
        key = ("whole", self._whole_nchunks, opt_cap, hash_once,
               tuple(b.capacity for b in batches))
        fn = self._update_cache.get(key)
        if fn is None:
            from ..runtime.program_cache import cached_program
            fn = cached_program(
                self._whole_grouped_program(self._whole_nchunks,
                                            opt_cap, hash_once),
                cls="HashAggregateExec", tag="whole",
                key=self._fp + (self._stage_fp, self._whole_nchunks,
                                opt_cap, hash_once))
            self._update_cache[key] = fn
        args = tuple((tuple(b.cvs()), b.row_mask) for b in batches)
        with m.timer("opTime"):
            outs, sl_c, count, overflow = fn(args)
            from ..utils.transfer import fetch
            cnt, ovf = fetch((count, overflow))
        xla_stats.count_dispatch()
        if bool(ovf):
            self._whole_disabled = True
            return None
        tbl = make_table(self.schema, outs, int(cnt))
        m.add("numOutputRows", int(cnt))
        m.add("numOutputBatches", 1)
        return DeviceBatch(tbl, int(cnt), sl_c, sl_c.shape[0])

    def execute_partition(self, ctx: ExecContext, pid: int):
        self._resolve_fusion()
        m = ctx.metrics_for(self._op_id)
        child = self._base
        child_pids = ([pid] if self.mode in ("per_partition", "partial",
                                             "final")
                      else range(child.num_partitions(ctx)))

        if self.mode == "final":
            yield from self._execute_final(ctx, pid, m)
            return
        if self.mode == "complete":
            whole = self._try_whole_input(ctx, m)
            if whole is not None:
                yield whole
                return

        from ..config import AGG_STRING_HASH_KEYS
        hash_once = (self._has_string_keys()
                     and bool(ctx.conf.get(AGG_STRING_HASH_KEYS)))

        def update_one(b):
            from .batch import maybe_compact
            b = maybe_compact(b, child.schema)
            nchunks = self._batch_nchunks(b)
            if self._hash_ok and not self._hash_disabled:
                hfn = self._update_cache.get(("hash", nchunks, hash_once))
                if hfn is None:
                    from ..runtime.program_cache import cached_program
                    hfn = cached_program(
                        self._hash_update_fn(nchunks, hash_once),
                        cls="HashAggregateExec", tag="hash_update",
                        key=self._fp + (self._stage_fp, nchunks,
                                        hash_once))
                    self._update_cache[("hash", nchunks, hash_once)] = hfn
                rep_rows, st, sl, leftover, n_live = hfn(b.cvs(),
                                                         b.row_mask)
                xla_stats.count_dispatch()
                from ..utils.transfer import fetch
                lo, nl = (int(v) for v in fetch((leftover, n_live)))
                if lo == 0:
                    return self._materialize_hash_partial(
                        b, rep_rows, st, sl, nl)
                # bucket-collision overflow (high-cardinality batch):
                # fall back to the exact sort path, and stop trying the
                # hash pass for the rest of this query
                self._hash_disabled = True
            fn = self._update_cache.get(nchunks)
            if fn is None:
                from ..runtime.program_cache import cached_program
                fn = cached_program(
                    self._update_fn(nchunks), cls="HashAggregateExec",
                    tag="update",
                    key=self._fp + (self._stage_fp, nchunks))
                self._update_cache[nchunks] = fn
            ks, st, sl = fn(b.cvs(), b.row_mask)
            xla_stats.count_dispatch()
            return (ks, st, sl, b.capacity)

        from ..config import AGG_MAX_MERGE_ROWS
        from ..memory.retry import with_retry
        from ..memory.spill import spill_store
        store = spill_store(ctx.conf)
        max_rows = ctx.conf.get(AGG_MAX_MERGE_ROWS)
        handles = []            # (spill handle, capacity)
        buffered = 0
        compactable = True      # do eager merges still shrink the state?
        for cpid in child_pids:
            for batch in child.execute_partition(ctx, cpid):
                ctx.check_cancel()
                with m.timer("opTime"):
                    # split-and-retry: idempotent per-batch first-pass agg
                    # re-executes on halves under memory pressure
                    for part in with_retry(batch, update_one):
                        handles.append((self._park(store, part), part[3]))
                        buffered += part[3]
                if compactable and buffered > max_rows and len(handles) > 1:
                    with m.timer("opTime"):
                        parts = [self._unpark(h) for h, _ in handles]
                        merged = self._merge_partials(parts)
                        handles = [(self._park(store, merged), merged[3])]
                        buffered = merged[3]
                        if merged[3] > max_rows // 2:
                            # high cardinality: merging no longer
                            # compacts; buffer spillably and let the
                            # bucket fallback split the final pass
                            compactable = False
        if not handles:
            if self.mode != "partial":
                yield DeviceBatch(make_table(self.schema, [
                    CV(jnp.zeros(128, f.dtype.np_dtype or jnp.int8),
                       jnp.zeros(128, jnp.bool_),
                       jnp.zeros(129, jnp.int32)
                       if f.dtype.is_variable_width else None)
                    for f in self.schema.fields], 0),
                    0, jnp.zeros(128, jnp.bool_), 128)
            return
        yield from self._emit_final(ctx, m, handles)

    # deepest bucket recursion (reference: 10 levels x 16 buckets,
    # GpuAggregateExec.scala:863-894)
    _MAX_BUCKET_DEPTH = 10

    def _emit_final(self, ctx: ExecContext, m, handles,
                    force_merge: bool = False, depth: int = 0):
        """Merge parked partials and emit finalized (or partial-format)
        batches under a bounded merge width: when the buffered group
        state exceeds maxMergeRows, repartition every partial into K
        hash buckets of disjoint keys and merge+emit per bucket,
        RECURSING (fresh hash seed per level) on buckets that still
        exceed the bound — the out-of-core fallback
        (GpuAggregateExec.scala:863-894, 16 buckets x 10 levels).
        Handles are closed on generator exit even when the consumer
        abandons the stream (limit/error)."""
        from ..config import AGG_MAX_MERGE_ROWS
        max_rows = ctx.conf.get(AGG_MAX_MERGE_ROWS)
        total = sum(c for _, c in handles)
        K = 1
        while K < 16 and total > K * max_rows:
            K *= 2
        emit_partial = self.mode == "partial"
        if K == 1:
            with m.timer("opTime"):
                parts = [self._unpark(h) for h, _ in handles]
                if (len(parts) > 1 or force_merge
                        or (not emit_partial and parts[0][3] > 4096)):
                    # the merge pass also sorts live groups first and
                    # compacts the output to the group count
                    part = self._merge_partials(parts)
                else:
                    part = parts[0]
                out = self._emit_batch(part, m, emit_partial)
            yield out
            return
        seed = (0x5EED ^ (depth * 0x9E3779B9)) & 0x7FFFFFFF
        fn = self._update_cache.get(("bslice", K, seed))
        if fn is None:
            fn = self._bucket_slice_fn(K, seed)
            self._update_cache[("bslice", K, seed)] = fn
        from ..memory.spill import spill_store
        store = spill_store(ctx.conf)
        open_handles = {h for h, _ in handles}
        try:
            for b in range(K):
                sub = None
                with m.timer("opTime"):
                    parts_b = []
                    for h, _ in handles:
                        close = (b == K - 1) and h in open_handles
                        ks, st, sl, cap = self._unpark(h, close=close)
                        if close:
                            open_handles.discard(h)
                        oks, ost, cnt = fn(ks, st, sl, jnp.int32(b))
                        nlive = fetch_int(cnt)
                        if nlive == 0:
                            continue
                        parts_b.append(self._shrink_to(oks, ost, nlive))
                    if not parts_b:
                        continue
                    bucket_rows = sum(p[3] for p in parts_b)
                    if (bucket_rows > max_rows
                            and depth + 1 < self._MAX_BUCKET_DEPTH
                            and bucket_rows < total):
                        # still oversized: park this bucket's parts and
                        # recurse with a fresh seed. The bucket_rows <
                        # total guard stops degenerate recursion when one
                        # key dominates (re-splitting can't shrink it).
                        sub = [(self._park(store, p), p[3])
                               for p in parts_b]
                        m.add("numBucketRecursions", 1)
                    else:
                        part = self._merge_partials(parts_b)
                        out = self._emit_batch(part, m, emit_partial)
                if sub is not None:
                    yield from self._emit_final(
                        ctx, m, sub, force_merge, depth + 1)
                else:
                    yield out
        finally:
            for h in open_handles:
                h.close()

    def _emit_batch(self, part, m, emit_partial: bool) -> DeviceBatch:
        ks, st, sl, cap = part
        if emit_partial:
            cvs = list(ks) + [CV(s, jnp.ones(cap, jnp.bool_)) for s in st]
            tbl = make_table(self.schema, cvs, cap)
            m.add("numOutputBatches", 1)
            return DeviceBatch(tbl, cap, sl, cap)
        outs = self._finalize_jit(ks, st, sl)
        xla_stats.count_dispatch()
        tbl = make_table(self.schema, outs, cap)
        m.add("numOutputBatches", 1)
        return DeviceBatch(tbl, cap, sl, cap)

    def _execute_final(self, ctx: ExecContext, pid: int, m):
        """Merge partial-format batches (keys + state columns) arriving
        from the exchange, then finalize — the final-mode half of the
        partial/final split. Arriving batches buffer spillably; a merge
        pass ALWAYS runs (a single exchanged batch still holds same-key
        partial rows from different map partitions), bucket-split when
        the combined state exceeds the merge bound."""
        from ..memory.spill import spill_store
        store = spill_store(ctx.conf)
        handles = []
        from ..memory.retry import retry_no_split
        for batch in self.children[0].execute_partition(ctx, pid):
            ctx.check_cancel()
            handles.append((retry_no_split(
                lambda b=batch: store.add_batch(b, priority=8)),
                batch.capacity))
        if not handles:
            return
        yield from self._emit_final(ctx, m, handles, force_merge=True)

    def _merge_partials(self, partials):
        if len(partials) == 1:
            ks, st, sl, cap = partials[0]
        else:
            cap = sum(p[3] for p in partials)
            nkeys = len(self.keys)
            ks = []
            for ki in range(nkeys):
                parts = [p[0][ki] for p in partials]
                ks.append(concat_cvs(parts, self.keys[ki].dtype))
            nst = len(partials[0][1])
            st = [jnp.concatenate([p[1][si] for p in partials])
                  for si in range(nst)]
            sl = concat_masks([p[2] for p in partials])
        nchunks = self._nchunks_for(ks, sl)
        fn = self._merge_cache.get(nchunks)
        if fn is None:
            from ..runtime.program_cache import cached_program
            fn = cached_program(
                self._merge_fn(nchunks), cls="HashAggregateExec",
                tag="merge", key=self._fp + (nchunks,))
            self._merge_cache[nchunks] = fn
        ks2, st2, sl2 = fn(ks, st, sl)
        xla_stats.count_dispatch()
        return self._compact_partial(ks2, st2, sl2)

    def _compact_partial(self, ks, st, sl):
        """Shrink a merged partial to a capacity sized by live group count.

        Merge output sorts live rows first, so live segments occupy the
        prefix [0, nlive); without this, the buffered partial stays at the
        concatenated input capacity and grows with total input rows even
        when there are few groups (reference shrinks on merge too:
        GpuAggregateExec.scala:863-894 repartition buckets)."""
        cap = sl.shape[0]
        nlive = fetch_int(jnp.sum(sl.astype(jnp.int32)))
        new_cap = bucket_capacity(max(nlive, 1))
        if new_cap >= cap:
            return (ks, st, sl, cap)
        idx = jnp.arange(new_cap)
        in_bounds = idx < nlive
        ks2 = []
        for kcv in ks:
            if kcv.offsets is not None:
                nbytes = fetch_int(kcv.offsets[nlive])
                byte_cap = bucket_capacity(max(nbytes, 1))
                byte_cap = min(byte_cap, kcv.data.shape[0])
                ks2.append(take_strings(kcv, idx, in_bounds=in_bounds,
                                        out_data_capacity=byte_cap))
            else:
                ks2.append(CV(kcv.data[:new_cap], kcv.validity[:new_cap]))
        st2 = [s[:new_cap] for s in st]
        return (ks2, st2, sl[:new_cap], new_cap)


class CollectAggExec(TpuExec):
    """Grouped aggregation when any aggregate is collect_list/collect_set.

    One stable sort of the partition's rows by (keys [, value for sets])
    makes each group's values contiguous: the sorted value column IS the
    concatenated list child, group-count cumsums are the offsets. Plain
    aggregates in the same GROUP BY ride the identical segmentation.
    (reference: GpuCollectList/GpuCollectSet in aggregateFunctions.scala,
    executed via cudf groupby collect; here the sort-segmented design means
    collect costs one value gather beyond the regular agg sort.)

    Distributed: the planner hash-exchanges input rows on the grouping keys
    first, so per_partition collects are final (disjoint keys).
    """

    def __init__(self, child: TpuExec, key_names, bound_keys, agg_names,
                 bound_aggs, schema: Schema, per_partition: bool = False):
        super().__init__([child], schema)
        self.key_names = list(key_names)
        self.keys = list(bound_keys)
        self.agg_names = list(agg_names)
        self.aggs = list(bound_aggs)
        self.per_partition = per_partition
        from ..runtime.program_cache import exprs_fp
        self._fp = (exprs_fp(self.keys), exprs_fp(self.aggs))
        self._run_cache = {}  # local memo over CachedProgram wrappers

    def num_partitions(self, ctx):
        if self.per_partition:
            return self.children[0].num_partitions(ctx)
        return 1

    def describe(self):
        return (f"CollectAggExec[keys={self.key_names}, "
                f"aggs={self.agg_names}]")

    def _value_nchunks(self, cvs, mask):
        """Static order-key chunk counts for string-typed collect_set
        values (dedup needs full-width comparisons)."""
        cap = mask.shape[0]
        ctx = EmitCtx(cvs, cap)
        ncs = []
        for a in self.aggs:
            if getattr(a, "is_set", False) and isinstance(
                    a.child.dtype, (dt.StringType, dt.BinaryType)):
                ncs.append(sk.string_nchunks(a.child.emit(ctx), mask))
            else:
                ncs.append(0)
        return tuple(ncs)

    def _key_nchunks(self, cvs, mask):
        cap = mask.shape[0]
        ctx = EmitCtx(cvs, cap)
        ncs = []
        for k in self.keys:
            if isinstance(k.dtype, (dt.StringType, dt.BinaryType)):
                ncs.append(sk.string_nchunks(k.emit(ctx), mask))
            else:
                ncs.append(0)
        return tuple(ncs)

    def _run_fn(self, nchunks, vnchunks):
        def fn(cvs, mask):
            cap = mask.shape[0]
            ctx = EmitCtx(cvs, cap)
            key_cvs = [k.emit(ctx) for k in self.keys]
            arrays = [jnp.logical_not(mask).astype(jnp.uint8)]  # dead last
            key_arrays = []
            for kcv, kexpr, nc in zip(key_cvs, self.keys, nchunks):
                ka = [jnp.logical_not(kcv.validity).astype(jnp.uint8)]
                ka += sk.order_keys(kcv, kexpr.dtype, nc)
                key_arrays.extend(ka)
                arrays.extend(ka)
            perm = sk.lexsort(arrays)
            keys_sorted = [a_[perm] for a_ in key_arrays]
            dead_sorted = arrays[0][perm]
            boundary = sk.group_boundaries([dead_sorted] + keys_sorted)
            seg_ids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
            live = mask[perm]
            seg_live = jax.ops.segment_max(live.astype(jnp.int32),
                                           seg_ids, cap) > 0
            if not self.keys:
                # ungrouped sort-path aggregates (count(DISTINCT x),
                # median, ...): every live row is one segment; an
                # all-dead batch still emits row 0 (count 0 / null /
                # empty list, matching Spark's ungrouped semantics)
                seg_live = seg_live.at[0].set(True)
            seg_start = jax.ops.segment_min(jnp.arange(cap), seg_ids, cap)
            src_rows = perm[jnp.clip(seg_start, 0, cap - 1)]
            outs = [take(kcv, src_rows, in_bounds=seg_live)
                    for kcv in key_cvs]
            for a, vnc in zip(self.aggs, vnchunks):
                if not getattr(a, "is_collect", False):
                    cv = (a.child.emit(ctx) if a.child is not None
                          else CV(jnp.zeros(cap, jnp.int8),
                                  jnp.ones(cap, jnp.bool_)))
                    if cv.offsets is not None:
                        scv = CV(jnp.zeros(cap, jnp.int8),
                                 cv.validity[perm])
                    else:
                        scv = CV(cv.data[perm], cv.validity[perm])
                    st = a.g_update(scv, live, seg_ids, cap)
                    v, okv = a.finalize(st)
                    if isinstance(v, CV):
                        outs.append(CV(v.data, v.validity & okv & seg_live,
                                       v.offsets, v.children))
                    else:
                        outs.append(CV(v, okv & seg_live))
                    continue
                vcv = a.child.emit(ctx)
                vs = take(vcv, perm)          # values in main (group) order
                valid = live & vs.validity    # collect family skips nulls
                from ..expr.aggregates import (_FirstLast,
                                              _seg_extreme_pos)
                if isinstance(a, _FirstLast):
                    # var-width first/last: per-segment positional select
                    # in input order (stable key sort preserves it)
                    elig = valid if a.ignore_nulls else live
                    sel, has = _seg_extreme_pos(elig, seg_ids, cap,
                                                a.take_first)
                    outs.append(take(vs, sel.astype(jnp.int32),
                                     in_bounds=has & seg_live))
                    continue
                if not getattr(a, "is_set", False):
                    # collect_list: stable main order == input order
                    outs.append(self._list_output(vs, valid, seg_ids, cap,
                                                  seg_live))
                    continue
                # per-agg SECONDARY sort: (segment, dead, null, value) —
                # each agg gets its own value ordering, so multiple
                # sorted aggs on different columns stay independent
                varrs = [jnp.logical_not(vs.validity).astype(jnp.uint8)]
                varrs += sk.order_keys(vs, a.child.dtype, vnc)
                order2 = sk.lexsort(
                    [seg_ids, jnp.logical_not(live).astype(jnp.uint8)]
                    + varrs)
                seg2 = seg_ids[order2]
                firsts2 = sk.group_boundaries(
                    [seg2] + [x[order2] for x in varrs])
                first_flag = jnp.zeros(cap, jnp.bool_).at[order2].set(
                    firsts2)
                kind = type(a).__name__
                if kind == "CountDistinct":
                    keep = valid & first_flag
                    cnt = jax.ops.segment_sum(keep.astype(jnp.int64),
                                              seg_ids, cap)
                    outs.append(CV(cnt, seg_live))
                elif kind in ("Percentile", "Median"):
                    outs.append(self._percentile_output(
                        a, vs, valid, seg_ids, order2, cap))
                else:                          # CollectSet
                    keep = valid & first_flag
                    outs.append(self._list_output(vs, keep, seg_ids, cap,
                                                  seg_live))
            return outs, seg_live
        return fn

    @staticmethod
    def _list_output(vs, keep, seg_ids, cap, seg_live):
        """Array column from kept rows: per-group counts -> offsets,
        global stable compaction preserves (group, position) order."""
        cnt = jax.ops.segment_sum(keep.astype(jnp.int32), seg_ids, cap)
        off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(cnt).astype(jnp.int32)])
        perm2 = jnp.argsort(jnp.logical_not(keep), stable=True)
        inb = jnp.arange(cap) < off[cap]
        child_cv = take(vs, perm2, inb)
        return CV(jnp.zeros(0, jnp.int8), seg_live, off, (child_cv,))

    def _percentile_output(self, a, vs, valid, seg_ids, order2, cap):
        """Rank-select percentiles from the per-agg value ordering:
        valid live values of segment g occupy order2 positions
        [start2[g], start2[g] + nvalid[g]) (dead/null rows sort last
        within the segment)."""
        rowpos = jnp.arange(cap, dtype=jnp.int32)
        seg2 = seg_ids[order2]
        start2 = jax.ops.segment_min(rowpos, seg2, cap)
        nvalid = jax.ops.segment_sum(valid.astype(jnp.int32),
                                     seg_ids, cap)
        ok_g = nvalid > 0
        sorted_vals = vs.data[order2]
        ps = a.percentages
        k = len(ps)

        def value_at(frac_idx):
            # frac_idx float per group; interpolate between floor/ceil
            lo = jnp.floor(frac_idx).astype(jnp.int32)
            hi = jnp.ceil(frac_idx).astype(jnp.int32)
            pos_lo = jnp.clip(start2 + lo, 0, cap - 1)
            pos_hi = jnp.clip(start2 + hi, 0, cap - 1)
            vlo = sorted_vals[pos_lo]
            vhi = sorted_vals[pos_hi]
            if a.interpolate:
                frac = frac_idx - lo.astype(jnp.float64)
                return (vlo.astype(jnp.float64) * (1 - frac)
                        + vhi.astype(jnp.float64) * frac)
            return vlo

        cols = []
        for p in ps:
            if a.interpolate:
                fi = p * jnp.maximum(nvalid - 1, 0).astype(jnp.float64)
            else:
                # Spark discrete: element at ceil(p*n)-1 (1-based rank)
                fi = jnp.maximum(
                    jnp.ceil(p * nvalid.astype(jnp.float64)) - 1,
                    0).astype(jnp.float64)
            cols.append(value_at(fi))
        if a.scalar_out:
            return CV(cols[0], ok_g)
        data = jnp.stack(cols, axis=1).reshape(-1)   # [cap*k] row-major
        child = CV(data, jnp.repeat(ok_g, k))
        off = jnp.arange(cap + 1, dtype=jnp.int32) * k
        return CV(jnp.zeros(0, jnp.int8), ok_g, off, (child,))

    def execute_partition(self, ctx: ExecContext, pid: int):
        m = ctx.metrics_for(self._op_id)
        child = self.children[0]
        child_pids = ([pid] if self.per_partition
                      else range(child.num_partitions(ctx)))
        batches = []
        for cpid in child_pids:
            batches.extend(child.execute_partition(ctx, cpid))
        if not batches:
            return
        ncols = len(child.schema.fields)
        with m.timer("opTime"):
            if len(batches) == 1:
                cvs, mask = batches[0].cvs(), batches[0].row_mask
            else:
                cvs = [concat_cvs([b.cvs()[i] for b in batches],
                                  child.schema.fields[i].dtype)
                       for i in range(ncols)]
                mask = concat_masks([b.row_mask for b in batches])
            nchunks = self._key_nchunks(cvs, mask)
            vnchunks = self._value_nchunks(cvs, mask)
            fn = self._run_cache.get((nchunks, vnchunks))
            if fn is None:
                from ..runtime.program_cache import cached_program
                fn = cached_program(
                    self._run_fn(nchunks, vnchunks),
                    cls="CollectAggExec", tag="run",
                    key=self._fp + (nchunks, vnchunks))
                self._run_cache[(nchunks, vnchunks)] = fn
            outs, seg_live = fn(cvs, mask)
            cap = mask.shape[0]
        tbl = make_table(self.schema, outs, cap)
        m.add("numOutputBatches", 1)
        yield DeviceBatch(tbl, cap, seg_live, cap)
