"""Core physical operators: scan, project, filter, limit, union, collect.

Analogs (reference): GpuFileSourceScanExec / basicPhysicalOperators.scala
(GpuProjectExec :~, GpuFilterExec), limit.scala, GpuUnionExec. The fused
project/filter path compiles each operator's bound expression list into one
jitted function over the batch's CV pytree.
"""
from __future__ import annotations

import concurrent.futures as cf
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.column import Column, bucket_capacity
from ..columnar.table import Field, Schema, Table
from ..expr.expressions import EmitCtx, Expression
from ..ops.kernel_utils import CV
from ..profiler import xla_stats
from ..runtime import faults
from .base import ExecContext, TpuExec
from .batch import DeviceBatch

__all__ = ["InMemoryScanExec", "CachedScanExec", "ParquetScanExec",
           "ProjectExec", "FilterExec",
           "LimitExec", "UnionExec", "collect_to_arrow", "cv_to_column",
           "make_table"]


def cv_to_column(cv: CV, dtype: dt.DataType, length: int) -> Column:
    children = []
    if isinstance(dtype, (dt.ArrayType, dt.MapType)):
        # child logical length = its full capacity: parent offsets only
        # reference the true element prefix, so trailing garbage is inert
        # (avoids a device sync to learn the exact element count in-trace)
        ch = cv.children[0]
        children = [cv_to_column(ch, Column.element_dtype(dtype),
                                 int(ch.validity.shape[0]))]
    elif isinstance(dtype, dt.StructType):
        children = [cv_to_column(ch, f.dtype, length)
                    for ch, f in zip(cv.children, dtype.fields)]
    return Column(dtype, length, cv.data, cv.validity, cv.offsets, children)


def make_table(schema: Schema, cvs: Sequence[CV], num_rows: int) -> Table:
    cols = [cv_to_column(cv, f.dtype, num_rows)
            for f, cv in zip(schema.fields, cvs)]
    return Table(schema.names, cols)


# ----------------------------------------------------------------------
class InMemoryScanExec(TpuExec):
    """Streams host (arrow) slices into HBM batches."""

    def __init__(self, arrow_table, schema: Schema):
        super().__init__([], schema)
        self.arrow = arrow_table

    def num_partitions(self, ctx):
        rows = self.arrow.num_rows
        per = max(1, ctx.conf.batch_size_rows)
        return max(1, -(-rows // per))

    def execute_partition(self, ctx, pid) -> Iterator[DeviceBatch]:
        per = max(1, ctx.conf.batch_size_rows)
        start = pid * per
        n = min(per, self.arrow.num_rows - start)
        if n <= 0 and pid > 0:
            return
        sl = self.arrow.slice(start, max(n, 0))
        m = ctx.metrics_for(self._op_id)
        with m.timer("scanTime"):
            tbl = Table.from_arrow(sl)
        m.add("numOutputRows", max(n, 0))
        m.add("numOutputBatches", 1)
        yield DeviceBatch(tbl, num_rows=max(n, 0))


def _rg_survives(stats, op: str, value) -> bool:
    """Can a row group with these column stats contain a matching row?"""
    try:
        if stats is None or not stats.has_min_max:
            return True
        # pyarrow raises ArrowNotImplementedError extracting stats for
        # some logical types (e.g. decimals stored as integers): keep
        # the group rather than die
        lo, hi = stats.min, stats.max
    except Exception:
        return True
    try:
        if op == ">=":
            return hi >= value
        if op == ">":
            return hi > value
        if op == "<=":
            return lo <= value
        if op == "<":
            return lo < value
        if op == "=":
            return lo <= value <= hi
    except TypeError:
        return True  # incomparable stat/literal types: keep the group
    return True


def prune_row_groups(pf, filters) -> List[int]:
    """Row groups whose footer stats might satisfy every conjunct
    (the filterBlocks analog: reference GpuParquetScan.scala:679)."""
    md = pf.metadata
    name_to_idx = {md.schema.column(i).name: i
                   for i in range(md.num_columns)}
    kept = []
    for rg in range(md.num_row_groups):
        g = md.row_group(rg)
        ok = True
        for (name, op, value) in filters:
            ci = name_to_idx.get(name)
            if ci is None:
                continue
            if not _rg_survives(g.column(ci).statistics, op, value):
                ok = False
                break
        if ok:
            kept.append(rg)
    return kept


class ParquetScanExec(TpuExec):
    """Parquet reader (reference: GpuParquetScan.scala reader types):
    - footer-stats row-group pruning from pushed-down conjuncts
      (filterBlocks :679)
    - MULTITHREADED mode: a thread pool decodes batches ahead of the
      device consumer through a bounded queue (the cloud reader :3134
      fetch/decode overlap, host-side)
    Host decode via Arrow C++, one H2D per batch; device decode is
    follow-on work (docs/compatibility.md)."""

    def __init__(self, paths: Sequence[str], schema: Schema,
                 columns: Optional[Sequence[str]] = None,
                 filters=None, dv=None, snapshot=None, delta_version=None):
        super().__init__([], schema)
        self.paths = list(paths)
        self.columns = list(columns) if columns else None
        self.filters = list(filters) if filters else None
        # {path: (table_root, deletionVector descriptor)} — dead-row
        # masks applied lazily per batch (Delta DVs); loaded once per
        # file at exec time, never at plan construction
        self.dv = dict(dv) if dv else None
        # bind-time (path, mtime_ns, size) pinning + Delta version,
        # copied from the logical scan (plan/logical.py). Public: both
        # flow into the exchange-subtree fingerprint the fragment cache
        # keys on. Verified per execute_partition — a file overwritten
        # MID-query raises instead of mixing old and new bytes
        # (between-action changes replan via DataFrame._execute).
        self.snapshot = tuple(snapshot) if snapshot else None
        self.delta_version = delta_version
        self._dv_cache = {}
        self._groups_cache = None

    def _verify_snapshot(self, ctx):
        if self.snapshot is None:
            return
        from ..io.snapshot import SnapshotMismatch, snapshot_current
        if not snapshot_current(self.snapshot):
            ctx.metrics_for(self._op_id).add("scanSnapshotViolations", 1)
            raise SnapshotMismatch(
                f"parquet files changed under a running scan: "
                f"{self.paths[:3]}{'...' if len(self.paths) > 3 else ''} "
                f"(bind-time snapshot no longer matches; re-run the "
                f"action to rebind)")

    def _reader_type(self, ctx) -> str:
        # cached: AUTO must not re-stat files per call — a flipped
        # decision mid-query would reinterpret partition indices (group
        # vs file) and silently drop rows
        rt = getattr(self, "_rt_cache", None)
        if rt is not None:
            return rt
        from ..config import (CLUSTER_EXECUTORS,
                              PARQUET_COALESCING_TARGET,
                              PARQUET_READER_TYPE)
        if ctx.conf.get(CLUSTER_EXECUTORS) > 0:
            # executor offload decodes per file; grouping is the
            # cluster scheduler's job there
            rt = "MULTITHREADED"
        else:
            rt = str(ctx.conf.get(PARQUET_READER_TYPE)).upper()
        if rt == "AUTO":
            # AUTO: many files each below the coalescing target ->
            # fewer uploads wins; else decode-prefetch overlap wins
            rt = "MULTITHREADED"
            if len(self.paths) >= 4:
                import os as _os
                target = ctx.conf.get(PARQUET_COALESCING_TARGET)
                try:
                    if all(_os.path.getsize(p) < target // 4
                           for p in self.paths):
                        rt = "COALESCING"
                except OSError:
                    pass
        self._rt_cache = rt
        return rt

    def _groups(self, ctx):
        """COALESCING reader: bin-pack files (in order) into groups of
        ~targetBytes on-disk size; one output partition per group."""
        if self._groups_cache is None:
            import os as _os
            from ..config import PARQUET_COALESCING_TARGET
            target = max(1, ctx.conf.get(PARQUET_COALESCING_TARGET))
            groups, cur, size = [], [], 0
            for p in self.paths:
                try:
                    fsz = _os.path.getsize(p)
                except OSError:
                    fsz = target
                if cur and size + fsz > target:
                    groups.append(cur)
                    cur, size = [], 0
                cur.append(p)
                size += fsz
            if cur:
                groups.append(cur)
            self._groups_cache = groups
        return self._groups_cache

    def num_partitions(self, ctx):
        if self._reader_type(ctx) == "COALESCING":
            return len(self._groups(ctx))    # 0 files -> 0 partitions
        return len(self.paths)

    def describe(self):
        f = f", filters={self.filters}" if self.filters else ""
        return f"ParquetScanExec[{len(self.paths)} files{f}]"

    def _dead_positions(self, path):
        """Dead row set for a DV-carrying file (cached per exec)."""
        if self.dv is None or path not in self.dv:
            return None
        got = self._dv_cache.get(path)
        if got is None:
            from ..io.dv import load_dv_positions
            root, desc = self.dv[path]
            # concurrent scan workers may both miss; setdefault keeps
            # one winner so every caller shares a single row set
            got = self._dv_cache.setdefault(
                path, set(load_dv_positions(root, desc)))
        return got

    def _device_decode_on(self, ctx) -> bool:
        """Device parquet decode applies when enabled AND the backend
        is a real accelerator; on the CPU backend pyarrow's native
        decoder shares the silicon with the 'device' kernels and wins,
        so there it only fires when the conf is set explicitly (tests,
        parity fuzzing, scan profiling)."""
        from ..config import PARQUET_DEVICE_DECODE
        if not ctx.conf.get(PARQUET_DEVICE_DECODE):
            return False
        if jax.default_backend() == "cpu":
            return ctx.conf.is_set(PARQUET_DEVICE_DECODE)
        return True

    def _device_decoded_batches(self, ctx, path, m):
        """Device-decode path (GpuParquetScan.scala:3364 analog): per
        row group, eligible column chunks decode ON DEVICE from one raw
        byte upload (staged through the pinned pool; snappy pages
        decompress in parallel on the prefetch thread pool); remaining
        columns ride the host pyarrow path and merge into the same
        DeviceBatch. Returns None when nothing in the file is
        device-decodable (caller uses the host path)."""
        import pyarrow.parquet as pq

        from ..columnar import dtypes as dt
        from ..columnar.column import Column, bucket_capacity
        from ..config import PARQUET_DEVICE_SNAPPY
        from ..io.file_cache import cached_local_path
        from ..io.parquet_device import (chunk_device_plan,
                                         decode_chunk_device,
                                         eligible_chunks,
                                         fallback_reasons)
        from ..memory.host import staging_pool
        try:
            lp = cached_local_path(path, ctx.conf)
            pf = pq.ParquetFile(lp)
        except FileNotFoundError:
            lp = path
            pf = pq.ParquetFile(path)
        cols = (self.columns if self.columns is not None
                else [f.name for f in self.schema.fields])
        if pf.metadata.num_row_groups == 0:
            return None
        if not eligible_chunks(pf, 0, cols):
            for name, (cat, _detail) in fallback_reasons(
                    pf, 0, cols).items():
                m.add(f"deviceDecodeFallback.{cat}", 1)
            return None
        kept = (prune_row_groups(pf, self.filters) if self.filters
                else list(range(pf.metadata.num_row_groups)))

        # the decode unit is a whole row group; cap the batch-size blowup
        # vs the host path (which slices to batch_size_rows) to bound the
        # device-memory spike on huge row groups. Checked BEFORE any
        # metric: the host fallback records skippedRowGroups itself.
        per = max(1, ctx.conf.batch_size_rows)
        if any(pf.metadata.row_group(rg).num_rows > 4 * per
               for rg in kept):
            return None
        m.add("skippedRowGroups", pf.metadata.num_row_groups - len(kept))
        field_by_name = {f.name: f for f in self.schema.fields}
        pool = staging_pool(ctx.conf)
        decomp = _decompress_pool(ctx)
        dev_snappy = ctx.conf.get(PARQUET_DEVICE_SNAPPY)

        import numpy as _np
        import pyarrow as _pa

        def gen():
            pool0 = dict(pool.metrics)
            for rg in kept:
                nrows = pf.metadata.row_group(rg).num_rows
                if nrows == 0:
                    continue
                cap = bucket_capacity(nrows)
                elig = eligible_chunks(pf, rg, cols)
                for name, (cat, _detail) in fallback_reasons(
                        pf, rg, cols).items():
                    m.add(f"deviceDecodeFallback.{cat}", 1)
                dev_cols = {}
                chunks = []
                rgmd = pf.metadata.row_group(rg)
                with m.timer("scanTime"):
                    for name, ci in list(elig.items()):
                        fld = field_by_name[name]
                        np_dt = fld.dtype.np_dtype
                        if np_dt is None or (
                                isinstance(fld.dtype, dt.DecimalType)
                                and fld.dtype.is_decimal128):
                            # decimal128 needs the two-limb buffer the
                            # fixed-width decode does not produce
                            m.add("deviceDecodeFallback.type", 1)
                            continue
                        af = pf.schema_arrow.field(name)
                        if (_pa.types.is_timestamp(af.type)
                                and af.type.unit != "us"):
                            # non-micros: host path converts
                            m.add("deviceDecodeFallback.type", 1)
                            continue
                        c = chunk_device_plan(
                            pf, lp, rg, ci, name, af.nullable,
                            pool=pool, decomp_pool=decomp,
                            device_snappy=dev_snappy, metrics=m)
                        try:
                            got = (decode_chunk_device(c, cap,
                                                       metrics=m)
                                   if c else None)
                        except Exception:
                            got = None      # leases must not leak
                        if got is None:
                            if c is not None:
                                c.close()
                            m.add("deviceDecodeFallback.pages", 1)
                            continue
                        chunks.append(c)
                        if isinstance(fld.dtype,
                                      (dt.StringType, dt.BinaryType)):
                            data, valid, offsets = got
                            dev_cols[name] = Column(fld.dtype, nrows,
                                                    data, valid,
                                                    offsets)
                        else:
                            vals, valid = got
                            if str(vals.dtype) != _np.dtype(np_dt).name:
                                vals = vals.astype(np_dt)
                            dev_cols[name] = Column(fld.dtype, nrows,
                                                    vals, valid)
                        m.add("deviceDecodeBytes", rgmd.column(ci)
                              .total_compressed_size)
                    rest = [n for n in cols if n not in dev_cols]
                    if rest:
                        at = pf.read_row_group(rg, columns=rest)
                        host_tbl = Table.from_arrow(at)
                        host_by_name = dict(zip(at.schema.names,
                                                host_tbl.columns))
                    else:
                        host_by_name = {}
                    out_cols = []
                    for n in cols:
                        if n in dev_cols:
                            out_cols.append(dev_cols[n])
                        else:
                            out_cols.append(host_by_name[n])
                    tbl = Table(list(cols), out_cols)
                # staging buffers go back to the pool only after the
                # decode OUTPUTS are materialized: jnp.asarray can alias
                # the host buffer zero-copy (CPU backend) and dispatch
                # is async, so a reused lease would be overwritten while
                # queued kernels still read it. Worker-side wait, off
                # the compute thread.
                if chunks:
                    outs = [(col.data, col.validity, col.offsets)
                            for col in dev_cols.values()
                            if col.offsets is not None] + \
                           [(col.data, col.validity)
                            for col in dev_cols.values()
                            if col.offsets is None]
                    # tpulint: allow[block-sync] prefetch-thread join:
                    jax.block_until_ready(outs)  # staging reuse must
                    # not race async kernels aliasing the host buffer
                for c in chunks:
                    c.close()
                m.add("numOutputRows", nrows)
                m.add("numOutputBatches", 1)
                m.add("deviceDecodedChunks", len(dev_cols))
                yield DeviceBatch(tbl, num_rows=nrows)
            for k, v in pool.metrics.items():
                delta = v - pool0.get(k, 0)
                if k.endswith("HeldBytes"):
                    m.set(k, v)
                elif delta:
                    m.add(k, delta)
        return gen()

    def _decoded_batches(self, ctx, path, m):
        import pyarrow as pa
        import pyarrow.parquet as pq
        from ..io.file_cache import cached_local_path
        per = max(1, ctx.conf.batch_size_rows)
        try:
            pf = pq.ParquetFile(cached_local_path(path, ctx.conf))
        except FileNotFoundError:
            # LRU eviction can unlink the cached copy between
            # local_path() and open; the source path is always valid
            pf = pq.ParquetFile(path)
        cols = (self.columns if self.columns is not None
                else [f.name for f in self.schema.fields])
        dead = self._dead_positions(path)
        # row-group pruning would shift file-row positions under a DV
        if self.filters and dead is None:
            kept = prune_row_groups(pf, self.filters)
            m.add("skippedRowGroups",
                  pf.metadata.num_row_groups - len(kept))
            if not kept:
                return
            it = pf.iter_batches(batch_size=per, columns=cols,
                                 row_groups=kept)
        else:
            it = pf.iter_batches(batch_size=per, columns=cols)
        off = 0
        for rb in it:
            at = pa.table(rb)
            if dead is not None:
                from ..io.dv import apply_dv_to_table
                n0 = at.num_rows
                batch_dead = {d - off for d in dead
                              if off <= d < off + n0}
                at = apply_dv_to_table(at, batch_dead)
                off += n0
                if at.num_rows == 0:
                    continue
            yield at

    def execute_partition(self, ctx, pid) -> Iterator[DeviceBatch]:
        from ..config import (CLUSTER_EXECUTORS,
                              MULTITHREADED_READ_THREADS,
                              PARQUET_READER_TYPE)
        m = ctx.metrics_for(self._op_id)
        self._verify_snapshot(ctx)
        reader_type = self._reader_type(ctx)
        if reader_type == "COALESCING":
            # pid indexes file GROUPS here, not files
            yield from self._execute_coalescing(ctx, pid, m)
            return
        path = self.paths[pid]
        if (ctx.conf.get(CLUSTER_EXECUTORS) > 0
                and ctx.session is not None
                and not (self.dv and path in self.dv)):
            # driver/executor split: host decode runs in an executor
            # process, Arrow IPC ships back (cluster/driver.py)
            cm = ctx.session.cluster_manager()
            fut = cm.submit(_remote_decode_parquet, path, self.columns
                            or [f.name for f in self.schema.fields],
                            self.filters, max(1, ctx.conf.batch_size_rows))
            import pyarrow as pa
            blobs, skipped = fut.result()
            m.add("skippedRowGroups", skipped)
            for blob in blobs:
                with pa.ipc.open_stream(blob) as rd:
                    at = rd.read_all()
                with m.timer("scanTime"):
                    tbl = Table.from_arrow(at)
                m.add("numOutputRows", at.num_rows)
                m.add("numOutputBatches", 1)
                yield DeviceBatch(tbl, num_rows=at.num_rows)
            return
        from ..config import PARQUET_DEVICE_DECODE
        if (self._device_decode_on(ctx)
                and not (self.dv and path in self.dv)):
            dev_iter = self._device_decoded_batches(ctx, path, m)
            if dev_iter is not None:
                # decompress + plan + upload staging runs on a worker
                # thread: device compute only ever waits on the queue
                # (prefetchWaitSecs), not on snappy or page parsing
                nthreads = max(1,
                               ctx.conf.get(MULTITHREADED_READ_THREADS))
                yield from _prefetched(dev_iter,
                                       depth=min(nthreads, 4),
                                       wait_metrics=(m,
                                                     "prefetchWaitSecs"))
                return
        host_iter = self._decoded_batches(ctx, path, m)
        if reader_type == "MULTITHREADED":
            nthreads = max(1, ctx.conf.get(MULTITHREADED_READ_THREADS))
            host_iter = _prefetched(host_iter, depth=min(nthreads, 4))
        for at in host_iter:
            with m.timer("scanTime"):
                tbl = Table.from_arrow(at)
            m.add("numOutputRows", at.num_rows)
            m.add("numOutputBatches", 1)
            yield DeviceBatch(tbl, num_rows=at.num_rows)

    def _execute_coalescing(self, ctx, pid, m):
        """COALESCING reader: the group's files decode IN PARALLEL on a
        thread pool, concatenate host-side, and upload as full-target
        batches — many small files cost one H2D per coalesced batch
        instead of one per file (reference: GpuParquetScan COALESCING
        reader, GpuMultiFileReader.scala)."""
        import pyarrow as pa
        import pyarrow.parquet as pq
        from concurrent.futures import ThreadPoolExecutor
        from ..config import MULTITHREADED_READ_THREADS
        group = self._groups(ctx)[pid]
        cols = (self.columns if self.columns is not None
                else [f.name for f in self.schema.fields])
        if not cols:
            # count-style scan: pf.read(columns=[]) drops the row count
            # (0-column Table), so stream per-file batches which keep it
            for p in group:
                for at in self._decoded_batches(ctx, p, m):
                    with m.timer("scanTime"):
                        tbl = Table.from_arrow(at)
                    m.add("numOutputRows", at.num_rows)
                    m.add("numOutputBatches", 1)
                    yield DeviceBatch(tbl, num_rows=at.num_rows)
            return

        from ..io.file_cache import cached_local_path

        def read_one(p):
            try:
                pf = pq.ParquetFile(cached_local_path(p, ctx.conf))
            except FileNotFoundError:
                # cache-eviction race: fall back to the source path
                pf = pq.ParquetFile(p)
            dead = self._dead_positions(p)
            if self.filters and dead is None:
                kept = prune_row_groups(pf, self.filters)
                skipped = pf.metadata.num_row_groups - len(kept)
                if not kept:
                    return None, skipped
                return pf.read_row_groups(kept, columns=cols), skipped
            at = pf.read(columns=cols)
            if dead is not None:
                from ..io.dv import apply_dv_to_table
                at = apply_dv_to_table(at, dead)
            return at, 0

        nthreads = max(1, ctx.conf.get(MULTITHREADED_READ_THREADS))
        with ThreadPoolExecutor(max_workers=nthreads,
                                thread_name_prefix="tpu-coalesce") as pool:
            parts = list(pool.map(read_one, group))
        tables = []
        for at, skipped in parts:
            m.add("skippedRowGroups", skipped)
            if at is not None and at.num_rows:
                tables.append(at)
        if not tables:
            return
        combined = (pa.concat_tables(tables) if len(tables) > 1
                    else tables[0])
        m.add("coalescedFiles", len(group))
        per = max(1, ctx.conf.batch_size_rows)
        for start in range(0, combined.num_rows, per):
            sl = combined.slice(start, min(per, combined.num_rows - start))
            with m.timer("scanTime"):
                tbl = Table.from_arrow(sl)
            m.add("numOutputRows", sl.num_rows)
            m.add("numOutputBatches", 1)
            yield DeviceBatch(tbl, num_rows=sl.num_rows)


# tpulint: allow[pool-cancel] remote-executor task, no ExecContext — cancel is task abort
def _remote_decode_parquet(path, columns, filters, batch_rows):
    """Executor-side parquet decode task: returns (list of Arrow IPC
    stream blobs — one per batch — , skipped row-group count). Pure
    host-side, idempotent (safe to re-execute after executor loss)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    pf = pq.ParquetFile(path)
    skipped = 0
    if filters:
        kept = prune_row_groups(pf, filters)
        skipped = pf.metadata.num_row_groups - len(kept)
        if not kept:
            return [], skipped
        it = pf.iter_batches(batch_size=batch_rows, columns=columns,
                             row_groups=kept)
    else:
        it = pf.iter_batches(batch_size=batch_rows, columns=columns)
    blobs = []
    for rb in it:
        at = pa.table(rb)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, at.schema) as w:
            w.write_table(at)
        blobs.append(sink.getvalue().to_pybytes())
    return blobs, skipped


_DECOMP_POOL = None
_DECOMP_LOCK = __import__("threading").Lock()


def _decompress_pool(ctx):
    """Shared thread pool for per-page snappy decompression in the
    device scan (the MULTITHREADED prefetch pool): pages of one chunk
    decompress in parallel, and the whole plan step already runs on
    the prefetch worker — never the compute thread."""
    global _DECOMP_POOL
    from ..config import MULTITHREADED_READ_THREADS
    n = max(1, ctx.conf.get(MULTITHREADED_READ_THREADS))
    with _DECOMP_LOCK:
        if _DECOMP_POOL is None:
            from concurrent.futures import ThreadPoolExecutor
            _DECOMP_POOL = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="tpu-decomp")
        return _DECOMP_POOL


def _prefetched(it: Iterator, depth: int, wait_metrics=None):
    """Run `it` on a worker thread with a bounded queue so host parquet
    decode overlaps device compute (async-IO analog, reference io/async
    ThrottlingExecutor). An abandoned consumer (e.g. under a LIMIT)
    signals the worker via a stop event and drains the queue so the
    blocked put unblocks — no leaked threads or pinned batches.
    `wait_metrics=(MetricSet, name)` records consumer block time on the
    queue — the observable proof that decode ran ahead of compute."""
    import queue
    import threading
    import time as _time
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    DONE = object()
    stop = threading.Event()
    err: List[BaseException] = []

    def work():
        try:
            for item in it:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # propagate to consumer
            err.append(e)
        finally:
            # the sentinel must arrive even when the queue is full; keep
            # trying unless the consumer already walked away
            while not stop.is_set():
                try:
                    q.put(DONE, timeout=0.1)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=work, daemon=True,
                         name="tpu-prefetch")
    t.start()
    try:
        while True:
            if wait_metrics is not None:
                t0 = _time.perf_counter()
                item = q.get()
                wait_metrics[0].add(wait_metrics[1],
                                    _time.perf_counter() - t0)
            else:
                item = q.get()
            if item is DONE:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        stop.set()
        while not q.empty():
            try:
                q.get_nowait()
            except queue.Empty:
                break


class CachedScanExec(TpuExec):
    """Serves HBM-resident batches directly (GpuInMemoryTableScan analog)."""

    def __init__(self, batches, schema: Schema):
        super().__init__([], schema)
        self.batches = list(batches)

    def num_partitions(self, ctx):
        return max(1, len(self.batches))

    def execute_partition(self, ctx, pid):
        if pid < len(self.batches):
            yield self.batches[pid]


# ----------------------------------------------------------------------
class ProjectExec(TpuExec):
    def __init__(self, child: TpuExec, bound_exprs: List[Expression],
                 schema: Schema):
        super().__init__([child], schema)
        self.bound = bound_exprs

        def _run(cvs, mask):
            ctx = EmitCtx(cvs, mask.shape[0])
            return [e.emit(ctx) for e in self.bound]

        from ..runtime.program_cache import cached_program, exprs_fp
        self._jit = cached_program(_run, cls="ProjectExec", tag="run",
                                   key=exprs_fp(self.bound))

    def describe(self):
        return f"ProjectExec[{', '.join(map(repr, self.bound))}]"

    def fusable_stage(self):
        def fn(cvs, mask):
            ctx = EmitCtx(cvs, mask.shape[0])
            return [e.emit(ctx) for e in self.bound], mask
        return fn

    def stage_fingerprint(self):
        from ..runtime.program_cache import exprs_fp
        return ("Project", exprs_fp(self.bound))

    def preserves_ordinals(self):
        return False

    def execute_partition(self, ctx, pid):
        from . import degrade
        m = ctx.metrics_for(self._op_id)
        for batch in self.children[0].execute_partition(ctx, pid):
            ctx.check_cancel()
            if self._op_id not in ctx.degraded:
                try:
                    if faults.ACTIVE:
                        faults.hit("device.dispatch",
                                   query_id=ctx.query_id,
                                   op="ProjectExec")
                    with m.timer("opTime"):
                        out = self._jit(batch.cvs(), batch.row_mask)
                except Exception as e:  # noqa: BLE001 - classified below
                    if not degrade.should_degrade(ctx, self, e):
                        raise
                else:
                    xla_stats.count_dispatch()
                    m.add("numOutputBatches", 1)
                    yield DeviceBatch(
                        make_table(self.schema, out, batch.num_rows),
                        batch.num_rows, batch.row_mask, batch.capacity)
                    continue
            # degraded (or this batch's dispatch just failed): the host
            # interpreter evaluates the same bound expressions
            with m.timer("hostEvalTime"):
                hb = degrade.host_project_batch(self, batch)
            m.add("degradedToHost", 1)
            m.add("numOutputBatches", 1)
            yield hb


class FilterExec(TpuExec):
    def __init__(self, child: TpuExec, bound_cond: Expression):
        super().__init__([child], child.schema)
        self.bound = bound_cond

        def _run(cvs, mask):
            ctx = EmitCtx(cvs, mask.shape[0])
            cv = self.bound.emit(ctx)
            return mask & cv.validity & cv.data.astype(jnp.bool_)

        from ..runtime.program_cache import cached_program, expr_fp
        self._jit = cached_program(_run, cls="FilterExec", tag="run",
                                   key=(expr_fp(self.bound),))

    def describe(self):
        return f"FilterExec[{self.bound!r}]"

    def fusable_stage(self):
        def fn(cvs, mask):
            ctx = EmitCtx(cvs, mask.shape[0])
            cv = self.bound.emit(ctx)
            return cvs, mask & cv.validity & cv.data.astype(jnp.bool_)
        return fn

    def stage_fingerprint(self):
        from ..runtime.program_cache import expr_fp
        return ("Filter", expr_fp(self.bound))

    def execute_partition(self, ctx, pid):
        from . import degrade
        m = ctx.metrics_for(self._op_id)
        for batch in self.children[0].execute_partition(ctx, pid):
            ctx.check_cancel()
            if self._op_id not in ctx.degraded:
                try:
                    if faults.ACTIVE:
                        faults.hit("device.dispatch",
                                   query_id=ctx.query_id,
                                   op="FilterExec")
                    with m.timer("opTime"):
                        new_mask = self._jit(batch.cvs(), batch.row_mask)
                except Exception as e:  # noqa: BLE001 - classified below
                    if not degrade.should_degrade(ctx, self, e):
                        raise
                else:
                    xla_stats.count_dispatch()
                    m.add("numOutputBatches", 1)
                    yield DeviceBatch(batch.table, batch.num_rows,
                                      new_mask, batch.capacity)
                    continue
            # degraded (or this batch's dispatch just failed): host
            # predicate evaluation over the same batch
            with m.timer("hostEvalTime"):
                hb = degrade.host_filter_batch(self, batch)
            m.add("degradedToHost", 1)
            if hb is None:
                continue
            m.add("numOutputBatches", 1)
            yield hb


class LimitExec(TpuExec):
    """Global limit; collapses to a single output partition.

    The limit itself is stateful across batches (`remaining` lives on
    the host), so it can never be a FusedStage member — instead it
    collapses its own fusable child chain into the clip program
    (collapse_fusable): stages + rank-clip run as one dispatch per
    batch."""

    fuses_child_chain = True

    def __init__(self, child: TpuExec, n: int):
        super().__init__([child], child.schema)
        self.n = n
        self._ncap = bucket_capacity(max(n, 1))
        # resolved lazily at first execute (children may be wrapped by
        # LORE dump pass-throughs after planning)
        self._base = None
        self._stages = None
        self._n_fused = 0

        from ..runtime.program_cache import cached_program

        def _clip(mask, remaining):
            ranks = jnp.cumsum(mask.astype(jnp.int64))
            new_mask = mask & (ranks <= remaining)
            return new_mask, jnp.sum(new_mask.astype(jnp.int64))

        self._clip = _clip
        self._jit = cached_program(_clip, cls="LimitExec", tag="clip")
        # _fused_jit is keyed on the fused chain's structure, which is
        # only known after _resolve_fusion — built there
        self._fused_jit = None
        ncap = self._ncap

        def _perm(mask):
            from ..ops.gather import compaction_perm
            perm, count = compaction_perm(mask)
            return perm[:ncap], jnp.arange(ncap) < count

        self._perm = cached_program(_perm, cls="LimitExec", tag="perm",
                                    key=(ncap,))

    def _resolve_fusion(self, ctx):
        if self._base is None:
            from ..config import STAGE_FUSION_ENABLED
            from .base import collapse_fusable
            if ctx.conf.get(STAGE_FUSION_ENABLED):
                self._base, self._stages, self._n_fused = collapse_fusable(
                    self.children[0])
            else:
                self._base, self._n_fused = self.children[0], 0
                self._stages = lambda cvs, mask: (cvs, mask)
        if self._fused_jit is None:
            from ..runtime.program_cache import cached_program
            clip = self._clip

            def _clip_fused(cvs, mask, remaining):
                cvs, mask = self._stages(cvs, mask)
                new_mask, took = clip(mask, remaining)
                return cvs, new_mask, took

            # tpulint: allow[fp-unstable-attr,unstable-program-key] id(self) is the documented per-instance fallback key: unshared, never falsely shared, excluded from warm packs
            self._fused_jit = cached_program(
                _clip_fused, cls="LimitExec", tag="clip_fused",
                key=getattr(self._stages, "_stage_fp",
                            ("inst", id(self))))

    def describe(self):
        fused = f", fused_stages={self._n_fused}" if self._n_fused else ""
        return f"LimitExec[{self.n}{fused}]"

    def num_partitions(self, ctx):
        return 1

    def execute_partition(self, ctx, pid):
        self._resolve_fusion(ctx)
        remaining = self.n
        child = self._base
        for cpid in range(child.num_partitions(ctx)):
            if remaining <= 0:
                return
            for batch in child.execute_partition(ctx, cpid):
                ctx.check_cancel()
                if remaining <= 0:
                    return
                if self._n_fused:
                    cvs, mask, took = self._fused_jit(
                        batch.cvs(), batch.row_mask, remaining)
                    tbl = None
                else:
                    cvs, tbl = batch.cvs(), batch.table
                    mask, took = self._jit(batch.row_mask, remaining)
                xla_stats.count_dispatch()
                took = int(took)
                if took == 0:
                    continue
                remaining -= took
                if batch.capacity > 2 * self._ncap:
                    # the surviving rows are a sliver of the batch: compact
                    # to a limit-sized capacity on device so collect fetches
                    # O(n) bytes, not the full sorted input
                    from ..ops.gather import gather_cols
                    idx, inb = self._perm(mask)
                    out = gather_cols(cvs, idx, inb)
                    yield DeviceBatch(make_table(self.schema, out, took),
                                      took, inb, self._ncap)
                else:
                    if tbl is None:
                        tbl = make_table(self.schema, cvs, batch.num_rows)
                    yield DeviceBatch(tbl, batch.num_rows, mask,
                                      batch.capacity)


class UnionExec(TpuExec):
    def __init__(self, children: List[TpuExec], schema: Schema):
        super().__init__(children, schema)
        self._offsets = []

    def num_partitions(self, ctx):
        return sum(c.num_partitions(ctx) for c in self.children)

    def execute_partition(self, ctx, pid):
        for c in self.children:
            n = c.num_partitions(ctx)
            if pid < n:
                for b in c.execute_partition(ctx, pid):
                    ctx.check_cancel()
                    # positional union: rename child columns to ours
                    yield DeviceBatch(b.table.rename(self.schema.names),
                                      b.num_rows, b.row_mask, b.capacity)
                return
            pid -= n


# ----------------------------------------------------------------------
def _batch_to_arrow(batch: DeviceBatch):
    import pyarrow as pa
    from ..columnar.column import Column
    from ..utils.transfer import fetch
    # fetch the mask together with all column buffers: ONE device_get
    host = fetch([c.device_buffers() for c in batch.table.columns]
                 + [batch.row_mask])
    # tpulint: allow[host-sync] `host` is fetched above — numpy view
    mask = np.asarray(host[-1])[:batch.num_rows]
    arrs = [Column.arrow_from_host(c.dtype, c.length, b)
            for c, b in zip(batch.table.columns, host[:-1])]
    at = (pa.Table.from_arrays(arrs, names=list(batch.table.names))
          if arrs else pa.table({}))
    if at.num_rows == 0 and batch.num_rows > 0:
        return pa.table({})  # zero-column batch (count(*) pipelines)
    if not mask.all():
        at = at.filter(pa.array(mask))
    return at


def collect_to_arrow(root: TpuExec, ctx: ExecContext):
    """Run the plan and materialize a host pyarrow Table (the analog of
    GpuColumnarToRowExec + collect). Partitions run as concurrent tasks
    bounded by the TpuSemaphore (the GpuSemaphore admission model:
    reference GpuSemaphore.scala:183)."""
    import pyarrow as pa
    nparts = root.num_partitions(ctx)
    if nparts <= 1:
        pieces = [_batch_to_arrow(b) for b in root.execute_all(ctx)]
    else:
        sem = _session_semaphore(ctx)
        import concurrent.futures as cf
        import threading as _threading
        sem_wait = [0.0]
        wait_lock = _threading.Lock()
        # pool-weight-derived base priority (service scheduler): heavier
        # pools get more-negative values and win permit ties; pid breaks
        # ties within a query via the heap's seq ordering
        base_prio = getattr(ctx, "sem_priority", 0)

        def run_part(pid):
            # GpuSemaphore model: hold the permit while DEVICE work runs
            # (advancing the iterator executes the jitted kernels), release
            # around the host-side fetch/convert
            out = []
            waited = 0.0
            it = root.execute_partition(ctx, pid)
            try:
                while True:
                    waited += sem.acquire(priority=base_prio,
                                          token=ctx.cancel)
                    try:
                        b = next(it, None)
                    finally:
                        sem.release()
                    if b is None:
                        break
                    ctx.check_cancel()
                    out.append(_batch_to_arrow(b))
            finally:
                with wait_lock:
                    sem_wait[0] += waited
            return out

        workers = min(nparts, max(2, ctx.conf.concurrent_tasks * 2))
        with cf.ThreadPoolExecutor(
                workers, thread_name_prefix="tpu-collect") as pool:
            results = list(pool.map(run_part, range(nparts)))
        pieces = [at for r in results for at in r]
        if sem_wait[0] > 0:
            # per-query chip-admission wait, surfaced on the root node
            # (Ms suffix on purpose: op_time_seconds sums *Time keys and
            # wait is not attributed operator time)
            ctx.metrics_for(root._op_id).add(
                "semaphoreWaitMs", round(sem_wait[0] * 1e3, 3))
    if not pieces:
        return root.schema.to_arrow().empty_table()
    return pa.concat_tables(pieces)


_SEM_LOCK = __import__("threading").Lock()


def _session_semaphore(ctx: ExecContext):
    from ..memory.semaphore import TpuSemaphore
    if ctx.session is None:
        return TpuSemaphore(ctx.conf.concurrent_tasks)
    with _SEM_LOCK:
        sem = getattr(ctx.session, "_semaphore", None)
        if sem is None:
            sem = TpuSemaphore(ctx.conf.concurrent_tasks)
            ctx.session._semaphore = sem
        return sem
