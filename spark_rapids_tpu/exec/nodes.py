"""Core physical operators: scan, project, filter, limit, union, collect.

Analogs (reference): GpuFileSourceScanExec / basicPhysicalOperators.scala
(GpuProjectExec :~, GpuFilterExec), limit.scala, GpuUnionExec. The fused
project/filter path compiles each operator's bound expression list into one
jitted function over the batch's CV pytree.
"""
from __future__ import annotations

import concurrent.futures as cf
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.column import Column, bucket_capacity
from ..columnar.table import Field, Schema, Table
from ..expr.expressions import EmitCtx, Expression
from ..ops.kernel_utils import CV
from .base import ExecContext, TpuExec
from .batch import DeviceBatch

__all__ = ["InMemoryScanExec", "CachedScanExec", "ParquetScanExec",
           "ProjectExec", "FilterExec",
           "LimitExec", "UnionExec", "collect_to_arrow", "cv_to_column",
           "make_table"]


def cv_to_column(cv: CV, dtype: dt.DataType, length: int) -> Column:
    return Column(dtype, length, cv.data, cv.validity, cv.offsets)


def make_table(schema: Schema, cvs: Sequence[CV], num_rows: int) -> Table:
    cols = [cv_to_column(cv, f.dtype, num_rows)
            for f, cv in zip(schema.fields, cvs)]
    return Table(schema.names, cols)


# ----------------------------------------------------------------------
class InMemoryScanExec(TpuExec):
    """Streams host (arrow) slices into HBM batches."""

    def __init__(self, arrow_table, schema: Schema):
        super().__init__([], schema)
        self.arrow = arrow_table

    def num_partitions(self, ctx):
        rows = self.arrow.num_rows
        per = max(1, ctx.conf.batch_size_rows)
        return max(1, -(-rows // per))

    def execute_partition(self, ctx, pid) -> Iterator[DeviceBatch]:
        per = max(1, ctx.conf.batch_size_rows)
        start = pid * per
        n = min(per, self.arrow.num_rows - start)
        if n <= 0 and pid > 0:
            return
        sl = self.arrow.slice(start, max(n, 0))
        m = ctx.metrics_for(self._op_id)
        with m.timer("scanTime"):
            tbl = Table.from_arrow(sl)
        m.add("numOutputRows", max(n, 0))
        m.add("numOutputBatches", 1)
        yield DeviceBatch(tbl, num_rows=max(n, 0))


class ParquetScanExec(TpuExec):
    """PERFILE/MULTITHREADED parquet reader: host decode via Arrow C++,
    one H2D per batch (reference: GpuParquetScan.scala readers; device
    decode is follow-on work — footnote in docs/compatibility.md)."""

    def __init__(self, paths: Sequence[str], schema: Schema,
                 columns: Optional[Sequence[str]] = None,
                 filters=None):
        super().__init__([], schema)
        self.paths = list(paths)
        self.columns = list(columns) if columns else None
        self.filters = filters

    def num_partitions(self, ctx):
        return len(self.paths)

    def execute_partition(self, ctx, pid) -> Iterator[DeviceBatch]:
        import pyarrow.parquet as pq
        m = ctx.metrics_for(self._op_id)
        path = self.paths[pid]
        per = max(1, ctx.conf.batch_size_rows)
        pf = pq.ParquetFile(path)
        cols = (self.columns if self.columns is not None
                else [f.name for f in self.schema.fields])
        for rb in pf.iter_batches(batch_size=per, columns=cols):
            with m.timer("scanTime"):
                import pyarrow as pa
                tbl = Table.from_arrow(pa.table(rb))
            m.add("numOutputRows", rb.num_rows)
            m.add("numOutputBatches", 1)
            yield DeviceBatch(tbl, num_rows=rb.num_rows)


class CachedScanExec(TpuExec):
    """Serves HBM-resident batches directly (GpuInMemoryTableScan analog)."""

    def __init__(self, batches, schema: Schema):
        super().__init__([], schema)
        self.batches = list(batches)

    def num_partitions(self, ctx):
        return max(1, len(self.batches))

    def execute_partition(self, ctx, pid):
        if pid < len(self.batches):
            yield self.batches[pid]


# ----------------------------------------------------------------------
class ProjectExec(TpuExec):
    def __init__(self, child: TpuExec, bound_exprs: List[Expression],
                 schema: Schema):
        super().__init__([child], schema)
        self.bound = bound_exprs

        def _run(cvs, mask):
            ctx = EmitCtx(cvs, mask.shape[0])
            return [e.emit(ctx) for e in self.bound]

        self._jit = jax.jit(_run)

    def describe(self):
        return f"ProjectExec[{', '.join(map(repr, self.bound))}]"

    def fusable_stage(self):
        def fn(cvs, mask):
            ctx = EmitCtx(cvs, mask.shape[0])
            return [e.emit(ctx) for e in self.bound], mask
        return fn

    def preserves_ordinals(self):
        return False

    def execute_partition(self, ctx, pid):
        m = ctx.metrics_for(self._op_id)
        for batch in self.children[0].execute_partition(ctx, pid):
            with m.timer("opTime"):
                out = self._jit(batch.cvs(), batch.row_mask)
            m.add("numOutputBatches", 1)
            yield DeviceBatch(make_table(self.schema, out, batch.num_rows),
                              batch.num_rows, batch.row_mask, batch.capacity)


class FilterExec(TpuExec):
    def __init__(self, child: TpuExec, bound_cond: Expression):
        super().__init__([child], child.schema)
        self.bound = bound_cond

        def _run(cvs, mask):
            ctx = EmitCtx(cvs, mask.shape[0])
            cv = self.bound.emit(ctx)
            return mask & cv.validity & cv.data.astype(jnp.bool_)

        self._jit = jax.jit(_run)

    def describe(self):
        return f"FilterExec[{self.bound!r}]"

    def fusable_stage(self):
        def fn(cvs, mask):
            ctx = EmitCtx(cvs, mask.shape[0])
            cv = self.bound.emit(ctx)
            return cvs, mask & cv.validity & cv.data.astype(jnp.bool_)
        return fn

    def execute_partition(self, ctx, pid):
        m = ctx.metrics_for(self._op_id)
        for batch in self.children[0].execute_partition(ctx, pid):
            with m.timer("opTime"):
                new_mask = self._jit(batch.cvs(), batch.row_mask)
            m.add("numOutputBatches", 1)
            yield DeviceBatch(batch.table, batch.num_rows, new_mask,
                              batch.capacity)


class LimitExec(TpuExec):
    """Global limit; collapses to a single output partition."""

    def __init__(self, child: TpuExec, n: int):
        super().__init__([child], child.schema)
        self.n = n

        def _clip(mask, remaining):
            ranks = jnp.cumsum(mask.astype(jnp.int64))
            new_mask = mask & (ranks <= remaining)
            return new_mask, jnp.sum(new_mask.astype(jnp.int64))

        self._jit = jax.jit(_clip)

    def num_partitions(self, ctx):
        return 1

    def execute_partition(self, ctx, pid):
        remaining = self.n
        child = self.children[0]
        for cpid in range(child.num_partitions(ctx)):
            if remaining <= 0:
                return
            for batch in child.execute_partition(ctx, cpid):
                if remaining <= 0:
                    return
                mask, took = self._jit(batch.row_mask, remaining)
                took = int(took)
                if took == 0:
                    continue
                remaining -= took
                yield DeviceBatch(batch.table, batch.num_rows, mask,
                                  batch.capacity)


class UnionExec(TpuExec):
    def __init__(self, children: List[TpuExec], schema: Schema):
        super().__init__(children, schema)
        self._offsets = []

    def num_partitions(self, ctx):
        return sum(c.num_partitions(ctx) for c in self.children)

    def execute_partition(self, ctx, pid):
        for c in self.children:
            n = c.num_partitions(ctx)
            if pid < n:
                for b in c.execute_partition(ctx, pid):
                    # positional union: rename child columns to ours
                    yield DeviceBatch(b.table.rename(self.schema.names),
                                      b.num_rows, b.row_mask, b.capacity)
                return
            pid -= n


# ----------------------------------------------------------------------
def _batch_to_arrow(batch: DeviceBatch):
    import pyarrow as pa
    from ..columnar.column import Column
    from ..utils.transfer import fetch
    # fetch the mask together with all column buffers: ONE device_get
    host = fetch([c.device_buffers() for c in batch.table.columns]
                 + [batch.row_mask])
    mask = np.asarray(host[-1])[:batch.num_rows]
    arrs = [Column.arrow_from_host(c.dtype, c.length, b)
            for c, b in zip(batch.table.columns, host[:-1])]
    at = (pa.Table.from_arrays(arrs, names=list(batch.table.names))
          if arrs else pa.table({}))
    if at.num_rows == 0 and batch.num_rows > 0:
        return pa.table({})  # zero-column batch (count(*) pipelines)
    if not mask.all():
        at = at.filter(pa.array(mask))
    return at


def collect_to_arrow(root: TpuExec, ctx: ExecContext):
    """Run the plan and materialize a host pyarrow Table (the analog of
    GpuColumnarToRowExec + collect). Partitions run as concurrent tasks
    bounded by the TpuSemaphore (the GpuSemaphore admission model:
    reference GpuSemaphore.scala:183)."""
    import pyarrow as pa
    nparts = root.num_partitions(ctx)
    if nparts <= 1:
        pieces = [_batch_to_arrow(b) for b in root.execute_all(ctx)]
    else:
        sem = _session_semaphore(ctx)
        import concurrent.futures as cf

        def run_part(pid):
            # GpuSemaphore model: hold the permit while DEVICE work runs
            # (advancing the iterator executes the jitted kernels), release
            # around the host-side fetch/convert
            out = []
            it = root.execute_partition(ctx, pid)
            while True:
                sem.acquire(priority=pid)
                try:
                    b = next(it, None)
                finally:
                    sem.release()
                if b is None:
                    break
                out.append(_batch_to_arrow(b))
            return out

        workers = min(nparts, max(2, ctx.conf.concurrent_tasks * 2))
        with cf.ThreadPoolExecutor(workers) as pool:
            results = list(pool.map(run_part, range(nparts)))
        pieces = [at for r in results for at in r]
    if not pieces:
        return root.schema.to_arrow().empty_table()
    return pa.concat_tables(pieces)


_SEM_LOCK = __import__("threading").Lock()


def _session_semaphore(ctx: ExecContext):
    from ..memory.semaphore import TpuSemaphore
    if ctx.session is None:
        return TpuSemaphore(ctx.conf.concurrent_tasks)
    with _SEM_LOCK:
        sem = getattr(ctx.session, "_semaphore", None)
        if sem is None:
            sem = TpuSemaphore(ctx.conf.concurrent_tasks)
            ctx.session._semaphore = sem
        return sem
