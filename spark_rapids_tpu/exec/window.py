"""Window function execution.

(reference: window/GpuWindowExec.scala + GpuRunningWindowExec — batched
running windows.) TPU-first: ONE sort by (partition, order) keys, then
every window function is a segment scan or segment reduction over the
sorted layout — ranking from boundary cumsums, running aggregates from
prefix sums (segmented via jax.lax.associative_scan for min/max), sliding
row frames from prefix-sum differences, lag/lead from shifted gathers.
All window expressions over the same spec fuse into one XLA program.
Output is in (partition, order) sorted order; Spark guarantees no
particular output order.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.table import Schema
from ..expr.expressions import EmitCtx, UnsupportedExpr
from ..ops import sortkeys as sk
from ..ops.concat import concat_cvs, concat_masks
from ..ops.gather import take
from ..ops.kernel_utils import CV
from ..utils.transfer import fetch_int
from ..window import CURRENT_ROW, UNBOUNDED, WindowExpr
from .base import ExecContext, TpuExec
from .batch import DeviceBatch
from .nodes import make_table

__all__ = ["WindowExec", "spec_signature"]


def spec_signature(spec):
    """Hashable (partition keys, orders) identity — frame excluded: one
    sort serves every frame over the same keys (the reference's window
    stage-splitting criterion, GpuWindowExecMeta.scala:182)."""
    return (tuple(repr(k) for k in spec.partition_keys),
            tuple((repr(o.expr), o.ascending, o.nulls_first)
                  for o in spec.orders))


def _floor_log2(length):
    """floor(log2(length)) for positive int lengths — pure integer binary
    reduction (frexp's s64 bitcast doesn't compile under the TPU x64
    rewrite)."""
    L = length.astype(jnp.int64)
    j = jnp.zeros_like(L)
    for b in (32, 16, 8, 4, 2, 1):
        big = L >= (jnp.int64(1) << b)
        j = j + jnp.where(big, b, 0)
        L = jnp.where(big, L >> b, L)
    return j.astype(jnp.int32)


def _rmq(x, valid, lo, hi, is_min: bool, nlev: int):
    """Range min/max over [lo, hi] per row via a sparse table (doubling):
    T[j][i] = reduce(x[i .. i+2^j-1]). nlev bounds table height (and
    memory, nlev*cap) to ceil(log2(max window length))+1. Invalid slots
    carry the identity; returns (reduced, any_valid)."""
    cap = x.shape[0]
    ident = _ident_of(x.dtype, is_min)
    red = jnp.minimum if is_min else jnp.maximum
    v = jnp.where(valid, x, ident)
    ok = valid
    levels, oks = [v], [ok]
    cur, curok = v, ok
    for j in range(1, nlev):
        sh = 1 << (j - 1)
        if sh >= cap:
            levels.append(cur)
            oks.append(curok)
            continue
        shifted = jnp.concatenate([cur[sh:], jnp.full((sh,), ident,
                                                      cur.dtype)])
        shok = jnp.concatenate([curok[sh:],
                                jnp.zeros(sh, jnp.bool_)])
        cur = red(cur, shifted)
        curok = curok | shok
        levels.append(cur)
        oks.append(curok)
    T = jnp.stack(levels)                       # (nlev, cap)
    TO = jnp.stack(oks)
    length = jnp.maximum(hi - lo + 1, 1)
    j = jnp.clip(_floor_log2(length), 0, nlev - 1)
    a_idx = jnp.clip(lo, 0, cap - 1)
    b_idx = jnp.clip(hi - (1 << j.astype(jnp.int64)) + 1, 0, cap - 1)
    flatT, flatO = T.reshape(-1), TO.reshape(-1)
    ja = j.astype(jnp.int64) * cap
    va = flatT[ja + a_idx]
    vb = flatT[ja + b_idx]
    oa = flatO[ja + a_idx] | flatO[ja + b_idx]
    out = red(va, vb)
    nonempty = hi >= lo
    return out, oa & nonempty


def _d128_lt(al, ah, bl, bh):
    """Lexicographic two's-complement 128-bit compare: signed hi limb,
    lo limb mapped to unsigned order via a sign-bit flip."""
    ul = al ^ jnp.int64(-2 ** 63)
    vl = bl ^ jnp.int64(-2 ** 63)
    return (ah < bh) | ((ah == bh) & (ul < vl))


def _rmq_d128(x2, valid, lo, hi, is_min: bool, nlev: int):
    """Two-limb sparse-table RMQ (the decimal128 analog of `_rmq`):
    T[j][i] = min/max over [i, i+2^j) under the lexicographic
    (hi signed, lo sign-flipped) order. Returns ((cap,2) packed limbs,
    any_valid). Reference: cudf rolling min/max windows over DECIMAL128
    (window/GpuWindowExec family)."""
    cap = x2.shape[0]
    hi_id = jnp.int64(jnp.iinfo(jnp.int64).max if is_min
                      else jnp.iinfo(jnp.int64).min)
    lo_id = jnp.int64(-1) if is_min else jnp.int64(0)
    cl = jnp.where(valid, x2[:, 0], lo_id)
    ch = jnp.where(valid, x2[:, 1], hi_id)
    cok = valid

    def red(al, ah, bl, bh):
        a_wins = _d128_lt(al, ah, bl, bh) if is_min \
            else _d128_lt(bl, bh, al, ah)
        return (jnp.where(a_wins, al, bl), jnp.where(a_wins, ah, bh))

    levels = [(cl, ch)]
    oks = [cok]
    for j in range(1, nlev):
        sh = 1 << (j - 1)
        if sh >= cap:
            levels.append((cl, ch))
            oks.append(cok)
            continue
        sl = jnp.concatenate([cl[sh:], jnp.full((sh,), lo_id)])
        shh = jnp.concatenate([ch[sh:], jnp.full((sh,), hi_id)])
        sok = jnp.concatenate([cok[sh:], jnp.zeros(sh, jnp.bool_)])
        cl, ch = red(cl, ch, sl, shh)
        cok = cok | sok
        levels.append((cl, ch))
        oks.append(cok)
    TL = jnp.stack([a for a, _ in levels]).reshape(-1)
    TH = jnp.stack([b for _, b in levels]).reshape(-1)
    TO = jnp.stack(oks).reshape(-1)
    length = jnp.maximum(hi - lo + 1, 1)
    j = jnp.clip(_floor_log2(length), 0, nlev - 1)
    a_idx = jnp.clip(lo, 0, cap - 1)
    b_idx = jnp.clip(hi - (1 << j.astype(jnp.int64)) + 1, 0, cap - 1)
    ja = j.astype(jnp.int64) * cap
    rl, rh = red(TL[ja + a_idx], TH[ja + a_idx],
                 TL[ja + b_idx], TH[ja + b_idx])
    ok = (TO[ja + a_idx] | TO[ja + b_idx]) & (hi >= lo)
    return jnp.stack([rl, rh], axis=1), ok


def _bsearch(skey, q, lo0, hi0, nbits: int, left: bool,
             descending: bool):
    """Per-row binary search over the (segment-)sorted key array: returns
    the first index in [lo0, hi0) whose key is >= q (left) or > q (right),
    under the given sort direction. All rows search concurrently with
    row-local bounds — the static-shape XLA answer to per-partition
    scans."""
    cap = skey.shape[0]

    def body(_, state):
        lo, hi = state
        mid = (lo + hi) // 2
        kv = skey[jnp.clip(mid, 0, cap - 1)]
        if descending:
            below = (kv > q) if left else (kv >= q)
        else:
            below = (kv < q) if left else (kv <= q)
        active = lo < hi
        new_lo = jnp.where(active & below, mid + 1, lo)
        new_hi = jnp.where(active & ~below, mid, hi)
        return new_lo, new_hi

    lo, _ = jax.lax.fori_loop(0, nbits + 1, body, (lo0, hi0))
    return lo


def _seg_scan_minmax(vals, valid, boundary, is_min: bool):
    """Segmented running min/max via associative scan."""
    ident = (jnp.inf if is_min else -jnp.inf) if jnp.issubdtype(
        vals.dtype, jnp.floating) else (
        jnp.iinfo(vals.dtype).max if is_min else jnp.iinfo(vals.dtype).min)
    v = jnp.where(valid, vals, ident)

    def combine(a, b):
        af, av = a
        bf, bv = b
        out_v = jnp.where(bf, bv,
                          jnp.minimum(av, bv) if is_min
                          else jnp.maximum(av, bv))
        return (af | bf, out_v)

    _, out = jax.lax.associative_scan(combine, (boundary, v))
    return out


class WindowExec(TpuExec):
    def __init__(self, child: TpuExec, names: Sequence[str],
                 wexprs: Sequence[WindowExpr], schema: Schema):
        super().__init__([child], schema)
        self.names = list(names)
        self.wexprs = list(wexprs)
        spec = self.wexprs[0].spec
        sig = spec_signature(spec)
        for w in self.wexprs[1:]:
            if spec_signature(w.spec) != sig:
                raise UnsupportedExpr(
                    "one WindowExec handles one (partition, order) spec; "
                    "the planner stages differing specs into a chain")
        self.spec = spec
        from ..runtime.program_cache import exprs_fp
        self._wfp = exprs_fp(self.wexprs)
        self._jit_cache = {}  # local memo over CachedProgram wrappers

    def num_partitions(self, ctx):
        return 1

    def describe(self):
        return f"WindowExec[{[w.fn for w in self.wexprs]}]"

    # ------------------------------------------------------------------
    def _compute(self, cvs, mask, nchunks, pk_nulls_first=False):
        ctx, wctx = self._prepare(cvs, mask, nchunks, pk_nulls_first)
        outs = []
        for w in self.wexprs:
            outs.append(self._one(w, ctx, wctx))
        sorted_cols = [take(cv, wctx["perm"], in_bounds=wctx["live"])
                       for cv in cvs]
        return sorted_cols, outs, wctx["live"]

    def _prepare(self, cvs, mask, nchunks, pk_nulls_first=False,
                 presorted=False):
        """Sort + segment the batch; returns (EmitCtx, window context).
        pk_nulls_first=True matches the chunked stream order (the
        internal OOC sort ranges with nulls first), so a null partition
        stays contiguous across chunk boundaries. presorted=True skips
        the multi-key lexsort (the chunked stream is already globally
        sorted; only dead capacity-padding rows need compacting to the
        back, a single-key stable sort)."""
        cap = mask.shape[0]
        ctx = EmitCtx(list(cvs), cap)
        pkeys = [k.emit(ctx) for k in self.spec.partition_keys]
        okeys = [o.expr.emit(ctx) for o in self.spec.orders]

        arrays = [jnp.logical_not(mask).astype(jnp.uint8)]
        pk_arrays = []
        i = 0
        for kcv, kexpr in zip(pkeys, self.spec.partition_keys):
            nullkey = (kcv.validity.astype(jnp.uint8) if pk_nulls_first
                       else jnp.logical_not(kcv.validity)
                       .astype(jnp.uint8))
            pk_arrays.append(nullkey)
            pk_arrays.extend(sk.order_keys(kcv, kexpr.dtype, nchunks[i]))
            i += 1
        ok_arrays = []
        for kcv, o in zip(okeys, self.spec.orders):
            vkey = kcv.validity.astype(jnp.uint8)
            ok_arrays.append(vkey if o.nulls_first else ~vkey)
            ok_arrays.extend(sk.order_keys(kcv, o.expr.dtype, nchunks[i],
                                           descending=not o.ascending))
            i += 1
        if presorted:
            perm = jnp.argsort(arrays[0], stable=True).astype(jnp.int32)
        else:
            perm = sk.lexsort(arrays + pk_arrays + ok_arrays)
        live = mask[perm]

        pb = sk.group_boundaries([a[perm] for a in arrays + pk_arrays])
        seg_ids = jnp.cumsum(pb.astype(jnp.int32)) - 1
        pos = jnp.arange(cap)
        seg_start = jax.ops.segment_min(pos, seg_ids, cap)[seg_ids]
        seg_cnt = jax.ops.segment_sum(jnp.ones(cap, jnp.int64), seg_ids,
                                      cap)
        cnt_row = seg_cnt[seg_ids]
        seg_end = seg_start + cnt_row - 1
        pos_in_seg = pos - seg_start
        # order-value change boundaries (for rank/dense_rank/peer frames)
        ob = pb | sk.group_boundaries(
            [a[perm] for a in arrays + pk_arrays + ok_arrays])
        peer_ids = jnp.cumsum(ob.astype(jnp.int32)) - 1
        peer_start = jax.ops.segment_min(pos, peer_ids, cap)[peer_ids]
        peer_end = jax.ops.segment_max(pos, peer_ids, cap)[peer_ids]
        # sorted first order key (range-offset frames search over it).
        # Integer keys widen to int64 BEFORE the null sentinel is applied
        # so a genuine key near the narrow dtype's domain edge can never
        # reach the sentinel via q = key + offset.
        skey = None
        if okeys and okeys[0].offsets is None:
            o0 = self.spec.orders[0]
            kcv = take(okeys[0], perm, in_bounds=live)
            kdata = kcv.data
            if jnp.issubdtype(kdata.dtype, jnp.integer) \
                    and kdata.dtype != jnp.int64:
                kdata = kdata.astype(jnp.int64)
            sentinel = _ident_of(
                kdata.dtype,
                for_min=(o0.nulls_first != o0.ascending))
            skey = (jnp.where(kcv.validity & live, kdata, sentinel),
                    kcv.validity & live)

        wctx = dict(perm=perm, live=live, pb=pb, ob=ob, seg_ids=seg_ids,
                    seg_start=seg_start, seg_end=seg_end, pos=pos,
                    pos_in_seg=pos_in_seg, cnt_row=cnt_row,
                    peer_start=peer_start, peer_end=peer_end, skey=skey,
                    cap=cap, pkeys=pkeys)
        return ctx, wctx

    # ---- chunked (out-of-core) windows --------------------------------
    _CHUNK_RUNNING = ("sum", "avg", "count", "min", "max")
    _CHUNK_RANKING = ("row_number", "rank", "dense_rank")

    def _chunkable(self) -> bool:
        """Running frames + ranking over fixed-width keys can stream
        chunk-by-chunk with carried per-partition state (reference:
        GpuRunningWindowExec.scala batched running windows). Everything
        else needs the whole partition resident."""
        if not self.spec.orders:
            return False
        fixed = lambda e: (not e.dtype.is_variable_width  # noqa: E731
                           and not e.dtype.is_nested
                           and not (isinstance(e.dtype, dt.DecimalType)
                                    and e.dtype.is_decimal128))
        if not all(fixed(k) for k in self.spec.partition_keys):
            return False
        if not all(fixed(o.expr) for o in self.spec.orders):
            return False
        for w in self.wexprs:
            if w.fn in self._CHUNK_RANKING:
                continue
            if (w.fn in self._CHUNK_RUNNING
                    and w.spec.frame == (UNBOUNDED, CURRENT_ROW)
                    and w.child is not None
                    and fixed(w.child)):
                continue
            return False
        return True

    def _zero_carry(self):
        pk = tuple((jnp.zeros((), k.dtype.np_dtype), jnp.zeros((), bool))
                   for k in self.spec.partition_keys)
        aggs = []
        for w in self.wexprs:
            if w.fn in self._CHUNK_RANKING:
                # ranking fns carry via part_rows/dense, no agg state
                aggs.append(None)
            else:
                acc = (jnp.float64 if jnp.issubdtype(
                    jnp.dtype(w.child.dtype.np_dtype), jnp.floating)
                    else jnp.int64)
                aggs.append((jnp.zeros((), acc), jnp.zeros((), jnp.int64)))
        return dict(valid=jnp.zeros((), bool), pk=pk,
                    part_rows=jnp.zeros((), jnp.int64),
                    dense=jnp.zeros((), jnp.int64), aggs=tuple(aggs))

    def _one_chunked(self, w, ctx, wc, cont_first, carry_s, carry_c,
                     carry_rows, carry_dense):
        """Chunkable window fns with carried-state adjustment applied to
        rows of the chunk's FIRST segment when it continues the previous
        chunk's partition. Returns (out CV, end_s, end_c) where end_*
        are the adjusted running states at arbitrary row index (gathered
        later for the next carry); ranking fns return (cv, None, None)
        since they carry via part_rows/dense instead."""
        live, pos = wc["live"], wc["pos"]
        seg_ids, pos_in_seg = wc["seg_ids"], wc["pos_in_seg"]
        seg_start = wc["seg_start"]
        first_seg = live & (seg_ids == seg_ids[0])
        adj = first_seg & cont_first
        if w.fn == "row_number":
            out = (pos_in_seg + 1
                   + jnp.where(adj, carry_rows, 0)).astype(jnp.int64)
            return CV(out.astype(jnp.int32), live), None, None
        if w.fn == "rank":
            last_ob = jax.lax.associative_scan(
                jnp.maximum, jnp.where(wc["ob"], pos, -1))
            rk = (last_ob - seg_start + 1).astype(jnp.int64)
            out = rk + jnp.where(adj, carry_rows, 0)
            return CV(out.astype(jnp.int32), live), None, None
        if w.fn == "dense_rank":
            c2 = jnp.cumsum(wc["ob"].astype(jnp.int32))
            base = c2[jnp.clip(seg_start, 0, wc["cap"] - 1)]
            loc = (c2 - base + 1).astype(jnp.int64)
            out = loc + jnp.where(adj, carry_dense, 0)
            return CV(out.astype(jnp.int32), live), None, None
        # running aggregate (UNBOUNDED PRECEDING .. CURRENT ROW)
        cv = w.child.emit(ctx)
        scv = take(cv, wc["perm"], in_bounds=live)
        valid = scv.validity & live
        x = scv.data
        acc_dt = (jnp.float64 if jnp.issubdtype(x.dtype, jnp.floating)
                  else jnp.int64)
        xz = jnp.where(valid, x, 0).astype(acc_dt)
        vz = valid.astype(jnp.int64)
        at = (wc["peer_end"] if w.spec.frame_mode == "range" else pos)
        if w.fn in ("min", "max"):
            s = _seg_scan_minmax(x, valid, wc["pb"], w.fn == "min")[at]
            c = _running(vz, wc["seg_start"])[at]
            red = jnp.minimum if w.fn == "min" else jnp.maximum
            have_carry = adj & (carry_c > 0)
            s_adj = jnp.where(
                have_carry,
                jnp.where(c > 0, red(s, carry_s.astype(s.dtype)),
                          carry_s.astype(s.dtype)), s)
            c_adj = c + jnp.where(adj, carry_c, 0)
            return (self._finish(w, s_adj, c_adj, live),
                    s_adj.astype(jnp.float64)
                    if jnp.issubdtype(s_adj.dtype, jnp.floating)
                    else s_adj.astype(jnp.int64), c_adj)
        s = _running(xz, wc["seg_start"])[at]
        c = _running(vz, wc["seg_start"])[at]
        s_adj = s + jnp.where(adj, carry_s.astype(s.dtype), 0)
        c_adj = c + jnp.where(adj, carry_c, 0)
        return self._finish(w, s_adj, c_adj, live), s_adj, c_adj

    def _compute_chunk(self, cvs, mask, nchunks, carry, emit_all: bool):
        """One streamed chunk: sort, compute adjusted window outputs,
        split off the HOLDBACK (last peer group of the last partition —
        possibly peer-incomplete until the next chunk arrives), and
        produce the next carry. Returns (sorted_cols, outs, emitted,
        n_emit, n_live, carry_next)."""
        ctx, wc = self._prepare(cvs, mask, nchunks, pk_nulls_first=True,
                                presorted=True)
        live, pos, cap = wc["live"], wc["pos"], wc["cap"]
        seg_ids = wc["seg_ids"]
        perm = wc["perm"]
        spkeys = [CV(kcv.data[perm], kcv.validity[perm])
                  for kcv in wc["pkeys"]]

        # does the first (sorted) row continue the carried partition?
        cont = carry["valid"]
        for (cd, cvl), kcv in zip(carry["pk"], spkeys):
            eq = (kcv.data[0] == cd) & kcv.validity[0] & cvl
            both_null = ~kcv.validity[0] & ~cvl
            cont = cont & (eq | both_null)

        outs, end_s, end_c = [], [], []
        for w, agg in zip(self.wexprs, carry["aggs"]):
            cs, cc = agg if agg is not None else (None, None)
            o, es, ec = self._one_chunked(
                w, ctx, wc, cont, cs, cc,
                carry["part_rows"], carry["dense"])
            outs.append(o)
            end_s.append(es)
            end_c.append(ec)

        n_live = jnp.sum(live.astype(jnp.int32))
        last_live = jnp.clip(n_live - 1, 0, cap - 1)
        if emit_all:
            emitted = live
            n_emit = n_live
        else:
            last_seg = seg_ids[last_live]
            holdback = live & (seg_ids == last_seg) \
                & (wc["peer_end"] == wc["seg_end"])
            emitted = live & ~holdback
            n_emit = jnp.sum(emitted.astype(jnp.int32))

        # next carry from the LAST EMITTED row (live rows are a sorted
        # prefix; holdback is its contiguous tail)
        e = jnp.clip(n_emit - 1, 0, cap - 1)
        any_emit = n_emit > 0
        same_seg = seg_ids[e] == seg_ids[0]
        cont_e = cont & same_seg
        pk_next = tuple(
            (jnp.where(any_emit, kcv.data[e], cd),
             jnp.where(any_emit, kcv.validity[e], cvl))
            for kcv, (cd, cvl) in zip(spkeys, carry["pk"]))
        part_rows_next = jnp.where(
            any_emit,
            wc["pos_in_seg"][e] + 1 + jnp.where(cont_e,
                                                carry["part_rows"], 0),
            carry["part_rows"])
        c2 = jnp.cumsum(wc["ob"].astype(jnp.int32))
        base = c2[jnp.clip(wc["seg_start"], 0, cap - 1)]
        dense_next = jnp.where(
            any_emit,
            (c2[e] - base[e] + 1).astype(jnp.int64)
            + jnp.where(cont_e, carry["dense"], 0),
            carry["dense"])
        aggs_next = tuple(
            None if agg is None else
            (jnp.where(any_emit, es[e], agg[0]).astype(agg[0].dtype),
             jnp.where(any_emit, ec[e], agg[1]).astype(agg[1].dtype))
            for (es, ec), agg in zip(zip(end_s, end_c), carry["aggs"]))
        carry_next = dict(valid=carry["valid"] | any_emit, pk=pk_next,
                          part_rows=part_rows_next, dense=dense_next,
                          aggs=aggs_next)
        sorted_cols = [take(cv, perm, in_bounds=live) for cv in cvs]
        return (sorted_cols, outs, emitted, n_emit, n_live, carry_next)

    def _execute_chunked(self, ctx: ExecContext, m, sorted_stream):
        """Drive the chunk stream: carry state forward, emit per chunk,
        re-queue each chunk's holdback in front of the next."""
        from ..ops.gather import gather_cols
        from ..columnar.column import bucket_capacity

        carry = self._zero_carry()
        hold_cvs, hold_mask = None, None
        nchunks = tuple(0 for _ in (list(self.spec.partition_keys)
                                    + list(self.spec.orders)))

        def assembled(batch):
            if hold_cvs is None:
                return list(batch.cvs()), batch.row_mask
            cvs = [concat_cvs([h, c], f.dtype) for h, c, f in
                   zip(hold_cvs, batch.cvs(),
                       self.children[0].schema.fields)]
            return cvs, concat_masks([hold_mask, batch.row_mask])

        stream = iter(sorted_stream)
        nxt = next(stream, None)
        while nxt is not None:
            batch = nxt
            nxt = next(stream, None)
            is_last = nxt is None
            cvs, mask = assembled(batch)
            with m.timer("opTime"):
                key = (mask.shape[0], is_last)
                fn = self._jit_cache.get(("chunk", key))
                if fn is None:
                    from ..runtime.program_cache import cached_program
                    fn = cached_program(
                        lambda c, mk, cr, _l=is_last:
                        self._compute_chunk(c, mk, nchunks, cr, _l),
                        cls="WindowExec", tag="chunk",
                        key=self._wfp + (nchunks, is_last))
                    self._jit_cache[("chunk", key)] = fn
                # this path runs under memory pressure by construction;
                # retry-after-spill like the in-core window (no input
                # split: the chunk is already the streaming unit)
                from ..memory.retry import retry_no_split
                (sorted_cols, outs, emitted, n_emit_d, n_live_d,
                 carry) = retry_no_split(lambda: fn(cvs, mask, carry))
                n_emit = fetch_int(n_emit_d)
                n_live = fetch_int(n_live_d)
            cap = mask.shape[0]
            if n_emit > 0:
                tbl = make_table(self.schema,
                                 list(sorted_cols) + list(outs), cap)
                m.add("numOutputBatches", 1)
                m.add("numChunks", 1)
                yield DeviceBatch(tbl, cap, emitted, cap)
            # holdback rows [n_emit, n_live) re-enter before next chunk
            if not is_last and n_live > n_emit:
                nh = n_live - n_emit
                hcap = bucket_capacity(nh)
                idx = jnp.arange(hcap, dtype=jnp.int32) + n_emit
                inb = jnp.arange(hcap) < nh
                hold_cvs = gather_cols(sorted_cols, idx, inb)
                hold_mask = inb
            else:
                hold_cvs, hold_mask = None, None

    def _frame_bounds(self, w: WindowExpr, wc):
        """Resolve the frame to per-row [lo, hi] index bounds over the
        sorted layout. None return values mean the natural segment bound
        (used to pick fast paths). Returns (lo, hi, max_len)."""
        k, m_ = w.spec.frame
        mode = w.spec.frame_mode
        seg_start, seg_end = wc["seg_start"], wc["seg_end"]
        pos, cap = wc["pos"], wc["cap"]
        if mode == "rows":
            lo = (seg_start if k is UNBOUNDED
                  else jnp.maximum(pos + k, seg_start))
            hi = (seg_end if m_ is UNBOUNDED
                  else jnp.minimum(pos + m_, seg_end))
            max_len = (cap if (k is UNBOUNDED or m_ is UNBOUNDED)
                       else max(int(m_) - int(k) + 1, 1))
            return lo, hi, max_len
        # RANGE frame: CURRENT_ROW bounds land on the peer group; numeric
        # offsets binary-search the (single, numeric) sorted order key
        def side(bound, is_lo):
            if bound is UNBOUNDED:
                return seg_start if is_lo else seg_end
            if bound == 0:
                return wc["peer_start"] if is_lo else wc["peer_end"]
            if wc["skey"] is None or len(w.spec.orders) != 1:
                raise UnsupportedExpr(
                    "RANGE offset frames need exactly one numeric "
                    "order key")
            skey, skvalid = wc["skey"]
            o0 = w.spec.orders[0]
            desc = not o0.ascending
            off = -bound if desc else bound
            if jnp.issubdtype(skey.dtype, jnp.integer):
                # key already widened to int64 in _compute; saturate at
                # the int64 domain edges so key+offset can't wrap
                q = skey + int(off)
                if off >= 0:
                    q = jnp.where(q < skey, jnp.iinfo(jnp.int64).max, q)
                else:
                    q = jnp.where(q > skey, jnp.iinfo(jnp.int64).min, q)
            else:
                q = skey + off
            nbits = max(1, int(cap).bit_length())
            idx = _bsearch(skey, q, seg_start.astype(jnp.int64),
                           (seg_end + 1).astype(jnp.int64), nbits,
                           left=is_lo, descending=desc)
            if not is_lo:
                idx = idx - 1
            # null-key rows frame = their peer (null) group
            return jnp.where(skvalid, idx,
                             wc["peer_start"] if is_lo else wc["peer_end"])
        return side(k, True), side(m_, False), wc["cap"]

    def _one(self, w: WindowExpr, ctx, wc):
        live, cap = wc["live"], wc["cap"]
        pos, pos_in_seg = wc["pos"], wc["pos_in_seg"]
        seg_start, seg_end = wc["seg_start"], wc["seg_end"]
        seg_ids, pb, ob = wc["seg_ids"], wc["pb"], wc["ob"]
        perm, cnt_row = wc["perm"], wc["cnt_row"]
        if w.fn == "row_number":
            return CV((pos_in_seg + 1).astype(jnp.int32), live)
        if w.fn in ("rank", "percent_rank"):
            last_ob = jax.lax.associative_scan(jnp.maximum,
                                               jnp.where(ob, pos, -1))
            rk = (last_ob - seg_start + 1).astype(jnp.int64)
            if w.fn == "rank":
                return CV(rk.astype(jnp.int32), live)
            denom = jnp.maximum(cnt_row - 1, 1).astype(jnp.float64)
            pr = jnp.where(cnt_row > 1,
                           (rk - 1).astype(jnp.float64) / denom, 0.0)
            return CV(pr, live)
        if w.fn == "dense_rank":
            c2 = jnp.cumsum(ob.astype(jnp.int32))
            base = c2[jnp.clip(seg_start, 0, cap - 1)]
            return CV((c2 - base + 1).astype(jnp.int32), live)
        if w.fn == "cume_dist":
            frac = ((wc["peer_end"] - seg_start + 1).astype(jnp.float64)
                    / cnt_row.astype(jnp.float64))
            return CV(frac, live)
        if w.fn == "ntile":
            n = w.offset
            q, r = cnt_row // n, cnt_row % n
            big = r * (q + 1)
            bucket = jnp.where(
                pos_in_seg < big, pos_in_seg // jnp.maximum(q + 1, 1),
                r + (pos_in_seg - big) // jnp.maximum(q, 1))
            return CV((bucket + 1).astype(jnp.int32), live)

        cv = w.child.emit(ctx)
        scv = take(cv, perm, in_bounds=live)
        if w.fn in ("lag", "lead"):
            off = w.offset if w.fn == "lag" else -w.offset
            j = pos - off
            in_seg = (j >= seg_start) & (j <= seg_end)
            j = jnp.clip(j, 0, cap - 1)
            out = take(scv, j.astype(jnp.int32), in_bounds=in_seg & live)
            if w.default is not None and scv.offsets is None:
                from ..expr.expressions import Literal
                dv = Literal(w.default, w.dtype).device_value()
                out = CV(jnp.where(in_seg, out.data, dv),
                         jnp.where(in_seg, out.validity, True) & live)
            return out

        if w.fn in ("first_value", "last_value", "nth_value"):
            lo, hi, _ = self._frame_bounds(w, wc)
            if w.fn == "first_value":
                idx = lo
            elif w.fn == "last_value":
                idx = hi
            else:
                idx = lo + w.offset - 1
            ok = live & (idx >= lo) & (idx <= hi) & (hi >= lo)
            return take(scv, jnp.clip(idx, 0, cap - 1).astype(jnp.int32),
                        in_bounds=ok)

        valid = scv.validity & live
        frame = w.spec.frame
        mode = w.spec.frame_mode
        if scv.offsets is not None:
            raise UnsupportedExpr(f"window {w.fn} over strings")
        x = scv.data
        if w.fn == "count":
            # count reads validity only: a dummy 1-D value keeps 2-limb
            # decimal inputs off the value math entirely
            x = jnp.zeros(cap, jnp.int8)
        elif x.ndim == 2 or (isinstance(w.dtype, dt.DecimalType)
                             and w.dtype.is_decimal128):
            return self._one_d128(w, wc, scv, live)
        acc_dt = (jnp.float64 if jnp.issubdtype(x.dtype, jnp.floating)
                  else jnp.int64)
        xz = jnp.where(valid, x, 0).astype(acc_dt)
        vz = valid.astype(jnp.int64)

        if frame == (UNBOUNDED, UNBOUNDED):
            if w.fn in ("sum", "avg", "count"):
                s = jax.ops.segment_sum(xz, seg_ids, cap)[seg_ids]
                c = jax.ops.segment_sum(vz, seg_ids, cap)[seg_ids]
            elif w.fn == "min":
                s = jax.ops.segment_min(
                    jnp.where(valid, x, _ident_of(x.dtype, True)),
                    seg_ids, cap)[seg_ids]
                c = jax.ops.segment_sum(vz, seg_ids, cap)[seg_ids]
            else:
                s = jax.ops.segment_max(
                    jnp.where(valid, x, _ident_of(x.dtype, False)),
                    seg_ids, cap)[seg_ids]
                c = jax.ops.segment_sum(vz, seg_ids, cap)[seg_ids]
            return self._finish(w, s, c, live)

        if frame == (UNBOUNDED, CURRENT_ROW):
            # running aggregate; in range mode the frame extends to the
            # end of the peer group (Spark default-frame tie semantics)
            at = (wc["peer_end"] if mode == "range" else pos)
            if w.fn in ("min", "max"):
                s = _seg_scan_minmax(x, valid, pb, w.fn == "min")[at]
                c = _running(vz, seg_start)[at]
                return self._finish(w, s, c, live)
            s = _running(xz, seg_start)[at]
            c = _running(vz, seg_start)[at]
            return self._finish(w, s, c, live)

        # general bounded frame: resolve [lo, hi] row bounds, then prefix
        # sums (sum/count/avg) or sparse-table RMQ (min/max)
        lo, hi, max_len = self._frame_bounds(w, wc)
        if w.fn in ("min", "max"):
            import math
            nlev = max(1, int(math.ceil(math.log2(
                max(2, min(max_len, cap))))) + 1)
            s, ok = _rmq(x, valid, lo, hi, w.fn == "min", nlev)
            c = jnp.where(ok, 1, 0)
            return self._finish(w, s, c, live)
        pre = jnp.cumsum(xz)
        prev = jnp.cumsum(vz)
        lo_idx = jnp.clip(lo - 1, 0, cap - 1)
        s = pre[jnp.clip(hi, 0, cap - 1)] - jnp.where(lo > 0,
                                                      pre[lo_idx], 0)
        c = prev[jnp.clip(hi, 0, cap - 1)] - jnp.where(lo > 0,
                                                       prev[lo_idx], 0)
        empty = hi < lo
        c = jnp.where(empty, 0, c)
        return self._finish(w, s, c, live)

    def _one_d128(self, w, wc, scv, live):
        """Decimal128 window aggregates via LIMB arithmetic: values are
        four 32-bit limbs in int64 lanes, per-limb segmented prefix
        sums stay exact (cap * 2^32 < 2^63) and ONE carry-propagation
        pass per output recovers the two's-complement 128-bit value —
        no data-dependent loops, everything rides the same scans as the
        64-bit path (reference: GpuWindowExec decimal windows over cuDF
        DECIMAL128 columns)."""
        from ..ops.decimal128 import (combine_limb_sums,
                                      split_d128_limbs, split_i64_limbs)
        valid = scv.validity & live
        frame = w.spec.frame
        mode = w.spec.frame_mode
        seg_ids, pos, cap = wc["seg_ids"], wc["pos"], wc["cap"]
        seg_start = wc["seg_start"]
        x = scv.data
        if x.ndim == 2:
            limbs = split_d128_limbs(x)      # top limb SIGNED: per-limb
        else:                                # prefix sums stay exact
            limbs = split_i64_limbs(x.astype(jnp.int64))
        lz = [jnp.where(valid, l, 0) for l in limbs]
        vz = valid.astype(jnp.int64)

        def finish_sum(slimbs, c):
            # exact reconstruction + true overflow (no 2^128 wrap):
            # the grouped Sum path's combine_limb_sums, per output row
            prec = (38 if w.fn == "avg" else w.dtype.precision)
            packed, ovf = combine_limb_sums(slimbs, prec)
            ok = live & (c > 0) & ~ovf
            packed = jnp.where(ok[:, None], packed, 0)
            if w.fn == "avg":
                f = (packed[:, 1].astype(jnp.float64) * (2.0 ** 64)
                     + jnp.where(packed[:, 0] < 0,
                                 packed[:, 0].astype(jnp.float64)
                                 + 2.0 ** 64,
                                 packed[:, 0].astype(jnp.float64)))
                scale = 10.0 ** w.child.dtype.scale
                safe = jnp.maximum(c, 1).astype(jnp.float64)
                return CV(f / safe / scale, ok)
            return CV(packed, ok)

        def minmax_whole(is_min):
            # lexicographic (hi signed, lo unsigned) in two passes
            hi_id = (jnp.iinfo(jnp.int64).max if is_min
                     else jnp.iinfo(jnp.int64).min)
            hi_v = jnp.where(valid, x[:, 1], hi_id)
            red = jax.ops.segment_min if is_min else jax.ops.segment_max
            mhi = red(hi_v, seg_ids, cap)[seg_ids]
            lo_u = x[:, 0] ^ jnp.int64(-2 ** 63)   # unsigned order
            lo_id = (jnp.iinfo(jnp.int64).max if is_min
                     else jnp.iinfo(jnp.int64).min)
            lo_v = jnp.where(valid & (x[:, 1] == mhi), lo_u, lo_id)
            mlo = red(lo_v, seg_ids, cap)[seg_ids] ^ jnp.int64(-2 ** 63)
            return jnp.stack([mlo, mhi], axis=-1)

        if frame == (UNBOUNDED, UNBOUNDED):
            if w.fn in ("sum", "avg"):
                s4 = [jax.ops.segment_sum(l, seg_ids, cap)[seg_ids]
                      for l in lz]
                c = jax.ops.segment_sum(vz, seg_ids, cap)[seg_ids]
                return finish_sum(s4, c)
            packed = minmax_whole(w.fn == "min")
            c = jax.ops.segment_sum(vz, seg_ids, cap)[seg_ids]
            ok = live & (c > 0)
            return CV(jnp.where(ok[:, None], packed, 0), ok)

        if frame == (UNBOUNDED, CURRENT_ROW):
            at = (wc["peer_end"] if mode == "range" else pos)
            if w.fn in ("sum", "avg"):
                s4 = [_running(l, seg_start)[at] for l in lz]
                c = _running(vz, seg_start)[at]
                return finish_sum(s4, c)
            packed = self._d128_scan_minmax(x, valid, wc["pb"],
                                            w.fn == "min")[at]
            c = _running(vz, seg_start)[at]
            ok = live & (c > 0)
            return CV(jnp.where(ok[:, None], packed, 0), ok)

        # general bounded frame: prefix-difference per limb (signed
        # diffs normalize exactly); min/max via the two-limb sparse
        # table RMQ
        if w.fn in ("min", "max"):
            import math
            lo_b, hi_b, max_len = self._frame_bounds(w, wc)
            x2 = (x if x.ndim == 2
                  else jnp.stack([x.astype(jnp.int64),
                                  x.astype(jnp.int64) >> 63], axis=1))
            nlev = max(1, int(math.ceil(math.log2(
                max(2, min(max_len, cap))))) + 1)
            packed, ok = _rmq_d128(x2, valid, lo_b, hi_b,
                                   w.fn == "min", nlev)
            ok = ok & live
            return CV(jnp.where(ok[:, None], packed, 0), ok)
        lo_b, hi_b, _ = self._frame_bounds(w, wc)
        lo_idx = jnp.clip(lo_b - 1, 0, cap - 1)
        hi_idx = jnp.clip(hi_b, 0, cap - 1)
        s4 = []
        for l in lz:
            pre = jnp.cumsum(l)
            s4.append(pre[hi_idx]
                      - jnp.where(lo_b > 0, pre[lo_idx], 0))
        prev = jnp.cumsum(vz)
        c = prev[hi_idx] - jnp.where(lo_b > 0, prev[lo_idx], 0)
        c = jnp.where(hi_b < lo_b, 0, c)
        s4 = [jnp.where(hi_b < lo_b, 0, s) for s in s4]
        return finish_sum(s4, c)

    @staticmethod
    def _d128_scan_minmax(x2, valid, boundary, is_min: bool):
        """Segmented running min/max over [cap,2] decimal128 via an
        associative scan on (flag, lo, hi) with lexicographic
        (hi signed, lo unsigned) compare."""
        hi_id = (jnp.iinfo(jnp.int64).max if is_min
                 else jnp.iinfo(jnp.int64).min)
        lo_id = jnp.int64(-1) if is_min else jnp.int64(0)
        lo = jnp.where(valid, x2[:, 0], lo_id)
        hi = jnp.where(valid, x2[:, 1], hi_id)

        def lt(al, ah, bl, bh):
            ul = al ^ jnp.int64(-2 ** 63)
            vl = bl ^ jnp.int64(-2 ** 63)
            return (ah < bh) | ((ah == bh) & (ul < vl))

        def combine(a, b):
            af, al, ah = a
            bf, bl, bh = b
            a_wins = lt(al, ah, bl, bh) if is_min else lt(bl, bh, al, ah)
            out_l = jnp.where(bf, bl, jnp.where(a_wins, al, bl))
            out_h = jnp.where(bf, bh, jnp.where(a_wins, ah, bh))
            return (af | bf, out_l, out_h)

        _, sl, sh = jax.lax.associative_scan(
            combine, (boundary, lo, hi))
        return jnp.stack([sl, sh], axis=-1)

    def _finish(self, w, s, c, live):
        if w.fn == "count":
            return CV(c.astype(jnp.int64), live)
        if w.fn == "avg":
            safe = jnp.where(c > 0, c, 1)
            return CV(s.astype(jnp.float64) / safe, live & (c > 0))
        return CV(s.astype(w.dtype.np_dtype), live & (c > 0))

    # ------------------------------------------------------------------
    def execute_partition(self, ctx: ExecContext, pid: int):
        from ..config import WINDOW_CHUNK_ROWS
        m = ctx.metrics_for(self._op_id)
        child = self.children[0]
        chunk_rows = ctx.conf.get(WINDOW_CHUNK_ROWS)
        if chunk_rows > 0 and self._chunkable():
            yield from self._execute_spillable(ctx, m, chunk_rows)
            return
        batches = []
        for cpid in range(child.num_partitions(ctx)):
            batches.extend(child.execute_partition(ctx, cpid))
        yield from self._execute_incore(ctx, m, batches)

    def _execute_spillable(self, ctx: ExecContext, m, chunk_rows: int):
        """Collect the child into spillable handles (the SpillStore keeps
        HBM bounded while the exact input size is measured — same pattern
        as SortExec), then stream chunk-by-chunk through the internal
        out-of-core sort when the input exceeds sql.window.chunkRows."""
        from ..memory.spill import spill_store
        from ..plan.logical import SortOrder
        from .sort import SortExec, _HandleScanExec
        child = self.children[0]
        store = spill_store(ctx.conf)
        handles = []
        total_rows = 0
        try:
            for cpid in range(child.num_partitions(ctx)):
                for b in child.execute_partition(ctx, cpid):
                    ctx.check_cancel()
                    total_rows += b.num_rows
                    handles.append(store.add_batch(b))
            if total_rows <= chunk_rows:
                yield from self._execute_incore(
                    ctx, m, [h.materialize() for h in handles])
                return
            orders = ([SortOrder(k, True, nulls_first=True)
                       for k in self.spec.partition_keys]
                      + list(self.spec.orders))
            schema = child.schema
            sorter = SortExec(_HandleScanExec(handles, schema), orders,
                              schema)

            def stream():
                for spid in range(sorter.num_partitions(ctx)):
                    yield from sorter.execute_partition(ctx, spid)

            yield from self._execute_chunked(ctx, m, stream())
        finally:
            for h in handles:
                h.close()

    def _execute_incore(self, ctx: ExecContext, m, batches):
        child = self.children[0]
        if not batches:
            return
        ncols = len(batches[0].table.columns)
        if len(batches) == 1:
            cvs, mask = batches[0].cvs(), batches[0].row_mask
        else:
            cvs = [concat_cvs([b.cvs()[i] for b in batches],
                              child.schema.fields[i].dtype)
                   for i in range(ncols)]
            mask = concat_masks([b.row_mask for b in batches])
        with m.timer("opTime"):
            nchunks = self._nchunks(cvs, mask)
            fn = self._jit_cache.get(nchunks)
            if fn is None:
                from ..runtime.program_cache import cached_program
                fn = cached_program(
                    lambda c, mk: self._compute(c, mk, nchunks),
                    cls="WindowExec", tag="whole",
                    key=self._wfp + (nchunks,))
                self._jit_cache[nchunks] = fn
            # window frames span the whole partition: input splitting is
            # not legal, so OOM protection is retry-after-spill only
            # (the GpuRetryOOM half of the reference's retry framework)
            from ..memory.retry import retry_no_split
            sorted_cols, outs, live = retry_no_split(
                lambda: fn(cvs, mask))
        cap = live.shape[0]
        tbl = make_table(self.schema, list(sorted_cols) + list(outs), cap)
        m.add("numOutputBatches", 1)
        yield DeviceBatch(tbl, cap, live, cap)

    def _nchunks(self, cvs, mask) -> Tuple[int, ...]:
        ctx = EmitCtx(list(cvs), mask.shape[0])
        ncs = []
        exprs = list(self.spec.partition_keys) + [o.expr for o in
                                                  self.spec.orders]
        for e in exprs:
            if isinstance(e.dtype, (dt.StringType, dt.BinaryType)):
                kcv = e.emit(ctx)
                lens = kcv.offsets[1:] - kcv.offsets[:-1]
                lens = jnp.where(mask & kcv.validity, lens, 0)
                ncs.append(sk.nchunks_for_len(
                    max(fetch_int(jnp.max(lens)), 1)))
            else:
                ncs.append(0)
        return tuple(ncs)


def _ident_of(dtype, for_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if for_min else -jnp.inf
    if dtype == jnp.bool_:
        return for_min
    return jnp.iinfo(dtype).max if for_min else jnp.iinfo(dtype).min


def _running(x, seg_start):
    """Segmented running sum: cumsum minus the segment's base prefix."""
    cap = x.shape[0]
    pre = jnp.cumsum(x)
    base_idx = jnp.clip(seg_start - 1, 0, cap - 1)
    base = jnp.where(seg_start > 0, pre[base_idx], 0)
    return pre - base
