"""Window function execution.

(reference: window/GpuWindowExec.scala + GpuRunningWindowExec — batched
running windows.) TPU-first: ONE sort by (partition, order) keys, then
every window function is a segment scan or segment reduction over the
sorted layout — ranking from boundary cumsums, running aggregates from
prefix sums (segmented via jax.lax.associative_scan for min/max), sliding
row frames from prefix-sum differences, lag/lead from shifted gathers.
All window expressions over the same spec fuse into one XLA program.
Output is in (partition, order) sorted order; Spark guarantees no
particular output order.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.table import Schema
from ..expr.expressions import EmitCtx, UnsupportedExpr
from ..ops import sortkeys as sk
from ..ops.concat import concat_cvs, concat_masks
from ..ops.gather import take
from ..ops.kernel_utils import CV
from ..utils.transfer import fetch_int
from ..window import CURRENT_ROW, UNBOUNDED, WindowExpr
from .base import ExecContext, TpuExec
from .batch import DeviceBatch
from .nodes import make_table

__all__ = ["WindowExec", "spec_signature"]


def spec_signature(spec):
    """Hashable (partition keys, orders) identity — frame excluded: one
    sort serves every frame over the same keys (the reference's window
    stage-splitting criterion, GpuWindowExecMeta.scala:182)."""
    return (tuple(repr(k) for k in spec.partition_keys),
            tuple((repr(o.expr), o.ascending, o.nulls_first)
                  for o in spec.orders))


def _floor_log2(length):
    """floor(log2(length)) for positive int lengths — pure integer binary
    reduction (frexp's s64 bitcast doesn't compile under the TPU x64
    rewrite)."""
    L = length.astype(jnp.int64)
    j = jnp.zeros_like(L)
    for b in (32, 16, 8, 4, 2, 1):
        big = L >= (jnp.int64(1) << b)
        j = j + jnp.where(big, b, 0)
        L = jnp.where(big, L >> b, L)
    return j.astype(jnp.int32)


def _rmq(x, valid, lo, hi, is_min: bool, nlev: int):
    """Range min/max over [lo, hi] per row via a sparse table (doubling):
    T[j][i] = reduce(x[i .. i+2^j-1]). nlev bounds table height (and
    memory, nlev*cap) to ceil(log2(max window length))+1. Invalid slots
    carry the identity; returns (reduced, any_valid)."""
    cap = x.shape[0]
    ident = _ident_of(x.dtype, is_min)
    red = jnp.minimum if is_min else jnp.maximum
    v = jnp.where(valid, x, ident)
    ok = valid
    levels, oks = [v], [ok]
    cur, curok = v, ok
    for j in range(1, nlev):
        sh = 1 << (j - 1)
        if sh >= cap:
            levels.append(cur)
            oks.append(curok)
            continue
        shifted = jnp.concatenate([cur[sh:], jnp.full((sh,), ident,
                                                      cur.dtype)])
        shok = jnp.concatenate([curok[sh:],
                                jnp.zeros(sh, jnp.bool_)])
        cur = red(cur, shifted)
        curok = curok | shok
        levels.append(cur)
        oks.append(curok)
    T = jnp.stack(levels)                       # (nlev, cap)
    TO = jnp.stack(oks)
    length = jnp.maximum(hi - lo + 1, 1)
    j = jnp.clip(_floor_log2(length), 0, nlev - 1)
    a_idx = jnp.clip(lo, 0, cap - 1)
    b_idx = jnp.clip(hi - (1 << j.astype(jnp.int64)) + 1, 0, cap - 1)
    flatT, flatO = T.reshape(-1), TO.reshape(-1)
    ja = j.astype(jnp.int64) * cap
    va = flatT[ja + a_idx]
    vb = flatT[ja + b_idx]
    oa = flatO[ja + a_idx] | flatO[ja + b_idx]
    out = red(va, vb)
    nonempty = hi >= lo
    return out, oa & nonempty


def _bsearch(skey, q, lo0, hi0, nbits: int, left: bool,
             descending: bool):
    """Per-row binary search over the (segment-)sorted key array: returns
    the first index in [lo0, hi0) whose key is >= q (left) or > q (right),
    under the given sort direction. All rows search concurrently with
    row-local bounds — the static-shape XLA answer to per-partition
    scans."""
    cap = skey.shape[0]

    def body(_, state):
        lo, hi = state
        mid = (lo + hi) // 2
        kv = skey[jnp.clip(mid, 0, cap - 1)]
        if descending:
            below = (kv > q) if left else (kv >= q)
        else:
            below = (kv < q) if left else (kv <= q)
        active = lo < hi
        new_lo = jnp.where(active & below, mid + 1, lo)
        new_hi = jnp.where(active & ~below, mid, hi)
        return new_lo, new_hi

    lo, _ = jax.lax.fori_loop(0, nbits + 1, body, (lo0, hi0))
    return lo


def _seg_scan_minmax(vals, valid, boundary, is_min: bool):
    """Segmented running min/max via associative scan."""
    ident = (jnp.inf if is_min else -jnp.inf) if jnp.issubdtype(
        vals.dtype, jnp.floating) else (
        jnp.iinfo(vals.dtype).max if is_min else jnp.iinfo(vals.dtype).min)
    v = jnp.where(valid, vals, ident)

    def combine(a, b):
        af, av = a
        bf, bv = b
        out_v = jnp.where(bf, bv,
                          jnp.minimum(av, bv) if is_min
                          else jnp.maximum(av, bv))
        return (af | bf, out_v)

    _, out = jax.lax.associative_scan(combine, (boundary, v))
    return out


class WindowExec(TpuExec):
    def __init__(self, child: TpuExec, names: Sequence[str],
                 wexprs: Sequence[WindowExpr], schema: Schema):
        super().__init__([child], schema)
        self.names = list(names)
        self.wexprs = list(wexprs)
        spec = self.wexprs[0].spec
        sig = spec_signature(spec)
        for w in self.wexprs[1:]:
            if spec_signature(w.spec) != sig:
                raise UnsupportedExpr(
                    "one WindowExec handles one (partition, order) spec; "
                    "the planner stages differing specs into a chain")
        self.spec = spec
        self._jit_cache = {}

    def num_partitions(self, ctx):
        return 1

    def describe(self):
        return f"WindowExec[{[w.fn for w in self.wexprs]}]"

    # ------------------------------------------------------------------
    def _compute(self, cvs, mask, nchunks):
        cap = mask.shape[0]
        ctx = EmitCtx(list(cvs), cap)
        pkeys = [k.emit(ctx) for k in self.spec.partition_keys]
        okeys = [o.expr.emit(ctx) for o in self.spec.orders]

        arrays = [jnp.logical_not(mask).astype(jnp.uint8)]
        pk_arrays = []
        i = 0
        for kcv, kexpr in zip(pkeys, self.spec.partition_keys):
            pk_arrays.append(jnp.logical_not(kcv.validity).astype(jnp.uint8))
            pk_arrays.extend(sk.order_keys(kcv, kexpr.dtype, nchunks[i]))
            i += 1
        ok_arrays = []
        for kcv, o in zip(okeys, self.spec.orders):
            vkey = kcv.validity.astype(jnp.uint8)
            ok_arrays.append(vkey if o.nulls_first else ~vkey)
            ok_arrays.extend(sk.order_keys(kcv, o.expr.dtype, nchunks[i],
                                           descending=not o.ascending))
            i += 1
        perm = sk.lexsort(arrays + pk_arrays + ok_arrays)
        live = mask[perm]

        pb = sk.group_boundaries([a[perm] for a in arrays + pk_arrays])
        seg_ids = jnp.cumsum(pb.astype(jnp.int32)) - 1
        pos = jnp.arange(cap)
        seg_start = jax.ops.segment_min(pos, seg_ids, cap)[seg_ids]
        seg_cnt = jax.ops.segment_sum(jnp.ones(cap, jnp.int64), seg_ids,
                                      cap)
        cnt_row = seg_cnt[seg_ids]
        seg_end = seg_start + cnt_row - 1
        pos_in_seg = pos - seg_start
        # order-value change boundaries (for rank/dense_rank/peer frames)
        ob = pb | sk.group_boundaries(
            [a[perm] for a in arrays + pk_arrays + ok_arrays])
        peer_ids = jnp.cumsum(ob.astype(jnp.int32)) - 1
        peer_start = jax.ops.segment_min(pos, peer_ids, cap)[peer_ids]
        peer_end = jax.ops.segment_max(pos, peer_ids, cap)[peer_ids]
        # sorted first order key (range-offset frames search over it).
        # Integer keys widen to int64 BEFORE the null sentinel is applied
        # so a genuine key near the narrow dtype's domain edge can never
        # reach the sentinel via q = key + offset.
        skey = None
        if okeys and okeys[0].offsets is None:
            o0 = self.spec.orders[0]
            kcv = take(okeys[0], perm, in_bounds=live)
            kdata = kcv.data
            if jnp.issubdtype(kdata.dtype, jnp.integer) \
                    and kdata.dtype != jnp.int64:
                kdata = kdata.astype(jnp.int64)
            sentinel = _ident_of(
                kdata.dtype,
                for_min=(o0.nulls_first != o0.ascending))
            skey = (jnp.where(kcv.validity & live, kdata, sentinel),
                    kcv.validity & live)

        wctx = dict(perm=perm, live=live, pb=pb, ob=ob, seg_ids=seg_ids,
                    seg_start=seg_start, seg_end=seg_end, pos=pos,
                    pos_in_seg=pos_in_seg, cnt_row=cnt_row,
                    peer_start=peer_start, peer_end=peer_end, skey=skey,
                    cap=cap)
        outs = []
        for w in self.wexprs:
            outs.append(self._one(w, ctx, wctx))
        sorted_cols = [take(cv, perm, in_bounds=live) for cv in cvs]
        return sorted_cols, outs, live

    def _frame_bounds(self, w: WindowExpr, wc):
        """Resolve the frame to per-row [lo, hi] index bounds over the
        sorted layout. None return values mean the natural segment bound
        (used to pick fast paths). Returns (lo, hi, max_len)."""
        k, m_ = w.spec.frame
        mode = w.spec.frame_mode
        seg_start, seg_end = wc["seg_start"], wc["seg_end"]
        pos, cap = wc["pos"], wc["cap"]
        if mode == "rows":
            lo = (seg_start if k is UNBOUNDED
                  else jnp.maximum(pos + k, seg_start))
            hi = (seg_end if m_ is UNBOUNDED
                  else jnp.minimum(pos + m_, seg_end))
            max_len = (cap if (k is UNBOUNDED or m_ is UNBOUNDED)
                       else max(int(m_) - int(k) + 1, 1))
            return lo, hi, max_len
        # RANGE frame: CURRENT_ROW bounds land on the peer group; numeric
        # offsets binary-search the (single, numeric) sorted order key
        def side(bound, is_lo):
            if bound is UNBOUNDED:
                return seg_start if is_lo else seg_end
            if bound == 0:
                return wc["peer_start"] if is_lo else wc["peer_end"]
            if wc["skey"] is None or len(w.spec.orders) != 1:
                raise UnsupportedExpr(
                    "RANGE offset frames need exactly one numeric "
                    "order key")
            skey, skvalid = wc["skey"]
            o0 = w.spec.orders[0]
            desc = not o0.ascending
            off = -bound if desc else bound
            if jnp.issubdtype(skey.dtype, jnp.integer):
                # key already widened to int64 in _compute; saturate at
                # the int64 domain edges so key+offset can't wrap
                q = skey + int(off)
                if off >= 0:
                    q = jnp.where(q < skey, jnp.iinfo(jnp.int64).max, q)
                else:
                    q = jnp.where(q > skey, jnp.iinfo(jnp.int64).min, q)
            else:
                q = skey + off
            nbits = max(1, int(cap).bit_length())
            idx = _bsearch(skey, q, seg_start.astype(jnp.int64),
                           (seg_end + 1).astype(jnp.int64), nbits,
                           left=is_lo, descending=desc)
            if not is_lo:
                idx = idx - 1
            # null-key rows frame = their peer (null) group
            return jnp.where(skvalid, idx,
                             wc["peer_start"] if is_lo else wc["peer_end"])
        return side(k, True), side(m_, False), wc["cap"]

    def _one(self, w: WindowExpr, ctx, wc):
        live, cap = wc["live"], wc["cap"]
        pos, pos_in_seg = wc["pos"], wc["pos_in_seg"]
        seg_start, seg_end = wc["seg_start"], wc["seg_end"]
        seg_ids, pb, ob = wc["seg_ids"], wc["pb"], wc["ob"]
        perm, cnt_row = wc["perm"], wc["cnt_row"]
        if w.fn == "row_number":
            return CV((pos_in_seg + 1).astype(jnp.int32), live)
        if w.fn in ("rank", "percent_rank"):
            last_ob = jax.lax.associative_scan(jnp.maximum,
                                               jnp.where(ob, pos, -1))
            rk = (last_ob - seg_start + 1).astype(jnp.int64)
            if w.fn == "rank":
                return CV(rk.astype(jnp.int32), live)
            denom = jnp.maximum(cnt_row - 1, 1).astype(jnp.float64)
            pr = jnp.where(cnt_row > 1,
                           (rk - 1).astype(jnp.float64) / denom, 0.0)
            return CV(pr, live)
        if w.fn == "dense_rank":
            c2 = jnp.cumsum(ob.astype(jnp.int32))
            base = c2[jnp.clip(seg_start, 0, cap - 1)]
            return CV((c2 - base + 1).astype(jnp.int32), live)
        if w.fn == "cume_dist":
            frac = ((wc["peer_end"] - seg_start + 1).astype(jnp.float64)
                    / cnt_row.astype(jnp.float64))
            return CV(frac, live)
        if w.fn == "ntile":
            n = w.offset
            q, r = cnt_row // n, cnt_row % n
            big = r * (q + 1)
            bucket = jnp.where(
                pos_in_seg < big, pos_in_seg // jnp.maximum(q + 1, 1),
                r + (pos_in_seg - big) // jnp.maximum(q, 1))
            return CV((bucket + 1).astype(jnp.int32), live)

        cv = w.child.emit(ctx)
        scv = take(cv, perm, in_bounds=live)
        if w.fn in ("lag", "lead"):
            off = w.offset if w.fn == "lag" else -w.offset
            j = pos - off
            in_seg = (j >= seg_start) & (j <= seg_end)
            j = jnp.clip(j, 0, cap - 1)
            out = take(scv, j.astype(jnp.int32), in_bounds=in_seg & live)
            if w.default is not None and scv.offsets is None:
                from ..expr.expressions import Literal
                dv = Literal(w.default, w.dtype).device_value()
                out = CV(jnp.where(in_seg, out.data, dv),
                         jnp.where(in_seg, out.validity, True) & live)
            return out

        if w.fn in ("first_value", "last_value", "nth_value"):
            lo, hi, _ = self._frame_bounds(w, wc)
            if w.fn == "first_value":
                idx = lo
            elif w.fn == "last_value":
                idx = hi
            else:
                idx = lo + w.offset - 1
            ok = live & (idx >= lo) & (idx <= hi) & (hi >= lo)
            return take(scv, jnp.clip(idx, 0, cap - 1).astype(jnp.int32),
                        in_bounds=ok)

        valid = scv.validity & live
        frame = w.spec.frame
        mode = w.spec.frame_mode
        if scv.offsets is not None:
            raise UnsupportedExpr(f"window {w.fn} over strings")
        x = scv.data
        acc_dt = (jnp.float64 if jnp.issubdtype(x.dtype, jnp.floating)
                  else jnp.int64)
        xz = jnp.where(valid, x, 0).astype(acc_dt)
        vz = valid.astype(jnp.int64)

        if frame == (UNBOUNDED, UNBOUNDED):
            if w.fn in ("sum", "avg", "count"):
                s = jax.ops.segment_sum(xz, seg_ids, cap)[seg_ids]
                c = jax.ops.segment_sum(vz, seg_ids, cap)[seg_ids]
            elif w.fn == "min":
                s = jax.ops.segment_min(
                    jnp.where(valid, x, _ident_of(x.dtype, True)),
                    seg_ids, cap)[seg_ids]
                c = jax.ops.segment_sum(vz, seg_ids, cap)[seg_ids]
            else:
                s = jax.ops.segment_max(
                    jnp.where(valid, x, _ident_of(x.dtype, False)),
                    seg_ids, cap)[seg_ids]
                c = jax.ops.segment_sum(vz, seg_ids, cap)[seg_ids]
            return self._finish(w, s, c, live)

        if frame == (UNBOUNDED, CURRENT_ROW):
            # running aggregate; in range mode the frame extends to the
            # end of the peer group (Spark default-frame tie semantics)
            at = (wc["peer_end"] if mode == "range" else pos)
            if w.fn in ("min", "max"):
                s = _seg_scan_minmax(x, valid, pb, w.fn == "min")[at]
                c = _running(vz, seg_start)[at]
                return self._finish(w, s, c, live)
            s = _running(xz, seg_start)[at]
            c = _running(vz, seg_start)[at]
            return self._finish(w, s, c, live)

        # general bounded frame: resolve [lo, hi] row bounds, then prefix
        # sums (sum/count/avg) or sparse-table RMQ (min/max)
        lo, hi, max_len = self._frame_bounds(w, wc)
        if w.fn in ("min", "max"):
            import math
            nlev = max(1, int(math.ceil(math.log2(
                max(2, min(max_len, cap))))) + 1)
            s, ok = _rmq(x, valid, lo, hi, w.fn == "min", nlev)
            c = jnp.where(ok, 1, 0)
            return self._finish(w, s, c, live)
        pre = jnp.cumsum(xz)
        prev = jnp.cumsum(vz)
        lo_idx = jnp.clip(lo - 1, 0, cap - 1)
        s = pre[jnp.clip(hi, 0, cap - 1)] - jnp.where(lo > 0,
                                                      pre[lo_idx], 0)
        c = prev[jnp.clip(hi, 0, cap - 1)] - jnp.where(lo > 0,
                                                       prev[lo_idx], 0)
        empty = hi < lo
        c = jnp.where(empty, 0, c)
        return self._finish(w, s, c, live)

    def _finish(self, w, s, c, live):
        if w.fn == "count":
            return CV(c.astype(jnp.int64), live)
        if w.fn == "avg":
            safe = jnp.where(c > 0, c, 1)
            return CV(s.astype(jnp.float64) / safe, live & (c > 0))
        return CV(s.astype(w.dtype.np_dtype), live & (c > 0))

    # ------------------------------------------------------------------
    def execute_partition(self, ctx: ExecContext, pid: int):
        m = ctx.metrics_for(self._op_id)
        child = self.children[0]
        batches = []
        for cpid in range(child.num_partitions(ctx)):
            batches.extend(child.execute_partition(ctx, cpid))
        if not batches:
            return
        ncols = len(batches[0].table.columns)
        if len(batches) == 1:
            cvs, mask = batches[0].cvs(), batches[0].row_mask
        else:
            cvs = [concat_cvs([b.cvs()[i] for b in batches],
                              child.schema.fields[i].dtype)
                   for i in range(ncols)]
            mask = concat_masks([b.row_mask for b in batches])
        with m.timer("opTime"):
            nchunks = self._nchunks(cvs, mask)
            fn = self._jit_cache.get(nchunks)
            if fn is None:
                fn = jax.jit(lambda c, mk: self._compute(c, mk, nchunks))
                self._jit_cache[nchunks] = fn
            # window frames span the whole partition: input splitting is
            # not legal, so OOM protection is retry-after-spill only
            # (the GpuRetryOOM half of the reference's retry framework)
            from ..memory.retry import retry_no_split
            sorted_cols, outs, live = retry_no_split(
                lambda: fn(cvs, mask))
        cap = live.shape[0]
        tbl = make_table(self.schema, list(sorted_cols) + list(outs), cap)
        m.add("numOutputBatches", 1)
        yield DeviceBatch(tbl, cap, live, cap)

    def _nchunks(self, cvs, mask) -> Tuple[int, ...]:
        ctx = EmitCtx(list(cvs), mask.shape[0])
        ncs = []
        exprs = list(self.spec.partition_keys) + [o.expr for o in
                                                  self.spec.orders]
        for e in exprs:
            if isinstance(e.dtype, (dt.StringType, dt.BinaryType)):
                kcv = e.emit(ctx)
                lens = kcv.offsets[1:] - kcv.offsets[:-1]
                lens = jnp.where(mask & kcv.validity, lens, 0)
                ncs.append(sk.nchunks_for_len(
                    max(fetch_int(jnp.max(lens)), 1)))
            else:
                ncs.append(0)
        return tuple(ncs)


def _ident_of(dtype, for_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if for_min else -jnp.inf
    if dtype == jnp.bool_:
        return for_min
    return jnp.iinfo(dtype).max if for_min else jnp.iinfo(dtype).min


def _running(x, seg_start):
    """Segmented running sum: cumsum minus the segment's base prefix."""
    cap = x.shape[0]
    pre = jnp.cumsum(x)
    base_idx = jnp.clip(seg_start - 1, 0, cap - 1)
    base = jnp.where(seg_start > 0, pre[base_idx], 0)
    return pre - base
