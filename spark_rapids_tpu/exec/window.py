"""Window function execution.

(reference: window/GpuWindowExec.scala + GpuRunningWindowExec — batched
running windows.) TPU-first: ONE sort by (partition, order) keys, then
every window function is a segment scan or segment reduction over the
sorted layout — ranking from boundary cumsums, running aggregates from
prefix sums (segmented via jax.lax.associative_scan for min/max), sliding
row frames from prefix-sum differences, lag/lead from shifted gathers.
All window expressions over the same spec fuse into one XLA program.
Output is in (partition, order) sorted order; Spark guarantees no
particular output order.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.table import Schema
from ..expr.expressions import EmitCtx, UnsupportedExpr
from ..ops import sortkeys as sk
from ..ops.concat import concat_cvs, concat_masks
from ..ops.gather import take
from ..ops.kernel_utils import CV
from ..utils.transfer import fetch_int
from ..window import CURRENT_ROW, UNBOUNDED, WindowExpr
from .base import ExecContext, TpuExec
from .batch import DeviceBatch
from .nodes import make_table

__all__ = ["WindowExec"]


def _seg_scan_minmax(vals, valid, boundary, is_min: bool):
    """Segmented running min/max via associative scan."""
    ident = (jnp.inf if is_min else -jnp.inf) if jnp.issubdtype(
        vals.dtype, jnp.floating) else (
        jnp.iinfo(vals.dtype).max if is_min else jnp.iinfo(vals.dtype).min)
    v = jnp.where(valid, vals, ident)

    def combine(a, b):
        af, av = a
        bf, bv = b
        out_v = jnp.where(bf, bv,
                          jnp.minimum(av, bv) if is_min
                          else jnp.maximum(av, bv))
        return (af | bf, out_v)

    _, out = jax.lax.associative_scan(combine, (boundary, v))
    return out


class WindowExec(TpuExec):
    def __init__(self, child: TpuExec, names: Sequence[str],
                 wexprs: Sequence[WindowExpr], schema: Schema):
        super().__init__([child], schema)
        self.names = list(names)
        self.wexprs = list(wexprs)
        spec = self.wexprs[0].spec
        for w in self.wexprs[1:]:
            if (len(w.spec.partition_keys) != len(spec.partition_keys)
                    or len(w.spec.orders) != len(spec.orders)):
                raise UnsupportedExpr(
                    "multiple window specs in one select: split into "
                    "separate selects (planner staging lands later)")
        self.spec = spec
        self._jit_cache = {}

    def num_partitions(self, ctx):
        return 1

    def describe(self):
        return f"WindowExec[{[w.fn for w in self.wexprs]}]"

    # ------------------------------------------------------------------
    def _compute(self, cvs, mask, nchunks):
        cap = mask.shape[0]
        ctx = EmitCtx(list(cvs), cap)
        pkeys = [k.emit(ctx) for k in self.spec.partition_keys]
        okeys = [o.expr.emit(ctx) for o in self.spec.orders]

        arrays = [jnp.logical_not(mask).astype(jnp.uint8)]
        pk_arrays = []
        i = 0
        for kcv, kexpr in zip(pkeys, self.spec.partition_keys):
            pk_arrays.append(jnp.logical_not(kcv.validity).astype(jnp.uint8))
            pk_arrays.extend(sk.order_keys(kcv, kexpr.dtype, nchunks[i]))
            i += 1
        ok_arrays = []
        for kcv, o in zip(okeys, self.spec.orders):
            vkey = kcv.validity.astype(jnp.uint8)
            ok_arrays.append(vkey if o.nulls_first else ~vkey)
            ok_arrays.extend(sk.order_keys(kcv, o.expr.dtype, nchunks[i],
                                           descending=not o.ascending))
            i += 1
        perm = sk.lexsort(arrays + pk_arrays + ok_arrays)
        live = mask[perm]

        pb = sk.group_boundaries([a[perm] for a in arrays + pk_arrays])
        seg_ids = jnp.cumsum(pb.astype(jnp.int32)) - 1
        pos = jnp.arange(cap)
        seg_start = jax.ops.segment_min(pos, seg_ids, cap)[seg_ids]
        seg_cnt = jax.ops.segment_sum(jnp.ones(cap, jnp.int64), seg_ids,
                                      cap)
        seg_end = seg_start + seg_cnt[seg_ids] - 1
        pos_in_seg = pos - seg_start
        # order-value change boundaries (for rank/dense_rank)
        ob = pb | sk.group_boundaries(
            [a[perm] for a in arrays + pk_arrays + ok_arrays])

        outs = []
        for w in self.wexprs:
            outs.append(self._one(w, ctx, perm, live, pb, ob, seg_ids,
                                  seg_start, seg_end, pos, pos_in_seg, cap))
        sorted_cols = [take(cv, perm, in_bounds=live) for cv in cvs]
        return sorted_cols, outs, live

    def _one(self, w: WindowExpr, ctx, perm, live, pb, ob, seg_ids,
             seg_start, seg_end, pos, pos_in_seg, cap):
        always = jnp.ones(cap, jnp.bool_)
        if w.fn == "row_number":
            return CV((pos_in_seg + 1).astype(jnp.int32), live)
        if w.fn == "rank":
            last_ob = jax.lax.associative_scan(jnp.maximum,
                                               jnp.where(ob, pos, -1))
            return CV((last_ob - seg_start + 1).astype(jnp.int32), live)
        if w.fn == "dense_rank":
            c2 = jnp.cumsum(ob.astype(jnp.int32))
            base = c2[jnp.clip(seg_start, 0, cap - 1)]
            return CV((c2 - base + 1).astype(jnp.int32), live)

        cv = w.child.emit(ctx)
        scv = take(cv, perm, in_bounds=live)
        if w.fn in ("lag", "lead"):
            off = w.offset if w.fn == "lag" else -w.offset
            j = pos - off
            in_seg = (j >= seg_start) & (j <= seg_end)
            j = jnp.clip(j, 0, cap - 1)
            out = take(scv, j.astype(jnp.int32), in_bounds=in_seg & live)
            if w.default is not None and scv.offsets is None:
                from ..expr.expressions import Literal
                dv = Literal(w.default, w.dtype).device_value()
                out = CV(jnp.where(in_seg, out.data, dv),
                         jnp.where(in_seg, out.validity, True) & live)
            return out

        valid = scv.validity & live
        frame = w.spec.frame
        if scv.offsets is not None:
            raise UnsupportedExpr(f"window {w.fn} over strings")
        x = scv.data
        acc_dt = (jnp.float64 if jnp.issubdtype(x.dtype, jnp.floating)
                  else jnp.int64)
        xz = jnp.where(valid, x, 0).astype(acc_dt)
        vz = valid.astype(jnp.int64)

        if frame == (UNBOUNDED, UNBOUNDED):
            if w.fn in ("sum", "avg", "count"):
                s = jax.ops.segment_sum(xz, seg_ids, cap)[seg_ids]
                c = jax.ops.segment_sum(vz, seg_ids, cap)[seg_ids]
            elif w.fn == "min":
                s = jax.ops.segment_min(
                    jnp.where(valid, x, _ident_of(x.dtype, True)),
                    seg_ids, cap)[seg_ids]
                c = jax.ops.segment_sum(vz, seg_ids, cap)[seg_ids]
            else:
                s = jax.ops.segment_max(
                    jnp.where(valid, x, _ident_of(x.dtype, False)),
                    seg_ids, cap)[seg_ids]
                c = jax.ops.segment_sum(vz, seg_ids, cap)[seg_ids]
            return self._finish(w, s, c, live)

        if frame == (UNBOUNDED, CURRENT_ROW):
            if w.fn in ("min", "max"):
                s = _seg_scan_minmax(x, valid, pb, w.fn == "min")
                c = _running(vz, seg_start)
                return self._finish(w, s, c, live)
            s = _running(xz, seg_start)
            c = _running(vz, seg_start)
            return self._finish(w, s, c, live)

        # bounded rows frame (-k .. m) via prefix sums
        k, m_ = frame
        if w.fn in ("min", "max"):
            raise UnsupportedExpr("bounded min/max window lands with the "
                                  "doubling scan")
        pre = jnp.cumsum(xz)
        prev = jnp.cumsum(vz)
        lo = seg_start if k is UNBOUNDED else jnp.maximum(pos + k,
                                                          seg_start)
        hi = seg_end if m_ is UNBOUNDED else jnp.minimum(pos + m_,
                                                         seg_end)
        lo_idx = jnp.clip(lo - 1, 0, cap - 1)
        s = pre[jnp.clip(hi, 0, cap - 1)] - jnp.where(lo > 0,
                                                      pre[lo_idx], 0)
        c = prev[jnp.clip(hi, 0, cap - 1)] - jnp.where(lo > 0,
                                                       prev[lo_idx], 0)
        empty = hi < lo
        c = jnp.where(empty, 0, c)
        return self._finish(w, s, c, live)

    def _finish(self, w, s, c, live):
        if w.fn == "count":
            return CV(c.astype(jnp.int64), live)
        if w.fn == "avg":
            safe = jnp.where(c > 0, c, 1)
            return CV(s.astype(jnp.float64) / safe, live & (c > 0))
        return CV(s.astype(w.dtype.np_dtype), live & (c > 0))

    # ------------------------------------------------------------------
    def execute_partition(self, ctx: ExecContext, pid: int):
        m = ctx.metrics_for(self._op_id)
        child = self.children[0]
        batches = []
        for cpid in range(child.num_partitions(ctx)):
            batches.extend(child.execute_partition(ctx, cpid))
        if not batches:
            return
        ncols = len(batches[0].table.columns)
        if len(batches) == 1:
            cvs, mask = batches[0].cvs(), batches[0].row_mask
        else:
            cvs = [concat_cvs([b.cvs()[i] for b in batches],
                              child.schema.fields[i].dtype)
                   for i in range(ncols)]
            mask = concat_masks([b.row_mask for b in batches])
        with m.timer("opTime"):
            nchunks = self._nchunks(cvs, mask)
            fn = self._jit_cache.get(nchunks)
            if fn is None:
                fn = jax.jit(lambda c, mk: self._compute(c, mk, nchunks))
                self._jit_cache[nchunks] = fn
            # window frames span the whole partition: input splitting is
            # not legal, so OOM protection is retry-after-spill only
            # (the GpuRetryOOM half of the reference's retry framework)
            from ..memory.retry import retry_no_split
            sorted_cols, outs, live = retry_no_split(
                lambda: fn(cvs, mask))
        cap = live.shape[0]
        tbl = make_table(self.schema, list(sorted_cols) + list(outs), cap)
        m.add("numOutputBatches", 1)
        yield DeviceBatch(tbl, cap, live, cap)

    def _nchunks(self, cvs, mask) -> Tuple[int, ...]:
        ctx = EmitCtx(list(cvs), mask.shape[0])
        ncs = []
        exprs = list(self.spec.partition_keys) + [o.expr for o in
                                                  self.spec.orders]
        for e in exprs:
            if isinstance(e.dtype, (dt.StringType, dt.BinaryType)):
                kcv = e.emit(ctx)
                lens = kcv.offsets[1:] - kcv.offsets[:-1]
                lens = jnp.where(mask & kcv.validity, lens, 0)
                ncs.append(sk.nchunks_for_len(
                    max(fetch_int(jnp.max(lens)), 1)))
            else:
                ncs.append(0)
        return tuple(ncs)


def _ident_of(dtype, for_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if for_min else -jnp.inf
    if dtype == jnp.bool_:
        return for_min
    return jnp.iinfo(dtype).max if for_min else jnp.iinfo(dtype).min


def _running(x, seg_start):
    """Segmented running sum: cumsum minus the segment's base prefix."""
    cap = x.shape[0]
    pre = jnp.cumsum(x)
    base_idx = jnp.clip(seg_start - 1, 0, cap - 1)
    base = jnp.where(seg_start > 0, pre[base_idx], 0)
    return pre - base
