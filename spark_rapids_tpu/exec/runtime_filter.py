"""Runtime bloom-filter join pruning.

Reference: the plugin's runtime filter path — GpuBloomFilterAggregate
feeding GpuBloomFilterMightContain through InSubqueryExec so the fact
side of a join drops non-matching rows BEFORE the shuffle. Standalone
analog: the planner wraps the STREAM side of a shuffled equi-join in
RuntimeBloomFilterExec, which folds the build keys into a device
bloom-filter bit vector and then masks every stream batch by k-hash
membership — rows that cannot match never reach the exchange.

Only sound for join types where a stream row WITHOUT a build match
contributes nothing (inner, left_semi, right). The build subtree is
wrapped in SharedBuildExec, so the filter and the join's build exchange
consume ONE materialization — any build shape with a row-count estimate
is eligible (no re-execution, no scan-shape restriction)."""
from __future__ import annotations

import threading
from typing import Iterator

import jax.numpy as jnp

from ..expr.expressions import EmitCtx
from .base import ExecContext, TpuExec
from .batch import DeviceBatch

__all__ = ["RuntimeBloomFilterExec", "SharedBuildExec"]


class SharedBuildExec(TpuExec):
    """Materializes its child ONCE per execution context (spill-backed)
    and replays the batches for every consumer — the join's build-side
    exchange and the runtime bloom filter read the SAME single scan,
    instead of re-executing the subtree per consumer (VERDICT r4 weak
    #4: the v1 filter double-scanned the build side). The reference
    derives its runtime filter from the subquery result it already has
    (GpuBloomFilterAggregate via InSubqueryExec)."""

    def __init__(self, child: TpuExec):
        super().__init__([child], child.schema)
        self._locks = {}
        self._lock = threading.Lock()

    def describe(self):
        return "SharedBuildExec"

    def num_partitions(self, ctx):
        return self.children[0].num_partitions(ctx)

    def _pid_lock(self, pid):
        with self._lock:
            return self._locks.setdefault(pid, threading.Lock())

    def execute_partition(self, ctx: ExecContext, pid: int):
        cache = ctx.shared_handles.setdefault(id(self), {})
        with self._pid_lock(pid):
            if pid not in cache:
                from ..memory.retry import retry_no_split
                from ..memory.spill import spill_store
                store = spill_store(ctx.conf)
                handles = []
                for b in self.children[0].execute_partition(ctx, pid):
                    ctx.check_cancel()
                    handles.append(retry_no_split(
                        lambda bb=b: store.add_batch(bb)))
                cache[pid] = handles
        for h in cache[pid]:
            yield h.materialize()


class RuntimeBloomFilterExec(TpuExec):
    def __init__(self, stream: TpuExec, build: TpuExec, stream_key,
                 build_key, estimated_items: int):
        super().__init__([stream], stream.schema)
        self.build = build
        self.stream_key = stream_key
        self.build_key = build_key
        from ..expr.aggregates import BloomFilterAggregate
        self._agg = BloomFilterAggregate(build_key,
                                         estimated_items=estimated_items)
        self._agg._resolve_type()
        self._bits = None
        self._lock = threading.Lock()
        self._probe_jit = None

    def describe(self):
        return (f"RuntimeBloomFilterExec[{self.stream_key!r} IN "
                f"bloom({self.build_key!r}), "
                f"bits={self._agg.num_bits}]")

    def release(self):
        self._bits = None
        self.build.release()
        super().release()

    # -- build ---------------------------------------------------------
    def _ensure_filter(self, ctx: ExecContext):
        if self._bits is not None:
            return self._bits
        with self._lock:
            if self._bits is not None:
                return self._bits
            m = ctx.metrics_for(self._op_id)
            a = self._agg
            state = None

            def upd(cvs, mask):
                ectx = EmitCtx(list(cvs), mask.shape[0])
                return a.update(a.child.emit(ectx), mask)

            from ..runtime.program_cache import cached_program, expr_fp
            afp = expr_fp(a)
            upd_jit = cached_program(upd, cls="RuntimeBloomFilterExec",
                                     tag="update", key=(afp,))
            merge_jit = cached_program(a.merge,
                                       cls="RuntimeBloomFilterExec",
                                       tag="merge", key=(afp,))
            with m.timer("bloomBuildTime"):
                for b in self.build.execute_all(ctx):
                    ctx.check_cancel()
                    st = upd_jit(b.cvs(), b.row_mask)
                    state = st if state is None else merge_jit(state, st)
                if state is None:          # empty build: nothing matches
                    state = (jnp.zeros(a.num_bits, jnp.bool_),)
            self._bits = state[0]
        return self._bits

    def execute_partition(self, ctx: ExecContext,
                          pid: int) -> Iterator[DeviceBatch]:
        m = ctx.metrics_for(self._op_id)
        bits = self._ensure_filter(ctx)
        if self._probe_jit is None:
            # close over the bound key + agg config only (not self):
            # the cached program must not pin this node's bloom bits
            # or build subtree. The bit vector is a traced argument.
            from ..runtime.program_cache import cached_program, expr_fp
            skey, agg = self.stream_key, self._agg

            def _probe(bits, cvs, mask):
                from ..ops.hash import bloom_positions
                ectx = EmitCtx(list(cvs), mask.shape[0])
                cv = skey.emit(ectx)
                nb = agg.num_bits
                hit = cv.validity
                for pos in bloom_positions(cv, skey.dtype, agg.k, nb):
                    hit = hit & bits[jnp.clip(pos, 0, nb - 1)]
                return mask & hit

            self._probe_jit = cached_program(
                _probe, cls="RuntimeBloomFilterExec", tag="probe",
                key=(expr_fp(skey), expr_fp(agg)))
        for batch in self.children[0].execute_partition(ctx, pid):
            ctx.check_cancel()
            with m.timer("bloomProbeTime"):
                new_mask = self._probe_jit(bits, batch.cvs(),
                                           batch.row_mask)
            m.add("numOutputBatches", 1)
            yield DeviceBatch(batch.table, batch.num_rows, new_mask,
                              batch.capacity)
