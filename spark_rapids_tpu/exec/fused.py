"""FusedStageExec: one jitted program per pipeline stage.

The WholeStageCodegen analog (reference: GpuExec chains fused by cudf
kernel launches; PAPERS.md "Rethinking Analytical Processing in the GPU
Era" on per-operator dispatch overhead): the plan-time fusion pass
(plan/fusion.py) collapses a maximal chain of narrow operators —
Filter, Project, limit-mask pre-chains — into one node whose single
`jax.jit` program composes the members' pure batch transforms
(TpuExec.fusable_stage) bottom-up. XLA then fuses the whole stage into
a handful of kernels: one dispatch per batch instead of one per
operator, and no intermediate DeviceBatch materialization between
members.

Member lore ids survive fusion: EXPLAIN renders
`FusedStage[loreId=N] { Filter[4] > Project[5] }` (top-down plan
order), and the profiler attributes one opTime to the fused node plus a
per-member `fusedRows.<Name>[<loreId>]` live-row counter (accumulated
on device, fetched once per partition — no per-batch sync).

Donation: dead input buffers (the child's cvs + mask) are donated on
real accelerators so XLA updates in place; on the CPU backend donation
is a warning-generating no-op, so it is skipped. Chains over
CachedScanExec are never fused (plan/fusion.py barrier), so donation
can never invalidate an HBM-cached batch.
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..profiler import xla_stats
from .base import ExecContext, TpuExec
from .batch import DeviceBatch

__all__ = ["FusedStageExec"]


class FusedStageExec(TpuExec):
    """A fused chain of narrow operators compiled as one program.

    `members` is the original chain in plan order (parent-most first);
    `base` is the first non-fused descendant that actually produces
    batches. Members keep their lore ids for EXPLAIN/profiling but are
    no longer in the `children` tree — `children == [base]`.
    """

    def __init__(self, members: List[TpuExec], base: TpuExec):
        super().__init__([base], members[0].schema)
        self.members = list(members)
        # execution order is bottom-up: the deepest member runs first
        stages = [m.fusable_stage() for m in reversed(self.members)]
        self._exec_order = list(reversed(self.members))

        def _run(cvs, mask, stats):
            counts = []
            for fn in stages:
                cvs, mask = fn(cvs, mask)
                counts.append(jnp.sum(mask, dtype=jnp.int64))
            return cvs, mask, stats + jnp.stack(counts)

        # donation is a no-op (with a warning) on the CPU backend; on
        # device backends the child's batch buffers and the running
        # stats vector are dead after the call and donated
        donate = () if jax.default_backend() == "cpu" else (0, 1, 2)
        from ..runtime.program_cache import cached_program
        self._jit = cached_program(
            _run, cls="FusedStageExec", tag="run",
            key=self.stage_fingerprint(), donate_argnums=donate)

    # ------------------------------------------------------------------
    def fusable_stage(self):
        """A FusedStage is itself fusable: parents that collapse their
        child chain (aggregate/limit/sort/join pre-stages) compose
        straight through it."""
        fns = [m.fusable_stage() for m in self._exec_order]

        def fn(cvs, mask):
            for f in fns:
                cvs, mask = f(cvs, mask)
            return cvs, mask
        return fn

    def preserves_ordinals(self) -> bool:
        return all(m.preserves_ordinals() for m in self.members)

    def stage_fingerprint(self) -> tuple:
        return ("FusedStage",) + tuple(
            m.stage_fingerprint() for m in self._exec_order)

    def describe(self) -> str:
        parts = " > ".join(
            f"{m.node_name().replace('Exec', '')}"
            f"[{getattr(m, 'lore_id', '?')}]" for m in self.members)
        return (f"FusedStage[loreId={getattr(self, 'lore_id', '?')}] "
                f"{{ {parts} }}")

    # ------------------------------------------------------------------
    def execute_partition(self, ctx: ExecContext, pid: int):
        from ..runtime import faults
        from ..utils.transfer import fetch
        from . import degrade
        from .nodes import make_table
        m = ctx.metrics_for(self._op_id)
        stats = jnp.zeros(len(self.members), dtype=jnp.int64)
        n_batches = 0
        for batch in self.children[0].execute_partition(ctx, pid):
            ctx.check_cancel()
            if self._op_id not in ctx.degraded:
                try:
                    if faults.ACTIVE:
                        faults.hit("device.dispatch",
                                   query_id=ctx.query_id,
                                   op="FusedStageExec")
                    with m.timer("opTime"):
                        cvs, mask, stats = self._jit(
                            batch.cvs(), batch.row_mask, stats)
                except Exception as e:  # noqa: BLE001 - classified below
                    if not (degrade.hostable_fused(self)
                            and degrade.should_degrade(ctx, self, e)):
                        raise
                else:
                    xla_stats.count_dispatch()
                    n_batches += 1
                    yield DeviceBatch(
                        make_table(self.schema, cvs, batch.num_rows),
                        batch.num_rows, mask, batch.capacity)
                    continue
            # degraded (or this batch's dispatch just failed): the host
            # interpreter runs the member chain bottom-up
            with m.timer("hostEvalTime"):
                hb = degrade.host_fused_batch(self, batch)
            m.add("degradedToHost", 1)
            if hb is None:
                continue
            n_batches += 1
            yield hb
        m.add("numOutputBatches", n_batches)
        if n_batches:
            # one partition-end fetch for every member counter
            vals = fetch(stats)
            for member, v in zip(self._exec_order, list(vals)):
                m.add(f"fusedRows.{member.node_name().replace('Exec', '')}"
                      f"[{getattr(member, 'lore_id', '?')}]", int(v))
